"""Process-wide observability registry: counters, gauges, histograms, spans.

One flat registry per process, guarded by a lock, holding four kinds of
runtime telemetry (SURVEY §1's blind spot — the reference has no equivalent):

* **counters** — monotonically increasing event counts (updates applied,
  collectives emitted, tracings per jitted step, buffer clamp risks).
* **gauges** — last-written values (per-metric state bytes, batches folded
  into the latest fused-epoch program).
* **histograms** — latency distributions over fixed log-spaced bins
  (:data:`HISTOGRAM_EDGES`: 6 buckets per decade, 1 µs – 100 s in ms), all
  host-side and jit-free: :func:`observe` is a bisect + three dict writes,
  and because every histogram shares the same static edges, snapshots from
  different processes/rounds compare and merge bucketwise.
  :func:`get_histogram` hands back a :class:`HistogramSnapshot` with
  ``p50``/``p95``/``p99`` accessors and arbitrary :meth:`~HistogramSnapshot.percentile`
  queries (bucket-interpolated, clamped to the observed min/max).
* **spans** — host-side wall-clock records of eager lifecycle phases
  (name, nesting depth, milliseconds), capped at ``max_spans`` so an
  unbounded training loop cannot leak memory; overflow is itself counted
  under ``obs.spans_dropped``.

Keys are ``name{label=value,...}`` with labels sorted, so the same logical
series always lands on one key and the Prometheus dumper
(:mod:`metrics_tpu.obs.export`) can re-split them mechanically; a label
value containing key syntax (``, = { } " \\`` or a newline) is stored
quoted with backslash escapes, so hostile values survive the round trip
instead of being mangled. :func:`sum_counter` totals a family across its
label values (e.g. every ``op=`` series of ``ft.degraded_syncs``).

The fault-tolerance subsystem (:mod:`metrics_tpu.ft`) reports through this
registry: ``ft.retries{op=}`` / ``ft.degraded_syncs{op=}`` from the DCN
retry policy, ``ft.checkpoint_saves{mode=}`` / ``ft.checkpoint_restores``
/ ``ft.checkpoints_rotated`` plus the ``ft.checkpoint_save_ms`` gauge from
the checkpoint manager — so a degraded or retry-storming sync is visible
in the same snapshot as the metric counters it distorts.

The registry is **disabled by default** and every instrumentation point in
the package checks :func:`enabled` before doing any work, so the disabled
mode adds nothing to compiled programs (the HLO-identity test in
``tests/bases/test_obs.py`` pins this) and only a predicate call to eager
paths. Enable with :func:`enable` or ``METRICS_TPU_OBS=1``.
"""
import math
import os
import re
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

__all__ = [
    "HISTOGRAM_EDGES",
    "HistogramSnapshot",
    "configure",
    "counters",
    "enable",
    "enabled",
    "gauges",
    "get_config",
    "get_counter",
    "get_gauge",
    "get_histogram",
    "histograms",
    "hops",
    "inc",
    "new_trace_id",
    "node_identity",
    "observe",
    "record_hop",
    "record_span",
    "reset",
    "set_gauge",
    "set_node_identity",
    "spans",
    "sum_counter",
]

_lock = threading.Lock()
_ENABLED = os.environ.get("METRICS_TPU_OBS", "").strip().lower() not in ("", "0", "false", "no", "off")

_counters: Dict[str, float] = {}
_gauges: Dict[str, float] = {}
# histogram series: key -> {"counts": per-bucket, "sum", "count", "min", "max"}
_histograms: Dict[str, Dict[str, Any]] = {}
# ring buffer: a full log drops the OLDEST span so the window always shows
# the most recent activity (a keep-oldest cap would freeze the log on
# run-start warmup forever); evictions are counted under obs.spans_dropped
_spans: Deque[Dict[str, Any]] = deque(maxlen=4096)
# per-hop payload lifecycle records from the serving tier (queue-wait /
# fold / ship / e2e per trace id) — same ring semantics as the span log,
# evictions counted under obs.hops_dropped. The unbounded accounting lives
# in the serve.hop_*_ms histograms; this ring feeds the Chrome-trace export
_hops: Deque[Dict[str, Any]] = deque(maxlen=4096)
# distinct-series count per (store kind, metric family) — the label-
# cardinality guard's O(1) read (see max_series_per_family below)
_family_counts: Dict[Tuple[str, str], int] = {}
# node identity stamped onto snapshots (obs federation keys its per-node
# table on it); None = derive "<hostname>:<pid>" lazily
_node_identity: Optional[str] = None

_config: Dict[str, Any] = {
    # warn when one jitted step has been traced this many times (shape/dtype
    # drift recompiles every distinct signature; see obs.recompile)
    "recompile_warn_threshold": 8,
    # host-side span ring size; evictions increment obs.spans_dropped
    "max_spans": 4096,
    # opt-in per-launch device timing: tracked/eager step launches
    # block_until_ready and land in step.latency_ms{step=} histograms
    # (adds one host sync per launch — see metrics_tpu.obs.profile)
    "device_timing": False,
    # opt-in cost-analysis attribution: every compile of a tracked step
    # pulls Compiled.cost_analysis() into step.flops / step.bytes_accessed
    # / step.arithmetic_intensity gauges (one AOT lower+compile per new
    # signature — see metrics_tpu.obs.profile.record_cost_analysis)
    "cost_analysis": False,
    # opt-in: each multi-process Metric.sync runs one tiny barrier
    # collective first and records the wait as the sync.arrival_skew_ms
    # gauge (this host's lead over the slowest peer;
    # utilities.distributed.record_arrival_skew). Default OFF because the
    # probe is a COLLECTIVE: it must be armed identically on every
    # process, and an ad-hoc obs.enable() on one host must never be able
    # to deadlock the fleet's next sync.
    "arrival_skew_probe": False,
    # label-cardinality guard: max distinct series per metric FAMILY per
    # store kind (counter/gauge/histogram). A hostile or buggy label
    # source (per-client ids, per-hop trace ids) must not grow the
    # registry without bound; writes past the cap are dropped and counted
    # under obs.series_dropped{family=}. None disables the guard.
    "max_series_per_family": 4096,
    # per-hop payload-lifecycle ring size (see record_hop); evictions
    # increment obs.hops_dropped
    "max_hops": 4096,
}

# thread-local nesting depth for the span recorder
_tls = threading.local()


def enable(on: bool = True) -> bool:
    """Turn the observability layer on (or off); returns the previous state."""
    global _ENABLED
    previous = _ENABLED
    _ENABLED = bool(on)
    return previous


def enabled() -> bool:
    """True when the observability layer is armed (``METRICS_TPU_OBS=1`` or
    :func:`enable`). Every hook in the package is behind this predicate."""
    return _ENABLED


def configure(**kwargs: Any) -> Dict[str, Any]:
    """Update config knobs (``recompile_warn_threshold``, ``max_spans``,
    ``max_hops``, ``device_timing``, ``cost_analysis``,
    ``arrival_skew_probe``, ``max_series_per_family``); returns the
    previous values of the keys that changed."""
    global _spans, _hops
    previous = {}
    with _lock:
        for key, value in kwargs.items():
            if key not in _config:
                raise ValueError(f"Unknown obs config key {key!r}; valid: {sorted(_config)}")
            if key in ("max_spans", "max_hops"):
                value = int(value)
                if value < 1:
                    raise ValueError(f"{key} must be >= 1, got {value}")
            if key == "max_series_per_family" and value is not None:
                value = int(value)
                if value < 1:
                    raise ValueError(f"max_series_per_family must be >= 1 (or None), got {value}")
            previous[key] = _config[key]
            _config[key] = value
            if key == "max_spans":
                # live resize: deque(iterable, maxlen) keeps the LAST items,
                # so a shrink preserves the newest spans — and the entries it
                # evicts are dropped spans like any ring overflow, counted
                evicted = len(_spans) - value
                if evicted > 0:
                    _counters["obs.spans_dropped"] = _counters.get("obs.spans_dropped", 0.0) + evicted
                _spans = deque(_spans, maxlen=value)
            if key == "max_hops":
                evicted = len(_hops) - value
                if evicted > 0:
                    _counters["obs.hops_dropped"] = _counters.get("obs.hops_dropped", 0.0) + evicted
                _hops = deque(_hops, maxlen=value)
    return previous


def get_config(key: str) -> Any:
    return _config[key]


def node_identity() -> str:
    """This process's identity on obs snapshots — the key the federation
    table (:mod:`metrics_tpu.obs.federation`) stores per-node snapshots
    under. Defaults to ``<hostname>:<pid>``; override with
    :func:`set_node_identity` (one identity per PROCESS: two aggregators in
    one process share a registry and therefore one identity — that is what
    keeps the in-process tree emulation from double-counting)."""
    global _node_identity
    if _node_identity is None:
        import socket

        _node_identity = f"{socket.gethostname()}:{os.getpid()}"
    return _node_identity


def set_node_identity(name: Optional[str]) -> Optional[str]:
    """Set (or with ``None``, re-derive lazily) the snapshot node identity;
    returns the previous explicit value."""
    global _node_identity
    previous = _node_identity
    _node_identity = None if name is None else str(name)
    return previous


def new_trace_id() -> str:
    """A fresh 16-hex-char trace id for wire payload provenance."""
    return os.urandom(8).hex()


_LABEL_UNSAFE = re.compile(r'[,={}"\\\n]')


def _escape_label_value(value: str) -> str:
    """Backslash-escape a label value: ``\\`` then ``"`` then newline (in
    that order so escapes are never double-escaped). ONE implementation,
    shared by the key quoting below and the Prometheus exposition dumper
    (:mod:`metrics_tpu.obs.export`) — the quoted-label round trip depends
    on both sides agreeing byte for byte."""
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_label_value(value: Any) -> str:
    """Render one label value into the flat series key.

    Plain values go in bare (``metric=Accuracy``) so existing keys stay
    stable; a value containing key syntax (``, = { } " \\`` or a newline)
    is stored QUOTED with backslash escapes — the Prometheus dumper
    (:func:`metrics_tpu.obs.export._parse_labels`) splits on commas only
    outside quotes and unescapes, so hostile values survive verbatim
    instead of being flattened to underscores.
    """
    s = str(value)
    if not _LABEL_UNSAFE.search(s):
        return s
    return f'"{_escape_label_value(s)}"'


def _key(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={_fmt_label_value(labels[k])}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def _admit_series(kind: str, store: Dict[str, Any], key: str, name: str) -> bool:
    """Label-cardinality guard (call under ``_lock``): True when a write to
    ``key`` may proceed. An existing series always may; a NEW series is
    admitted while its family holds fewer than ``max_series_per_family``
    distinct series, else the write is dropped and counted under
    ``obs.series_dropped{family=}`` (written directly — the drop counter
    itself must never be refused or recurse into the guard)."""
    if key in store:
        return True
    cap = _config["max_series_per_family"]
    if cap is None:
        _family_counts[(kind, name)] = _family_counts.get((kind, name), 0) + 1
        return True
    count = _family_counts.get((kind, name), 0)
    if count >= cap:
        drop_key = _key("obs.series_dropped", {"family": name})
        _counters[drop_key] = _counters.get(drop_key, 0.0) + 1.0
        return False
    _family_counts[(kind, name)] = count + 1
    return True


def inc(name: str, value: float = 1.0, **labels: Any) -> None:
    """Add ``value`` to counter ``name`` (labels become part of the series key)."""
    key = _key(name, labels)
    with _lock:
        if not _admit_series("counter", _counters, key, name):
            return
        _counters[key] = _counters.get(key, 0.0) + value


def set_gauge(name: str, value: float, **labels: Any) -> None:
    """Set gauge ``name`` to its latest observed ``value``."""
    key = _key(name, labels)
    with _lock:
        if not _admit_series("gauge", _gauges, key, name):
            return
        _gauges[key] = float(value)


def get_counter(name: str, **labels: Any) -> float:
    with _lock:
        return _counters.get(_key(name, labels), 0.0)


def get_gauge(name: str, **labels: Any) -> Optional[float]:
    with _lock:
        return _gauges.get(_key(name, labels))


# Fixed log-spaced bucket upper bounds (ms): 6 buckets per decade over
# 1 µs .. 100 s, plus an implicit +Inf overflow bucket. Shared by EVERY
# histogram so snapshots from different steps/hosts/rounds line up
# bucketwise; the ~47% bucket width bounds any percentile's relative error
# by the same factor, which is plenty to flag a 2x latency regression.
HISTOGRAM_EDGES: Tuple[float, ...] = tuple(10.0 ** (i / 6.0 - 3.0) for i in range(49))


class HistogramSnapshot:
    """Read-only view of one histogram series (see :func:`get_histogram`).

    ``counts`` has ``len(HISTOGRAM_EDGES) + 1`` per-bucket (non-cumulative)
    entries, the last being the +Inf overflow bucket. ``p50``/``p95``/``p99``
    and :meth:`percentile` interpolate linearly inside the hit bucket and
    clamp to the observed ``[min, max]``, so a single-valued series reports
    that exact value at every quantile.
    """

    __slots__ = ("counts", "sum", "count", "min", "max")

    def __init__(self, counts: List[int], total: float, count: int, vmin: float, vmax: float) -> None:
        self.counts = list(counts)
        self.sum = float(total)
        self.count = int(count)
        self.min = float(vmin)
        self.max = float(vmax)

    def percentile(self, q: float) -> Optional[float]:
        """Value at quantile ``q`` in [0, 1]; ``None`` on an empty series."""
        if self.count == 0:
            return None
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        target = q * self.count
        nonzero = [i for i, c in enumerate(self.counts) if c]
        first_nz, last_nz = nonzero[0], nonzero[-1]
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            prev = cum
            cum += c
            if cum >= target:
                lo = HISTOGRAM_EDGES[i - 1] if i > 0 else 0.0
                hi = HISTOGRAM_EDGES[i] if i < len(HISTOGRAM_EDGES) else self.max
                # the observed extremes live in the first/last hit bucket by
                # construction (bisect puts min/max there), so interpolating
                # from the bucket EDGE would smear a tight single-bucket
                # series across the whole bucket and then clamp every
                # quantile to max — anchor those two buckets on min/max
                if i == first_nz:
                    lo = self.min
                if i == last_nz:
                    hi = self.max
                value = lo + (hi - lo) * ((target - prev) / c)
                return min(max(value, self.min), self.max)
        return self.max

    @property
    def p50(self) -> Optional[float]:
        return self.percentile(0.50)

    @property
    def p95(self) -> Optional[float]:
        return self.percentile(0.95)

    @property
    def p99(self) -> Optional[float]:
        return self.percentile(0.99)

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "HistogramSnapshot":
        """Rebuild a snapshot from the :meth:`to_dict` shape (tolerating the
        wire-compact form with ``edges`` stripped) — the ONE inverse every
        consumer (federation merge, federated health reads) shares, so the
        dict shape can never drift between hand-rolled copies."""
        return cls(
            list(data.get("buckets") or []),
            float(data.get("sum", 0.0)),
            int(data.get("count", 0)),
            float(data.get("min", math.inf)),
            float(data.get("max", -math.inf)),
        )

    def to_dict(self) -> Dict[str, Any]:
        """Plain-dict form for :func:`metrics_tpu.obs.snapshot` / JSON: raw
        bucket counts plus the shared edges (self-describing) and the three
        headline percentiles precomputed."""
        return {
            "buckets": list(self.counts),
            "edges": list(HISTOGRAM_EDGES),
            "sum": self.sum,
            "count": self.count,
            "min": self.min,
            "max": self.max,
            "p50": self.p50,
            "p95": self.p95,
            "p99": self.p99,
        }

    def __repr__(self) -> str:
        if not self.count:
            return "HistogramSnapshot(empty)"
        return (
            f"HistogramSnapshot(count={self.count}, p50={self.p50:.3g},"
            f" p95={self.p95:.3g}, p99={self.p99:.3g}, max={self.max:.3g})"
        )


def observe(name: str, value: float, **labels: Any) -> None:
    """Record one sample into histogram ``name`` (fixed log-spaced bins,
    host-side, jit-free — a bisect plus three dict writes under the lock)."""
    v = float(value)
    if not math.isfinite(v):
        return  # NaN/inf would poison sum/mean/max (and inf breaks strict JSON)
    key = _key(name, labels)
    idx = bisect_left(HISTOGRAM_EDGES, v)
    with _lock:
        if not _admit_series("histogram", _histograms, key, name):
            return
        h = _histograms.get(key)
        if h is None:
            h = _histograms[key] = {
                "counts": [0] * (len(HISTOGRAM_EDGES) + 1),
                "sum": 0.0,
                "count": 0,
                "min": math.inf,
                "max": -math.inf,
            }
        h["counts"][idx] += 1
        h["sum"] += v
        h["count"] += 1
        if v < h["min"]:
            h["min"] = v
        if v > h["max"]:
            h["max"] = v


def get_histogram(name: str, **labels: Any) -> Optional[HistogramSnapshot]:
    """Snapshot of one histogram series, or ``None`` if never observed."""
    with _lock:
        h = _histograms.get(_key(name, labels))
        if h is None:
            return None
        return HistogramSnapshot(h["counts"], h["sum"], h["count"], h["min"], h["max"])


def histograms() -> Dict[str, Dict[str, Any]]:
    """A plain-dict copy of every histogram series (see
    :meth:`HistogramSnapshot.to_dict` for the per-series shape)."""
    with _lock:
        out = {}
        for key, h in _histograms.items():
            out[key] = HistogramSnapshot(h["counts"], h["sum"], h["count"], h["min"], h["max"]).to_dict()
        return out


def sum_counter(name: str) -> float:
    """Total of counter family ``name`` across ALL of its labeled series
    (plus any unlabeled one). ``get_counter`` addresses one exact series;
    this answers "did ANY ft.degraded_syncs fire" without enumerating the
    op labels."""
    prefix = name + "{"
    with _lock:
        return sum(v for k, v in _counters.items() if k == name or k.startswith(prefix))


def record_span(
    name: str,
    wall_ms: float,
    depth: int,
    category: Optional[str] = None,
    start_s: Optional[float] = None,
) -> None:
    """Append one finished host-side span to the ring (evicting the oldest
    when ``max_spans`` is reached, so the log always covers recent work).

    ``start_s`` is the span's start on the MONOTONIC clock
    (``time.perf_counter()``); the stored span carries ``start_ms`` /
    ``end_ms`` on that clock (span ordering/nesting survives wall-clock
    steps) plus the wall-clock ``t`` at completion, which is what the
    Chrome-trace export uses so host spans and cross-process payload hops
    share one timeline (:func:`metrics_tpu.obs.export.to_chrome_trace`)."""
    if start_s is None:
        start_s = time.perf_counter() - wall_ms / 1000.0
    span = {
        "name": name,
        "wall_ms": wall_ms,
        "depth": depth,
        "t": time.time(),
        "start_ms": start_s * 1000.0,
        "end_ms": start_s * 1000.0 + wall_ms,
    }
    if category is not None:
        span["category"] = category
    with _lock:
        if len(_spans) == _spans.maxlen:
            _counters["obs.spans_dropped"] = _counters.get("obs.spans_dropped", 0.0) + 1.0
        _spans.append(span)


def record_hop(trace_id: str, node: str, phase: str, dur_ms: float, **extra: Any) -> None:
    """Append one per-hop payload-lifecycle record (``phase`` in
    ``queue_wait`` / ``fold`` / ``ship`` / ``e2e``) to the hop ring.

    ``ts`` (wall-clock seconds, stamped here at completion) is shared with
    the trace context's ``encoded_at`` / ``accept_ts`` stamps, so a
    payload's lifecycle renders as one coherent track per trace id in the
    Chrome-trace export. The ring is capped (``max_hops``); the unbounded
    accounting lives in the ``serve.hop_*_ms{node=}`` histograms."""
    hop = {"trace": str(trace_id), "node": str(node), "phase": str(phase),
           "dur_ms": float(dur_ms), "ts": time.time()}
    if extra:
        hop.update(extra)
    with _lock:
        if len(_hops) == _hops.maxlen:
            _counters["obs.hops_dropped"] = _counters.get("obs.hops_dropped", 0.0) + 1.0
        _hops.append(hop)


def hops() -> List[Dict[str, Any]]:
    """A copy of the per-hop payload-lifecycle ring (serving tier only —
    empty unless payloads carried trace context through an aggregator)."""
    with _lock:
        return [dict(h) for h in _hops]


def _span_depth() -> int:
    return getattr(_tls, "depth", 0)


def _push_span() -> int:
    depth = getattr(_tls, "depth", 0)
    _tls.depth = depth + 1
    return depth


def _pop_span() -> None:
    _tls.depth = max(0, getattr(_tls, "depth", 1) - 1)


def counters() -> Dict[str, float]:
    """A copy of every counter series."""
    with _lock:
        return dict(_counters)


def gauges() -> Dict[str, float]:
    """A copy of every gauge series."""
    with _lock:
        return dict(_gauges)


def spans() -> List[Dict[str, Any]]:
    """A copy of the host-side span log (eager lifecycle phases only —
    device-side attribution lives in the profiler timeline, not here)."""
    with _lock:
        return [dict(s) for s in _spans]


def reset() -> None:
    """Clear all counters, gauges, histograms, spans, hop records and the
    cardinality-guard bookkeeping (the enabled flag, config and node
    identity survive — reset separates measurement windows, it doesn't
    disarm). The federation table is cleared by :func:`metrics_tpu.obs.reset`,
    which wraps this."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _histograms.clear()
        _spans.clear()
        _hops.clear()
        _family_counts.clear()
