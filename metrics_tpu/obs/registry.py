"""Process-wide observability registry: counters, gauges, span log, config.

One flat registry per process, guarded by a lock, holding three kinds of
runtime telemetry (SURVEY §1's blind spot — the reference has no equivalent):

* **counters** — monotonically increasing event counts (updates applied,
  collectives emitted, tracings per jitted step, buffer clamp risks).
* **gauges** — last-written values (per-metric state bytes, batches folded
  into the latest fused-epoch program).
* **spans** — host-side wall-clock records of eager lifecycle phases
  (name, nesting depth, milliseconds), capped at ``max_spans`` so an
  unbounded training loop cannot leak memory; overflow is itself counted
  under ``obs.spans_dropped``.

Keys are ``name{label=value,...}`` with labels sorted, so the same logical
series always lands on one key and the Prometheus dumper
(:mod:`metrics_tpu.obs.export`) can re-split them mechanically;
:func:`sum_counter` totals a family across its label values (e.g. every
``op=`` series of ``ft.degraded_syncs``).

The fault-tolerance subsystem (:mod:`metrics_tpu.ft`) reports through this
registry: ``ft.retries{op=}`` / ``ft.degraded_syncs{op=}`` from the DCN
retry policy, ``ft.checkpoint_saves{mode=}`` / ``ft.checkpoint_restores``
/ ``ft.checkpoints_rotated`` plus the ``ft.checkpoint_save_ms`` gauge from
the checkpoint manager — so a degraded or retry-storming sync is visible
in the same snapshot as the metric counters it distorts.

The registry is **disabled by default** and every instrumentation point in
the package checks :func:`enabled` before doing any work, so the disabled
mode adds nothing to compiled programs (the HLO-identity test in
``tests/bases/test_obs.py`` pins this) and only a predicate call to eager
paths. Enable with :func:`enable` or ``METRICS_TPU_OBS=1``.
"""
import os
import re
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

__all__ = [
    "configure",
    "counters",
    "enable",
    "enabled",
    "gauges",
    "get_config",
    "get_counter",
    "get_gauge",
    "inc",
    "record_span",
    "reset",
    "set_gauge",
    "spans",
    "sum_counter",
]

_lock = threading.Lock()
_ENABLED = os.environ.get("METRICS_TPU_OBS", "").strip().lower() not in ("", "0", "false", "no", "off")

_counters: Dict[str, float] = {}
_gauges: Dict[str, float] = {}
# ring buffer: a full log drops the OLDEST span so the window always shows
# the most recent activity (a keep-oldest cap would freeze the log on
# run-start warmup forever); evictions are counted under obs.spans_dropped
_spans: Deque[Dict[str, Any]] = deque(maxlen=4096)

_config: Dict[str, Any] = {
    # warn when one jitted step has been traced this many times (shape/dtype
    # drift recompiles every distinct signature; see obs.recompile)
    "recompile_warn_threshold": 8,
    # host-side span ring size; evictions increment obs.spans_dropped
    "max_spans": 4096,
}

# thread-local nesting depth for the span recorder
_tls = threading.local()


def enable(on: bool = True) -> bool:
    """Turn the observability layer on (or off); returns the previous state."""
    global _ENABLED
    previous = _ENABLED
    _ENABLED = bool(on)
    return previous


def enabled() -> bool:
    """True when the observability layer is armed (``METRICS_TPU_OBS=1`` or
    :func:`enable`). Every hook in the package is behind this predicate."""
    return _ENABLED


def configure(**kwargs: Any) -> Dict[str, Any]:
    """Update config knobs (``recompile_warn_threshold``, ``max_spans``);
    returns the previous values of the keys that changed."""
    global _spans
    previous = {}
    with _lock:
        for key, value in kwargs.items():
            if key not in _config:
                raise ValueError(f"Unknown obs config key {key!r}; valid: {sorted(_config)}")
            previous[key] = _config[key]
            _config[key] = value
            if key == "max_spans":
                _spans = deque(_spans, maxlen=int(value))
    return previous


def get_config(key: str) -> Any:
    return _config[key]


_LABEL_UNSAFE = re.compile(r'[,={}"\\\n]')


def _key(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    # label values are sanitized into the flat series key: ',' '=' '{' '}'
    # quotes/backslashes/newlines would make the key un-splittable for the
    # Prometheus dumper (and produce scrape-breaking exposition text)
    inner = ",".join(f"{k}={_LABEL_UNSAFE.sub('_', str(labels[k]))}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def inc(name: str, value: float = 1.0, **labels: Any) -> None:
    """Add ``value`` to counter ``name`` (labels become part of the series key)."""
    key = _key(name, labels)
    with _lock:
        _counters[key] = _counters.get(key, 0.0) + value


def set_gauge(name: str, value: float, **labels: Any) -> None:
    """Set gauge ``name`` to its latest observed ``value``."""
    with _lock:
        _gauges[_key(name, labels)] = float(value)


def get_counter(name: str, **labels: Any) -> float:
    with _lock:
        return _counters.get(_key(name, labels), 0.0)


def get_gauge(name: str, **labels: Any) -> Optional[float]:
    with _lock:
        return _gauges.get(_key(name, labels))


def sum_counter(name: str) -> float:
    """Total of counter family ``name`` across ALL of its labeled series
    (plus any unlabeled one). ``get_counter`` addresses one exact series;
    this answers "did ANY ft.degraded_syncs fire" without enumerating the
    op labels."""
    prefix = name + "{"
    with _lock:
        return sum(v for k, v in _counters.items() if k == name or k.startswith(prefix))


def record_span(name: str, wall_ms: float, depth: int, category: Optional[str] = None) -> None:
    """Append one finished host-side span to the ring (evicting the oldest
    when ``max_spans`` is reached, so the log always covers recent work)."""
    span = {"name": name, "wall_ms": wall_ms, "depth": depth, "t": time.time()}
    if category is not None:
        span["category"] = category
    with _lock:
        if len(_spans) == _spans.maxlen:
            _counters["obs.spans_dropped"] = _counters.get("obs.spans_dropped", 0.0) + 1.0
        _spans.append(span)


def _span_depth() -> int:
    return getattr(_tls, "depth", 0)


def _push_span() -> int:
    depth = getattr(_tls, "depth", 0)
    _tls.depth = depth + 1
    return depth


def _pop_span() -> None:
    _tls.depth = max(0, getattr(_tls, "depth", 1) - 1)


def counters() -> Dict[str, float]:
    """A copy of every counter series."""
    with _lock:
        return dict(_counters)


def gauges() -> Dict[str, float]:
    """A copy of every gauge series."""
    with _lock:
        return dict(_gauges)


def spans() -> List[Dict[str, Any]]:
    """A copy of the host-side span log (eager lifecycle phases only —
    device-side attribution lives in the profiler timeline, not here)."""
    with _lock:
        return [dict(s) for s in _spans]


def reset() -> None:
    """Clear all counters, gauges and spans (the enabled flag and config
    survive — reset separates measurement windows, it doesn't disarm)."""
    with _lock:
        _counters.clear()
        _gauges.clear()
        _spans.clear()
