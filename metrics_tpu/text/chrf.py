"""CHRFScore metric class.

Behavioral equivalent of reference ``torchmetrics/text/chrf.py:46``; the
per-order scalar-dict states become six sum-reduced count vectors (see
``functional/text/chrf.py`` redesign note).
"""
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.chrf import _chrf_score_compute, _chrf_score_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class CHRFScore(Metric):
    """chrF / chrF++ score; six per-order count-vector sum states.

    Args:
        n_char_order: character n-gram order (6 = official chrF/chrF++).
        n_word_order: word n-gram order (2 = chrF++, 0 = chrF).
        beta: recall weight in the F-score.
        lowercase: case-insensitive matching.
        whitespace: keep whitespace in char n-grams.
        return_sentence_level_score: also return per-sentence scores.

    Example:
        >>> from metrics_tpu import CHRFScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> chrf = CHRFScore()
        >>> chrf(preds, target)
        Array(0.8640465, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
        if not isinstance(n_word_order, int) or n_word_order < 0:
            raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
        if beta < 0:
            raise ValueError("Expected argument `beta` to be greater than 0.")
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("matching_char", default=jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("matching_word", default=jnp.zeros(n_word_order), dist_reduce_fx="sum")
        self.add_state("hyp_char", default=jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("hyp_word", default=jnp.zeros(n_word_order), dist_reduce_fx="sum")
        self.add_state("ref_char", default=jnp.zeros(n_char_order), dist_reduce_fx="sum")
        self.add_state("ref_word", default=jnp.zeros(n_word_order), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_chrf_score", default=[], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]) -> None:
        scores: Optional[list] = [] if self.return_sentence_level_score else None
        m_char, m_word, h_char, h_word, r_char, r_word = _chrf_score_update(
            preds, target, self.n_char_order, self.n_word_order, self.beta, self.lowercase, self.whitespace, scores
        )
        self.matching_char = self.matching_char + m_char
        self.matching_word = self.matching_word + m_word
        self.hyp_char = self.hyp_char + h_char
        self.hyp_word = self.hyp_word + h_word
        self.ref_char = self.ref_char + r_char
        self.ref_word = self.ref_word + r_word
        if scores is not None:
            self.sentence_chrf_score = self.sentence_chrf_score + scores

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        score = _chrf_score_compute(
            self.matching_char, self.matching_word, self.hyp_char, self.hyp_word, self.ref_char, self.ref_word, self.beta
        )
        if self.return_sentence_level_score:
            return score, dim_zero_cat(self.sentence_chrf_score)
        return score
