"""CharErrorRate metric class.

Behavioral equivalent of reference ``torchmetrics/text/cer.py:24``.
"""
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.cer import _cer_compute, _cer_update
from metrics_tpu.metric import Metric

Array = jax.Array


class CharErrorRate(Metric):
    """Character error rate; O(1) sum states, psum-synced over the mesh.

    Example:
        >>> from metrics_tpu import CharErrorRate
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> metric = CharErrorRate()
        >>> metric(preds, target)
        Array(0.34146342, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _cer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        return _cer_compute(self.errors, self.total)
