"""TranslationEditRate metric class.

Behavioral equivalent of reference ``torchmetrics/text/ter.py:24``.
"""
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.ter import _TercomTokenizer, _ter_compute, _ter_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class TranslationEditRate(Metric):
    """Translation edit rate; scalar sum states + optional per-sentence cat state.

    Args:
        normalize: apply general Tercom tokenization.
        no_punctuation: strip punctuation before scoring.
        lowercase: case-insensitive matching.
        asian_support: split CJK characters during tokenization.
        return_sentence_level_score: also return per-sentence TER.

    Example:
        >>> from metrics_tpu import TranslationEditRate
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> ter = TranslationEditRate()
        >>> ter(preds, target)
        Array(0.15384616, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        for name, value in (
            ("normalize", normalize),
            ("no_punctuation", no_punctuation),
            ("lowercase", lowercase),
            ("asian_support", asian_support),
        ):
            if not isinstance(value, bool):
                raise ValueError(f"Expected argument `{name}` to be of type boolean but got {value}")
        self.tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("total_num_edits", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total_tgt_length", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_ter", default=[], dist_reduce_fx="cat")

    def update(
        self, preds: Union[str, Sequence[str]], target: Union[Sequence[str], Sequence[Sequence[str]]]
    ) -> None:
        scores: Optional[list] = [] if self.return_sentence_level_score else None
        num_edits, tgt_length = _ter_update(preds, target, self.tokenizer, scores)
        self.total_num_edits = self.total_num_edits + num_edits
        self.total_tgt_length = self.total_tgt_length + tgt_length
        if scores is not None:
            self.sentence_ter = self.sentence_ter + scores

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        score = _ter_compute(self.total_num_edits, self.total_tgt_length)
        if self.return_sentence_level_score:
            return score, dim_zero_cat(self.sentence_ter)
        return score
