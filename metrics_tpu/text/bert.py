"""BERTScore metric class.

Behavioral equivalent of reference ``torchmetrics/text/bert.py:40``: states
are the tokenized input buffers (``input_ids``/``attention_mask`` cat
states, statically padded to ``max_length`` so the distributed all-gather is
shape-stable), and the encoder forward + matching kernel run in ``compute``.
"""
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.helper import _put_all
from metrics_tpu.functional.text.bert import _DEFAULT_MODEL, _load_tokenizer_and_model, _tokenize, bert_score
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


class BERTScore(Metric):
    """BERTScore with a Flax/JAX encoder.

    Args:
        model_name_or_path: transformers model id (loaded as ``FlaxAutoModel``).
        num_layers: hidden layer to take embeddings from (default: last).
        model: a user's own (Flax) model; combine with ``user_tokenizer`` and
            ``user_forward_fn``.
        user_tokenizer: callable ``(List[str], max_length) -> {"input_ids",
            "attention_mask"}`` of numpy/jnp arrays, padded to max_length.
        user_forward_fn: callable ``(model, batch_dict) -> (B, S, D)`` jnp array.
        verbose: log a progress line per embedding batch.
        idf: weight token matches by inverse document frequency.
        device: accepted for reference API parity and ignored — JAX places
            the encoder on the default device.
        max_length: static pad length for the token buffers.
        batch_size: encoder forward batch size inside ``compute``.
        num_threads: accepted for reference API parity and ignored — there
            is no dataloader thread pool here.
        rescale_with_baseline: rescale with a precomputed baseline csv.
        baseline_path: local path of the baseline csv.
        baseline_url: accepted for API parity; remote baselines are not
            fetched — pass ``baseline_path`` instead.
        all_layers: score every hidden layer (incl. the embedding layer);
            results gain a leading ``num_layers`` axis. Only valid with
            default ``transformers`` models.
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        model: Optional[Any] = None,
        user_tokenizer: Any = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        device: Optional[Any] = None,
        max_length: int = 512,
        batch_size: int = 64,
        num_threads: int = 4,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_path: Optional[str] = None,
        baseline_url: Optional[str] = None,
        all_layers: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if model is None and model_name_or_path is None:
            rank_zero_warn(
                f"The argument `model_name_or_path` was not specified while it is required when the default "
                f"`transformers` model is used. It will use the default recommended model - {_DEFAULT_MODEL!r}."
            )
            model_name_or_path = _DEFAULT_MODEL
        if model is None:
            self.tokenizer, self.model = _load_tokenizer_and_model(model_name_or_path)
        else:
            self.tokenizer = user_tokenizer
            self.model = model
        self.model_name_or_path = model_name_or_path
        self.num_layers = num_layers
        self.user_tokenizer = user_tokenizer
        self.user_forward_fn = user_forward_fn
        self.verbose = verbose
        self.idf = idf
        self.device = device  # accepted for API parity; JAX owns placement
        self.num_threads = num_threads  # idem: no dataloader thread pool
        self.max_length = max_length
        self.batch_size = batch_size
        self.return_hash = return_hash
        self.lang = lang
        self.rescale_with_baseline = rescale_with_baseline
        self.baseline_path = baseline_path
        self.baseline_url = baseline_url
        self.all_layers = all_layers

        self.add_state("preds_input_ids", default=[], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", default=[], dist_reduce_fx="cat")
        self.add_state("target_input_ids", default=[], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", default=[], dist_reduce_fx="cat")

    def update(self, preds: List[str], target: List[str]) -> None:
        """Tokenize and buffer the sentences (model forward is deferred to compute)."""
        own_tokenizer = self.user_tokenizer is not None
        preds_tok = _tokenize(self.tokenizer, list(preds), self.max_length, own_tokenizer)
        target_tok = _tokenize(self.tokenizer, list(target), self.max_length, own_tokenizer)
        p_ids, p_mask, t_ids, t_mask = _put_all(
            preds_tok["input_ids"], preds_tok["attention_mask"],
            target_tok["input_ids"], target_tok["attention_mask"],
        )
        self.preds_input_ids.append(p_ids)
        self.preds_attention_mask.append(p_mask)
        self.target_input_ids.append(t_ids)
        self.target_attention_mask.append(t_mask)

    def compute(self) -> Dict[str, Union[List[float], str]]:
        return bert_score(
            preds={
                "input_ids": np.asarray(dim_zero_cat(self.preds_input_ids)),
                "attention_mask": np.asarray(dim_zero_cat(self.preds_attention_mask)),
            },
            target={
                "input_ids": np.asarray(dim_zero_cat(self.target_input_ids)),
                "attention_mask": np.asarray(dim_zero_cat(self.target_attention_mask)),
            },
            model_name_or_path=self.model_name_or_path,
            num_layers=self.num_layers,
            model=self.model,
            user_forward_fn=self.user_forward_fn,
            verbose=self.verbose,
            idf=self.idf,
            device=self.device,
            max_length=self.max_length,
            batch_size=self.batch_size,
            num_threads=self.num_threads,
            return_hash=self.return_hash,
            lang=self.lang,
            rescale_with_baseline=self.rescale_with_baseline,
            baseline_path=self.baseline_path,
            baseline_url=self.baseline_url,
            all_layers=self.all_layers,
        )
