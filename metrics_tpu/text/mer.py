"""MatchErrorRate metric class.

Behavioral equivalent of reference ``torchmetrics/text/mer.py:24``.
"""
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.mer import _mer_compute, _mer_update
from metrics_tpu.metric import Metric

Array = jax.Array


class MatchErrorRate(Metric):
    """Match error rate; O(1) sum states, psum-synced over the mesh.

    Example:
        >>> from metrics_tpu import MatchErrorRate
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> metric = MatchErrorRate()
        >>> metric(preds, target)
        Array(0.44444445, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        errors, total = _mer_update(preds, target)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        return _mer_compute(self.errors, self.total)
