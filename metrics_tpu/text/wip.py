"""WordInfoPreserved metric class.

Behavioral equivalent of reference ``torchmetrics/text/wip.py:23``; state is
the positive hit count (see ``functional/text/wil.py`` redesign note).
"""
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.wil import _word_info_update
from metrics_tpu.functional.text.wip import _wip_compute
from metrics_tpu.metric import Metric

Array = jax.Array


class WordInfoPreserved(Metric):
    """Word information preserved; O(1) sum states, psum-synced over the mesh.

    Example:
        >>> from metrics_tpu import WordInfoPreserved
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> metric = WordInfoPreserved()
        >>> metric(preds, target)
        Array(0.34722224, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("hits", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("preds_total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        hits, target_total, preds_total = _word_info_update(preds, target)
        self.hits = self.hits + hits
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def compute(self) -> Array:
        return _wip_compute(self.hits, self.target_total, self.preds_total)
