"""BLEUScore metric class.

Behavioral equivalent of reference ``torchmetrics/text/bleu.py:29``.
"""
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update, _tokenize_fn
from metrics_tpu.metric import Metric

Array = jax.Array


class BLEUScore(Metric):
    """BLEU score; states are ``(n_gram,)`` count vectors + scalar lengths,
    all psum-synced over the mesh.

    Args:
        n_gram: maximum n-gram order.
        smooth: add-one smoothing for orders > 1.
        weights: optional per-order weights (default uniform).

    Example:
        >>> from metrics_tpu import BLEUScore
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> bleu = BLEUScore()
        >>> bleu(preds, target)
        Array(0.7598357, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = True

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        weights: Optional[Sequence[float]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        if weights is not None and len(weights) != n_gram:
            raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
        self.weights = weights if weights is not None else [1.0 / n_gram] * n_gram
        self.tokenizer = _tokenize_fn

        self.add_state("preds_len", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_len", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("numerator", default=jnp.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", default=jnp.zeros(n_gram), dist_reduce_fx="sum")

    def update(self, preds: Sequence[str], target: Sequence[Sequence[str]]) -> None:
        preds_ = [preds] if isinstance(preds, str) else preds
        target_ = [[t] if isinstance(t, str) else t for t in target]
        if len(preds_) != len(target_):
            raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
        numerator, denominator, preds_len, target_len = _bleu_score_update(
            preds_, target_, self.n_gram, self.tokenizer
        )
        self.numerator = self.numerator + numerator
        self.denominator = self.denominator + denominator
        self.preds_len = self.preds_len + preds_len
        self.target_len = self.target_len + target_len

    def compute(self) -> Array:
        return _bleu_score_compute(
            self.preds_len, self.target_len, self.numerator, self.denominator, self.n_gram, self.smooth, self.weights
        )
