"""ExtendedEditDistance metric class.

Behavioral equivalent of reference ``torchmetrics/text/eed.py:24``.
"""
from typing import Any, Sequence, Tuple, Union

import jax

from metrics_tpu.functional.text.eed import _eed_compute, _eed_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class ExtendedEditDistance(Metric):
    """Extended edit distance; per-sentence scores as a cat state.

    Args:
        language: 'en' or 'ja'.
        return_sentence_level_score: also return per-sentence EED.
        alpha: jump penalty.
        rho: coverage (repetition) penalty.
        deletion: deletion penalty.
        insertion: insertion/substitution penalty.

    Example:
        >>> from metrics_tpu import ExtendedEditDistance
        >>> preds = ["this is the prediction", "here is an other sample"]
        >>> target = ["this is the reference", "here is another one"]
        >>> eed = ExtendedEditDistance()
        >>> eed(preds=preds, target=target)
        Array(0.30776307, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(
        self,
        language: str = "en",
        return_sentence_level_score: bool = False,
        alpha: float = 2.0,
        rho: float = 0.3,
        deletion: float = 0.2,
        insertion: float = 1.0,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if language not in ("en", "ja"):
            raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")
        self.language = language
        self.return_sentence_level_score = return_sentence_level_score
        for name, param in (("alpha", alpha), ("rho", rho), ("deletion", deletion), ("insertion", insertion)):
            if not isinstance(param, float) or param < 0:
                raise ValueError(f"Parameter `{name}` is expected to be a non-negative float.")
        self.alpha = alpha
        self.rho = rho
        self.deletion = deletion
        self.insertion = insertion

        self.add_state("sentence_eed", default=[], dist_reduce_fx="cat")

    def update(
        self, preds: Union[str, Sequence[str]], target: Sequence[Union[str, Sequence[str]]]
    ) -> None:
        self.sentence_eed = _eed_update(
            preds, target, self.language, self.alpha, self.rho, self.deletion, self.insertion, self.sentence_eed
        )

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        average = _eed_compute(self.sentence_eed)
        if self.return_sentence_level_score:
            return average, dim_zero_cat(self.sentence_eed)
        return average
