"""WordInfoLost metric class.

Behavioral equivalent of reference ``torchmetrics/text/wil.py:23``; state is
the positive hit count (see ``functional/text/wil.py`` redesign note).
"""
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.wil import _wil_compute, _word_info_update
from metrics_tpu.metric import Metric

Array = jax.Array


class WordInfoLost(Metric):
    """Word information lost; O(1) sum states, psum-synced over the mesh.

    Example:
        >>> from metrics_tpu import WordInfoLost
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> metric = WordInfoLost()
        >>> metric(preds, target)
        Array(0.6527778, dtype=float32)
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("hits", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("target_total", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("preds_total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Union[str, List[str]], target: Union[str, List[str]]) -> None:
        hits, target_total, preds_total = _word_info_update(preds, target)
        self.hits = self.hits + hits
        self.target_total = self.target_total + target_total
        self.preds_total = self.preds_total + preds_total

    def compute(self) -> Array:
        return _wil_compute(self.hits, self.target_total, self.preds_total)
