"""RetrievalFallOut metric class.

Behavioral equivalent of reference ``torchmetrics/retrieval/fall_out.py:22``.
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.retrieval._segment import (
    GroupContext,
    TopKContext,
    fall_out_scores,
    fall_out_scores_topk,
)
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalFallOut(RetrievalMetric):
    """Mean fall-out@k over queries; lower is better.

    The empty-query policy is inverted relative to the other retrieval
    metrics: a query with no NEGATIVE target is undefined (reference
    ``retrieval/fall_out.py:89-140``), and ``empty_target_action`` defaults
    to ``"pos"``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalFallOut
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> fo = RetrievalFallOut(k=2)
        >>> fo(preds, target, indexes=indexes)
        Array(0.5, dtype=float32)
    """

    higher_is_better = False
    _required_kind = "negative"

    def __init__(
        self,
        empty_target_action: str = "pos",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (k is not None) and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        self.k = k

    def _valid_groups(self, ctx: GroupContext) -> Array:
        return (ctx.count.astype(ctx.npos.dtype) - ctx.npos) > 0

    def _metric_vectorized(self, ctx: GroupContext) -> Array:
        return fall_out_scores(ctx, k=self.k)

    def _topk_k(self) -> Optional[int]:
        return self.k

    def _metric_topk(self, tctx: TopKContext) -> Array:
        return fall_out_scores_topk(tctx)

    def _valid_groups_topk(self, tctx: TopKContext) -> Array:
        return (tctx.count.astype(tctx.npos.dtype) - tctx.npos) > 0
