"""RetrievalRecall metric class.

Behavioral equivalent of reference ``torchmetrics/retrieval/recall.py:22``.
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.retrieval._segment import (
    GroupContext,
    TopKContext,
    recall_scores,
    recall_scores_topk,
)
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalRecall(RetrievalMetric):
    """Mean recall@k over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalRecall
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> r2 = RetrievalRecall(k=2)
        >>> r2(preds, target, indexes=indexes)
        Array(0.75, dtype=float32)
    """

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (k is not None) and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        self.k = k

    def _metric_vectorized(self, ctx: GroupContext) -> Array:
        return recall_scores(ctx, k=self.k)

    def _topk_k(self) -> Optional[int]:
        return self.k

    def _metric_topk(self, tctx: TopKContext) -> Array:
        return recall_scores_topk(tctx)
