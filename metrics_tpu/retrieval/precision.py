"""RetrievalPrecision metric class.

Behavioral equivalent of reference ``torchmetrics/retrieval/precision.py:22``.
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.retrieval._segment import (
    GroupContext,
    TopKContext,
    precision_scores,
    precision_scores_topk,
)
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalPrecision(RetrievalMetric):
    """Mean precision@k over queries.

    Args:
        k: consider only the top ``k`` documents per query (default: all).
        adaptive_k: adjust ``k`` to ``min(k, n_documents)`` per query.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalPrecision
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> p2 = RetrievalPrecision(k=2)
        >>> p2(preds, target, indexes=indexes)
        Array(0.5, dtype=float32)
    """

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        adaptive_k: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (k is not None) and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        if not isinstance(adaptive_k, bool):
            raise ValueError("`adaptive_k` has to be a boolean")
        self.k = k
        self.adaptive_k = adaptive_k

    def _metric_vectorized(self, ctx: GroupContext) -> Array:
        return precision_scores(ctx, k=self.k, adaptive_k=self.adaptive_k)

    def _topk_k(self) -> Optional[int]:
        return self.k

    def _metric_topk(self, tctx: TopKContext) -> Array:
        return precision_scores_topk(tctx, k=self.k, adaptive_k=self.adaptive_k)
