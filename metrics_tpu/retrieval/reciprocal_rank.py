"""RetrievalMRR metric class.

Behavioral equivalent of reference ``torchmetrics/retrieval/reciprocal_rank.py:22``.
"""
import jax

from metrics_tpu.functional.retrieval._segment import GroupContext, reciprocal_rank_scores
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMRR(RetrievalMetric):
    """Mean reciprocal rank over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalMRR
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> mrr = RetrievalMRR()
        >>> mrr(preds, target, indexes=indexes)
        Array(0.75, dtype=float32)
    """

    def _metric_vectorized(self, ctx: GroupContext) -> Array:
        return reciprocal_rank_scores(ctx)
