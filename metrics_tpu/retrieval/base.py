"""RetrievalMetric base class.

Behavioral equivalent of reference ``torchmetrics/retrieval/base.py:27``, with
a TPU-first compute: instead of the reference's per-query Python loop over
``get_group_indexes`` (``utilities/data.py:196-220`` — a dict of ``.item()``
calls), ALL queries are scored in one fused lexsort + segment-op XLA program
(see ``metrics_tpu/functional/retrieval/_segment.py``). Queries with no
positive (for fall-out: no negative) target follow ``empty_target_action``.
"""
from abc import ABC, abstractmethod
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.retrieval._segment import (
    GroupContext,
    TopKContext,
    dense_group_shape,
    make_group_context,
    make_topk_context,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.buffers import _cat_state_default
from metrics_tpu.utilities.checks import _check_retrieval_inputs
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class RetrievalMetric(Metric, ABC):
    """Base for IR metrics over ``(preds, target, indexes)`` triplets.

    ``indexes`` assigns each prediction to a query; the metric value is the
    mean of the per-query score. States are cat-lists synced with
    ``all_gather`` (``dist_reduce_fx=None`` → per-rank concat), mirroring the
    reference's ``retrieval/base.py:97-99``.

    Args:
        empty_target_action: ``"neg"`` (score 0), ``"pos"`` (score 1),
            ``"skip"`` (drop query), or ``"error"`` for queries with no
            positive target.
        ignore_index: drop samples whose target equals this value.
        sample_capacity: switch the unbounded cat-list states to
            fixed-capacity HBM buffers (static shapes: jit/scan/shard_map
            carries and in-graph mesh sync work; see
            ``utilities/buffers.CapacityBuffer``). Incompatible with
            ``ignore_index`` (row-dropping is a dynamic shape).
    """

    higher_is_better = True
    is_differentiable = False
    allow_non_binary_target = False

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        sample_capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        empty_target_action_options = ("error", "skip", "neg", "pos")
        if empty_target_action not in empty_target_action_options:
            raise ValueError(f"Argument `empty_target_action` received a wrong value `{empty_target_action}`.")
        self.empty_target_action = empty_target_action
        if ignore_index is not None and not isinstance(ignore_index, int):
            raise ValueError("Argument `ignore_index` must be an integer or None.")
        if sample_capacity is not None and ignore_index is not None:
            raise ValueError(
                "`sample_capacity` cannot be combined with `ignore_index`: dropping ignored rows is a"
                " dynamic shape, which fixed-capacity buffer states cannot hold."
            )
        self.ignore_index = ignore_index

        self.add_state("indexes", default=_cat_state_default(sample_capacity), dist_reduce_fx=None)
        self.add_state("preds", default=_cat_state_default(sample_capacity), dist_reduce_fx=None)
        self.add_state("target", default=_cat_state_default(sample_capacity), dist_reduce_fx=None)

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        if indexes is None:
            raise ValueError("Argument `indexes` cannot be None")
        indexes, preds, target = _check_retrieval_inputs(
            indexes,
            preds,
            target,
            allow_non_binary_target=self.allow_non_binary_target,
            ignore_index=self.ignore_index,
        )
        self.indexes.append(indexes)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        indexes = dim_zero_cat(self.indexes)
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)

        # segment-local top-k fast path: an @k metric over a dense regular
        # layout selects its k documents with one per-query lax.top_k
        # instead of the full multi-operand sort (bitwise-equal; pinned by
        # tests/retrieval/test_k_grid.py). Ragged layouts, k >= docs and
        # full-rank metrics fall through to the sorted pipeline.
        k = self._topk_k()
        if k is not None:
            shape = dense_group_shape(indexes)
            if shape is not None and k < shape[1]:
                return self._compute_topk(preds, target, shape, k)

        ctx = make_group_context(preds, target, indexes)
        scores = self._metric_vectorized(ctx)
        valid = self._valid_groups(ctx)
        nonempty = ctx.nonempty

        if self.empty_target_action == "error":
            if bool(jnp.any(nonempty & ~valid)):
                raise ValueError(f"`compute` method was provided with a query with no {self._required_kind} target.")

        if self.empty_target_action == "skip":
            keep = nonempty & valid
        else:
            fill = 1.0 if self.empty_target_action == "pos" else 0.0
            scores = jnp.where(valid, scores, fill)
            keep = nonempty

        n_keep = keep.sum().astype(jnp.float32)
        total = jnp.where(keep, scores, 0.0).sum()
        return jnp.where(n_keep > 0, total / jnp.maximum(n_keep, 1.0), 0.0).astype(preds.dtype)

    # which groups produce a defined score (fall-out overrides to "negative")
    _required_kind = "positive"

    def _valid_groups(self, ctx: GroupContext) -> Array:
        return ctx.npos > 0

    @abstractmethod
    def _metric_vectorized(self, ctx: GroupContext) -> Array:
        """Dense (num_segments,) per-group scores."""

    # ------------------------------------------------------------------
    # Dense top-k fast path (see functional/retrieval/_segment.py)
    # ------------------------------------------------------------------

    def _topk_k(self) -> Optional[int]:
        """The metric's top-k cutoff, or None when it reads every rank (the
        @k subclasses return their ``k``)."""
        return None

    def _metric_topk(self, tctx: TopKContext) -> Array:
        """Per-query scores on the dense top-k view; subclasses returning a
        non-None :meth:`_topk_k` must implement this."""
        raise NotImplementedError

    def _valid_groups_topk(self, tctx: TopKContext) -> Array:
        return tctx.npos > 0

    def _compute_topk(self, preds: Array, target: Array, shape, k: int) -> Array:
        tctx = make_topk_context(preds, target, shape, k)
        scores = self._metric_topk(tctx)
        valid = self._valid_groups_topk(tctx)

        if self.empty_target_action == "error":
            if bool(jnp.any(~valid)):
                raise ValueError(f"`compute` method was provided with a query with no {self._required_kind} target.")

        if self.empty_target_action == "skip":
            keep = valid
        else:
            fill = 1.0 if self.empty_target_action == "pos" else 0.0
            scores = jnp.where(valid, scores, fill)
            keep = jnp.ones_like(valid)

        n_keep = keep.sum().astype(jnp.float32)
        total = jnp.where(keep, scores, 0.0).sum()
        return jnp.where(n_keep > 0, total / jnp.maximum(n_keep, 1.0), 0.0).astype(preds.dtype)
