"""RetrievalRPrecision metric class.

Behavioral equivalent of reference ``torchmetrics/retrieval/r_precision.py:22``.
"""
import jax

from metrics_tpu.functional.retrieval._segment import GroupContext, r_precision_scores
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalRPrecision(RetrievalMetric):
    """Mean R-precision over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalRPrecision
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> p2 = RetrievalRPrecision()
        >>> p2(preds, target, indexes=indexes)
        Array(0.75, dtype=float32)
    """

    def _metric_vectorized(self, ctx: GroupContext) -> Array:
        return r_precision_scores(ctx)
