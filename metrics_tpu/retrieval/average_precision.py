"""RetrievalMAP metric class.

Behavioral equivalent of reference ``torchmetrics/retrieval/average_precision.py:22``.
"""
import jax

from metrics_tpu.functional.retrieval._segment import GroupContext, average_precision_scores
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMAP(RetrievalMetric):
    """Mean average precision over queries.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalMAP
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> rmap = RetrievalMAP()
        >>> rmap(preds, target, indexes=indexes)
        Array(0.7916667, dtype=float32)
    """

    def _metric_vectorized(self, ctx: GroupContext) -> Array:
        return average_precision_scores(ctx)
