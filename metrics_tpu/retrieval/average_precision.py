"""RetrievalMAP metric class.

Behavioral equivalent of reference ``torchmetrics/retrieval/average_precision.py:22``,
plus the MAP@k cutoff of the reference's later ``top_k`` argument (precision
summed over the first ``k`` ranks, normalized by ``min(npos, k)``).
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.retrieval._segment import (
    GroupContext,
    TopKContext,
    average_precision_scores,
    average_precision_scores_topk,
)
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalMAP(RetrievalMetric):
    """Mean average precision over queries, optionally @k.

    Args:
        k: consider only the top ``k`` documents per query (default: all).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalMAP
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> rmap = RetrievalMAP()
        >>> rmap(preds, target, indexes=indexes)
        Array(0.7916667, dtype=float32)
    """

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        *,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        # k is keyword-only: this class's third POSITIONAL argument has
        # historically been the base's sample_capacity, and silently
        # reinterpreting it as k would change existing callers' semantics
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (k is not None) and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        self.k = k

    def _metric_vectorized(self, ctx: GroupContext) -> Array:
        return average_precision_scores(ctx, k=self.k)

    def _topk_k(self) -> Optional[int]:
        return self.k

    def _metric_topk(self, tctx: TopKContext) -> Array:
        return average_precision_scores_topk(tctx, k=self.k)
