"""RetrievalNormalizedDCG metric class.

Behavioral equivalent of reference ``torchmetrics/retrieval/ndcg.py:22``.
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.retrieval._segment import (
    GroupContext,
    TopKContext,
    ndcg_scores,
    ndcg_scores_topk,
)
from metrics_tpu.retrieval.base import RetrievalMetric

Array = jax.Array


class RetrievalNormalizedDCG(RetrievalMetric):
    """Mean normalized DCG over queries; non-binary targets allowed.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import RetrievalNormalizedDCG
        >>> indexes = jnp.asarray([0, 0, 0, 1, 1, 1, 1])
        >>> preds = jnp.asarray([0.2, 0.3, 0.5, 0.1, 0.3, 0.5, 0.2])
        >>> target = jnp.asarray([False, False, True, False, True, False, True])
        >>> ndcg = RetrievalNormalizedDCG()
        >>> ndcg(preds, target, indexes=indexes)
        Array(0.84670985, dtype=float32)
    """

    allow_non_binary_target = True

    def __init__(
        self,
        empty_target_action: str = "neg",
        ignore_index: Optional[int] = None,
        k: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(empty_target_action=empty_target_action, ignore_index=ignore_index, **kwargs)
        if (k is not None) and not (isinstance(k, int) and k > 0):
            raise ValueError("`k` has to be a positive integer or None")
        self.k = k

    def _valid_groups(self, ctx: GroupContext) -> Array:
        # float targets allowed: "no positive" means the target sum is zero
        # (reference ndcg.py routes through base.compute's mini_target.sum()).
        total = ctx.group_sum(ctx.target.astype(ctx.npos.dtype))
        return total != 0

    def _metric_vectorized(self, ctx: GroupContext) -> Array:
        return ndcg_scores(ctx, k=self.k)

    def _topk_k(self) -> Optional[int]:
        return self.k

    def _metric_topk(self, tctx: TopKContext) -> Array:
        return ndcg_scores_topk(tctx)

    def _valid_groups_topk(self, tctx: TopKContext) -> Array:
        # float targets allowed: "no positive" means the target sum is zero
        total = tctx.target2d.astype(tctx.npos.dtype).sum(axis=1)
        return total != 0
