"""Structural Similarity Index Measure (SSIM) and Multi-Scale SSIM.

Behavioral equivalent of reference ``torchmetrics/functional/image/ssim.py``
(``_ssim_update`` :26, ``_ssim_compute`` :49, ``structural_similarity_index_
measure`` :197, ``_multiscale_ssim_compute`` :303, ``multiscale_structural_
similarity_index_measure`` :415). The five windowed moments are computed in
ONE depthwise conv over a stacked ``(5B, C, ...)`` tensor so XLA sees a
single big MXU-friendly convolution; downsampling between MS-SSIM scales is
``lax.reduce_window``.
"""
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.helper import (
    _avg_pool,
    _gaussian,
    _reflection_pad,
    _separable_depthwise_conv,
)
from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.distributed import reduce

Array = jax.Array


def _ssim_check_inputs(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Shape/type gate (reference ``_ssim_update`` :26)."""
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_same_shape(preds, target)
    if preds.ndim not in (4, 5):
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW or BxCxDxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


# reference name parity
_ssim_update = _ssim_check_inputs


def _normalize_kernel_args(
    is_3d: bool, kernel_size: Union[int, Sequence[int]], sigma: Union[float, Sequence[float]]
) -> Tuple[Sequence[int], Sequence[float]]:
    n = 3 if is_3d else 2
    if not isinstance(kernel_size, Sequence):
        kernel_size = n * [kernel_size]
    if not isinstance(sigma, Sequence):
        sigma = n * [sigma]
    if len(kernel_size) not in (2, 3) or len(kernel_size) != n:
        raise ValueError(
            f"`kernel_size` has dimension {len(kernel_size)}, but expected to be two less than target dimensionality"
        )
    if len(sigma) != n:
        raise ValueError(f"`sigma` has dimension {len(sigma)}, but expected to be two less than target dimensionality")
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")
    return list(kernel_size), list(sigma)


def _ssim_compute(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """Windowed-moment SSIM (reference ``_ssim_compute`` :49)."""
    is_3d = preds.ndim == 5
    kernel_size, sigma = _normalize_kernel_args(is_3d, kernel_size, sigma)

    if data_range is None:
        data_range = jnp.maximum(preds.max() - preds.min(), target.max() - target.min())
    data_range = jnp.asarray(data_range, dtype=preds.dtype)

    c1 = (k1 * data_range) ** 2
    c2 = (k2 * data_range) ** 2

    dtype = preds.dtype if jnp.issubdtype(preds.dtype, jnp.floating) else jnp.float32
    preds = preds.astype(dtype)
    target = target.astype(dtype)
    # the reference sizes the window from sigma when gaussian (ssim.py:136)
    gauss_kernel_size = [int(3.5 * s + 0.5) * 2 + 1 for s in sigma]
    conv_kernel_size = gauss_kernel_size if gaussian_kernel else kernel_size

    pads = [(k - 1) // 2 for k in conv_kernel_size]
    preds = _reflection_pad(preds, pads)
    target = _reflection_pad(target, pads)

    # separable window: gaussian/uniform factor exactly into 1D kernels,
    # one depthwise pass per spatial dim (sum-of-taps cost, not product)
    if gaussian_kernel:
        kernels_1d = [_gaussian(k, s, dtype) for k, s in zip(gauss_kernel_size, sigma)]
    else:
        kernels_1d = [jnp.ones((1, k), dtype) / k for k in kernel_size]

    # one conv over the 5 stacked moment inputs: mu_p, mu_t, E[p^2], E[t^2], E[pt]
    input_list = jnp.concatenate([preds, target, preds * preds, target * target, preds * target])
    outputs = _separable_depthwise_conv(input_list, kernels_1d)
    b = preds.shape[0]
    mu_pred, mu_target, e_pred_sq, e_target_sq, e_pred_target = (outputs[i * b : (i + 1) * b] for i in range(5))

    mu_pred_sq = mu_pred**2
    mu_target_sq = mu_target**2
    mu_pred_target = mu_pred * mu_target

    sigma_pred_sq = e_pred_sq - mu_pred_sq
    sigma_target_sq = e_target_sq - mu_target_sq
    sigma_pred_target = e_pred_target - mu_pred_target

    upper = 2 * sigma_pred_target + c2
    lower = sigma_pred_sq + sigma_target_sq + c2

    ssim_full = ((2 * mu_pred_target + c1) * upper) / ((mu_pred_sq + mu_target_sq + c1) * lower)
    # the VALID conv already removed (k-1) border rows; with reflect padding of
    # (k-1)//2 the output grid aligns with the unpadded image, and the
    # reference then crops another pad from each side (ssim.py:180-183)
    crop = tuple(slice(p, s - p) for p, s in zip(pads, ssim_full.shape[2:]))
    ssim_idx = ssim_full[(...,) + crop]

    per_image = ssim_idx.reshape(ssim_idx.shape[0], -1).mean(-1)

    if return_contrast_sensitivity:
        contrast = (upper / lower)[(...,) + crop]
        return reduce(per_image, reduction), reduce(contrast.reshape(contrast.shape[0], -1).mean(-1), reduction)
    if return_full_image:
        return reduce(per_image, reduction), reduce(ssim_full, reduction)
    return reduce(per_image, reduction)


def structural_similarity_index_measure(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    return_full_image: bool = False,
    return_contrast_sensitivity: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """Compute SSIM (reference ``ssim.py:197``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (8, 3, 16, 16))
        >>> target = preds * 0.75
        >>> float(structural_similarity_index_measure(preds, target)) > 0.9
        True
    """
    preds, target = _ssim_check_inputs(preds, target)
    return _ssim_compute(
        preds,
        target,
        gaussian_kernel,
        sigma,
        kernel_size,
        reduction,
        data_range,
        k1,
        k2,
        return_full_image,
        return_contrast_sensitivity,
    )


def _get_normalized_sim_and_cs(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    normalize: Optional[str] = None,
) -> Tuple[Array, Array]:
    sim, cs = _ssim_compute(
        preds,
        target,
        gaussian_kernel,
        sigma,
        kernel_size,
        reduction,
        data_range,
        k1,
        k2,
        return_contrast_sensitivity=True,
    )
    if normalize == "relu":
        sim = jax.nn.relu(sim)
        cs = jax.nn.relu(cs)
    return sim, cs


def _multiscale_ssim_validate_size(
    preds: Array, kernel_size: Union[int, Sequence[int]], sigma: Union[float, Sequence[float]], n_scales: int
) -> None:
    """Image-size preconditions for an n_scales pyramid (reference
    ``ssim.py:364-382``); shared by the batch and streaming paths."""
    kernel_size_l, _ = _normalize_kernel_args(preds.ndim == 5, kernel_size, sigma)
    if preds.shape[-1] < 2**n_scales or preds.shape[-2] < 2**n_scales:
        raise ValueError(
            f"For a given number of `betas` parameters {n_scales}, the image height and width dimensions must be"
            f" larger than or equal to {2 ** n_scales}."
        )
    _betas_div = max(1, (n_scales - 1)) ** 2
    if preds.shape[-2] // _betas_div <= kernel_size_l[0] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {n_scales} and kernel size {kernel_size_l[0]},"
            f" the image height must be larger than {(kernel_size_l[0] - 1) * _betas_div}."
        )
    if preds.shape[-1] // _betas_div <= kernel_size_l[1] - 1:
        raise ValueError(
            f"For a given number of `betas` parameters {n_scales} and kernel size {kernel_size_l[1]},"
            f" the image width must be larger than {(kernel_size_l[1] - 1) * _betas_div}."
        )


def _multiscale_ssim_per_image(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    n_scales: int = 5,
) -> Tuple[Array, Array]:
    """Per-image, per-scale raw (sim, cs) values, each ``(n_scales, B)``.

    Streaming building block: scale-wise sums of these across batches
    reproduce the reference's reduce-then-combine MS-SSIM exactly
    (``ssim.py:386-414`` reduces per scale BEFORE the beta-weighted product).
    """
    _multiscale_ssim_validate_size(preds, kernel_size, sigma, n_scales)
    sims = []
    css = []
    for _ in range(n_scales):
        sim, cs = _ssim_compute(
            preds,
            target,
            gaussian_kernel,
            sigma,
            kernel_size,
            "none",
            data_range,
            k1,
            k2,
            return_contrast_sensitivity=True,
        )
        sims.append(sim)
        css.append(cs)
        preds = _avg_pool(preds, 2)
        target = _avg_pool(target, 2)
    return jnp.stack(sims), jnp.stack(css)


def _multiscale_ssim_from_scale_stats(
    sim_stat: Array, cs_stat: Array, betas: Tuple[float, ...], normalize: Optional[str]
) -> Array:
    """Combine per-scale reduced (sim, cs) stats into the MS-SSIM scalar."""
    if normalize == "relu":
        sim_stat = jax.nn.relu(sim_stat)
        cs_stat = jax.nn.relu(cs_stat)
    if normalize == "simple":
        sim_stat = (sim_stat + 1) / 2
        cs_stat = (cs_stat + 1) / 2
    betas_arr = jnp.asarray(betas, dtype=sim_stat.dtype)
    sim_stat = sim_stat**betas_arr
    cs_stat = cs_stat**betas_arr
    return jnp.prod(cs_stat[:-1]) * sim_stat[-1]


def _multiscale_ssim_compute(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = None,
) -> Array:
    """Pyramid SSIM (reference ``_multiscale_ssim_compute`` :303)."""
    _multiscale_ssim_validate_size(preds, kernel_size, sigma, len(betas))

    sim_list = []
    cs_list = []
    for _ in range(len(betas)):
        sim, cs = _get_normalized_sim_and_cs(
            preds, target, gaussian_kernel, sigma, kernel_size, reduction, data_range, k1, k2, normalize=normalize
        )
        sim_list.append(sim)
        cs_list.append(cs)
        preds = _avg_pool(preds, 2)
        target = _avg_pool(target, 2)

    sim_stack = jnp.stack(sim_list)
    cs_stack = jnp.stack(cs_list)

    if normalize == "simple":
        sim_stack = (sim_stack + 1) / 2
        cs_stack = (cs_stack + 1) / 2

    betas_arr = jnp.asarray(betas, dtype=sim_stack.dtype)
    if reduction is None or reduction == "none":
        sim_stack = sim_stack ** betas_arr[:, None]
        cs_stack = cs_stack ** betas_arr[:, None]
        cs_and_sim = jnp.concatenate([cs_stack[:-1], sim_stack[-1:]], axis=0)
        return jnp.prod(cs_and_sim, axis=0)
    sim_stack = sim_stack**betas_arr
    cs_stack = cs_stack**betas_arr
    return jnp.prod(cs_stack[:-1]) * sim_stack[-1]


def multiscale_structural_similarity_index_measure(
    preds: Array,
    target: Array,
    gaussian_kernel: bool = True,
    sigma: Union[float, Sequence[float]] = 1.5,
    kernel_size: Union[int, Sequence[int]] = 11,
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
    k1: float = 0.01,
    k2: float = 0.03,
    betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
    normalize: Optional[str] = None,
) -> Array:
    """Compute Multi-Scale SSIM (reference ``ssim.py:415``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (2, 3, 180, 180))
        >>> target = preds * 0.75
        >>> float(multiscale_structural_similarity_index_measure(preds, target)) > 0.7
        True
    """
    if not isinstance(betas, tuple) or not all(isinstance(b, float) for b in betas):
        raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
    if normalize is not None and normalize not in ("relu", "simple"):
        raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")
    preds, target = _ssim_check_inputs(preds, target)
    return _multiscale_ssim_compute(
        preds, target, gaussian_kernel, sigma, kernel_size, reduction, data_range, k1, k2, betas, normalize
    )
