"""Universal Image Quality Index.

Behavioral equivalent of reference ``torchmetrics/functional/image/uqi.py``
(``_uqi_update`` :26, ``_uqi_compute`` :49, ``universal_image_quality_index``
:126). One stacked depthwise conv produces all five windowed moments.

Intentional fix vs the reference for ANISOTROPIC kernels: the reference pads
with ``F.pad(x, (pad_h, pad_h, pad_w, pad_w))`` (uqi.py:102-103), which puts
the height-derived pad on the WIDTH axis (torch pads last-dim-first) while
cropping in (H, W) order — inconsistent for ``kh != kw``. Here padding and
cropping both use natural (H, W) order; identical for the (default) square
kernel.
"""
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.helper import _gaussian, _reflection_pad, _separable_depthwise_conv
from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.distributed import reduce

Array = jax.Array


def _uqi_check_inputs(preds: Array, target: Array) -> Tuple[Array, Array]:
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


_uqi_update = _uqi_check_inputs


def _uqi_compute(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    if len(kernel_size) != 2 or len(sigma) != 2:
        raise ValueError(
            "Expected `kernel_size` and `sigma` to have the length of two."
            f" Got kernel_size: {len(kernel_size)} and sigma: {len(sigma)}."
        )
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    if any(y <= 0 for y in sigma):
        raise ValueError(f"Expected `sigma` to have positive number. Got {sigma}.")

    dtype = preds.dtype if jnp.issubdtype(preds.dtype, jnp.floating) else jnp.float32
    preds = preds.astype(dtype)
    target = target.astype(dtype)
    kernels_1d = [_gaussian(k, s, dtype) for k, s in zip(kernel_size, sigma)]
    pads = [(k - 1) // 2 for k in kernel_size]

    preds_p = _reflection_pad(preds, pads)
    target_p = _reflection_pad(target, pads)

    input_list = jnp.concatenate([preds_p, target_p, preds_p * preds_p, target_p * target_p, preds_p * target_p])
    outputs = _separable_depthwise_conv(input_list, kernels_1d)
    b = preds.shape[0]
    mu_pred, mu_target, e_pred_sq, e_target_sq, e_pred_target = (outputs[i * b : (i + 1) * b] for i in range(5))

    mu_pred_sq = mu_pred**2
    mu_target_sq = mu_target**2
    mu_pred_target = mu_pred * mu_target

    sigma_pred_sq = e_pred_sq - mu_pred_sq
    sigma_target_sq = e_target_sq - mu_target_sq
    sigma_pred_target = e_pred_target - mu_pred_target

    upper = 2 * sigma_pred_target
    lower = sigma_pred_sq + sigma_target_sq

    uqi_idx = ((2 * mu_pred_target) * upper) / ((mu_pred_sq + mu_target_sq) * lower)
    crop = tuple(slice(p, s - p) for p, s in zip(pads, uqi_idx.shape[2:]))
    uqi_idx = uqi_idx[(...,) + crop]
    return reduce(uqi_idx, reduction)


def universal_image_quality_index(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
    data_range: Optional[float] = None,
) -> Array:
    """Compute UQI (reference ``uqi.py:126``; ``data_range`` kept for
    signature parity — UQI has no stabilizing constants so it cancels out).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (8, 3, 16, 16))
        >>> target = preds * 0.75
        >>> float(universal_image_quality_index(preds, target)) > 0.9
        True
    """
    preds, target = _uqi_check_inputs(preds, target)
    return _uqi_compute(preds, target, kernel_size, sigma, reduction)
