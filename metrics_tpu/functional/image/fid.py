"""Frechet distance math: on-device PSD matrix sqrt via eigendecomposition.

TPU-native replacement for the reference's CPU round-trip
(``torchmetrics/image/fid.py:60-94`` — ``MatrixSquareRoot`` dispatches to
``scipy.linalg.sqrtm`` on host numpy). Here the whole FID formula runs in
XLA: ``tr(sqrtm(S1 @ S2))`` for symmetric PSD ``S1, S2`` equals
``sum(sqrt(eigvalsh(A @ S2 @ A)))`` with ``A = sqrtm(S1)`` — three matmuls
and two ``eigh`` calls, no host transfer. Runs in f64 when
``jax_enable_x64`` is set, f32 otherwise (documented tolerance).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _sqrtm_psd(mat: Array) -> Array:
    """Matrix square root of a symmetric PSD matrix via ``eigh``."""
    vals, vecs = jnp.linalg.eigh(mat)
    vals = jnp.clip(vals, 0, None)
    return jnp.matmul(vecs * jnp.sqrt(vals)[None, :], vecs.T, precision="float32")


def _trace_sqrtm_product_eigh(sigma1: Array, sigma2: Array) -> Array:
    """``tr(sqrtm(sigma1 @ sigma2))`` via two eigendecompositions (exact)."""
    a = _sqrtm_psd(sigma1)
    inner = jnp.matmul(jnp.matmul(a, sigma2, precision="float32"), a, precision="float32")
    inner = (inner + inner.T) / 2  # re-symmetrize against fp error
    vals = jnp.clip(jnp.linalg.eigvalsh(inner), 0, None)
    return jnp.sum(jnp.sqrt(vals))


def _trace_sqrtm_product_ns(sigma1: Array, sigma2: Array, iters: int = 14) -> Array:
    """``tr(sqrtm(sigma1 @ sigma2))`` via Newton-Schulz iteration (unchecked)."""
    return _trace_sqrtm_product_ns_checked(sigma1, sigma2, iters)[0]


def _trace_sqrtm_product_ns_checked(sigma1: Array, sigma2: Array, iters: int = 14) -> Tuple[Array, Array]:
    """Accelerated Newton-Schulz trace plus a convergence verdict.

    ``sigma1 @ sigma2`` is similar to the PSD matrix ``A sigma2 A`` (with
    ``A = sqrtm(sigma1)``), so its square root exists and the coupled
    Newton-Schulz iteration converges after Frobenius normalization. Three
    refinements over the plain iteration:

    * **trace scaling**: each step rescales by ``mu = sqrt(d / tr(Z Y))``,
      pushing the mean eigenvalue of ``mu^2 Z Y`` toward 1 (Y, Z, T are
      polynomials in the normalized product, so they commute and the
      ``Y = M Z`` invariant survives the rescale);
    * **basin clamp** ``mu^2 <= 2``: one unscaled NS step maps the spectrum
      into (0, 1], so a <=2x rescale keeps every eigenvalue inside the
      iteration's (0, 3) basin — unclamped trace scaling DIVERGES on
      decaying (power-law / multi-decade) spectra whose lambda_max far
      exceeds lambda_mean;
    * **convergence freeze**: once ``||Z Y - I||_F`` is small the carry
      stops updating, so extra iterations cannot re-amplify fp noise in
      near-null directions (the instability that otherwise corrupts
      converged iterates).

    Flat covariance spectra at D=2048 converge in ~8 iterations to ~5e-7
    relative error and 3-4-decade spreads by ~14 (the unscaled iteration
    needed 30 for ~2e-6 on flat spectra) — all matmuls, MXU-resident,
    ~1.7x faster end to end.

    The iteration still produces garbage when fp noise pushes eigenvalues
    of the product negative — rank-deficient covariances (fewer samples
    than feature dims) or spreads beyond f32. Returns ``(trace, ok)``
    where ``ok`` checks both finiteness and the sqrt residual
    ``||Y@Y*norm - M||_F / ||M||_F``.
    """
    d = sigma1.shape[0]
    m = jnp.matmul(sigma1, sigma2, precision="float32")
    norm = jnp.linalg.norm(m)
    safe_norm = jnp.maximum(norm, 1e-30)
    y = m / safe_norm
    eye = jnp.eye(d, dtype=m.dtype)
    eye3 = 3.0 * eye
    z = eye

    def body(_, carry):
        y, z = carry
        zy = jnp.matmul(z, y, precision="float32")
        delta = jnp.linalg.norm(zy - eye)
        mu2 = jnp.minimum(d / jnp.maximum(jnp.abs(jnp.trace(zy)), 1e-30), 2.0)
        mu = jnp.sqrt(mu2)
        t = 0.5 * (eye3 - mu2 * zy)
        y_next = mu * jnp.matmul(y, t, precision="float32")
        z_next = mu * jnp.matmul(t, z, precision="float32")
        frozen = delta < 1e-5 * d
        return jnp.where(frozen, y, y_next), jnp.where(frozen, z, z_next)

    y, _ = jax.lax.fori_loop(0, iters, body, (y, z))
    trace = jnp.where(norm > 0, jnp.trace(y) * jnp.sqrt(norm), 0.0)
    residual = jnp.linalg.norm(jnp.matmul(y, y, precision="float32") * safe_norm - m) / safe_norm
    ok = jnp.isfinite(trace) & (residual < 1e-3) | (norm == 0)
    return trace, ok


def _trace_sqrtm_product(sigma1: Array, sigma2: Array) -> Array:
    """``tr(sqrtm(sigma1 @ sigma2))`` for symmetric PSD inputs.

    Dispatch: Newton-Schulz (pure matmuls, MXU-resident) on TPU with a
    runtime ``lax.cond`` fallback to the exact ``eigh`` path when the
    iteration failed to converge (ill-conditioned / rank-deficient
    covariances — the analogue of the reference's eps-offset retry at
    ``image/fid.py:110-118``); exact ``eigh`` everywhere else (LAPACK eigh on
    CPU is fast and keeps oracle tests bit-faithful).
    """
    if jax.default_backend() == "tpu":
        trace, ok = _trace_sqrtm_product_ns_checked(sigma1, sigma2)
        return jax.lax.cond(
            ok,
            lambda s1, s2: trace,
            _trace_sqrtm_product_eigh,
            sigma1,
            sigma2,
        )
    return _trace_sqrtm_product_eigh(sigma1, sigma2)


def _mean_cov_from_moments(feat_sum: Array, outer_sum: Array, n: Array) -> Tuple[Array, Array]:
    """Exact mean + unbiased covariance from streaming moments.

    The reference accumulates full feature cat-lists and materializes them at
    compute (``image/fid.py:270-287``); sum / outer-product-sum moments give
    the identical mean/cov with O(D^2) state — mesh-reducible with plain
    psum.
    """
    mean = feat_sum / n
    cov = (outer_sum - n * jnp.outer(mean, mean)) / jnp.maximum(n - 1, 1)
    return mean, cov


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array) -> Array:
    """FID formula (reference ``image/fid.py:97-124``)."""
    diff = mu1 - mu2
    tr_covmean = _trace_sqrtm_product(sigma1, sigma2)
    return jnp.dot(diff, diff, precision="float32") + jnp.trace(sigma1) + jnp.trace(sigma2) - 2 * tr_covmean
