"""Finite-difference image gradients.

Behavioral equivalent of reference
``torchmetrics/functional/image/gradients.py`` (``image_gradients`` :48).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _image_gradients_validate(img: Array) -> None:
    if not isinstance(img, (jnp.ndarray, jax.Array)):
        raise TypeError(f"The `img` expects an array type but got {type(img)}")
    if img.ndim != 4:
        raise RuntimeError(f"The `img` expects a 4D tensor but got {img.ndim}D tensor")


def _compute_image_gradients(img: Array) -> Tuple[Array, Array]:
    dy = img[..., 1:, :] - img[..., :-1, :]
    dx = img[..., :, 1:] - img[..., :, :-1]
    dy = jnp.pad(dy, ((0, 0), (0, 0), (0, 1), (0, 0)))
    dx = jnp.pad(dx, ((0, 0), (0, 0), (0, 0), (0, 1)))
    return dy, dx


def image_gradients(img: Array) -> Tuple[Array, Array]:
    """Compute ``(dy, dx)`` one-step finite differences (reference
    ``gradients.py:48``; last row/column zero-padded).

    Example:
        >>> import jax.numpy as jnp
        >>> image = jnp.arange(0, 25, dtype=jnp.float32).reshape(1, 1, 5, 5)
        >>> dy, dx = image_gradients(image)
        >>> dy[0, 0, :2, :2]
        Array([[5., 5.],
               [5., 5.]], dtype=float32)
    """
    _image_gradients_validate(img)
    return _compute_image_gradients(img)
