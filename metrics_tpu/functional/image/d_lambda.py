"""Spectral Distortion Index (D-lambda).

Behavioral equivalent of reference
``torchmetrics/functional/image/d_lambda.py`` (``_spectral_distortion_index_
update`` :27, ``_spectral_distortion_index_compute`` :48, ``spectral_
distortion_index`` :92). TPU-first: instead of a Python double loop of
per-channel-pair UQI calls (reference :78-81), all L*L channel pairs are
evaluated in ONE batched UQI pass by expanding the pair grid into the batch
axis — a single fused conv on the MXU.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.uqi import _uqi_compute
from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.distributed import reduce

Array = jax.Array


def _spectral_distortion_index_check_inputs(preds: Array, target: Array) -> Tuple[Array, Array]:
    if preds.dtype != target.dtype:
        raise TypeError(
            f"Expected `ms` and `fused` to have the same data type. Got ms: {preds.dtype} and fused: {target.dtype}."
        )
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(
            f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


_spectral_distortion_index_update = _spectral_distortion_index_check_inputs


def _pairwise_uqi_matrix(x: Array) -> Array:
    """(L, L) matrix of UQI between every channel pair of ``x`` (B,C,H,W)."""
    length = x.shape[1]
    ii, jj = jnp.meshgrid(jnp.arange(length), jnp.arange(length), indexing="ij")
    # (L*L, B, 1, H, W) pair grid folded into the batch axis: one conv call
    a = x[:, ii.reshape(-1), :, :].transpose(1, 0, 2, 3)[:, :, None]
    b = x[:, jj.reshape(-1), :, :].transpose(1, 0, 2, 3)[:, :, None]
    flat_a = a.reshape(-1, 1, *x.shape[2:])
    flat_b = b.reshape(-1, 1, *x.shape[2:])
    uqi = _uqi_compute(flat_a, flat_b, reduction="none")  # (L*L*B, 1, h, w)
    per_pair = uqi.reshape(length * length, -1).mean(axis=1)
    return per_pair.reshape(length, length)


def _spectral_distortion_index_compute(
    preds: Array,
    target: Array,
    p: int = 1,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    length = preds.shape[1]
    m1 = _pairwise_uqi_matrix(target)
    m2 = _pairwise_uqi_matrix(preds)

    diff = jnp.abs(m1 - m2) ** p
    if length == 1:
        output = diff[0, 0] ** (1.0 / p)
    else:
        output = (jnp.sum(diff) / (length * (length - 1))) ** (1.0 / p)
    return reduce(output, reduction)


def spectral_distortion_index(
    preds: Array,
    target: Array,
    p: int = 1,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """Compute D-lambda (reference ``d_lambda.py:92``).

    Example:
        >>> import jax
        >>> preds = jax.random.uniform(jax.random.PRNGKey(42), (4, 3, 16, 16))
        >>> target = jax.random.uniform(jax.random.PRNGKey(123), (4, 3, 16, 16))
        >>> bool(spectral_distortion_index(preds, target) >= 0)
        True
    """
    if not isinstance(p, int) or p <= 0:
        raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
    preds, target = _spectral_distortion_index_check_inputs(preds, target)
    return _spectral_distortion_index_compute(preds, target, p, reduction)
