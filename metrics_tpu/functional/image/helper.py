"""Shared image-kernel helpers: separable gaussian kernels + grouped conv.

Behavioral equivalent of reference ``torchmetrics/functional/image/helper.py``
(``_gaussian`` :11, ``_gaussian_kernel_2d`` :29, ``_gaussian_kernel_3d`` :62,
reflection padding :87-122). TPU-first differences: the depthwise convolution
is expressed as ``lax.conv_general_dilated`` with
``feature_group_count=channels`` so XLA lowers it straight onto the MXU, and
reflection padding is a single fused ``jnp.pad(mode="reflect")``.
"""
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array


def _gaussian(kernel_size: int, sigma: float, dtype: jnp.dtype) -> Array:
    """1D gaussian kernel, normalized to sum 1; shape ``(1, kernel_size)``."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1.0, dtype=dtype)
    gauss = jnp.exp(-jnp.power(dist / sigma, 2) / 2)
    return (gauss / gauss.sum())[None, :]


def _depthwise_conv(inputs: Array, kernel: Array) -> Array:
    """Depthwise (grouped) VALID conv; NCHW/NCDHW inputs, (C,1,*k) kernel."""
    spatial = inputs.ndim - 2
    dn = ("NCHW", "OIHW", "NCHW") if spatial == 2 else ("NCDHW", "OIDHW", "NCDHW")
    return lax.conv_general_dilated(
        inputs,
        kernel,
        window_strides=(1,) * spatial,
        padding="VALID",
        dimension_numbers=dn,
        feature_group_count=kernel.shape[0],
        precision="float32",  # default precision truncates to bf16 on TPU
    )


def _separable_depthwise_conv(inputs: Array, kernels_1d: Sequence[Array]) -> Array:
    """Depthwise VALID conv with a separable window: one 1D pass per spatial
    dim.

    Gaussian and uniform windows factor exactly into outer products of 1D
    kernels, and a depthwise conv has NO contraction depth for the MXU
    (feature_group_count == channels), so its cost scales with tap count —
    ``sum(k)`` taps here vs ``prod(k)`` for the full-window form (11x11:
    22 vs 121, measured 16.1 -> ~4 ms on the 64x3x256x256 SSIM bench row).
    Equal to the full-window conv up to float reassociation.
    """
    spatial = inputs.ndim - 2
    channel = inputs.shape[1]
    out = inputs
    for axis, k1 in enumerate(kernels_1d):
        shape = [1] * spatial
        shape[axis] = k1.shape[-1]
        kernel = jnp.broadcast_to(k1.reshape(1, 1, *shape), (channel, 1, *shape))
        out = _depthwise_conv(out, kernel)
    return out


def _reflection_pad(inputs: Array, pads: Sequence[int]) -> Array:
    """Reflect-pad the trailing spatial dims by ``pads`` (one int per dim)."""
    pad_width = [(0, 0), (0, 0)] + [(p, p) for p in pads]
    return jnp.pad(inputs, pad_width, mode="reflect")


def _avg_pool(inputs: Array, window: int = 2) -> Array:
    """Average-pool the trailing spatial dims by ``window`` (NCHW/NCDHW)."""
    spatial = inputs.ndim - 2
    dims = (1, 1) + (window,) * spatial
    out = lax.reduce_window(inputs, 0.0, lax.add, dims, dims, "VALID")
    return out / float(window**spatial)
