"""Shared image-kernel helpers: separable gaussian kernels + grouped conv.

Behavioral equivalent of reference ``torchmetrics/functional/image/helper.py``
(``_gaussian`` :11, ``_gaussian_kernel_2d`` :29, ``_gaussian_kernel_3d`` :62,
reflection padding :87-122). TPU-first differences: the depthwise convolution
is expressed as ``lax.conv_general_dilated`` with
``feature_group_count=channels`` so XLA lowers it straight onto the MXU, and
reflection padding is a single fused ``jnp.pad(mode="reflect")``.
"""
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array


def _gaussian(kernel_size: int, sigma: float, dtype: jnp.dtype) -> Array:
    """1D gaussian kernel, normalized to sum 1; shape ``(1, kernel_size)``."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1.0, dtype=dtype)
    gauss = jnp.exp(-jnp.power(dist / sigma, 2) / 2)
    return (gauss / gauss.sum())[None, :]


def _gaussian_kernel_2d(
    channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype: jnp.dtype
) -> Array:
    """2D gaussian kernel of shape ``(channel, 1, kh, kw)`` (depthwise OIHW)."""
    kernel_x = _gaussian(kernel_size[0], sigma[0], dtype)
    kernel_y = _gaussian(kernel_size[1], sigma[1], dtype)
    kernel = kernel_x.T @ kernel_y  # (kh, kw)
    return jnp.broadcast_to(kernel, (channel, 1, kernel_size[0], kernel_size[1]))


def _gaussian_kernel_3d(
    channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype: jnp.dtype
) -> Array:
    """3D gaussian kernel of shape ``(channel, 1, kh, kw, kd)``."""
    kernel_x = _gaussian(kernel_size[0], sigma[0], dtype)
    kernel_y = _gaussian(kernel_size[1], sigma[1], dtype)
    kernel_z = _gaussian(kernel_size[2], sigma[2], dtype)
    kernel_xy = kernel_x.T @ kernel_y  # (kh, kw)
    kernel = kernel_xy[:, :, None] * kernel_z[0][None, None, :]
    return jnp.broadcast_to(kernel, (channel, 1, *kernel_size))


def _uniform_kernel_2d(channel: int, kernel_size: Sequence[int], dtype: jnp.dtype) -> Array:
    kernel = jnp.ones(tuple(kernel_size), dtype=dtype) / float(jnp.prod(jnp.asarray(kernel_size)))
    return jnp.broadcast_to(kernel, (channel, 1, *kernel_size))


def _uniform_kernel_3d(channel: int, kernel_size: Sequence[int], dtype: jnp.dtype) -> Array:
    return _uniform_kernel_2d(channel, kernel_size, dtype)


def _depthwise_conv(inputs: Array, kernel: Array) -> Array:
    """Depthwise (grouped) VALID conv; NCHW/NCDHW inputs, (C,1,*k) kernel."""
    spatial = inputs.ndim - 2
    dn = ("NCHW", "OIHW", "NCHW") if spatial == 2 else ("NCDHW", "OIDHW", "NCDHW")
    return lax.conv_general_dilated(
        inputs,
        kernel,
        window_strides=(1,) * spatial,
        padding="VALID",
        dimension_numbers=dn,
        feature_group_count=kernel.shape[0],
        precision="float32",  # default precision truncates to bf16 on TPU
    )


def _reflection_pad(inputs: Array, pads: Sequence[int]) -> Array:
    """Reflect-pad the trailing spatial dims by ``pads`` (one int per dim)."""
    pad_width = [(0, 0), (0, 0)] + [(p, p) for p in pads]
    return jnp.pad(inputs, pad_width, mode="reflect")


def _avg_pool(inputs: Array, window: int = 2) -> Array:
    """Average-pool the trailing spatial dims by ``window`` (NCHW/NCDHW)."""
    spatial = inputs.ndim - 2
    dims = (1, 1) + (window,) * spatial
    out = lax.reduce_window(inputs, 0.0, lax.add, dims, dims, "VALID")
    return out / float(window**spatial)
