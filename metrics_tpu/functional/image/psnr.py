"""Peak Signal-to-Noise Ratio.

Behavioral equivalent of reference ``torchmetrics/functional/image/psnr.py``
(``_psnr_compute`` :23, ``_psnr_update`` :58, ``peak_signal_noise_ratio``
:96). O(1) sum states; fully jittable.
"""
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.distributed import reduce
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _psnr_compute(
    sum_squared_error: Array,
    n_obs: Array,
    data_range: Array,
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    psnr_base_e = 2 * jnp.log(data_range) - jnp.log(
        sum_squared_error / jnp.asarray(n_obs, dtype=sum_squared_error.dtype)
    )
    psnr_vals = psnr_base_e * (10 / jnp.log(jnp.asarray(base)))
    return reduce(psnr_vals, reduction)


def _psnr_update(
    preds: Array, target: Array, dim: Optional[Union[int, Tuple[int, ...]]] = None
) -> Tuple[Array, Array]:
    """Sum of squared error + observation count, optionally per-``dim``."""
    # promote to at least f32 without result_type (which is a strict-mode
    # promotion error for bf16 vs f32): sub-32-bit floats and ints go to f32
    if not jnp.issubdtype(preds.dtype, jnp.floating) or jnp.finfo(preds.dtype).bits < 32:
        preds = preds.astype(jnp.float32)
    target = target.astype(preds.dtype)
    if dim is None:
        sum_squared_error = jnp.sum((preds - target) ** 2)
        n_obs = jnp.asarray(target.size)
        return sum_squared_error, n_obs

    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=dim)
    dim_list = [dim] if isinstance(dim, int) else list(dim)
    if not dim_list:
        n_obs = jnp.asarray(target.size)
    else:
        n_obs = jnp.prod(jnp.asarray([target.shape[d] for d in dim_list]))
        n_obs = jnp.broadcast_to(n_obs, sum_squared_error.shape)
    return sum_squared_error, n_obs


def peak_signal_noise_ratio(
    preds: Array,
    target: Array,
    data_range: Optional[float] = None,
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> Array:
    """Compute PSNR (reference ``psnr.py:96``).

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.asarray([[0.0, 1.0], [2.0, 3.0]])
        >>> target = jnp.asarray([[3.0, 2.0], [1.0, 0.0]])
        >>> float(jnp.round(peak_signal_noise_ratio(preds, target), 4))
        2.5527
    """
    if dim is None and reduction != "elementwise_mean":
        rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")
    if data_range is None:
        if dim is not None:
            raise ValueError("The `data_range` must be given when `dim` is not None.")
        data_range = target.max() - target.min()
    else:
        data_range = jnp.asarray(float(data_range))
    sum_squared_error, n_obs = _psnr_update(preds, target, dim=dim)
    return _psnr_compute(sum_squared_error, n_obs, data_range, base=base, reduction=reduction)
