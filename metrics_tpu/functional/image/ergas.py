"""Erreur Relative Globale Adimensionnelle de Synthese (ERGAS).

Behavioral equivalent of reference ``torchmetrics/functional/image/ergas.py``
(``_ergas_update`` :25, ``_ergas_compute`` :47, ``error_relative_global_
dimensionless_synthesis`` :86).
"""
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.distributed import reduce

Array = jax.Array


def _ergas_check_inputs(preds: Array, target: Array) -> Tuple[Array, Array]:
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_same_shape(preds, target)
    if preds.ndim != 4:
        raise ValueError(
            "Expected `preds` and `target` to have BxCxHxW shape."
            f" Got preds: {preds.shape} and target: {target.shape}."
        )
    return preds, target


_ergas_update = _ergas_check_inputs


def _ergas_compute(
    preds: Array,
    target: Array,
    ratio: Union[int, float] = 4,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    b, c, h, w = preds.shape
    preds = preds.reshape(b, c, h * w)
    target = target.reshape(b, c, h * w)

    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=2)
    rmse_per_band = jnp.sqrt(sum_squared_error / (h * w))
    mean_target = jnp.mean(target, axis=2)

    ergas_score = 100 * ratio * jnp.sqrt(jnp.sum((rmse_per_band / mean_target) ** 2, axis=1) / c)
    return reduce(ergas_score, reduction)


def error_relative_global_dimensionless_synthesis(
    preds: Array,
    target: Array,
    ratio: Union[int, float] = 4,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """Compute ERGAS (reference ``ergas.py:86``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> preds = jax.random.uniform(jax.random.PRNGKey(42), (4, 3, 16, 16))
        >>> target = preds * 0.75
        >>> bool(error_relative_global_dimensionless_synthesis(preds, target) > 0)
        True
    """
    preds, target = _ergas_check_inputs(preds, target)
    return _ergas_compute(preds, target, ratio, reduction)
