"""Bounding-box primitives in jnp.

The reference delegates these to ``torchvision.ops`` (``box_convert``,
``box_area``, ``box_iou`` — see reference ``detection/mean_ap.py:23-27``);
here they are native jnp so the detection pipeline has no torch dependency.
``box_convert`` is used on-device in ``MeanAveragePrecision.update``;
``box_area``/``box_iou`` are the public on-device primitives (the mAP
evaluation itself runs host-side on numpy twins — ``_np_box_iou`` in
``metrics_tpu/detection/mean_ap.py`` — kept consistent by a cross-check
test in ``tests/detection/test_map.py``).
"""
import jax
import jax.numpy as jnp

Array = jax.Array

_ALLOWED_FORMATS = ("xyxy", "xywh", "cxcywh")


def box_convert(boxes: Array, in_fmt: str, out_fmt: str) -> Array:
    """Convert ``(N, 4)`` boxes between xyxy / xywh / cxcywh formats."""
    if in_fmt not in _ALLOWED_FORMATS or out_fmt not in _ALLOWED_FORMATS:
        raise ValueError(f"Supported box formats are {_ALLOWED_FORMATS}, got {in_fmt} -> {out_fmt}")
    if in_fmt == out_fmt:
        return boxes
    # normalize to xyxy first
    if in_fmt == "xywh":
        x, y, w, h = jnp.split(boxes, 4, axis=-1)
        boxes = jnp.concatenate([x, y, x + w, y + h], axis=-1)
    elif in_fmt == "cxcywh":
        cx, cy, w, h = jnp.split(boxes, 4, axis=-1)
        boxes = jnp.concatenate([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    if out_fmt == "xyxy":
        return boxes
    x1, y1, x2, y2 = jnp.split(boxes, 4, axis=-1)
    if out_fmt == "xywh":
        return jnp.concatenate([x1, y1, x2 - x1, y2 - y1], axis=-1)
    return jnp.concatenate([(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1], axis=-1)


def box_area(boxes: Array) -> Array:
    """Area of ``(N, 4)`` xyxy boxes."""
    return (boxes[..., 2] - boxes[..., 0]) * (boxes[..., 3] - boxes[..., 1])


def box_iou(boxes1: Array, boxes2: Array) -> Array:
    """Pairwise IoU matrix ``(N, M)`` for xyxy boxes."""
    area1 = box_area(boxes1)
    area2 = box_area(boxes2)
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area1[:, None] + area2[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)
