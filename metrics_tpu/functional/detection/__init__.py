from metrics_tpu.functional.detection.box_ops import box_area, box_convert, box_iou  # noqa: F401
