"""Pairwise linear (dot-product) similarity.

Behavioral equivalent of reference
``torchmetrics/functional/pairwise/linear.py`` (update :22, public :40).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import run_pairwise

Array = jax.Array


def _core(x: Array, y: Array) -> Array:
    return jnp.matmul(x, y.T, precision="float32")



def pairwise_linear_similarity(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise dot-product similarity between rows of ``x`` and ``y`` (or ``x``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pairwise_linear_similarity
        >>> x = jnp.asarray([[2.0, 3.0], [3.0, 5.0], [5.0, 8.0]])
        >>> y = jnp.asarray([[1.0, 0.0], [2.0, 1.0]])
        >>> pairwise_linear_similarity(x, y)
        Array([[ 2.,  7.],
               [ 3., 11.],
               [ 5., 18.]], dtype=float32)
    """
    return run_pairwise(_core, x, y, reduction, zero_diagonal)
