"""Pairwise euclidean distance.

Behavioral equivalent of reference
``torchmetrics/functional/pairwise/euclidean.py`` (update :22, public :41)
via the ||x||^2 + ||y||^2 - 2 x.y expansion (one matmul, MXU-friendly).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import run_pairwise

Array = jax.Array


def _core(x: Array, y: Array) -> Array:
    x_norm = jnp.sum(x * x, axis=1, keepdims=True)
    y_norm = jnp.sum(y * y, axis=1)
    sq = x_norm + y_norm - 2 * jnp.matmul(x, y.T, precision="float32")
    return jnp.sqrt(jnp.clip(sq, min=0.0))



def pairwise_euclidean_distance(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise euclidean distance between rows of ``x`` and ``y`` (or ``x``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pairwise_euclidean_distance
        >>> x = jnp.asarray([[2.0, 3.0], [3.0, 5.0], [5.0, 8.0]])
        >>> y = jnp.asarray([[1.0, 0.0], [2.0, 1.0]])
        >>> pairwise_euclidean_distance(x, y)
        Array([[3.1622777, 2.       ],
               [5.3851647, 4.1231055],
               [8.944272 , 7.615773 ]], dtype=float32)
    """
    return run_pairwise(_core, x, y, reduction, zero_diagonal)
