"""Pairwise euclidean distance.

Behavioral equivalent of reference
``torchmetrics/functional/pairwise/euclidean.py`` (update :22, public :41)
via the ||x||^2 + ||y||^2 - 2 x.y expansion (one matmul, MXU-friendly).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal
from metrics_tpu.utilities.data import _to_float

Array = jax.Array


def _pairwise_euclidean_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x = _to_float(x)
    y = _to_float(y)
    x_norm = jnp.sum(x * x, axis=1, keepdims=True)
    y_norm = jnp.sum(y * y, axis=1)
    distance = x_norm + y_norm - 2 * jnp.matmul(x, y.T, precision="float32")
    if zero_diagonal:
        distance = _zero_diagonal(distance)
    return jnp.sqrt(jnp.clip(distance, min=0.0))


def pairwise_euclidean_distance(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise euclidean distance between rows of ``x`` and ``y`` (or ``x``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pairwise_euclidean_distance
        >>> x = jnp.asarray([[2.0, 3.0], [3.0, 5.0], [5.0, 8.0]])
        >>> y = jnp.asarray([[1.0, 0.0], [2.0, 1.0]])
        >>> pairwise_euclidean_distance(x, y)
        Array([[3.1622777, 2.       ],
               [5.3851647, 4.1231055],
               [8.944272 , 7.615773 ]], dtype=float32)
    """
    distance = _pairwise_euclidean_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
