"""Pairwise manhattan (L1) distance.

Behavioral equivalent of reference
``torchmetrics/functional/pairwise/manhattan.py`` (update :22, public :40).
The |x_i - y_j| sum is computed via a broadcasted [N,1,d]-[M,d] difference.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import run_pairwise

Array = jax.Array


def _core(x: Array, y: Array) -> Array:
    return jnp.sum(jnp.abs(x[:, None] - y[None, :]), axis=-1)



def pairwise_manhattan_distance(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise L1 distance between rows of ``x`` and ``y`` (or ``x``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pairwise_manhattan_distance
        >>> x = jnp.asarray([[2.0, 3.0], [3.0, 5.0], [5.0, 8.0]])
        >>> y = jnp.asarray([[1.0, 0.0], [2.0, 1.0]])
        >>> pairwise_manhattan_distance(x, y)
        Array([[ 4.,  2.],
               [ 7.,  5.],
               [12., 10.]], dtype=float32)
    """
    return run_pairwise(_core, x, y, reduction, zero_diagonal)
