"""Pairwise cosine similarity.

Behavioral equivalent of reference
``torchmetrics/functional/pairwise/cosine.py`` (update :23, public :46). The
core is one [N,d]x[d,M] matmul over row-normalized inputs — MXU-friendly.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.pairwise.helpers import run_pairwise

Array = jax.Array


def _core(x: Array, y: Array) -> Array:
    x = x / jnp.linalg.norm(x, axis=1, keepdims=True)
    y = y / jnp.linalg.norm(y, axis=1, keepdims=True)
    return jnp.matmul(x, y.T, precision="float32")



def pairwise_cosine_similarity(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise cosine similarity between rows of ``x`` and ``y`` (or ``x``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pairwise_cosine_similarity
        >>> x = jnp.asarray([[2.0, 3.0], [3.0, 5.0], [5.0, 8.0]])
        >>> y = jnp.asarray([[1.0, 0.0], [2.0, 1.0]])
        >>> pairwise_cosine_similarity(x, y)
        Array([[0.5547002 , 0.86824316],
               [0.51449573, 0.8436959 ],
               [0.5299989 , 0.85334015]], dtype=float32)
    """
    return run_pairwise(_core, x, y, reduction, zero_diagonal)
