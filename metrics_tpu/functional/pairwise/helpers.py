"""Shared driver for the pairwise kernels.

Counterpart of reference ``torchmetrics/functional/pairwise/helpers.py``
(``_check_input`` :19, ``_reduce_distance_matrix`` :46), restructured: the
reference threads a validate → compute → fill-diagonal → reduce sequence
through every kernel; here ONE driver (:func:`run_pairwise`) owns that
lifecycle and each kernel supplies only its ``[N,d],[M,d] -> [N,M]`` core.
Error strings match the reference for drop-in parity.
"""
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.data import _to_float

Array = jax.Array

# last-dim reductions of the [N, M] matrix, keyed by the public `reduction`
# argument; unknown keys fail fast (before any compute)
_ROW_REDUCERS: Dict[Optional[str], Callable[[Array], Array]] = {
    "mean": lambda mat: mat.mean(axis=-1),
    "sum": lambda mat: mat.sum(axis=-1),
    "none": lambda mat: mat,
    None: lambda mat: mat,
}


def run_pairwise(
    core: Callable[[Array, Array], Array],
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Run a pairwise core inside the shared frame.

    The frame owns everything around the math: shape validation, float
    promotion, the x-vs-x default (whose diagonal zeroes unless the caller
    says otherwise), diagonal masking, and the optional row reduction.
    """
    try:
        reduce_rows = _ROW_REDUCERS[reduction]
    except (KeyError, TypeError):  # unknown key, or unhashable value
        raise ValueError(
            f"Expected reduction to be one of `['mean', 'sum', None]` but got {reduction}"
        ) from None
    x = jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"Expected argument `x` to be a 2D tensor of shape `[N, d]` but got {x.shape}")
    if y is None:
        y = x
        if zero_diagonal is None:
            zero_diagonal = True  # comparing x against itself
    else:
        y = jnp.asarray(y)
        if y.ndim != 2 or y.shape[1] != x.shape[1]:
            raise ValueError(
                "Expected argument `y` to be a 2D tensor of shape `[M, d]` where"
                " `d` should be same as the last dimension of `x`"
            )
    mat = core(_to_float(x), _to_float(y))
    if zero_diagonal:
        mat = jnp.where(jnp.eye(mat.shape[0], mat.shape[1], dtype=bool), 0.0, mat)
    return reduce_rows(mat)
