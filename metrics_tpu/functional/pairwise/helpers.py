"""Shared input validation / reduction for pairwise kernels.

Behavioral equivalent of reference
``torchmetrics/functional/pairwise/helpers.py`` (``_check_input`` :19,
``_reduce_distance_matrix`` :46).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _check_input(x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None) -> Tuple[Array, Array, bool]:
    """Validate [N,d]/[M,d] shapes; default ``zero_diagonal`` to the x-vs-x case."""
    x = jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"Expected argument `x` to be a 2D tensor of shape `[N, d]` but got {x.shape}")
    if y is not None:
        y = jnp.asarray(y)
        if y.ndim != 2 or y.shape[1] != x.shape[1]:
            raise ValueError(
                "Expected argument `y` to be a 2D tensor of shape `[M, d]` where"
                " `d` should be same as the last dimension of `x`"
            )
        zero_diagonal = False if zero_diagonal is None else zero_diagonal
    else:
        y = x
        zero_diagonal = True if zero_diagonal is None else zero_diagonal
    return x, y, zero_diagonal


def _zero_diagonal(distance: Array) -> Array:
    """Zero out the diagonal of a square distance matrix (functional form of
    the reference's in-place ``fill_diagonal_``)."""
    n, m = distance.shape
    mask = jnp.eye(n, m, dtype=bool)
    return jnp.where(mask, 0.0, distance)


def _reduce_distance_matrix(distmat: Array, reduction: Optional[str] = None) -> Array:
    """Reduce a [N,M] distance matrix along its last dimension."""
    if reduction == "mean":
        return distmat.mean(axis=-1)
    if reduction == "sum":
        return distmat.sum(axis=-1)
    if reduction is None or reduction == "none":
        return distmat
    raise ValueError(f"Expected reduction to be one of `['mean', 'sum', None]` but got {reduction}")
