"""Spearman rank correlation (functional).

Behavioral equivalent of reference
``torchmetrics/functional/regression/spearman.py`` (``_rank_data`` :35,
update :56, compute :79). The reference averages tied ranks with a Python
loop over repeated values (:48-51); here tie-averaging is a fully jittable
sort + segment-sum kernel (O(n log n), no host round-trips) — the TPU-first
reformulation called for in SURVEY.md §7.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _rank_data(data: Array) -> Array:
    """Rank elements 1..n, ties receiving the mean of their ordinal ranks."""
    n = data.size
    order = jnp.argsort(data)
    sorted_data = data[order]
    ordinal = jnp.arange(1, n + 1, dtype=jnp.float32)

    # Equal-value runs share one segment id; each tied element gets the mean
    # ordinal rank of its run via two segment sums.
    change = jnp.concatenate([jnp.asarray([True]), sorted_data[1:] != sorted_data[:-1]])
    seg_id = jnp.cumsum(change) - 1
    seg_sum = jax.ops.segment_sum(ordinal, seg_id, num_segments=n)
    seg_cnt = jax.ops.segment_sum(jnp.ones_like(ordinal), seg_id, num_segments=n)
    mean_rank = seg_sum / jnp.maximum(seg_cnt, 1.0)

    ranks_sorted = mean_rank[seg_id]
    return jnp.zeros(n, dtype=jnp.float32).at[order].set(ranks_sorted)


def _spearman_corrcoef_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Validate and flatten inputs."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.dtype != target.dtype:
        raise TypeError(
            "Expected `preds` and `target` to have the same data type."
            f" Got preds: {preds.dtype} and target: {target.dtype}."
        )
    _check_same_shape(preds, target)
    preds = preds.squeeze()
    target = target.squeeze()
    if preds.ndim > 1 or target.ndim > 1:
        raise ValueError("Expected both predictions and target to be 1 dimensional tensors.")
    return preds, target


def _spearman_corrcoef_compute(preds: Array, target: Array, eps: float = 1e-6) -> Array:
    """Pearson correlation over the rank-transformed inputs."""
    preds = _rank_data(preds)
    target = _rank_data(target)

    preds_diff = preds - preds.mean()
    target_diff = target - target.mean()

    cov = (preds_diff * target_diff).mean()
    preds_std = jnp.sqrt((preds_diff * preds_diff).mean())
    target_std = jnp.sqrt((target_diff * target_diff).mean())

    corrcoef = cov / (preds_std * target_std + eps)
    return jnp.clip(corrcoef, -1.0, 1.0)


def spearman_corrcoef(preds: Array, target: Array) -> Array:
    """Compute Spearman's rank correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import spearman_corrcoef
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> spearman_corrcoef(preds, target)
        Array(1., dtype=float32)
    """
    preds, target = _spearman_corrcoef_update(preds, target)
    return _spearman_corrcoef_compute(preds, target)
