"""Symmetric mean absolute percentage error (functional).

Behavioral equivalent of reference
``torchmetrics/functional/regression/symmetric_mape.py`` (update :22,
compute :51).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.data import _to_float

Array = jax.Array


def _symmetric_mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = 1.17e-06
) -> Tuple[Array, int]:
    """Batch -> (2 * sum of symmetric percentage errors, observation count)."""
    _check_same_shape(preds, target)
    preds = _to_float(preds)
    target = _to_float(target)
    abs_per_error = jnp.abs(preds - target) / jnp.clip(jnp.abs(target) + jnp.abs(preds), min=epsilon)
    return 2 * jnp.sum(abs_per_error), target.size


def _symmetric_mean_absolute_percentage_error_compute(sum_abs_per_error: Array, n_obs) -> Array:
    return sum_abs_per_error / jnp.asarray(n_obs, dtype=sum_abs_per_error.dtype)


def symmetric_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """Compute symmetric mean absolute percentage error (SMAPE).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import symmetric_mean_absolute_percentage_error
        >>> target = jnp.asarray([1.0, 10, 1e6])
        >>> preds = jnp.asarray([0.9, 15, 1.2e6])
        >>> symmetric_mean_absolute_percentage_error(preds, target)
        Array(0.2290271, dtype=float32)
    """
    sum_abs_per_error, n_obs = _symmetric_mean_absolute_percentage_error_update(preds, target)
    return _symmetric_mean_absolute_percentage_error_compute(sum_abs_per_error, n_obs)
