"""Cosine similarity (functional).

Behavioral equivalent of reference
``torchmetrics/functional/regression/cosine_similarity.py`` (update :22,
compute :41).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.data import _to_float

Array = jax.Array


def _cosine_similarity_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Validate shapes and cast to float."""
    _check_same_shape(preds, target)
    return _to_float(preds), _to_float(target)


def _cosine_similarity_compute(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    """Row-wise cosine similarity with batch reduction."""
    dot_product = jnp.sum(preds * target, axis=-1)
    preds_norm = jnp.linalg.norm(preds, axis=-1)
    target_norm = jnp.linalg.norm(target, axis=-1)
    similarity = dot_product / (preds_norm * target_norm)
    reduction_mapping = {
        "sum": jnp.sum,
        "mean": jnp.mean,
        "none": lambda x: x,
        None: lambda x: x,
    }
    return reduction_mapping[reduction](similarity)


def cosine_similarity(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    """Compute cosine similarity between row vectors of ``preds`` and ``target``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import cosine_similarity
        >>> target = jnp.asarray([[1.0, 2, 3, 4], [1, 2, 3, 4]])
        >>> preds = jnp.asarray([[1.0, 2, 3, 4], [-1, -2, -3, -4]])
        >>> cosine_similarity(preds, target, 'none')
        Array([ 1., -1.], dtype=float32)
    """
    preds, target = _cosine_similarity_update(preds, target)
    return _cosine_similarity_compute(preds, target, reduction)
