"""Explained variance (functional).

Behavioral equivalent of reference
``torchmetrics/functional/regression/explained_variance.py`` (update :22,
compute :44). The compute re-expresses the reference's boolean-mask
assignments as ``jnp.where`` selects so the kernel stays jittable.
"""
from typing import Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.data import _to_float

Array = jax.Array


def _explained_variance_update(preds: Array, target: Array) -> Tuple[int, Array, Array, Array, Array]:
    """Batch -> (n, sum error, sum sq error, sum target, sum sq target)."""
    _check_same_shape(preds, target)
    preds = _to_float(preds)
    target = _to_float(target)
    n_obs = preds.shape[0]
    diff = target - preds
    sum_error = jnp.sum(diff, axis=0)
    sum_squared_error = jnp.sum(diff * diff, axis=0)
    sum_target = jnp.sum(target, axis=0)
    sum_squared_target = jnp.sum(target * target, axis=0)
    return n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target


def _explained_variance_compute(
    n_obs: Union[int, Array],
    sum_error: Array,
    sum_squared_error: Array,
    sum_target: Array,
    sum_squared_target: Array,
    multioutput: str = "uniform_average",
) -> Array:
    """Sufficient stats -> explained variance score."""
    n_obs = jnp.asarray(n_obs, dtype=sum_error.dtype)
    diff_avg = sum_error / n_obs
    numerator = sum_squared_error / n_obs - diff_avg * diff_avg
    target_avg = sum_target / n_obs
    denominator = sum_squared_target / n_obs - target_avg * target_avg

    # perfect predictions score 1; zero target variance with nonzero error
    # scores 0 (sklearn convention, mirrored from the reference :83-86)
    nonzero_numerator = numerator != 0
    nonzero_denominator = denominator != 0
    output_scores = jnp.where(
        nonzero_numerator & nonzero_denominator,
        1.0 - numerator / jnp.where(nonzero_denominator, denominator, 1.0),
        jnp.where(nonzero_numerator & ~nonzero_denominator, 0.0, 1.0),
    )

    if multioutput == "raw_values":
        return output_scores
    if multioutput == "uniform_average":
        return jnp.mean(output_scores)
    if multioutput == "variance_weighted":
        denom_sum = jnp.sum(denominator)
        return jnp.sum(denominator / denom_sum * output_scores)
    raise ValueError(
        "Argument `multioutput` must be either `raw_values`, `uniform_average` or `variance_weighted`."
        f" Received {multioutput}."
    )


def explained_variance(preds: Array, target: Array, multioutput: str = "uniform_average") -> Array:
    """Compute explained variance.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import explained_variance
        >>> target = jnp.asarray([3.0, -0.5, 2, 7])
        >>> preds = jnp.asarray([2.5, 0.0, 2, 8])
        >>> explained_variance(preds, target)
        Array(0.95717883, dtype=float32)
    """
    n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target = _explained_variance_update(preds, target)
    return _explained_variance_compute(
        n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target, multioutput
    )
