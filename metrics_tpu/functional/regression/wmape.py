"""Weighted mean absolute percentage error (functional).

Behavioral equivalent of reference
``torchmetrics/functional/regression/wmape.py`` (update :22, compute :43).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.data import _to_float

Array = jax.Array


def _weighted_mean_absolute_percentage_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Batch -> (sum of absolute errors, sum of absolute targets)."""
    _check_same_shape(preds, target)
    preds = _to_float(preds)
    target = _to_float(target)
    sum_abs_error = jnp.sum(jnp.abs(preds - target))
    sum_scale = jnp.sum(jnp.abs(target))
    return sum_abs_error, sum_scale


def _weighted_mean_absolute_percentage_error_compute(
    sum_abs_error: Array, sum_scale: Array, epsilon: float = 1.17e-06
) -> Array:
    return sum_abs_error / jnp.clip(sum_scale, min=epsilon)


def weighted_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """Compute weighted mean absolute percentage error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import weighted_mean_absolute_percentage_error
        >>> target = jnp.asarray([1.0, 10.0, 1e6])
        >>> preds = jnp.asarray([0.9, 15.0, 1.2e6])
        >>> weighted_mean_absolute_percentage_error(preds, target)
        Array(0.2000051, dtype=float32)
    """
    sum_abs_error, sum_scale = _weighted_mean_absolute_percentage_error_update(preds, target)
    return _weighted_mean_absolute_percentage_error_compute(sum_abs_error, sum_scale)
