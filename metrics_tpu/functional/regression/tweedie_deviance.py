"""Tweedie deviance score (functional).

Behavioral equivalent of reference
``torchmetrics/functional/regression/tweedie_deviance.py`` (update :29,
compute :93). The power-dependent branch is resolved statically (``power`` is
a Python float), so each specialization traces to a single fused XLA kernel;
the reference's data-value validity errors become host-side checks in the
eager wrapper, keeping ``_tweedie_deviance_score_update`` jittable.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.data import _to_float

Array = jax.Array


def _xlogy(x: Array, y: Array) -> Array:
    """x * log(y), defined as 0 where x == 0."""
    return jax.scipy.special.xlogy(x, y)


def _check_tweedie_inputs(preds: Array, targets: Array, power: float) -> None:
    """Host-side domain validation (mirrors reference :56-80); skipped under jit."""
    if isinstance(jnp.asarray(preds), jax.core.Tracer):
        return
    if power == 1 or 1 < power < 2:
        if bool(jnp.any(preds <= 0)) or bool(jnp.any(targets < 0)):
            raise ValueError(
                f"For power={power}, 'preds' has to be strictly positive and 'targets' cannot be negative."
            )
    elif power < 0:
        if bool(jnp.any(preds <= 0)):
            raise ValueError(f"For power={power}, 'preds' has to be strictly positive.")
    elif power >= 2:
        if bool(jnp.any(preds <= 0)) or bool(jnp.any(targets <= 0)):
            raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")


def _tweedie_deviance_score_update(preds: Array, targets: Array, power: float = 0.0) -> Tuple[Array, Array]:
    """Batch -> (sum of deviance scores, observation count)."""
    _check_same_shape(preds, targets)
    if 0 < power < 1:
        raise ValueError(f"Deviance Score is not defined for power={power}.")
    preds = _to_float(preds)
    targets = _to_float(targets)

    if power == 0:
        deviance_score = jnp.square(targets - preds)
    elif power == 1:  # Poisson
        deviance_score = 2 * (_xlogy(targets, targets / preds) + preds - targets)
    elif power == 2:  # Gamma
        deviance_score = 2 * (jnp.log(preds / targets) + targets / preds - 1)
    else:
        term_1 = jnp.power(jnp.maximum(targets, 0.0), 2 - power) / ((1 - power) * (2 - power))
        term_2 = targets * jnp.power(preds, 1 - power) / (1 - power)
        term_3 = jnp.power(preds, 2 - power) / (2 - power)
        deviance_score = 2 * (term_1 - term_2 + term_3)

    sum_deviance_score = jnp.sum(deviance_score)
    num_observations = jnp.asarray(deviance_score.size)
    return sum_deviance_score, num_observations


def _tweedie_deviance_score_compute(sum_deviance_score: Array, num_observations: Array) -> Array:
    return sum_deviance_score / jnp.asarray(num_observations, dtype=sum_deviance_score.dtype)


def tweedie_deviance_score(preds: Array, targets: Array, power: float = 0.0) -> Array:
    """Compute the Tweedie deviance score for the given ``power``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import tweedie_deviance_score
        >>> targets = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        >>> preds = jnp.asarray([4.0, 3.0, 2.0, 1.0])
        >>> tweedie_deviance_score(preds, targets, power=2)
        Array(1.2083334, dtype=float32)
    """
    _check_tweedie_inputs(preds, targets, power)
    sum_deviance_score, num_observations = _tweedie_deviance_score_update(preds, targets, power=power)
    return _tweedie_deviance_score_compute(sum_deviance_score, num_observations)
