"""Mean squared error (functional).

Behavioral equivalent of reference ``torchmetrics/functional/regression/mse.py``
(update :22, compute :38). Pure ``(preds, target) -> sufficient stats`` kernels,
fully jit-traceable.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.data import _to_float

Array = jax.Array


def _mean_squared_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    """Batch -> (sum of squared errors, observation count)."""
    _check_same_shape(preds, target)
    preds = _to_float(preds)
    target = _to_float(target)
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff)
    return sum_squared_error, target.size


def _mean_squared_error_compute(sum_squared_error: Array, n_obs, squared: bool = True) -> Array:
    """Sufficient stats -> MSE (or RMSE when ``squared=False``)."""
    n_obs = jnp.asarray(n_obs, dtype=sum_squared_error.dtype)
    mse = sum_squared_error / n_obs
    return mse if squared else jnp.sqrt(mse)


def mean_squared_error(preds: Array, target: Array, squared: bool = True) -> Array:
    """Compute mean squared error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import mean_squared_error
        >>> x = jnp.asarray([0.0, 1, 2, 3])
        >>> y = jnp.asarray([0.0, 1, 2, 2])
        >>> mean_squared_error(x, y)
        Array(0.25, dtype=float32)
    """
    sum_squared_error, n_obs = _mean_squared_error_update(preds, target)
    return _mean_squared_error_compute(sum_squared_error, n_obs, squared=squared)
