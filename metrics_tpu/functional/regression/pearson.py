"""Pearson correlation coefficient (functional).

Behavioral equivalent of reference
``torchmetrics/functional/regression/pearson.py`` (update :22, compute :65)
using streaming (Welford-style) moment accumulation so the class metric keeps
O(1) state.
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.data import _to_float

Array = jax.Array


def _pearson_corrcoef_update(
    preds: Array,
    target: Array,
    mean_x: Array,
    mean_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    n_prior: Array,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Fold a batch into the running first/second moments."""
    _check_same_shape(preds, target)
    preds = _to_float(preds).squeeze()
    target = _to_float(target).squeeze()
    if preds.ndim > 1 or target.ndim > 1:
        raise ValueError("Expected both predictions and target to be 1 dimensional tensors.")

    n_obs = preds.size
    mx_new = (n_prior * mean_x + preds.mean() * n_obs) / (n_prior + n_obs)
    my_new = (n_prior * mean_y + target.mean() * n_obs) / (n_prior + n_obs)
    n_new = n_prior + n_obs
    var_x = var_x + ((preds - mx_new) * (preds - mean_x)).sum()
    var_y = var_y + ((target - my_new) * (target - mean_y)).sum()
    corr_xy = corr_xy + ((preds - mx_new) * (target - mean_y)).sum()
    return mx_new, my_new, var_x, var_y, corr_xy, n_new


def _pearson_corrcoef_compute(var_x: Array, var_y: Array, corr_xy: Array, nb: Array) -> Array:
    """Final correlation from accumulated (co)variances."""
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    corr_xy = corr_xy / (nb - 1)
    corrcoef = jnp.squeeze(corr_xy / jnp.sqrt(var_x * var_y))
    return jnp.clip(corrcoef, -1.0, 1.0)


def _final_aggregation(
    means_x: Array,
    means_y: Array,
    vars_x: Array,
    vars_y: Array,
    corrs_xy: Array,
    nbs: Array,
) -> Tuple[Array, Array, Array, Array]:
    """Merge per-device moment sets (role of reference
    ``regression/pearson.py:23-54``) via the Chan et al. pairwise update.

    The states are raw sums of squared deviations / cross-deviations (not
    normalized variances), so the correct merge is ``M2 = M2a + M2b +
    delta^2 * na*nb/n`` (the reference's own formula mixes up the two
    conventions — a known upstream defect — so the correct form is used
    here; tests pin the result to the scipy oracle). On TPU this loop runs
    over the gathered (n_devices,) vectors inside the jitted compute; the
    device count is static so it unrolls at trace time.
    """
    mx1, my1, vx1, vy1, cxy1, n1 = means_x[0], means_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0]
    for i in range(1, means_x.shape[0]):
        mx2, my2, vx2, vy2, cxy2, n2 = means_x[i], means_y[i], vars_x[i], vars_y[i], corrs_xy[i], nbs[i]
        nb = n1 + n2
        dx = mx2 - mx1
        dy = my2 - my1
        mean_x = mx1 + dx * n2 / nb
        mean_y = my1 + dy * n2 / nb
        var_x = vx1 + vx2 + dx * dx * n1 * n2 / nb
        var_y = vy1 + vy2 + dy * dy * n1 * n2 / nb
        corr_xy = cxy1 + cxy2 + dx * dy * n1 * n2 / nb
        mx1, my1, vx1, vy1, cxy1, n1 = mean_x, mean_y, var_x, var_y, corr_xy, nb
    return vx1, vy1, cxy1, n1


def pearson_corrcoef(preds: Array, target: Array) -> Array:
    """Compute the Pearson correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import pearson_corrcoef
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> pearson_corrcoef(preds, target)
        Array(0.98488414, dtype=float32)
    """
    zero = jnp.zeros((), dtype=jnp.float32)
    _, _, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, zero, zero, zero, zero, zero, zero
    )
    return _pearson_corrcoef_compute(var_x, var_y, corr_xy, nb)
