"""Mean absolute error (functional).

Behavioral equivalent of reference ``torchmetrics/functional/regression/mae.py``
(update :22, compute :40).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.data import _to_float

Array = jax.Array


def _mean_absolute_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    """Batch -> (sum of absolute errors, observation count)."""
    _check_same_shape(preds, target)
    preds = _to_float(preds)
    target = _to_float(target)
    sum_abs_error = jnp.sum(jnp.abs(preds - target))
    return sum_abs_error, target.size


def _mean_absolute_error_compute(sum_abs_error: Array, n_obs) -> Array:
    return sum_abs_error / jnp.asarray(n_obs, dtype=sum_abs_error.dtype)


def mean_absolute_error(preds: Array, target: Array) -> Array:
    """Compute mean absolute error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import mean_absolute_error
        >>> x = jnp.asarray([0.0, 1, 2, 3])
        >>> y = jnp.asarray([0.0, 1, 2, 1])
        >>> mean_absolute_error(x, y)
        Array(0.5, dtype=float32)
    """
    sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
    return _mean_absolute_error_compute(sum_abs_error, n_obs)
