"""Mean squared logarithmic error (functional).

Behavioral equivalent of reference
``torchmetrics/functional/regression/log_mse.py`` (update :22, compute :38).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.data import _to_float

Array = jax.Array


def _mean_squared_log_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    """Batch -> (sum of squared log errors, observation count)."""
    _check_same_shape(preds, target)
    preds = _to_float(preds)
    target = _to_float(target)
    diff = jnp.log1p(preds) - jnp.log1p(target)
    sum_squared_log_error = jnp.sum(diff * diff)
    return sum_squared_log_error, target.size


def _mean_squared_log_error_compute(sum_squared_log_error: Array, n_obs) -> Array:
    return sum_squared_log_error / jnp.asarray(n_obs, dtype=sum_squared_log_error.dtype)


def mean_squared_log_error(preds: Array, target: Array) -> Array:
    """Compute mean squared logarithmic error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import mean_squared_log_error
        >>> x = jnp.asarray([0.0, 1, 2, 3])
        >>> y = jnp.asarray([0.0, 1, 2, 2])
        >>> mean_squared_log_error(x, y)
        Array(0.02069722, dtype=float32)
    """
    sum_squared_log_error, n_obs = _mean_squared_log_error_update(preds, target)
    return _mean_squared_log_error_compute(sum_squared_log_error, n_obs)
