"""Short-time objective intelligibility (STOI).

Behavioral equivalent of reference ``torchmetrics/functional/audio/stoi.py``
— but self-contained: the reference only wraps the ``pystoi`` package,
while this build ships a native implementation of the published algorithm
(``_stoi_native.py``, Taal 2011 / Jensen 2016) and uses ``pystoi`` merely
as the bit-parity backend when it happens to be installed.
"""
import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.imports import _PYSTOI_AVAILABLE

Array = jax.Array

__doctest_skip__ = ["short_time_objective_intelligibility"]


def short_time_objective_intelligibility(
    preds: Array,
    target: Array,
    fs: int,
    extended: bool = False,
    keep_same_device: bool = False,
    implementation: str = "auto",
) -> Array:
    """STOI (~0..1, higher is more intelligible), computed host-side.

    Args:
        preds: shape ``[..., time]``.
        target: shape ``[..., time]``.
        fs: sampling frequency.
        extended: use the extended STOI (ESTOI) variant.
        keep_same_device: kept for API parity (XLA manages placement).
        implementation: ``"auto"`` uses ``pystoi`` when installed (bit parity
            with the reference wrapper) and the in-repo native algorithm
            otherwise; ``"native"`` / ``"pystoi"`` force one backend.

    Example:
        >>> import jax
        >>> from metrics_tpu.functional import short_time_objective_intelligibility
        >>> preds = jax.random.normal(jax.random.PRNGKey(0), (8000,))
        >>> target = jax.random.normal(jax.random.PRNGKey(1), (8000,))
        >>> short_time_objective_intelligibility(preds, target, 8000)  # doctest: +SKIP
        Array(-0.0842, dtype=float32)
    """
    if implementation not in ("auto", "native", "pystoi"):
        raise ValueError(
            f"Expected argument `implementation` to be 'auto', 'native' or 'pystoi' but got {implementation}"
        )
    use_pystoi = implementation == "pystoi" or (implementation == "auto" and _PYSTOI_AVAILABLE)
    if implementation == "pystoi" and not _PYSTOI_AVAILABLE:
        raise ModuleNotFoundError(
            "implementation='pystoi' requires that `pystoi` is installed. Either install as"
            " `pip install metrics-tpu[audio]` or `pip install pystoi` — or use the built-in"
            " implementation='native'."
        )
    if use_pystoi:
        import pystoi

        def one(t: np.ndarray, p: np.ndarray) -> float:
            return pystoi.stoi(t, p, fs, extended=extended)

    else:
        from metrics_tpu.functional.audio._stoi_native import stoi_native

        def one(t: np.ndarray, p: np.ndarray) -> float:
            return stoi_native(t, p, fs, extended=extended)

    _check_same_shape(preds, target)

    preds_np = np.asarray(preds, dtype=np.float64)
    target_np = np.asarray(target, dtype=np.float64)
    if preds_np.ndim == 1:
        return jnp.asarray(one(target_np, preds_np), dtype=jnp.float32)

    flat_preds = preds_np.reshape(-1, preds_np.shape[-1])
    flat_target = target_np.reshape(-1, target_np.shape[-1])
    scores = [one(t, p) for t, p in zip(flat_target, flat_preds)]
    return jnp.asarray(scores, dtype=jnp.float32).reshape(preds_np.shape[:-1])
