"""Short-time objective intelligibility (STOI).

Behavioral equivalent of reference ``torchmetrics/functional/audio/stoi.py``:
a host callback into the ``pystoi`` implementation, gated on the optional
dependency exactly like the reference.
"""
import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.imports import _PYSTOI_AVAILABLE

Array = jax.Array

__doctest_skip__ = ["short_time_objective_intelligibility"]


def short_time_objective_intelligibility(
    preds: Array, target: Array, fs: int, extended: bool = False, keep_same_device: bool = False
) -> Array:
    """STOI (0..1, higher is more intelligible), computed host-side by pystoi.

    Args:
        preds: shape ``[..., time]``.
        target: shape ``[..., time]``.
        fs: sampling frequency.
        extended: use the extended STOI variant.
        keep_same_device: kept for API parity (XLA manages placement).

    Example:
        >>> import jax
        >>> from metrics_tpu.functional import short_time_objective_intelligibility
        >>> preds = jax.random.normal(jax.random.PRNGKey(0), (8000,))
        >>> target = jax.random.normal(jax.random.PRNGKey(1), (8000,))
        >>> short_time_objective_intelligibility(preds, target, 8000)  # doctest: +SKIP
        Array(-0.0842, dtype=float32)
    """
    if not _PYSTOI_AVAILABLE:
        raise ModuleNotFoundError(
            "STOI metric requires that `pystoi` is installed. Either install as `pip install metrics-tpu[audio]` "
            "or `pip install pystoi`."
        )
    import pystoi

    _check_same_shape(preds, target)

    preds_np = np.asarray(preds, dtype=np.float64)
    target_np = np.asarray(target, dtype=np.float64)
    if preds_np.ndim == 1:
        score = pystoi.stoi(target_np, preds_np, fs, extended=extended)
        return jnp.asarray(score, dtype=jnp.float32)

    flat_preds = preds_np.reshape(-1, preds_np.shape[-1])
    flat_target = target_np.reshape(-1, target_np.shape[-1])
    scores = [pystoi.stoi(t, p, fs, extended=extended) for t, p in zip(flat_target, flat_preds)]
    return jnp.asarray(scores, dtype=jnp.float32).reshape(preds_np.shape[:-1])
