"""Signal-to-noise ratio family.

Behavioral equivalent of reference ``torchmetrics/functional/audio/snr.py``
(``signal_noise_ratio`` :21, ``scale_invariant_signal_noise_ratio`` :67).
Pure jnp over the trailing time axis — fully jittable and vmap/shard_map
friendly.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio._utils import upcast_half_precision
from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SNR = 10 log10(||target||^2 / ||target - preds||^2), shape ``[..., time] -> [...]``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import signal_noise_ratio
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> signal_noise_ratio(preds, target)
        Array(16.180521, dtype=float32)
    """
    _check_same_shape(preds, target)
    preds, target = upcast_half_precision(preds, target)
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    noise = target - preds
    snr_value = (jnp.sum(target**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(snr_value)


def scale_invariant_signal_noise_ratio(preds: Array, target: Array) -> Array:
    """SI-SNR: SNR after optimally scaling the (zero-meaned) target.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import scale_invariant_signal_noise_ratio
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> scale_invariant_signal_noise_ratio(preds, target)
        Array(15.091808, dtype=float32)
    """
    from metrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio

    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=True)
