"""Native STOI / ESTOI implementation (no C/pystoi dependency).

Implements the published algorithms directly:

* classic STOI — C. H. Taal, R. C. Hendriks, R. Heusdens, J. Jensen, "An
  Algorithm for Intelligibility Prediction of Time-Frequency Weighted Noisy
  Speech", IEEE TASLP 2011.
* extended STOI (ESTOI) — J. Jensen, C. H. Taal, "An Algorithm for
  Predicting the Intelligibility of Speech Masked by Modulated Noise
  Maskers", IEEE TASLP 2016.

The reference (``torchmetrics/functional/audio/stoi.py``) only wraps the
``pystoi`` package; this module makes the metric self-contained. The
pipeline (10 kHz resample -> silent-frame removal -> 256/512 hann STFT ->
15 one-third-octave bands -> 384 ms segment correlations) follows the
papers with pystoi's published constants, and the optional
``tests/audio/test_stoi.py`` pinning test cross-checks against pystoi
whenever that package is installed.

Silent-frame removal makes intermediate shapes data-dependent, so the
computation is host-side numpy by design (same split the reference makes:
the accumulator states are the only device tensors).
"""
import warnings

import numpy as np

FS = 10_000  # internal sampling rate [Hz]
N_FRAME = 256  # STFT window length at FS (25.6 ms)
NFFT = 512  # STFT FFT size
NUMBAND = 15  # number of one-third-octave bands
MINFREQ = 150  # lowest band-edge centre frequency [Hz]
N = 30  # frames per intelligibility segment (384 ms)
BETA = -15.0  # lower signal-to-distortion bound [dB]
DYN_RANGE = 40.0  # silent-frame dynamic range [dB]

_EPS = np.finfo(np.float64).eps


def _hann(n: int) -> np.ndarray:
    """The interior Hann window both papers use (endpoints dropped)."""
    return np.hanning(n + 2)[1:-1]


def _resample_to_fs(x: np.ndarray, fs_in: int) -> np.ndarray:
    """Polyphase resample to the internal 10 kHz rate."""
    if fs_in == FS:
        return x
    from fractions import Fraction

    try:
        from scipy.signal import resample_poly
    except ModuleNotFoundError as err:
        raise ModuleNotFoundError(
            f"Native STOI needs scipy to resample {fs_in} Hz input to its internal 10 kHz rate."
            " Install as `pip install metrics-tpu[audio]` or `pip install scipy` (or pass signals"
            " already sampled at 10000 Hz)."
        ) from err

    frac = Fraction(FS, int(fs_in))
    return resample_poly(x, frac.numerator, frac.denominator)


def _frames(x: np.ndarray, framelen: int, hop: int) -> np.ndarray:
    """(n_frames, framelen) hop-spaced windows.

    Frame starts follow pystoi's EXCLUSIVE ``range(0, len(x) - framelen,
    hop)`` convention (a final exactly-fitting frame is dropped) so the
    native scores stay bit-comparable with the pystoi backend.
    """
    n = max(0, -(-(len(x) - framelen) // hop))  # ceil((len - framelen) / hop)
    if n <= 0:
        return np.empty((0, framelen), dtype=x.dtype)
    idx = np.arange(framelen)[None, :] + hop * np.arange(n)[:, None]
    return x[idx]


def _remove_silent_frames(
    x: np.ndarray, y: np.ndarray, dyn_range: float, framelen: int, hop: int
) -> tuple:
    """Drop frames whose TARGET energy is > dyn_range below the loudest frame,
    then overlap-add the survivors back into time signals (Taal 2011 §II-A)."""
    w = _hann(framelen)
    x_frames = _frames(x, framelen, hop) * w
    y_frames = _frames(y, framelen, hop) * w
    energies = 20.0 * np.log10(np.linalg.norm(x_frames, axis=1) + _EPS)
    mask = energies > (np.max(energies) - dyn_range) if energies.size else np.zeros(0, bool)
    x_frames, y_frames = x_frames[mask], y_frames[mask]
    n_kept = x_frames.shape[0]
    if n_kept == 0:
        return np.zeros(0), np.zeros(0)
    out_len = (n_kept - 1) * hop + framelen
    x_sil = np.zeros(out_len)
    y_sil = np.zeros(out_len)
    for i in range(n_kept):
        x_sil[i * hop : i * hop + framelen] += x_frames[i]
        y_sil[i * hop : i * hop + framelen] += y_frames[i]
    return x_sil, y_sil


def _stft(x: np.ndarray, framelen: int, hop: int, nfft: int) -> np.ndarray:
    """(n_frames, nfft//2 + 1) one-sided spectra of hann-windowed frames."""
    return np.fft.rfft(_frames(x, framelen, hop) * _hann(framelen), n=nfft)


def _thirdoct(fs: int, nfft: int, num_bands: int, min_freq: float) -> np.ndarray:
    """(num_bands, nfft//2 + 1) one-third-octave band matrix (Taal 2011 §II-B)."""
    f = np.linspace(0, fs, nfft + 1)[: nfft // 2 + 1]
    k = np.arange(num_bands)
    freq_low = min_freq * 2.0 ** ((2.0 * k - 1.0) / 6.0)
    freq_high = min_freq * 2.0 ** ((2.0 * k + 1.0) / 6.0)
    obm = np.zeros((num_bands, len(f)))
    for i in range(num_bands):
        lo = int(np.argmin((f - freq_low[i]) ** 2))
        hi = int(np.argmin((f - freq_high[i]) ** 2))
        obm[i, lo:hi] = 1.0
    return obm


_OBM = _thirdoct(FS, NFFT, NUMBAND, MINFREQ)


def _band_envelopes(x_sil: np.ndarray) -> np.ndarray:
    """(NUMBAND, n_frames) one-third-octave amplitude envelopes."""
    spec = _stft(x_sil, N_FRAME, N_FRAME // 2, NFFT)  # (frames, bins)
    power = np.abs(spec) ** 2
    return np.sqrt(_OBM @ power.T)


def _segments(tob: np.ndarray) -> np.ndarray:
    """(n_segments, NUMBAND, N) sliding length-N segments of the envelopes."""
    n_frames = tob.shape[1]
    n_seg = n_frames - N + 1
    idx = np.arange(N)[None, :] + np.arange(n_seg)[:, None]
    return tob[:, idx].transpose(1, 0, 2)


def stoi_native(target: np.ndarray, preds: np.ndarray, fs: int, extended: bool = False) -> float:
    """STOI / ESTOI of a single pair of 1-D signals (higher = more intelligible).

    Args:
        target: the clean reference signal.
        preds: the degraded/processed signal.
        fs: sampling rate of both signals [Hz].
        extended: compute ESTOI (Jensen 2016) instead of classic STOI.
    """
    x = _resample_to_fs(np.asarray(target, np.float64), fs)
    y = _resample_to_fs(np.asarray(preds, np.float64), fs)
    x_sil, y_sil = _remove_silent_frames(x, y, DYN_RANGE, N_FRAME, N_FRAME // 2)

    x_tob = _band_envelopes(x_sil)
    y_tob = _band_envelopes(y_sil)
    if x_tob.shape[1] < N:
        warnings.warn(
            "Not enough STFT frames to compute one 384 ms STOI segment (signal too short or"
            " fully silent); returning 1e-5.",
            RuntimeWarning,
        )
        return 1e-5

    x_seg = _segments(x_tob)  # (M, bands, N)
    y_seg = _segments(y_tob)

    if extended:
        # row (band) normalization, then column (frame) normalization, then
        # the mean column inner product (Jensen 2016 eq. 4-6)
        def row_col_normalize(seg):
            seg = seg - seg.mean(axis=2, keepdims=True)
            seg = seg / (np.linalg.norm(seg, axis=2, keepdims=True) + _EPS)
            seg = seg - seg.mean(axis=1, keepdims=True)
            return seg / (np.linalg.norm(seg, axis=1, keepdims=True) + _EPS)

        xn = row_col_normalize(x_seg)
        yn = row_col_normalize(y_seg)
        return float(np.sum(xn * yn) / (N * x_seg.shape[0]))

    # classic: scale each band to the clean energy, clip the SDR at BETA dB,
    # then average the per-band envelope correlations (Taal 2011 eq. 2-5)
    alpha = np.sqrt(
        np.sum(x_seg**2, axis=2, keepdims=True) / (np.sum(y_seg**2, axis=2, keepdims=True) + _EPS)
    )
    y_prime = np.minimum(alpha * y_seg, x_seg * (1.0 + 10.0 ** (-BETA / 20.0)))
    xc = x_seg - x_seg.mean(axis=2, keepdims=True)
    yc = y_prime - y_prime.mean(axis=2, keepdims=True)
    corr = np.sum(xc * yc, axis=2) / (
        np.linalg.norm(xc, axis=2) * np.linalg.norm(yc, axis=2) + _EPS
    )
    return float(corr.mean())
