"""Signal-to-distortion ratio family.

Behavioral equivalent of reference ``torchmetrics/functional/audio/sdr.py``
(``signal_distortion_ratio`` :51, ``scale_invariant_signal_distortion_ratio``
:202). The reference delegates the distortion-filter math to the
``fast_bss_eval`` package; here the full algorithm (Scheibler 2021, "SDR —
Medium Rare with Fast Computations") is implemented natively in JAX:

1. unit-normalize both signals along time;
2. FFT-based autocorrelation of the target and cross-correlation
   target<->preds, truncated to ``filter_length`` lags;
3. solve the Toeplitz system ``R h = b`` for the optimal distortion filter —
   either densely (``jnp.linalg.solve``) or by ``use_cg_iter`` steps of
   circulant-preconditioned conjugate gradient whose matvec is an FFT
   product (never materializing R — the TPU-friendly path for long filters);
4. SDR = 10 log10(coh / (1 - coh)) with coherence ``coh = <b, h>``.

Everything is jittable; the solve batches over leading axes.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.audio._utils import upcast_half_precision
from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array


def _normalize(x: Array) -> Array:
    return x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), jnp.finfo(x.dtype).tiny)


def _compute_stats(target: Array, preds: Array, length: int):
    """FFT auto-/cross-correlation, first ``length`` lags (fast_bss_eval's compute_stats)."""
    n = target.shape[-1]  # static under jit
    n_fft = 1 << int(n + length - 1).bit_length()
    t_f = jnp.fft.rfft(target, n=n_fft)
    p_f = jnp.fft.rfft(preds, n=n_fft)
    acf = jnp.fft.irfft(t_f * jnp.conj(t_f), n=n_fft)[..., :length]
    xcorr = jnp.fft.irfft(jnp.conj(t_f) * p_f, n=n_fft)[..., :length]
    return acf, xcorr


def _toeplitz_matvec(acf: Array, x: Array) -> Array:
    """y = T(acf) @ x via circulant embedding (one FFT round trip, O(L log L))."""
    length = acf.shape[-1]
    # first column == first row == acf (symmetric Toeplitz)
    circ = jnp.concatenate([acf, jnp.zeros_like(acf[..., :1]), acf[..., :0:-1]], axis=-1)
    n_fft = circ.shape[-1]
    y = jnp.fft.irfft(jnp.fft.rfft(circ) * jnp.fft.rfft(x, n=n_fft), n=n_fft)
    return y[..., :length]


def _toeplitz_conjugate_gradient(acf: Array, b: Array, n_iter: int) -> Array:
    """CG on the symmetric-positive-definite Toeplitz system, FFT matvecs."""
    x = jnp.zeros_like(b)
    r = b - _toeplitz_matvec(acf, x)
    p = r
    rs = jnp.sum(r * r, axis=-1, keepdims=True)

    def body(_, state):
        x, r, p, rs = state
        ap = _toeplitz_matvec(acf, p)
        alpha = rs / jnp.maximum(jnp.sum(p * ap, axis=-1, keepdims=True), jnp.finfo(b.dtype).tiny)
        x = x + alpha * p
        r = r - alpha * ap
        rs_new = jnp.sum(r * r, axis=-1, keepdims=True)
        p = r + (rs_new / jnp.maximum(rs, jnp.finfo(b.dtype).tiny)) * p
        return x, r, p, rs_new

    x, _, _, _ = jax.lax.fori_loop(0, n_iter, body, (x, r, p, rs))
    return x


def _toeplitz_dense(acf: Array) -> Array:
    """Materialize the symmetric Toeplitz matrix T[i, j] = acf[|i - j|]."""
    length = acf.shape[-1]
    idx = jnp.abs(jnp.arange(length)[:, None] - jnp.arange(length)[None, :])
    return acf[..., idx]


def signal_distortion_ratio(
    preds: Array,
    target: Array,
    use_cg_iter: Optional[int] = None,
    filter_length: int = 512,
    zero_mean: bool = False,
    load_diag: Optional[float] = None,
) -> Array:
    """SDR with an optimal ``filter_length``-tap distortion filter; shape ``[..., time] -> [...]``.

    Args:
        preds: estimated signal ``[..., time]``.
        target: reference signal ``[..., time]``.
        use_cg_iter: if given, solve the filter with this many conjugate-
            gradient iterations (FFT matvecs; recommended ~10) instead of a
            dense solve.
        filter_length: number of allowed distortion-filter taps.
        zero_mean: subtract the time mean of both signals first.
        load_diag: diagonal loading for numerical stabilization.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.functional import signal_distortion_ratio
        >>> k1, k2 = jax.random.split(jax.random.PRNGKey(0))
        >>> preds, target = jax.random.normal(k1, (8000,)), jax.random.normal(k2, (8000,))
        >>> float(signal_distortion_ratio(preds, target))  # doctest: +SKIP
        -12.1
    """
    _check_same_shape(preds, target)
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        preds = preds.astype(jnp.float32)
    if preds.dtype == jnp.float16:
        preds = preds.astype(jnp.float32)
    target = target.astype(preds.dtype)

    if zero_mean:
        preds = preds - preds.mean(axis=-1, keepdims=True)
        target = target - target.mean(axis=-1, keepdims=True)

    preds = _normalize(preds)
    target = _normalize(target)

    acf, xcorr = _compute_stats(target, preds, filter_length)
    if load_diag is not None:
        acf = acf.at[..., 0].add(load_diag)

    if use_cg_iter is not None:
        sol = _toeplitz_conjugate_gradient(acf, xcorr, n_iter=use_cg_iter)
    else:
        sol = jnp.linalg.solve(_toeplitz_dense(acf), xcorr[..., None])[..., 0]

    coh = jnp.einsum("...l,...l->...", xcorr, sol, precision="float32")
    ratio = coh / (1 - coh)
    return 10.0 * jnp.log10(ratio)


def scale_invariant_signal_distortion_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """SI-SDR: SDR after optimally scaling the target; shape ``[..., time] -> [...]``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import scale_invariant_signal_distortion_ratio
        >>> target = jnp.asarray([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.asarray([2.5, 0.0, 2.0, 8.0])
        >>> scale_invariant_signal_distortion_ratio(preds, target)
        Array(18.403925, dtype=float32)
    """
    _check_same_shape(preds, target)
    preds, target = upcast_half_precision(preds, target)
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + eps) / (
        jnp.sum(target**2, axis=-1, keepdims=True) + eps
    )
    target_scaled = alpha * target
    noise = target_scaled - preds
    val = (jnp.sum(target_scaled**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(val)
