"""Permutation invariant training (PIT).

Behavioral equivalent of reference ``torchmetrics/functional/audio/pit.py``
(``permutation_invariant_training`` :96, ``pit_permutate`` :180, best-perm
search :29/:52). The pairwise metric matrix is built with a double ``vmap``
over speaker pairs (one fused batched call instead of the reference's
Python double loop), and the exhaustive permutation search is a jnp gather
over the precomputed permutation table — jit-friendly for the practical
speaker counts. For many speakers, scipy's Hungarian solver is used
host-side (same cutoff the reference applies via ``linear_sum_assignment``).
"""
from functools import lru_cache
from itertools import permutations
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utilities.imports import _SCIPY_AVAILABLE

Array = jax.Array

# beyond this speaker count, the factorial table is larger than the
# Hungarian-solver overhead is worth
_EXHAUSTIVE_MAX_SPK = 6


@lru_cache(maxsize=32)
def _perm_table(spk_num: int) -> np.ndarray:
    """All permutations, shape (perm_num, spk_num)."""
    return np.asarray(list(permutations(range(spk_num))), dtype=np.int32)


def _find_best_perm_exhaustive(metric_mtx: Array, eval_op: str) -> Tuple[Array, Array]:
    """Score every permutation with a gather; reduce with min/max."""
    spk_num = metric_mtx.shape[-1]
    ps = jnp.asarray(_perm_table(spk_num))  # (perm, spk)
    # metric_of_ps[b, p] = mean_i metric_mtx[b, i, ps[p, i]]
    metric_of_ps = jnp.mean(metric_mtx[..., jnp.arange(spk_num)[None, :], ps], axis=-1)
    if eval_op == "max":
        best_idx = jnp.argmax(metric_of_ps, axis=-1)
        best_metric = jnp.max(metric_of_ps, axis=-1)
    else:
        best_idx = jnp.argmin(metric_of_ps, axis=-1)
        best_metric = jnp.min(metric_of_ps, axis=-1)
    return best_metric, ps[best_idx]


def _find_best_perm_hungarian(metric_mtx: Array, eval_op: str) -> Tuple[Array, Array]:
    """Hungarian assignment per batch element (host-side scipy)."""
    from scipy.optimize import linear_sum_assignment

    mtx = np.asarray(metric_mtx)
    best_perm = np.stack([linear_sum_assignment(m, eval_op == "max")[1] for m in mtx])
    best_perm_j = jnp.asarray(best_perm)
    best_metric = jnp.take_along_axis(metric_mtx, best_perm_j[:, :, None], axis=2).mean(axis=(-1, -2))
    return best_metric, best_perm_j


def permutation_invariant_training(
    preds: Array,
    target: Array,
    metric_func: Callable,
    eval_func: str = "max",
    **kwargs: Any,
) -> Tuple[Array, Array]:
    """Best metric value over speaker permutations.

    Args:
        preds: ``[batch, spk, ...]`` estimates.
        target: ``[batch, spk, ...]`` references.
        metric_func: batched pairwise metric ``(preds, target) -> [batch]``.
        eval_func: ``'max'`` (higher better) or ``'min'``.
        kwargs: forwarded to ``metric_func``.

    Returns:
        (best_metric ``[batch]``, best_perm ``[batch, spk]``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import (
        ...     permutation_invariant_training, scale_invariant_signal_distortion_ratio)
        >>> preds = jnp.asarray([[[-0.0579,  0.3560, -0.9604], [-0.1719,  0.3205,  0.2951]]])
        >>> target = jnp.asarray([[[ 1.0958, -0.1648,  0.5228], [-0.4100,  1.1942, -0.5103]]])
        >>> best_metric, best_perm = permutation_invariant_training(
        ...     preds, target, scale_invariant_signal_distortion_ratio, 'max')
        >>> best_perm
        Array([[0, 1]], dtype=int32)
    """
    if eval_func not in ("max", "min"):
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if preds.ndim < 2 or target.ndim < 2 or preds.shape[:2] != target.shape[:2] or target.shape[0] < 1:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    spk_num = target.shape[1]

    # pairwise metric matrix [batch, target_spk, pred_spk] via nested vmap
    def pair_metric(t_i, p_j):
        return metric_func(p_j, t_i, **kwargs)

    # map over target speakers (axis 1 of target), then pred speakers
    metric_mtx = jax.vmap(
        lambda t_i: jax.vmap(lambda p_j: pair_metric(t_i, p_j), in_axes=1, out_axes=-1)(preds),
        in_axes=1,
        out_axes=1,
    )(target)  # [batch, target_spk, pred_spk]

    if spk_num <= _EXHAUSTIVE_MAX_SPK or not _SCIPY_AVAILABLE:
        return _find_best_perm_exhaustive(metric_mtx, eval_func)
    return _find_best_perm_hungarian(metric_mtx, eval_func)


def pit_permutate(preds: Array, perm: Array) -> Array:
    """Reorder ``preds`` ``[batch, spk, ...]`` by the PIT permutation ``[batch, spk]``."""
    return jnp.take_along_axis(preds, perm.reshape(perm.shape + (1,) * (preds.ndim - 2)), axis=1)
