"""Perceptual evaluation of speech quality (PESQ).

Behavioral equivalent of reference ``torchmetrics/functional/audio/pesq.py``:
a thin wrapper over the ``pesq`` C library via a host callback (the metric
is defined by that ITU-T P.862 implementation; there is no tensor math to
port). Gated on the optional dependency exactly like the reference.
"""
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utilities.checks import _check_same_shape
from metrics_tpu.utilities.imports import _PESQ_AVAILABLE

Array = jax.Array

__doctest_skip__ = ["perceptual_evaluation_speech_quality"]


def perceptual_evaluation_speech_quality(
    preds: Array, target: Array, fs: int, mode: str, keep_same_device: bool = False, **kwargs: Any
) -> Array:
    """PESQ via the reference ITU-T P.862 implementation (host-side).

    Args:
        preds: shape ``[..., time]``.
        target: shape ``[..., time]``.
        fs: sampling frequency (8000 or 16000).
        mode: ``'wb'`` (wide-band) or ``'nb'`` (narrow-band).
        keep_same_device: kept for API parity (XLA manages placement).

    Example:
        >>> import jax
        >>> from metrics_tpu.functional import perceptual_evaluation_speech_quality
        >>> preds = jax.random.normal(jax.random.PRNGKey(0), (8000,))
        >>> target = jax.random.normal(jax.random.PRNGKey(1), (8000,))
        >>> perceptual_evaluation_speech_quality(preds, target, 8000, 'nb')  # doctest: +SKIP
        Array(1.15, dtype=float32)
    """
    if not _PESQ_AVAILABLE:
        raise ModuleNotFoundError(
            "PESQ metric requires that pesq is installed. Either install as `pip install metrics-tpu[audio]` "
            "or `pip install pesq`."
        )
    if fs not in (8000, 16000):
        raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
    if mode not in ("wb", "nb"):
        raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
    import pesq as pesq_backend

    _check_same_shape(preds, target)

    preds_np = np.asarray(preds, dtype=np.float32)
    target_np = np.asarray(target, dtype=np.float32)
    if preds_np.ndim == 1:
        score = pesq_backend.pesq(fs, target_np, preds_np, mode)
        return jnp.asarray(score, dtype=jnp.float32)

    flat_preds = preds_np.reshape(-1, preds_np.shape[-1])
    flat_target = target_np.reshape(-1, target_np.shape[-1])
    scores = [pesq_backend.pesq(fs, t, p, mode) for t, p in zip(flat_target, flat_preds)]
    return jnp.asarray(scores, dtype=jnp.float32).reshape(preds_np.shape[:-1])
