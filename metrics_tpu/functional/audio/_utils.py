"""Shared audio-kernel helpers."""
import jax
import jax.numpy as jnp

Array = jax.Array


def upcast_half_precision(preds: Array, target: Array) -> tuple:
    """Promote sub-f32 float inputs to f32 for energy accumulations.

    bf16/f16 are storage types for audio metrics: the noise/scale terms are
    near-cancellations, and half-precision sums of squares lose several dB on
    noise-like signals. Elementwise work may stay half, but every energy
    reduction must accumulate in f32.
    """
    if jnp.issubdtype(preds.dtype, jnp.floating) and jnp.finfo(preds.dtype).bits < 32:
        preds = preds.astype(jnp.float32)
    if jnp.issubdtype(target.dtype, jnp.floating) and jnp.finfo(target.dtype).bits < 32:
        target = target.astype(jnp.float32)
    # unify on the promoted dtype; f64 targets stay f64 rather than being
    # silently truncated, and integer inputs are lifted to f32 so the energy
    # math (and downstream finfo()) is well-defined
    common = jnp.promote_types(preds.dtype, target.dtype)
    if not jnp.issubdtype(common, jnp.floating):
        common = jnp.float32
    return preds.astype(common), target.astype(common)
