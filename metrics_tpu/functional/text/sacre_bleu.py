"""SacreBLEU score: BLEU with canonical sacrebleu tokenizers.

Behavioral equivalent of reference
``torchmetrics/functional/text/sacre_bleu.py`` (``_SacreBLEUTokenizer`` :82,
``sacre_bleu_score`` :262). The tokenizers follow the published sacrebleu
spec (mteval-v13a, international, char, none, zh); shares the BLEU
statistics/compute kernels.
"""
import re
import string
from functools import lru_cache
from typing import Sequence, Union

import jax

from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update
from metrics_tpu.utilities.imports import _REGEX_AVAILABLE

Array = jax.Array

AVAILABLE_TOKENIZERS = ("none", "13a", "zh", "intl", "char")

# CJK ranges used by the sacrebleu `zh` tokenizer to isolate Chinese
# characters before the western-language regex pass. Kept as STRING pairs
# compared lexicographically — including the astral-plane entries written as
# surrogate-free 2-char strings (" 0" == " " + "0") — because
# sacrebleu's published tokenizer compares this way, and the comparison quirk
# (e.g. U+201C/U+2026 punctuation matching the " 0" entry) is part of
# its observable tokenization behavior.
_CJK_RANGES = (
    ("\u3400", "\u4db5"),  # CJK Unified Ideographs Extension A
    ("\u4e00", "\u9fa5"),  # CJK Unified Ideographs
    ("\u9fa6", "\u9fbb"),
    ("\uf900", "\ufa2d"),  # CJK Compatibility Ideographs
    ("\ufa30", "\ufa6a"),
    ("\ufa70", "\ufad9"),
    ("\u20000", "\u2a6d6"),  # Extension B as 2-char strings (see note above)
    ("\u2f800", "\u2fa1d"),
    ("\uff00", "\uffef"),  # full-width ASCII + half-width kana
    ("\u2e80", "\u2eff"),  # CJK Radicals Supplement
    ("\u3000", "\u303f"),  # CJK punctuation
    ("\u31c0", "\u31ef"),  # CJK strokes
    ("\u2f00", "\u2fdf"),  # Kangxi Radicals
    ("\u2ff0", "\u2fff"),
    ("\u3100", "\u312f"),  # phonetic symbols
    ("\u31a0", "\u31bf"),
    ("\ufe10", "\ufe1f"),
    ("\ufe30", "\ufe4f"),
    ("\u2600", "\u26ff"),
    ("\u2700", "\u27bf"),
    ("\u3200", "\u32ff"),
    ("\u3300", "\u33ff"),
)

_13A_REGEXES = (
    (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
    (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
    (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
    (re.compile(r"([0-9])(-)"), r"\1 \2 "),
)

if _REGEX_AVAILABLE:
    import regex

    _INTL_REGEXES = (
        (regex.compile(r"(\P{N})(\p{P})"), r"\1 \2 "),
        (regex.compile(r"(\p{P})(\P{N})"), r" \1 \2"),
        (regex.compile(r"(\p{S})"), r" \1 "),
    )


def _apply_regexes(line: str, regexes) -> str:
    for pattern, repl in regexes:
        line = pattern.sub(repl, line)
    return " ".join(line.split())


def _is_chinese_char(char: str) -> bool:
    return any(lo <= char <= hi for lo, hi in _CJK_RANGES)


class _SacreBLEUTokenizer:
    """Canonical sacrebleu tokenizers (13a/intl/char/none/zh)."""

    def __init__(self, tokenize: str = "13a", lowercase: bool = False) -> None:
        if tokenize not in AVAILABLE_TOKENIZERS:
            raise ValueError(f"Unsupported tokenizer selected. Please, choose one of {AVAILABLE_TOKENIZERS}")
        if tokenize == "intl" and not _REGEX_AVAILABLE:
            raise ModuleNotFoundError(
                "`'intl'` tokenization requires the `regex` package; install it with `pip install regex`."
            )
        self.tokenize = tokenize
        self.lowercase = lowercase

    def __call__(self, line: str) -> Sequence[str]:
        tokenized = getattr(self, f"_tokenize_{self.tokenize}")(line)
        if self.lowercase:
            tokenized = tokenized.lower()
        return tokenized.split()

    @staticmethod
    def _tokenize_none(line: str) -> str:
        return line

    @staticmethod
    def _tokenize_char(line: str) -> str:
        return " ".join(char for char in line)

    @classmethod
    @lru_cache(maxsize=2**16)
    def _tokenize_13a(cls, line: str) -> str:
        # mteval-v13a: unescape entities, drop skipped markers, then the
        # language-dependent regex pass
        line = line.replace("<skipped>", "").replace("-\n", "").replace("\n", " ")
        if "&" in line:
            line = (
                line.replace("&quot;", '"').replace("&amp;", "&").replace("&lt;", "<").replace("&gt;", ">")
            )
        return _apply_regexes(line, _13A_REGEXES)

    @classmethod
    @lru_cache(maxsize=2**16)
    def _tokenize_intl(cls, line: str) -> str:
        return _apply_regexes(line, _INTL_REGEXES)

    @classmethod
    @lru_cache(maxsize=2**16)
    def _tokenize_zh(cls, line: str) -> str:
        line = line.strip()
        out = []
        for char in line:
            if _is_chinese_char(char):
                out.append(f" {char} ")
            else:
                out.append(char)
        return _apply_regexes("".join(out), _13A_REGEXES)


def sacre_bleu_score(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    smooth: bool = False,
    tokenize: str = "13a",
    lowercase: bool = False,
    weights: Sequence[float] = None,
) -> Array:
    """BLEU with sacrebleu-canonical tokenization.

    Example:
        >>> from metrics_tpu.functional import sacre_bleu_score
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> sacre_bleu_score(preds, target)
        Array(0.7598357, dtype=float32)
    """
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)
    numerator, denominator, preds_len, target_len = _bleu_score_update(preds, target, n_gram, tokenizer)
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, smooth, weights)
