"""Extended edit distance (EED).

Behavioral equivalent of reference ``torchmetrics/functional/text/eed.py``
(``_eed_function`` :118, ``_preprocess_en`` :173, ``_preprocess_ja`` :217,
``_eed_update`` :315, ``extended_edit_distance`` :357), following the
published EED algorithm (Stanchev, Wang, Ney, WMT 2019): a CDER-style
character alignment grid with a long-jump operation at blank positions and a
coverage penalty for repeated visits.

Redesign: the reference's per-cell Python DP is replaced by a numpy
row-vectorized DP. The in-row deletion dependency ``next[i-1] + deletion``
collapses with a weighted prefix-min: ``next[i] = min_k<=i (c[k] +
(i-k)*deletion) = minimum.accumulate(c - i*deletion) + i*deletion``.

Tie-breaking note: the coverage term counts visits at ``argmin(next_row)``.
When several cells tie in exact arithmetic, the reference's per-cell float
chains break the tie by accumulated rounding noise; here the row is snapped
to a 1e-9 grid before the argmin so ties resolve deterministically to the
first minimal index. Values agree exactly whenever the costs are exactly
representable (see the dyadic-cost fuzz test); with noisy ties either
implementation is an arbitrary member of the tie set.
"""
import re
import unicodedata
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.helper import _validate_inputs

Array = jax.Array


def _eed_function(
    hyp: str,
    ref: str,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> float:
    """Sentence-level EED between two preprocessed strings (chars as symbols)."""
    n_h = len(hyp)
    if len(ref) == 0:
        return 1.0 if n_h else 0.0

    hyp_codes = np.frombuffer(hyp.encode("utf-32-le"), dtype=np.uint32)
    ref_codes = np.frombuffer(ref.encode("utf-32-le"), dtype=np.uint32)

    idx = np.arange(n_h + 1)
    del_w = idx * deletion
    visits = np.full(n_h + 1, -1, dtype=np.int64)

    row = np.ones(n_h + 1)
    row[0] = 0.0  # CDER initialisation: (0,0)=0, rest of first row 1.0
    for w in range(1, len(ref_codes) + 1):
        sub_cost = (hyp_codes != ref_codes[w - 1]).astype(np.float64)
        # candidates without the in-row deletion chain
        cand = np.concatenate(([row[0] + 1.0], np.minimum(row[:-1] + sub_cost, row[1:] + insertion)))
        next_row = np.minimum.accumulate(cand - del_w) + del_w

        visits[np.argmin(np.round(next_row, 9))] += 1
        if ref[w - 1] == " ":  # long jump from the best position
            next_row = np.minimum(next_row, alpha + next_row.min())
        row = next_row

    coverage = rho * float(np.where(visits >= 0, visits, 1).sum())
    return min(1.0, (row[-1] + coverage) / (float(len(ref)) + coverage))


def _preprocess_en(sentence: str) -> str:
    """English preprocessing per the published EED util (spaced punctuation)."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    sentence = sentence.rstrip()
    for char in (".", "!", "?", ","):
        sentence = sentence.replace(char, f" {char}")
    sentence = re.sub(r"\s+", " ", sentence)
    sentence = re.sub(r"(\d) ([.,]) (\d)", r"\1\2\3", sentence)
    sentence = re.sub(r"(Dr|Jr|Prof|Rev|Gen|Mr|Mt|Mrs|Ms) .", r"\1.", sentence)
    for spaced, joined in (("e . g .", "e.g."), ("i . e .", "i.e."), ("U . S .", "U.S.")):
        sentence = sentence.replace(spaced, joined)
    return f" {sentence} "


def _preprocess_ja(sentence: str) -> str:
    """Japanese preprocessing: NFKC normalization only."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    return unicodedata.normalize("NFKC", sentence.rstrip())


def _eed_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
    sentence_eed: Optional[List[Array]] = None,
) -> List[Array]:
    """Host-side: corpus -> per-sentence best-reference EED scores (cat state)."""
    preds, target = _validate_inputs(preds, target)
    if language == "en":
        preprocess = _preprocess_en
    elif language == "ja":
        preprocess = _preprocess_ja
    else:
        raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")

    if sentence_eed is None:
        sentence_eed = []
    if 0 in (len(preds), len(target[0])):
        return sentence_eed

    for pred, refs in zip(preds, target):
        hyp = preprocess(pred)
        score = min(_eed_function(hyp, preprocess(ref), alpha, rho, deletion, insertion) for ref in refs)
        sentence_eed.append(jnp.asarray([score], dtype=jnp.float32))
    return sentence_eed


def _eed_compute(sentence_level_scores: List[Array]) -> Array:
    """Average of sentence scores."""
    if len(sentence_level_scores) == 0:
        return jnp.asarray(0.0)
    return jnp.mean(jnp.concatenate(sentence_level_scores))


def extended_edit_distance(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    return_sentence_level_score: bool = False,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> Union[Array, Tuple[Array, Array]]:
    """Extended edit distance; 0 is a perfect score.

    Example:
        >>> from metrics_tpu.functional import extended_edit_distance
        >>> preds = ["this is the prediction", "here is an other sample"]
        >>> target = ["this is the reference", "here is another one"]
        >>> extended_edit_distance(preds=preds, target=target)
        Array(0.30776307, dtype=float32)
    """
    if not isinstance(alpha, float) or alpha < 0:
        raise ValueError(f"Parameter `alpha` is expected to be a non-negative float, but got {alpha}.")
    if not isinstance(rho, float) or rho < 0:
        raise ValueError(f"Parameter `rho` is expected to be a non-negative float, but got {rho}.")
    if not isinstance(deletion, float) or deletion < 0:
        raise ValueError(f"Parameter `deletion` is expected to be a non-negative float, but got {deletion}.")
    if not isinstance(insertion, float) or insertion < 0:
        raise ValueError(f"Parameter `insertion` is expected to be a non-negative float, but got {insertion}.")

    sentence_scores = _eed_update(preds, target, language, alpha, rho, deletion, insertion)
    average = _eed_compute(sentence_scores)
    if return_sentence_level_score:
        return average, jnp.concatenate(sentence_scores) if sentence_scores else jnp.zeros(0)
    return average
