"""Word error rate.

Behavioral equivalent of reference ``torchmetrics/functional/text/wer.py``
(``_wer_update`` :23, ``_wer_compute`` :51, ``word_error_rate`` :63).
Tokenization + Levenshtein run host-side; the sufficient statistics
(edit-op count, reference word count) are returned as jnp scalars so the
stateful class accumulates and psum-syncs them like any other sum state.
"""
from typing import List, Tuple, Union

import jax

from metrics_tpu.functional.text.helper import _corpus_edit_stats, _normalize_corpus, _put_scalars

Array = jax.Array


def _wer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Host-side: corpus -> (total edit operations, total reference words)."""
    preds, target = _normalize_corpus(preds, target)
    dists, _, cnt_t = _corpus_edit_stats(preds, target, "words")
    return _put_scalars(dists.sum(), cnt_t.sum())


def _wer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def word_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word error rate of ASR transcriptions; 0 is a perfect score.

    Example:
        >>> from metrics_tpu.functional import word_error_rate
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> word_error_rate(preds=preds, target=target)
        Array(0.5, dtype=float32)
    """
    errors, total = _wer_update(preds, target)
    return _wer_compute(errors, total)
