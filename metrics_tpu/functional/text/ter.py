"""Translation edit rate (TER).

Behavioral equivalent of reference ``torchmetrics/functional/text/ter.py``
(``_TercomTokenizer`` :57, ``_shift_words`` :311, ``_translation_edit_rate``
:390, ``_ter_update`` :469, ``translation_edit_rate`` :523), following the
published Tercom algorithm (Snover et al. 2006) as specified by sacrebleu's
``lib_ter``: greedy phrase shifts are applied to the hypothesis while they
reduce the word-level Levenshtein distance; TER = (shifts + edits) / avg
reference length.

Redesign: the edit-distance DP runs one numpy-vectorized row at a time. The
in-row insertion dependency is collapsed with the prefix-min identity (see
``helper.py``), and the op backtrace is recovered *after* the row cost is
known by re-checking which candidate achieved it — preserving Tercom's
sub > del > ins tie-break order without a Python cell loop.
"""
import re
from functools import lru_cache
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.helper import _encode_tokens, _validate_inputs, _put_scalars, _put_all

Array = jax.Array

# Tercom-inspired limits (same values as sacrebleu / reference ter.py:50-54)
_MAX_SHIFT_SIZE = 10
_MAX_SHIFT_DIST = 50
_MAX_SHIFT_CANDIDATES = 1000

# op codes in the backtrace matrix
_OP_NOP, _OP_SUB, _OP_DEL, _OP_INS = 0, 1, 2, 3


class _TercomTokenizer:
    """Tercom normalizer/tokenizer (spec: tercom's Normalizer.java via sacrebleu)."""

    _ASIAN_PUNCT = r"([、。〈-】〔-〟｡-･・])"
    _FULL_WIDTH_PUNCT = r"([．，？：；！＂（）])"

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
    ) -> None:
        self.normalize = normalize
        self.no_punctuation = no_punctuation
        self.lowercase = lowercase
        self.asian_support = asian_support

    @lru_cache(maxsize=2**16)
    def __call__(self, sentence: str) -> str:
        if not sentence:
            return ""
        if self.lowercase:
            sentence = sentence.lower()
        if self.normalize:
            sentence = self._normalize_general(sentence)
            if self.asian_support:
                sentence = self._normalize_asian(sentence)
        if self.no_punctuation:
            sentence = re.sub(r"[\.,\?:;!\"\(\)]", "", sentence)
            if self.asian_support:
                sentence = re.sub(self._ASIAN_PUNCT, "", sentence)
                sentence = re.sub(self._FULL_WIDTH_PUNCT, "", sentence)
        return " ".join(sentence.split())

    @staticmethod
    def _normalize_general(sentence: str) -> str:
        sentence = f" {sentence} "
        for pattern, repl in (
            (r"\n-", ""),
            (r"\n", " "),
            (r"&quot;", '"'),
            (r"&amp;", "&"),
            (r"&lt;", "<"),
            (r"&gt;", ">"),
            (r"([{-~[-` -&(-+:-@/])", r" \1 "),
            (r"'s ", r" 's "),
            (r"'s$", r" 's"),
            (r"([^0-9])([\.,])", r"\1 \2 "),
            (r"([\.,])([^0-9])", r" \1 \2"),
            (r"([0-9])(-)", r"\1 \2 "),
        ):
            sentence = re.sub(pattern, repl, sentence)
        return sentence

    @classmethod
    def _normalize_asian(cls, sentence: str) -> str:
        sentence = re.sub(r"([一-鿿㐀-䶿])", r" \1 ", sentence)
        sentence = re.sub(r"([㇀-㇯⺀-⻿])", r" \1 ", sentence)
        sentence = re.sub(r"([㌀-㏿豈-﫿︰-﹏])", r" \1 ", sentence)
        sentence = re.sub(r"([㈀-㼢])", r" \1 ", sentence)
        sentence = re.sub(cls._ASIAN_PUNCT, r" \1 ", sentence)
        sentence = re.sub(cls._FULL_WIDTH_PUNCT, r" \1 ", sentence)
        return sentence


def _edit_distance_with_trace(hyp: List[str], ref: List[str]) -> Tuple[int, str]:
    """Word Levenshtein + op trace, numpy row-vectorized.

    Returns the distance and a trace string over ops {' ', 's', 'd', 'i'}
    describing how to rewrite hyp into ref (same orientation as sacrebleu's
    ``BeamEditDistance.__call__``; rows = hyp, cols = ref).
    """
    n_h, n_r = len(hyp), len(ref)
    if n_r == 0:
        return n_h, "d" * n_h
    if n_h == 0:
        return n_r, "i" * n_r

    h, r = _encode_tokens(hyp, ref)

    idx = np.arange(n_r + 1)
    prev = idx.copy()
    ops = np.empty((n_h, n_r + 1), dtype=np.int8)
    for i in range(1, n_h + 1):
        sub_cost = (r != h[i - 1]).astype(np.int64)
        sub_cand = prev[:-1] + sub_cost
        del_cand = prev[1:] + 1
        cand = np.minimum(sub_cand, del_cand)
        full = np.concatenate(([i], cand))
        cur = np.minimum.accumulate(full - idx) + idx
        # recover ops with Tercom preference: sub/nop > del > ins
        row_ops = np.where(
            cur[1:] == sub_cand,
            np.where(sub_cost == 0, _OP_NOP, _OP_SUB),
            np.where(cur[1:] == del_cand, _OP_DEL, _OP_INS),
        ).astype(np.int8)
        ops[i - 1, 1:] = row_ops
        ops[i - 1, 0] = _OP_DEL
        prev = cur

    # backtrace
    trace_chars = []
    op_chars = {_OP_NOP: " ", _OP_SUB: "s", _OP_DEL: "d", _OP_INS: "i"}
    i, j = n_h, n_r
    while i > 0 or j > 0:
        if i == 0:
            op = _OP_INS
        elif j == 0:
            op = _OP_DEL
        else:
            op = int(ops[i - 1, j])
        trace_chars.append(op_chars[op])
        if op in (_OP_NOP, _OP_SUB):
            i, j = i - 1, j - 1
        elif op == _OP_INS:
            j -= 1
        else:
            i -= 1
    return int(prev[-1]), "".join(reversed(trace_chars))


def _trace_to_alignment(trace: str) -> Tuple[Dict[int, int], List[int], List[int]]:
    """Flipped-trace -> (ref->hyp alignment, ref error flags, hyp error flags).

    Mirrors sacrebleu's ``trace_to_alignment`` on the flipped trace: the trace
    from ``_edit_distance_with_trace`` rewrites hyp->ref, so 'd'/'i' swap
    meaning here.
    """
    pos_hyp = pos_ref = -1
    align: Dict[int, int] = {}
    ref_err: List[int] = []
    hyp_err: List[int] = []
    for op in trace:
        if op == " ":
            pos_hyp += 1
            pos_ref += 1
            align[pos_ref] = pos_hyp
            hyp_err.append(0)
            ref_err.append(0)
        elif op == "s":
            pos_hyp += 1
            pos_ref += 1
            align[pos_ref] = pos_hyp
            hyp_err.append(1)
            ref_err.append(1)
        elif op == "d":  # hyp-only word (flipped: deletion from hyp)
            pos_hyp += 1
            hyp_err.append(1)
        else:  # "i": ref-only word
            pos_ref += 1
            align[pos_ref] = pos_hyp
            ref_err.append(1)
    return align, ref_err, hyp_err


def _find_shifted_pairs(hyp: List[str], ref: List[str]) -> Iterator[Tuple[int, int, int]]:
    """Yield (hyp_start, ref_start, length) for every matching word span."""
    for start_h in range(len(hyp)):
        for start_r in range(len(ref)):
            if abs(start_r - start_h) > _MAX_SHIFT_DIST:
                continue
            length = 0
            while (
                start_h + length < len(hyp)
                and start_r + length < len(ref)
                and hyp[start_h + length] == ref[start_r + length]
                and length < _MAX_SHIFT_SIZE
            ):
                length += 1
                yield start_h, start_r, length
                if len(hyp) == start_h + length or len(ref) == start_r + length:
                    break


def _perform_shift(words: List[str], start: int, length: int, target: int) -> List[str]:
    """Move ``words[start:start+length]`` so it lands before ``target``."""
    if target < start:
        return words[:target] + words[start : start + length] + words[target:start] + words[start + length :]
    if target > start + length:
        return words[:start] + words[start + length : target] + words[start : start + length] + words[target:]
    return words[:start] + words[start + length : length + target] + words[start : start + length] + words[length + target :]


def _best_shift(
    hyp: List[str], ref: List[str], checked_candidates: int
) -> Tuple[int, List[str], int]:
    """One round of Tercom's greedy shift search."""
    pre_score, trace = _edit_distance_with_trace(hyp, ref)
    align, ref_err, hyp_err = _trace_to_alignment(trace)

    best: Optional[Tuple] = None
    for start_h, start_r, length in _find_shifted_pairs(hyp, ref):
        # only shift spans that are wrong in hyp AND whose ref position is unmatched
        if sum(hyp_err[start_h : start_h + length]) == 0:
            continue
        if sum(ref_err[start_r : start_r + length]) == 0:
            continue
        if start_h <= align[start_r] < start_h + length:
            continue

        prev_idx = -1
        for offset in range(-1, length):
            if start_r + offset == -1:
                idx = 0
            elif start_r + offset in align:
                idx = align[start_r + offset] + 1
            else:
                break
            if idx == prev_idx:
                continue
            prev_idx = idx
            shifted = _perform_shift(hyp, start_h, length, idx)
            # Tercom's ranking: gain, then longest, then earliest
            candidate = (
                pre_score - _edit_distance_with_trace(shifted, ref)[0],
                length,
                -start_h,
                -idx,
                shifted,
            )
            checked_candidates += 1
            if best is None or candidate > best:
                best = candidate
        if checked_candidates >= _MAX_SHIFT_CANDIDATES:
            break

    if best is None:
        return 0, hyp, checked_candidates
    return best[0], best[4], checked_candidates


def _translation_edit_rate(hyp_words: List[str], ref_words: List[str]) -> int:
    """Shifts + word edits needed to turn hypothesis into one reference."""
    if len(ref_words) == 0:
        return len(hyp_words)
    num_shifts = 0
    checked_candidates = 0
    input_words = hyp_words
    while True:
        delta, new_input, checked_candidates = _best_shift(input_words, ref_words, checked_candidates)
        if checked_candidates >= _MAX_SHIFT_CANDIDATES or delta <= 0:
            break
        num_shifts += 1
        input_words = new_input
    return num_shifts + _edit_distance_with_trace(input_words, ref_words)[0]


def _compute_sentence_statistics(
    hyp_words: List[str], target_words: List[List[str]]
) -> Tuple[float, float]:
    """Best-reference edits + average reference length for one sample."""
    best_num_edits = float("inf")
    tgt_lengths = 0.0
    for ref_words in target_words:
        best_num_edits = min(best_num_edits, _translation_edit_rate(hyp_words, ref_words))
        tgt_lengths += len(ref_words)
    return float(best_num_edits), tgt_lengths / len(target_words)


def _ter_update(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    tokenizer: _TercomTokenizer,
    sentence_ter: Optional[List[Array]] = None,
) -> Tuple[Array, Array]:
    """Host-side: corpus -> (total edits, total average reference length)."""
    preds, target = _validate_inputs(preds, target)
    total_num_edits = 0.0
    total_tgt_length = 0.0
    host_sentence_scores: List[float] = []
    for pred, tgt in zip(preds, target):
        tgt_words_ = [tokenizer(_t.rstrip()).split() for _t in tgt]
        pred_words_ = tokenizer(pred.rstrip()).split()
        num_edits, tgt_length = _compute_sentence_statistics(pred_words_, tgt_words_)
        total_num_edits += num_edits
        total_tgt_length += tgt_length
        if sentence_ter is not None:
            host_sentence_scores.append(_score_from_statistics(num_edits, tgt_length))
    if sentence_ter is not None and host_sentence_scores:
        # one batched transfer for all sentence scores, not one per sentence
        sentence_ter.extend(_put_all(*(np.asarray([s], dtype=np.float32) for s in host_sentence_scores)))
    return _put_scalars(total_num_edits, total_tgt_length)


def _score_from_statistics(num_edits: float, tgt_length: float) -> float:
    if tgt_length > 0:
        return num_edits / tgt_length
    return 1.0 if num_edits > 0 else 0.0


def _ter_compute(total_num_edits: Array, total_tgt_length: Array) -> Array:
    """Pure-jnp corpus score with the empty-reference edge cases masked in."""
    score = total_num_edits / jnp.maximum(total_tgt_length, 1e-16)
    return jnp.where(
        total_tgt_length > 0, score, jnp.where(total_num_edits > 0, 1.0, 0.0)
    )


def translation_edit_rate(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """Translation edit rate; 0 is a perfect score.

    Example:
        >>> from metrics_tpu.functional import translation_edit_rate
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> translation_edit_rate(preds, target)
        Array(0.15384616, dtype=float32)
    """
    tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
    sentence_ter: Optional[List[Array]] = [] if return_sentence_level_score else None
    total_num_edits, total_tgt_length = _ter_update(preds, target, tokenizer, sentence_ter)
    score = _ter_compute(total_num_edits, total_tgt_length)
    if sentence_ter is not None:
        return score, jnp.concatenate(sentence_ter)
    return score
