"""BERTScore.

Behavioral equivalent of reference ``torchmetrics/functional/text/bert.py``
(``TextDataset`` :136 incl. IDF weighting :178, embedding loop
``_get_embeddings_and_idf_scale`` :248, greedy matching
``_get_precision_recall_f1`` :337, baseline rescale :369+, ``bert_score``
:437): contextual token embeddings are greedily matched by cosine
similarity; precision averages over hypothesis tokens, recall over reference
tokens, optionally IDF-weighted and baseline-rescaled.

TPU redesign:

* The model is a **Flax/JAX** encoder — either ``transformers``
  ``FlaxAutoModel`` (from ``model_name_or_path``) or a user-supplied model +
  ``user_forward_fn`` returning ``(batch, seq_len, dim)`` jnp arrays — so the
  forward runs jitted on device (ref runs a torch model inside ``update``).
* The whole scoring half (normalize -> mask special tokens -> cosine matrix
  -> idf-weighted greedy match -> P/R/F1) is one jitted kernel over
  statically-padded ``(B, L)`` token buffers.
"""
import csv
import math
from collections import Counter, defaultdict
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utilities.imports import _TRANSFORMERS_AVAILABLE
from metrics_tpu.utilities.prints import rank_zero_info, rank_zero_warn

Array = jax.Array

_DEFAULT_MODEL = "roberta-large"


def _process_attention_mask_for_special_tokens(attention_mask: Array) -> Array:
    """Zero out [CLS] (first) and [SEP] (last non-pad) positions."""
    mask = attention_mask.at[:, 0].set(0)
    sep_pos = jnp.argmax(jnp.cumsum(attention_mask - 0.1, axis=-1), axis=-1)
    return mask.at[jnp.arange(mask.shape[0]), sep_pos].set(0)


def _compute_tokens_idf(input_ids: np.ndarray) -> Dict[int, float]:
    """Token IDF over a corpus: log((N+1) / (df+1)); default log(N+1)."""
    num_sentences = len(input_ids)
    counter: Counter = Counter()
    for row in input_ids:
        counter.update(set(row.tolist()))
    idf: Dict[int, float] = defaultdict(lambda: math.log(num_sentences + 1))
    idf.update({tok: math.log((num_sentences + 1) / (df + 1)) for tok, df in counter.items()})
    return idf


def _idf_matrix(input_ids: np.ndarray, tokens_idf: Dict[int, float]) -> np.ndarray:
    lookup = np.vectorize(lambda t: tokens_idf[int(t)])
    return lookup(input_ids).astype(np.float32)


@partial(jax.jit, static_argnames=("idf",))
def _bert_score_kernel(
    preds_emb: Array,
    preds_mask: Array,
    preds_idf: Array,
    target_emb: Array,
    target_mask: Array,
    target_idf: Array,
    idf: bool = False,
) -> Tuple[Array, Array, Array]:
    """Greedy cosine matching -> per-sentence (precision, recall, f1).

    Shapes: ``*_emb (B, S, D)``, ``*_mask/(idf) (B, S)``. Embeddings at
    masked positions are zeroed so they never win a max. (The ``all_layers``
    path loops this kernel per layer — one layer's ``(B, S, S)`` similarity
    on device at a time, never an ``L``-fold blowup.)
    """
    preds_mask = _process_attention_mask_for_special_tokens(preds_mask)
    target_mask = _process_attention_mask_for_special_tokens(target_mask)

    def _prep(emb, mask, idf_w):
        emb = emb / jnp.maximum(jnp.linalg.norm(emb, axis=-1, keepdims=True), 1e-12)
        emb = emb * mask[..., None]
        weight = idf_w * mask if idf else mask.astype(emb.dtype)
        weight = weight / jnp.maximum(weight.sum(-1, keepdims=True), 1e-12)
        return emb, weight

    preds_emb, preds_w = _prep(preds_emb, preds_mask, preds_idf)
    target_emb, target_w = _prep(target_emb, target_mask, target_idf)

    cos_sim = jnp.einsum("bpd, brd -> bpr", preds_emb, target_emb, precision="float32")
    precision = (cos_sim.max(axis=2) * preds_w).sum(-1)
    recall = (cos_sim.max(axis=1) * target_w).sum(-1)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    f1 = jnp.where(precision + recall > 0, f1, 0.0)
    return precision, recall, f1


def _default_forward(
    model: Any, input_ids: Array, attention_mask: Array, num_layers: Optional[int], all_layers: bool = False
) -> Array:
    """Forward through a transformers Flax model, picking hidden layer(s)."""
    out = model(input_ids=input_ids, attention_mask=attention_mask, output_hidden_states=True)
    if all_layers:
        # every hidden state incl. the embedding layer, on a layer axis
        # (reference functional/text/bert.py:304-305)
        return jnp.stack([jnp.asarray(h) for h in out.hidden_states], axis=1)
    return jnp.asarray(out.hidden_states[num_layers if num_layers is not None else -1])


def _get_embeddings(
    input_ids: np.ndarray,
    attention_mask: np.ndarray,
    model: Any,
    batch_size: int,
    num_layers: Optional[int],
    user_forward_fn: Optional[Callable],
    all_layers: bool = False,
    verbose: bool = False,
) -> Array:
    """Host batching loop around the (jitted) encoder forward."""
    if all_layers and user_forward_fn is not None:
        raise ValueError("The option `all_layers=True` can be used only with default `transformers` models.")
    chunks = []
    n_batches = -(-len(input_ids) // batch_size) if len(input_ids) else 0
    for bi, start in enumerate(range(0, len(input_ids), batch_size)):
        if verbose:
            rank_zero_info(f"bert_score embeddings: batch {bi + 1}/{n_batches}")
        ids = jnp.asarray(input_ids[start : start + batch_size])
        mask = jnp.asarray(attention_mask[start : start + batch_size])
        if user_forward_fn is not None:
            out = user_forward_fn(model, {"input_ids": ids, "attention_mask": mask})
            if out.ndim != 3 or out.shape[:2] != ids.shape[:2]:
                raise ValueError(
                    "The model output must be a jnp array of shape [batch_size, seq_len, model_dim], "
                    f"i.e. [{ids.shape[0]}, {ids.shape[1]}, model_dim], but got {out.shape}."
                )
        else:
            out = _default_forward(model, ids, mask, num_layers, all_layers)
        # all_layers: stash each (b, L, S, D) chunk in HOST memory — the
        # reference does the same (embeddings_list.append(out.cpu()),
        # bert.py:312) — so device memory never holds the L-fold corpus
        chunks.append(np.asarray(out) if all_layers else out)
    if not chunks:
        return jnp.zeros((0, 0, 0))
    return np.concatenate(chunks) if all_layers else jnp.concatenate(chunks)


def _load_tokenizer_and_model(model_name_or_path: str) -> Tuple[Any, Any]:
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`bert_score` with default models requires the `transformers` package; "
            "otherwise pass your own `model`, `user_tokenizer` and `user_forward_fn`."
        )
    from transformers import AutoTokenizer, FlaxAutoModel

    tokenizer = AutoTokenizer.from_pretrained(model_name_or_path)
    model = FlaxAutoModel.from_pretrained(model_name_or_path)
    return tokenizer, model


def _tokenize(tokenizer: Any, text: List[str], max_length: int, own_tokenizer: bool) -> Dict[str, np.ndarray]:
    if own_tokenizer:
        data = tokenizer(text, max_length)
    else:
        data = tokenizer(text, padding="max_length", max_length=max_length, truncation=True, return_tensors="np")
    return {"input_ids": np.asarray(data["input_ids"]), "attention_mask": np.asarray(data["attention_mask"])}


def _read_csv_baseline(baseline_path: str) -> Array:
    with open(baseline_path) as fname:
        rows = [[float(x) for x in row] for i, row in enumerate(csv.reader(fname)) if i > 0]
    return jnp.asarray(rows)[:, 1:]


def _rescale_with_baseline(
    precision: Array, recall: Array, f1: Array, baseline: Array, num_layers: Optional[int], all_layers: bool = False
) -> Tuple[Array, Array, Array]:
    """(x - b) / (1 - b) per metric, using the requested layer's baseline row.

    With ``all_layers`` the scores carry a leading layer axis and each layer
    rescales against its own baseline row (reference ``bert.py:425-431``).
    """
    if all_layers:
        n_layers = precision.shape[0]
        if baseline.shape[0] != n_layers:
            # a row-count mismatch in either direction means the csv belongs
            # to a different model — rescaling against it is silently wrong
            raise ValueError(
                f"The baseline csv has {baseline.shape[0]} rows but the model produced "
                f"{n_layers} hidden layers; an `all_layers` rescale needs exactly one row per layer."
            )
        rows = baseline  # (L, 3)
        p = (precision - rows[:, 0:1]) / (1 - rows[:, 0:1])
        r = (recall - rows[:, 1:2]) / (1 - rows[:, 1:2])
        f = (f1 - rows[:, 2:3]) / (1 - rows[:, 2:3])
        return p, r, f
    scale = baseline[num_layers if num_layers is not None else -1]
    stack = jnp.stack([precision, recall, f1], axis=-1)
    stack = (stack - scale) / (1 - scale)
    return stack[..., 0], stack[..., 1], stack[..., 2]


def bert_score(
    preds: Union[List[str], Dict[str, np.ndarray]],
    target: Union[List[str], Dict[str, np.ndarray]],
    model_name_or_path: Optional[str] = None,
    num_layers: Optional[int] = None,
    model: Optional[Any] = None,
    user_tokenizer: Any = None,
    user_forward_fn: Optional[Callable] = None,
    verbose: bool = False,
    idf: bool = False,
    device: Optional[Any] = None,
    max_length: int = 512,
    batch_size: int = 64,
    num_threads: int = 4,
    return_hash: bool = False,
    lang: str = "en",
    rescale_with_baseline: bool = False,
    baseline_path: Optional[str] = None,
    baseline_url: Optional[str] = None,
    all_layers: bool = False,
) -> Dict[str, Union[List[float], str]]:
    """BERTScore: greedy contextual-embedding matching by cosine similarity.

    ``preds``/``target`` are raw sentences (tokenized here) or pre-tokenized
    ``{"input_ids", "attention_mask"}`` dicts. Returns per-sentence
    precision/recall/f1 lists (API parity with the reference); with
    ``all_layers`` each entry is the per-layer list of scores.

    ``device`` and ``num_threads`` are accepted for API parity and ignored:
    JAX owns device placement, and there is no dataloader thread pool.
    """
    if device is not None:
        rank_zero_warn("`device` is ignored: JAX places the encoder on the default device.")
    if model is None and model_name_or_path is None:
        rank_zero_warn(
            f"The argument `model_name_or_path` was not specified while it is required when the default "
            f"`transformers` model is used. It will use the default recommended model - {_DEFAULT_MODEL!r}."
        )
        model_name_or_path = _DEFAULT_MODEL

    # empty corpus: nothing to tokenize or embed (HF fast tokenizers raise on
    # an empty batch, and the all_layers stack would trip on a 0-width axis);
    # the count check must come first so a one-sided empty input gets the
    # real error, not an opaque tokenizer crash
    n_preds = len(preds["input_ids"]) if isinstance(preds, dict) else len(preds)
    n_target = len(target["input_ids"]) if isinstance(target, dict) else len(target)
    if n_preds != n_target:
        raise ValueError("Number of predicted and reference sentences must be the same!")
    if n_preds == 0 and n_target == 0:
        output: Dict[str, Union[List[float], str]] = {"precision": [], "recall": [], "f1": []}
        if return_hash:
            output["hash"] = f"{model_name_or_path}_L{num_layers}{'_idf' if idf else '_no-idf'}"
        return output

    if model is None:
        tokenizer, model = _load_tokenizer_and_model(model_name_or_path)
    else:
        tokenizer = user_tokenizer
        if tokenizer is None and not isinstance(preds, dict):
            raise ValueError("A `user_tokenizer` must be provided with a user `model` and raw-text inputs.")

    own_tokenizer = user_tokenizer is not None
    if isinstance(preds, dict):
        preds_tok = {"input_ids": np.asarray(preds["input_ids"]), "attention_mask": np.asarray(preds["attention_mask"])}
    else:
        preds_tok = _tokenize(tokenizer, list(preds), max_length, own_tokenizer)
    if isinstance(target, dict):
        target_tok = {
            "input_ids": np.asarray(target["input_ids"]),
            "attention_mask": np.asarray(target["attention_mask"]),
        }
    else:
        target_tok = _tokenize(tokenizer, list(target), max_length, own_tokenizer)

    # IDF weights are computed on the reference corpus (bert_score convention)
    if idf:
        tokens_idf = _compute_tokens_idf(target_tok["input_ids"])
        preds_idf = _idf_matrix(preds_tok["input_ids"], tokens_idf)
        target_idf = _idf_matrix(target_tok["input_ids"], tokens_idf)
    else:
        preds_idf = np.ones_like(preds_tok["input_ids"], dtype=np.float32)
        target_idf = np.ones_like(target_tok["input_ids"], dtype=np.float32)

    preds_emb = _get_embeddings(
        preds_tok["input_ids"], preds_tok["attention_mask"], model, batch_size, num_layers, user_forward_fn,
        all_layers=all_layers, verbose=verbose,
    )
    target_emb = _get_embeddings(
        target_tok["input_ids"], target_tok["attention_mask"], model, batch_size, num_layers, user_forward_fn,
        all_layers=all_layers, verbose=verbose,
    )

    preds_mask_j = jnp.asarray(preds_tok["attention_mask"], dtype=jnp.float32)
    preds_idf_j = jnp.asarray(preds_idf)
    target_mask_j = jnp.asarray(target_tok["attention_mask"], dtype=jnp.float32)
    target_idf_j = jnp.asarray(target_idf)
    if all_layers:
        # one layer on device at a time; outputs (L, B) like the reference's
        # transpose (functional/text/bert.py:330)
        per_layer = [
            _bert_score_kernel(
                jnp.asarray(preds_emb[:, l]), preds_mask_j, preds_idf_j,
                jnp.asarray(target_emb[:, l]), target_mask_j, target_idf_j, idf=idf,
            )
            for l in range(preds_emb.shape[1])
        ]
        precision = jnp.stack([p for p, _, _ in per_layer])
        recall = jnp.stack([r for _, r, _ in per_layer])
        f1 = jnp.stack([f for _, _, f in per_layer])
    else:
        precision, recall, f1 = _bert_score_kernel(
            preds_emb, preds_mask_j, preds_idf_j, target_emb, target_mask_j, target_idf_j, idf=idf
        )

    if rescale_with_baseline:
        if baseline_path is None:
            # The reference resolves a baseline from (lang, model_name_or_path)
            # or `baseline_url` by downloading it; this build is offline-only,
            # so an explicit local csv is required for rescaling to take effect.
            rank_zero_warn(
                f"`rescale_with_baseline` requires a local `baseline_path` (remote baseline lookup by "
                f"lang={lang!r}/model{'/baseline_url' if baseline_url else ''} is not supported); "
                "returning unrescaled scores."
            )
        else:
            baseline = _read_csv_baseline(baseline_path)
            precision, recall, f1 = _rescale_with_baseline(precision, recall, f1, baseline, num_layers, all_layers)

    output: Dict[str, Union[List[float], str]] = {
        "precision": np.asarray(precision).tolist(),
        "recall": np.asarray(recall).tolist(),
        "f1": np.asarray(f1).tolist(),
    }
    if return_hash:
        output["hash"] = f"{model_name_or_path}_L{num_layers}{'_idf' if idf else '_no-idf'}"
    return output
