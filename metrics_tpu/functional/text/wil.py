"""Word information lost.

Behavioral equivalent of reference ``torchmetrics/functional/text/wil.py``
(``_wil_update`` :23, ``_wil_compute`` :56, ``word_information_lost`` :70).

Redesign note: the reference accumulates ``edit_distance - max_total`` — the
*negative* hit count — and relies on the sign cancelling when squared in
compute (``wil.py:66``: ``1 - (E/tt)*(E/pt)``). Here the state is the
positive hit count ``H = sum_i max(|t_i|, |p_i|) - d_i`` and

    WIP = (H / target_total) * (H / preds_total),   WIL = 1 - WIP

which is the same value with a sum-reducible, sign-honest state.
"""
from typing import List, Tuple, Union

import jax

import numpy as np

from metrics_tpu.functional.text.helper import _corpus_edit_stats, _normalize_corpus, _put_scalars

Array = jax.Array


def _word_info_update(
    preds: Union[str, List[str]], target: Union[str, List[str]]
) -> Tuple[Array, Array, Array]:
    """Host-side: corpus -> (hits, total target words, total pred words)."""
    preds, target = _normalize_corpus(preds, target)
    dists, cnt_p, cnt_t = _corpus_edit_stats(preds, target, "words")
    hits = (np.maximum(cnt_p, cnt_t) - dists).sum()
    return _put_scalars(hits, cnt_t.sum(), cnt_p.sum())


def _wil_compute(hits: Array, target_total: Array, preds_total: Array) -> Array:
    return 1 - (hits / target_total) * (hits / preds_total)


def word_information_lost(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word information lost; 0 is a perfect score.

    Example:
        >>> from metrics_tpu.functional import word_information_lost
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> word_information_lost(preds, target)
        Array(0.6527778, dtype=float32)
    """
    hits, target_total, preds_total = _word_info_update(preds, target)
    return _wil_compute(hits, target_total, preds_total)
