"""ROUGE score.

Behavioral equivalent of reference ``torchmetrics/functional/text/rouge.py``
(``_rouge_score_update`` :260, ``_rouge_score_compute`` :373, ``rouge_score``
:390), following the official ROUGE definitions (Lin 2004) and the
google-research ``rouge_scorer`` behavior it mirrors: rouge1..9 n-gram F,
rougeL (sentence LCS), rougeLsum (summary-level union-LCS over sentences,
nltk sentence splitting).

Redesign notes: the LCS DP rows are numpy-vectorized via the running-max
identity ``cur = maximum.accumulate(max(prev, shift(prev) + match))`` (valid
because LCS tables are monotone, so the dropped candidates are dominated).
Unlike the reference, nltk sentence-splitting is only invoked when a
``Lsum`` key is actually requested.
"""
import re
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.helper import _encode_tokens
from metrics_tpu.utilities.imports import _NLTK_AVAILABLE

Array = jax.Array

ALLOWED_ROUGE_KEYS: Dict[str, Union[int, str]] = {
    "rouge1": 1,
    "rouge2": 2,
    "rouge3": 3,
    "rouge4": 4,
    "rouge5": 5,
    "rouge6": 6,
    "rouge7": 7,
    "rouge8": 8,
    "rouge9": 9,
    "rougeL": "L",
    "rougeLsum": "Lsum",
}
ALLOWED_ACCUMULATE_VALUES = ("avg", "best")


def _split_sentence(x: str) -> Sequence[str]:
    """Sentence split for summary-level rougeLsum.

    Uses nltk's punkt tokenizer when its data is available; otherwise falls
    back to newline + sentence-punctuation boundaries (the newline split is
    what the google ``rouge_scorer`` package uses for rougeLsum).
    """
    x = re.sub("<n>", "", x)  # remove pegasus newline char
    if _NLTK_AVAILABLE:
        import nltk

        try:
            return nltk.sent_tokenize(x)
        except LookupError:
            pass
    return [s for s in re.split(r"(?<=[.!?])\s+|\n", x) if s.strip()]


def _compute_metrics(hits_or_lcs: int, pred_len: int, target_len: int) -> Dict[str, float]:
    """(hits, |pred|, |target|) -> precision/recall/fmeasure dict."""
    precision = hits_or_lcs / pred_len
    recall = hits_or_lcs / target_len
    if precision == recall == 0.0:
        return dict(precision=0.0, recall=0.0, fmeasure=0.0)
    return dict(precision=precision, recall=recall, fmeasure=2 * precision * recall / (precision + recall))


def _lcs_table(pred: Sequence[str], target: Sequence[str]) -> np.ndarray:
    """Full LCS DP table, rows vectorized; shape (|target|+1, |pred|+1)."""
    p, t = _encode_tokens(pred, target)
    table = np.zeros((len(t) + 1, len(p) + 1), dtype=np.int64)
    for i in range(1, len(t) + 1):
        prev = table[i - 1]
        diag = prev[:-1] + (p == t[i - 1])
        table[i, 1:] = np.maximum.accumulate(np.maximum(prev[1:], diag))
    return table


def _lcs_length(pred: Sequence[str], target: Sequence[str]) -> int:
    return int(_lcs_table(pred, target)[-1, -1])


def _backtracked_lcs(lcs_table: np.ndarray, pred: Sequence[str], target: Sequence[str]) -> List[int]:
    """Indices (into target) of one longest common subsequence."""
    i, j = len(pred), len(target)
    out: List[int] = []
    while i > 0 and j > 0:
        if pred[i - 1] == target[j - 1]:
            out.append(j - 1)
            i -= 1
            j -= 1
        elif lcs_table[j][i - 1] > lcs_table[j - 1][i]:
            i -= 1
        else:
            j -= 1
    return out[::-1]


def _union_lcs(pred_sentences: Sequence[Sequence[str]], target_sentence: Sequence[str]) -> List[str]:
    """Union of per-pred-sentence LCS hits against one target sentence."""
    indices = set()
    for pred in pred_sentences:
        indices.update(_backtracked_lcs(_lcs_table(pred, target_sentence), pred, target_sentence))
    return [target_sentence[i] for i in sorted(indices)]


def _normalize_and_tokenize_text(
    text: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Sequence[str]:
    """Lowercase-alnum normalize (or custom), split, optionally Porter-stem."""
    text = normalizer(text) if callable(normalizer) else re.sub(r"[^a-z0-9]+", " ", text.lower())
    tokens = tokenizer(text) if callable(tokenizer) else re.split(r"\s+", text)
    if stemmer:
        # only stem words longer than 3 chars (rouge_scorer convention)
        tokens = [stemmer.stem(x) if len(x) > 3 else x for x in tokens]
    return [x for x in tokens if isinstance(x, str) and len(x) > 0]


def _rouge_n_score(pred: Sequence[str], target: Sequence[str], n_gram: int) -> Dict[str, float]:
    """N-gram overlap precision/recall/F."""

    def _ngrams(tokens: Sequence[str], n: int) -> Counter:
        return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))

    pred_ngrams, target_ngrams = _ngrams(pred, n_gram), _ngrams(target, n_gram)
    pred_len, target_len = sum(pred_ngrams.values()), sum(target_ngrams.values())
    if 0 in (pred_len, target_len):
        return dict(precision=0.0, recall=0.0, fmeasure=0.0)
    hits = sum((pred_ngrams & target_ngrams).values())
    return _compute_metrics(hits, pred_len, target_len)


def _rouge_l_score(pred: Sequence[str], target: Sequence[str]) -> Dict[str, float]:
    """Sentence-level LCS precision/recall/F."""
    if 0 in (len(pred), len(target)):
        return dict(precision=0.0, recall=0.0, fmeasure=0.0)
    return _compute_metrics(_lcs_length(pred, target), len(pred), len(target))


def _rouge_lsum_score(pred: Sequence[Sequence[str]], target: Sequence[Sequence[str]]) -> Dict[str, float]:
    """Summary-level union-LCS precision/recall/F (google rouge_scorer semantics)."""
    pred_len = sum(map(len, pred))
    target_len = sum(map(len, target))
    if 0 in (pred_len, target_len):
        return dict(precision=0.0, recall=0.0, fmeasure=0.0)

    pred_counts: Counter = Counter()
    target_counts: Counter = Counter()
    for sentence in pred:
        pred_counts.update(sentence)
    for sentence in target:
        target_counts.update(sentence)

    hits = 0
    for tgt in target:
        for token in _union_lcs(pred, tgt):
            if pred_counts[token] > 0 and target_counts[token] > 0:
                hits += 1
                pred_counts[token] -= 1
                target_counts[token] -= 1
    return _compute_metrics(hits, pred_len, target_len)


def _rouge_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    rouge_keys_values: List[Union[int, str]],
    accumulate: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Dict[Union[int, str], List[Dict[str, float]]]:
    """Per-sample scores for every requested key.

    Multi-reference policy: ``best`` keeps the reference with the highest
    first-key fmeasure; ``avg`` averages each stat over references.
    """
    results: Dict[Union[int, str], List[Dict[str, float]]] = {key: [] for key in rouge_keys_values}
    want_lsum = "Lsum" in rouge_keys_values

    for pred_raw, targets_raw in zip(preds, target):
        pred = _normalize_and_tokenize_text(pred_raw, stemmer, normalizer, tokenizer)
        pred_lsum = (
            [
                _normalize_and_tokenize_text(s, stemmer, normalizer, tokenizer)
                for s in _split_sentence(pred_raw)
            ]
            if want_lsum
            else []
        )

        per_ref: List[Dict[Union[int, str], Dict[str, float]]] = []
        for target_raw in targets_raw:
            tgt = _normalize_and_tokenize_text(target_raw, stemmer, normalizer, tokenizer)
            scores: Dict[Union[int, str], Dict[str, float]] = {}
            for key in rouge_keys_values:
                if isinstance(key, int):
                    scores[key] = _rouge_n_score(pred, tgt, key)
                elif key == "L":
                    scores[key] = _rouge_l_score(pred, tgt)
                else:  # Lsum
                    tgt_lsum = [
                        _normalize_and_tokenize_text(s, stemmer, normalizer, tokenizer)
                        for s in _split_sentence(target_raw)
                    ]
                    scores[key] = _rouge_lsum_score(pred_lsum, tgt_lsum)
            per_ref.append(scores)

        if accumulate == "best":
            first_key = rouge_keys_values[0]
            best_idx = int(np.argmax([s[first_key]["fmeasure"] for s in per_ref]))
            for key in rouge_keys_values:
                results[key].append(per_ref[best_idx][key])
        else:  # avg
            for key in rouge_keys_values:
                results[key].append(
                    {
                        stat: float(np.mean([s[key][stat] for s in per_ref]))
                        for stat in ("precision", "recall", "fmeasure")
                    }
                )
    return results


def _rouge_score_compute(sentence_results: Dict[str, List[Array]]) -> Dict[str, Array]:
    """Mean over accumulated per-sample stats."""
    return {key: jnp.mean(jnp.concatenate(scores)) for key, scores in sentence_results.items() if scores}


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    accumulate: str = "best",
    use_stemmer: bool = False,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
) -> Dict[str, Array]:
    """ROUGE score (rouge1..9, rougeL, rougeLsum).

    Example:
        >>> from metrics_tpu.functional import rouge_score
        >>> preds = "My name is John"
        >>> target = "Is your name John"
        >>> rouge = rouge_score(preds, target, rouge_keys="rouge1")
        >>> round(float(rouge["rouge1_fmeasure"]), 4)
        0.75
    """
    if use_stemmer and not _NLTK_AVAILABLE:
        raise ModuleNotFoundError("Stemmer requires that `nltk` is installed. Use `pip install nltk`.")
    stemmer = None
    if use_stemmer:
        import nltk

        stemmer = nltk.stem.porter.PorterStemmer()

    if accumulate not in ALLOWED_ACCUMULATE_VALUES:
        raise ValueError(
            f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
        )
    if isinstance(rouge_keys, str):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS:
            raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}")
    rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]

    if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
        target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [[target]]

    sentence_results = _rouge_score_update(
        preds, target, rouge_keys_values, accumulate, stemmer, normalizer, tokenizer
    )
    output: Dict[str, Array] = {}
    for key, scores in sentence_results.items():
        for stat in ("precision", "recall", "fmeasure"):
            output[f"rouge{key}_{stat}"] = jnp.asarray(
                np.mean([s[stat] for s in scores]) if scores else 0.0, dtype=jnp.float32
            )
    return output
