"""BLEU score.

Behavioral equivalent of reference ``torchmetrics/functional/text/bleu.py``
(``_bleu_score_update`` :59, ``_bleu_score_compute`` :105, ``bleu_score``
:146). N-gram counting runs host-side (Counter over token tuples); the
sufficient statistics are two ``(n_gram,)`` clipped-count vectors plus two
scalar lengths — all sum-reducible — and the compute half is pure jnp
(branch-free ``where`` masking instead of the reference's Python
``if min(numerator) == 0`` check) so it stays jit-traceable.
"""
from collections import Counter
from typing import Callable, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.helper import _put_all

Array = jax.Array


def _count_ngram(tokens: Sequence[str], n_gram: int) -> Counter:
    """Count all 1..n_gram grams of a token sequence."""
    counts: Counter = Counter()
    for n in range(1, n_gram + 1):
        for i in range(len(tokens) - n + 1):
            counts[tuple(tokens[i : i + n])] += 1
    return counts


def _tokenize_fn(sentence: str) -> Sequence[str]:
    return sentence.split()


def _bleu_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn,
) -> Tuple[Array, Array, Array, Array]:
    """Host-side: corpus -> (numerator, denominator, preds_len, target_len).

    ``numerator[n-1]`` is the clipped n-gram match count; ``denominator`` the
    total hypothesis n-gram count; the effective reference length per sample
    is the closest-length reference (ref ``bleu.py:87-89``).
    """
    numerator = np.zeros(n_gram)
    denominator = np.zeros(n_gram)
    preds_len = 0.0
    target_len = 0.0

    for pred, targets in zip(preds, target):
        pred_tokens = tokenizer(pred) if pred else []
        target_tokens = [tokenizer(t) if t else [] for t in targets]
        preds_len += len(pred_tokens)
        len_diffs = [abs(len(pred_tokens) - len(t)) for t in target_tokens]
        target_len += len(target_tokens[len_diffs.index(min(len_diffs))])

        pred_counter = _count_ngram(pred_tokens, n_gram)
        target_counter: Counter = Counter()
        for t in target_tokens:
            target_counter |= _count_ngram(t, n_gram)
        clipped = pred_counter & target_counter

        for ngram, count in clipped.items():
            numerator[len(ngram) - 1] += count
        for ngram, count in pred_counter.items():
            denominator[len(ngram) - 1] += count

    return _put_all(
        np.asarray(numerator, dtype=np.float32),
        np.asarray(denominator, dtype=np.float32),
        np.float32(preds_len),
        np.float32(target_len),
    )


def _bleu_score_compute(
    preds_len: Array,
    target_len: Array,
    numerator: Array,
    denominator: Array,
    n_gram: int = 4,
    smooth: bool = False,
    weights: Sequence[float] = None,
) -> Array:
    """Pure-jnp compute: geometric mean of modified precisions x brevity penalty."""
    if weights is None:
        weights = [1.0 / n_gram] * n_gram
    w = jnp.asarray(weights, dtype=jnp.float32)

    if smooth:
        # add-one smoothing for orders > 1 (ref bleu.py:127-133)
        precision = (numerator + 1.0) / (denominator + 1.0)
        precision = precision.at[0].set(numerator[0] / denominator[0])
    else:
        precision = numerator / denominator

    log_precision = jnp.where(precision > 0, jnp.log(jnp.where(precision > 0, precision, 1.0)), 0.0)
    geometric_mean = jnp.exp(jnp.sum(w * log_precision))
    brevity_penalty = jnp.where(preds_len > target_len, 1.0, jnp.exp(1 - target_len / jnp.maximum(preds_len, 1e-16)))
    bleu = brevity_penalty * geometric_mean
    # any unmatched order zeroes the score (ref bleu.py:123-124)
    return jnp.where(jnp.min(numerator) == 0, 0.0, bleu)


def bleu_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
    weights: Sequence[float] = None,
) -> Array:
    """BLEU score of machine-translated text against one or more references.

    Example:
        >>> from metrics_tpu.functional import bleu_score
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> bleu_score(preds, target)
        Array(0.7598357, dtype=float32)
    """
    preds_ = [preds] if isinstance(preds, str) else preds
    target_ = [[t] if isinstance(t, str) else t for t in target]
    if len(preds_) != len(target_):
        raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    numerator, denominator, preds_len, target_len = _bleu_score_update(preds_, target_, n_gram)
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, smooth, weights)
