"""Word information preserved.

Behavioral equivalent of reference ``torchmetrics/functional/text/wip.py``
(``_wip_update`` :22, ``_wip_compute`` :55, ``word_information_preserved``
:69). Shares the hit-count update with WIL; see ``wil.py`` for the
sign-honest state redesign.
"""
from typing import List, Union

import jax

from metrics_tpu.functional.text.wil import _word_info_update

Array = jax.Array


def _wip_compute(hits: Array, target_total: Array, preds_total: Array) -> Array:
    return (hits / target_total) * (hits / preds_total)


def word_information_preserved(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Word information preserved; 1 is a perfect score.

    Example:
        >>> from metrics_tpu.functional import word_information_preserved
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> word_information_preserved(preds, target)
        Array(0.34722224, dtype=float32)
    """
    hits, target_total, preds_total = _word_info_update(preds, target)
    return _wip_compute(hits, target_total, preds_total)
