"""Char error rate.

Behavioral equivalent of reference ``torchmetrics/functional/text/cer.py``
(``_cer_update`` :23, ``_cer_compute`` :51, ``char_error_rate`` :63).
Characters (including spaces) are the edit-distance alphabet, matching the
reference's ``list(pred)`` tokenization (``cer.py:43-47``).
"""
from typing import List, Tuple, Union

import jax

from metrics_tpu.functional.text.helper import _corpus_edit_stats, _normalize_corpus, _put_scalars

Array = jax.Array


def _cer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Host-side: corpus -> (total char edit operations, total reference chars)."""
    preds, target = _normalize_corpus(preds, target)
    dists, _, cnt_t = _corpus_edit_stats(preds, target, "chars")
    return _put_scalars(dists.sum(), cnt_t.sum())


def _cer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def char_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Character error rate of transcriptions; 0 is a perfect score.

    Example:
        >>> from metrics_tpu.functional import char_error_rate
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> char_error_rate(preds=preds, target=target)
        Array(0.34146342, dtype=float32)
    """
    errors, total = _cer_update(preds, target)
    return _cer_compute(errors, total)
