"""Match error rate.

Behavioral equivalent of reference ``torchmetrics/functional/text/mer.py``
(``_mer_update`` :23, ``_mer_compute`` :53, ``match_error_rate`` :65).
Denominator is ``max(len(target), len(pred))`` per sample.
"""
from typing import List, Tuple, Union

import jax

import numpy as np

from metrics_tpu.functional.text.helper import _corpus_edit_stats, _normalize_corpus, _put_scalars

Array = jax.Array


def _mer_update(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Tuple[Array, Array]:
    """Host-side: corpus -> (total edit operations, total max-length words)."""
    preds, target = _normalize_corpus(preds, target)
    dists, cnt_p, cnt_t = _corpus_edit_stats(preds, target, "words")
    return _put_scalars(dists.sum(), np.maximum(cnt_p, cnt_t).sum())


def _mer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def match_error_rate(preds: Union[str, List[str]], target: Union[str, List[str]]) -> Array:
    """Match error rate of transcriptions; 0 is a perfect score.

    Example:
        >>> from metrics_tpu.functional import match_error_rate
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> match_error_rate(preds=preds, target=target)
        Array(0.44444445, dtype=float32)
    """
    errors, total = _mer_update(preds, target)
    return _mer_compute(errors, total)
