"""SQuAD exact-match / F1.

Behavioral equivalent of reference ``torchmetrics/functional/text/squad.py``
(``_normalize_text`` :41, ``_compute_f1_score`` :66, ``_squad_update`` :131,
``squad`` :197), following the official SQuAD v1.1 evaluation script
semantics (lowercase, strip punctuation/articles, token-level F1, max over
ground truths).
"""
import re
import string
from collections import Counter
from typing import Any, Dict, List, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.helper import _put_all

from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

SINGLE_PRED_TYPE = Dict[str, str]
PREDS_TYPE = Union[SINGLE_PRED_TYPE, List[SINGLE_PRED_TYPE]]
SINGLE_TARGET_TYPE = Dict[str, Any]
TARGETS_TYPE = Union[SINGLE_TARGET_TYPE, List[SINGLE_TARGET_TYPE]]

_ARTICLES_RE = re.compile(r"\b(a|an|the)\b")
_PUNCT_TABLE = str.maketrans("", "", string.punctuation)


def _normalize_text(s: str) -> str:
    """Lowercase, remove punctuation/articles, collapse whitespace."""
    return " ".join(_ARTICLES_RE.sub(" ", s.lower().translate(_PUNCT_TABLE)).split())


def _get_tokens(s: str) -> List[str]:
    return _normalize_text(s).split() if s else []


def _f1_score(prediction: str, ground_truth: str) -> float:
    """Token-overlap F1 between one prediction and one answer."""
    pred_tokens = _get_tokens(prediction)
    target_tokens = _get_tokens(ground_truth)
    if len(target_tokens) == 0 or len(pred_tokens) == 0:
        # a no-answer scores 1 only if both are no-answers
        return float(target_tokens == pred_tokens)
    num_same = sum((Counter(target_tokens) & Counter(pred_tokens)).values())
    if num_same == 0:
        return 0.0
    precision = num_same / len(pred_tokens)
    recall = num_same / len(target_tokens)
    return 2 * precision * recall / (precision + recall)


def _exact_match_score(prediction: str, ground_truth: str) -> float:
    return float(_normalize_text(prediction) == _normalize_text(ground_truth))


def _squad_input_check(
    preds: PREDS_TYPE, targets: TARGETS_TYPE
) -> Tuple[Dict[str, str], List[Dict[str, Any]]]:
    """Validate + convert inputs to {id: prediction} and SQuAD-format dataset."""
    if isinstance(preds, dict):
        preds = [preds]
    if isinstance(targets, dict):
        targets = [targets]
    for pred in preds:
        keys = pred.keys()
        if "prediction_text" not in keys or "id" not in keys:
            raise KeyError(
                "Expected keys in a single prediction are 'prediction_text' and 'id'. "
                "Please make sure that 'prediction' maps to both 'prediction_text' and 'id'."
            )
    for target in targets:
        keys = target.keys()
        if "answers" not in keys or "id" not in keys:
            raise KeyError(
                "Expected keys in a single target are 'answers' and 'id'. "
                "Please make sure that 'target' maps to both 'answers' and 'id'."
            )
        answers_keys = target["answers"].keys()
        if "text" not in answers_keys:
            raise KeyError(
                "Expected keys in a 'answers' are 'text'. "
                "Please make sure that 'answer' maps to 'text'."
            )

    preds_dict = {p["id"]: p["prediction_text"] for p in preds}
    _fn_answer = lambda tgt: {"answers": [{"text": txt} for txt in tgt["answers"]["text"]], "id": tgt["id"]}  # noqa: E731
    targets_dict = [{"paragraphs": [{"qas": [_fn_answer(t) for t in targets]}]}]
    return preds_dict, targets_dict


def _squad_update(
    preds: Dict[str, str],
    target: List[Dict[str, Any]],
) -> Tuple[Array, Array, Array]:
    """Host-side: (f1 sum, exact-match sum, count) over all questions."""
    f1 = 0.0
    exact_match = 0.0
    total = 0
    for article in target:
        for paragraph in article["paragraphs"]:
            for qa in paragraph["qas"]:
                total += 1
                if qa["id"] not in preds:
                    rank_zero_warn(f"Unanswered question {qa['id']} will receive score 0.")
                    continue
                ground_truths = [x["text"] for x in qa["answers"]]
                pred = preds[qa["id"]]
                exact_match += max(_exact_match_score(pred, t) for t in ground_truths)
                f1 += max(_f1_score(pred, t) for t in ground_truths)
    return _put_all(np.float32(f1), np.float32(exact_match), np.int32(total))


def _squad_compute(f1_score: Array, exact_match: Array, total: Array) -> Dict[str, Array]:
    """Scale sums to percentages."""
    total = jnp.asarray(total, dtype=jnp.float32)
    return {
        "exact_match": 100.0 * exact_match.astype(jnp.float32) / total,
        "f1": 100.0 * f1_score.astype(jnp.float32) / total,
    }


def squad(preds: PREDS_TYPE, target: TARGETS_TYPE) -> Dict[str, Array]:
    """SQuAD v1.1 exact-match and F1.

    Example:
        >>> from metrics_tpu.functional import squad
        >>> preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]
        >>> target = [{"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"}]
        >>> squad(preds, target)
        {'exact_match': Array(100., dtype=float32), 'f1': Array(100., dtype=float32)}
    """
    preds_dict, target_dict = _squad_input_check(preds, target)
    f1, exact_match, total = _squad_update(preds_dict, target_dict)
    return _squad_compute(f1, exact_match, total)
