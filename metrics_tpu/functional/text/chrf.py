"""chrF / chrF++ score.

Behavioral equivalent of reference ``torchmetrics/functional/text/chrf.py``
(``_chrf_score_update`` :375, ``_chrf_score_compute`` :484, ``chrf_score``
:523), following the published chrF algorithm (Popovic 2015/2017, and the
sacrebleu implementation it cites).

State redesign: the reference keeps ``4 + 2*(n_char_order+n_word_order)``
scalar tensors in per-order dicts. Here the sufficient statistics are six
**vectors** — matching/hyp-total/ref-total counts with shape
``(n_char_order,)`` and ``(n_word_order,)`` — each plainly sum-reducible, and
the F-score compute half is vectorized jnp over the order axis.
"""
import string
from collections import Counter, defaultdict
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.helper import _validate_inputs

Array = jax.Array

_EPS_SMOOTHING = 1e-16
_PUNCTUATIONS = set(string.punctuation)


def _get_characters(sentence: str, whitespace: bool) -> List[str]:
    """Character list; whitespace stripped unless ``whitespace=True``."""
    if whitespace:
        return list(sentence)
    return list(sentence.strip().replace(" ", ""))


def _get_words_and_punctuation(sentence: str) -> List[str]:
    """Whitespace-split with leading/trailing punctuation split into its own token."""
    out: List[str] = []
    for word in sentence.strip().split():
        if len(word) > 1 and word[-1] in _PUNCTUATIONS:
            out.extend([word[:-1], word[-1]])
        elif len(word) > 1 and word[0] in _PUNCTUATIONS:
            out.extend([word[0], word[1:]])
        else:
            out.append(word)
    return out


def _ngram_counts(tokens: List[str], max_order: int) -> Dict[int, Counter]:
    """Per-order n-gram Counters for orders 1..max_order."""
    counts: Dict[int, Counter] = defaultdict(Counter)
    for n in range(1, max_order + 1):
        for i in range(len(tokens) - n + 1):
            counts[n][tuple(tokens[i : i + n])] += 1
    return counts


def _totals(counts: Dict[int, Counter], max_order: int) -> np.ndarray:
    return np.asarray([sum(counts[n].values()) for n in range(1, max_order + 1)], dtype=np.float64)


def _matches(hyp: Dict[int, Counter], ref: Dict[int, Counter], max_order: int) -> np.ndarray:
    return np.asarray(
        [sum((hyp[n] & ref[n]).values()) for n in range(1, max_order + 1)], dtype=np.float64
    )


def _sentence_stats(
    sentence: str, n_char_order: int, n_word_order: int, lowercase: bool, whitespace: bool
) -> Tuple[Dict[int, Counter], Dict[int, Counter], np.ndarray, np.ndarray]:
    if lowercase:
        sentence = sentence.lower()
    char_counts = _ngram_counts(_get_characters(sentence, whitespace), n_char_order)
    word_counts = _ngram_counts(_get_words_and_punctuation(sentence), n_word_order)
    return char_counts, word_counts, _totals(char_counts, n_char_order), _totals(word_counts, n_word_order)


def _fscore_from_stats(
    matching_char: np.ndarray,
    matching_word: np.ndarray,
    hyp_char: np.ndarray,
    hyp_word: np.ndarray,
    ref_char: np.ndarray,
    ref_word: np.ndarray,
    n_order: float,
    beta: float,
) -> float:
    """Order-averaged F-beta over char + word n-gram orders (numpy host path)."""
    matching = np.concatenate([matching_char, matching_word])
    hyp = np.concatenate([hyp_char, hyp_word])
    ref = np.concatenate([ref_char, ref_word])
    precision = np.where(hyp > 0, matching / np.maximum(hyp, 1), 0.0)
    recall = np.where(ref > 0, matching / np.maximum(ref, 1), 0.0)
    denom = np.maximum(beta**2 * precision + recall, _EPS_SMOOTHING)
    f_score = (1 + beta**2) * precision * recall / denom
    return float(f_score.sum() / n_order)


def _chrf_score_update(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    n_char_order: int,
    n_word_order: int,
    beta: float,
    lowercase: bool,
    whitespace: bool,
    sentence_scores: Optional[List[Array]] = None,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Host-side: corpus -> six per-order count vectors.

    Multi-reference policy (ref ``chrf.py:289-373``): the reference whose
    sentence-level F-score is highest contributes its matching/total counts.
    """
    preds, target = _validate_inputs(preds, target)
    n_order = float(n_char_order + n_word_order)

    tot_match_char = np.zeros(n_char_order)
    tot_match_word = np.zeros(n_word_order)
    tot_hyp_char = np.zeros(n_char_order)
    tot_hyp_word = np.zeros(n_word_order)
    tot_ref_char = np.zeros(n_char_order)
    tot_ref_word = np.zeros(n_word_order)

    for pred, refs in zip(preds, target):
        h_char_counts, h_word_counts, h_char, h_word = _sentence_stats(
            pred, n_char_order, n_word_order, lowercase, whitespace
        )

        # Best-reference selection per sacrebleu's _compute_segment_statistics:
        # start below any reachable F so the first reference's stats are always
        # kept, and zero the hypothesis count at orders where the chosen
        # reference has no n-grams ("don't count hits if no reference exists").
        best_f = -1.0
        best = None
        for ref in refs:
            r_char_counts, r_word_counts, r_char, r_word = _sentence_stats(
                ref, n_char_order, n_word_order, lowercase, whitespace
            )
            m_char = _matches(h_char_counts, r_char_counts, n_char_order)
            m_word = _matches(h_word_counts, r_word_counts, n_word_order)
            eff_h_char = np.where(r_char > 0, h_char, 0.0)
            eff_h_word = np.where(r_word > 0, h_word, 0.0)
            f = _fscore_from_stats(m_char, m_word, eff_h_char, eff_h_word, r_char, r_word, n_order, beta)
            if f > best_f:
                best_f = f
                best = (m_char, m_word, eff_h_char, eff_h_word, r_char, r_word)
        if best is None:  # no references for this sample
            continue
        tot_match_char += best[0]
        tot_match_word += best[1]
        tot_hyp_char += best[2]
        tot_hyp_word += best[3]
        tot_ref_char += best[4]
        tot_ref_word += best[5]
        if sentence_scores is not None:
            sentence_scores.append(jnp.asarray([best_f], dtype=jnp.float32))

    as_jnp = lambda a: jnp.asarray(a, dtype=jnp.float32)  # noqa: E731
    return (
        as_jnp(tot_match_char),
        as_jnp(tot_match_word),
        as_jnp(tot_hyp_char),
        as_jnp(tot_hyp_word),
        as_jnp(tot_ref_char),
        as_jnp(tot_ref_word),
    )


def _chrf_score_compute(
    matching_char: Array,
    matching_word: Array,
    hyp_char: Array,
    hyp_word: Array,
    ref_char: Array,
    ref_word: Array,
    beta: float,
) -> Array:
    """Pure-jnp corpus-level F-beta, vectorized over the order axis."""
    matching = jnp.concatenate([matching_char, matching_word])
    hyp = jnp.concatenate([hyp_char, hyp_word])
    ref = jnp.concatenate([ref_char, ref_word])
    precision = jnp.where(hyp > 0, matching / jnp.maximum(hyp, 1), 0.0)
    recall = jnp.where(ref > 0, matching / jnp.maximum(ref, 1), 0.0)
    denom = jnp.maximum(beta**2 * precision + recall, _EPS_SMOOTHING)
    f_score = (1 + beta**2) * precision * recall / denom
    return jnp.sum(f_score) / matching.shape[0]


def chrf_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_char_order: int = 6,
    n_word_order: int = 2,
    beta: float = 2.0,
    lowercase: bool = False,
    whitespace: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """chrF (``n_word_order=0``) / chrF++ (``n_word_order=2``) score.

    Example:
        >>> from metrics_tpu.functional import chrf_score
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> chrf_score(preds, target)
        Array(0.8640465, dtype=float32)
    """
    if not isinstance(n_char_order, int) or n_char_order < 1:
        raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
    if not isinstance(n_word_order, int) or n_word_order < 0:
        raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
    if beta < 0:
        raise ValueError("Expected argument `beta` to be greater than 0.")
    sentence_scores: Optional[List[Array]] = [] if return_sentence_level_score else None
    stats = _chrf_score_update(
        preds, target, n_char_order, n_word_order, beta, lowercase, whitespace, sentence_scores
    )
    score = _chrf_score_compute(*stats, beta)
    if sentence_scores is not None:
        return score, jnp.concatenate(sentence_scores)
    return score
