"""Functional text metrics.

Text metrics split host/device work the same way the reference does
implicitly (``torchmetrics/functional/text/``): tokenization and
string-matching run host-side (strings are not XLA types), and only the
sufficient statistics live on device as jnp scalars/vectors, so the
accumulate + distributed-sync path is identical to every other domain.
"""
from metrics_tpu.functional.text.bert import bert_score  # noqa: F401
from metrics_tpu.functional.text.bleu import bleu_score  # noqa: F401
from metrics_tpu.functional.text.cer import char_error_rate  # noqa: F401
from metrics_tpu.functional.text.chrf import chrf_score  # noqa: F401
from metrics_tpu.functional.text.eed import extended_edit_distance  # noqa: F401
from metrics_tpu.functional.text.mer import match_error_rate  # noqa: F401
from metrics_tpu.functional.text.rouge import rouge_score  # noqa: F401
from metrics_tpu.functional.text.sacre_bleu import sacre_bleu_score  # noqa: F401
from metrics_tpu.functional.text.squad import squad  # noqa: F401
from metrics_tpu.functional.text.ter import translation_edit_rate  # noqa: F401
from metrics_tpu.functional.text.wer import word_error_rate  # noqa: F401
from metrics_tpu.functional.text.wil import word_information_lost  # noqa: F401
from metrics_tpu.functional.text.wip import word_information_preserved  # noqa: F401
