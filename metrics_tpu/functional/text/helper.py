"""Shared host-side text kernels.

Counterpart of the reference's ``torchmetrics/functional/text/helper.py``
(``_edit_distance`` :333, ``_validate_inputs`` :298). The Levenshtein DP here
is redesigned: instead of the reference's pure-Python cell-by-cell loop, each
DP row is computed with vectorized numpy using the prefix-min identity

    dist[j] = min_k<=j ( cand[k] + (j - k) )
            = minimum.accumulate(cand - j)[j] + j

which collapses the in-row left-to-right dependency into one
``np.minimum.accumulate`` — O(n) numpy ops per row instead of O(n) Python
iterations, a large constant-factor win on long transcripts.
"""
from typing import List, Sequence, Tuple, Union

import jax
import numpy as np


def _put_all(*values) -> Tuple[jax.Array, ...]:
    """Ship host values (numpy arrays/scalars, dtypes preserved) as ONE
    device transfer — a put per value pays a dispatch round trip each on
    tunneled TPUs."""
    return tuple(jax.device_put(tuple(values)))


def _put_scalars(*values) -> Tuple[jax.Array, ...]:
    """`_put_all` with everything cast to f32 scalars."""
    return _put_all(*(np.float32(v) for v in values))


def _encode_tokens(*token_lists: Sequence[str]) -> Tuple[np.ndarray, ...]:
    """Integer-encode token sequences over a shared vocabulary so the inner
    DP comparisons become numpy broadcasts."""
    vocab: dict = {}
    return tuple(
        np.fromiter((vocab.setdefault(t, len(vocab)) for t in tokens), dtype=np.int64, count=len(tokens))
        for tokens in token_lists
    )


def _edit_distance_numpy(pred: np.ndarray, ref: np.ndarray) -> int:
    """Vectorized-row DP fallback over integer-encoded sequences."""
    n_pred, n_ref = len(pred), len(ref)
    idx = np.arange(n_ref + 1)
    prev = idx.copy()  # dist(0, j) = j
    for i in range(1, n_pred + 1):
        # candidates ignoring the in-row dependency: deletion from above,
        # substitution/match from the diagonal
        cand = np.minimum(prev[1:] + 1, prev[:-1] + (ref != pred[i - 1]))
        full = np.concatenate(([i], cand))  # dist(i, 0) = i seeds the prefix min
        prev = np.minimum.accumulate(full - idx) + idx
    return int(prev[-1])


def _edit_distance(prediction_tokens: List[str], reference_tokens: List[str]) -> int:
    """Word/char-level Levenshtein distance (unit costs).

    Behavioral equivalent of reference ``functional/text/helper.py:333-355``.
    Dispatches to the native C kernel (``metrics_tpu/native``) when it is
    available; the numpy row DP is the fallback.
    """
    n_pred, n_ref = len(prediction_tokens), len(reference_tokens)
    if n_ref == 0:
        return n_pred
    if n_pred == 0:
        return n_ref
    pred, ref = _encode_tokens(prediction_tokens, reference_tokens)

    from metrics_tpu import native

    out = native.edit_distance(pred, ref)
    if out is not None:
        return out
    return _edit_distance_numpy(pred, ref)


def _edit_distance_corpus(
    preds_tokens: List[List[str]], refs_tokens: List[List[str]]
) -> List[int]:
    """Per-pair Levenshtein over a whole corpus — ONE native call.

    The WER-family updates call this instead of ``_edit_distance`` per pair:
    the C batch kernel amortizes the FFI crossing and the encoding pass over
    the full batch.
    """
    encoded = []
    for p, r in zip(preds_tokens, refs_tokens):
        encoded.append(_encode_tokens(p, r))
    from metrics_tpu import native

    out = native.edit_distance_batch([e[0] for e in encoded], [e[1] for e in encoded])
    if out is not None:
        return [int(x) for x in out]
    # _edit_distance_numpy handles empty sequences (the DP degenerates to
    # the remaining length), so no special-casing is needed here
    return [_edit_distance_numpy(p, r) for p, r in encoded]


def _corpus_edit_stats(
    preds: Sequence[str], target: Sequence[str], unit: str = "words"
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-pair ``(edit distance, pred units, target units)`` for a corpus.

    The WER-family sufficient statistics in one shot. ``unit`` is ``"words"``
    (CPython ``str.split`` semantics) or ``"chars"`` (code points, reference
    ``cer.py:43-47``). Fast path: the native batch kernel tokenizes, encodes,
    and runs the DP over the raw UTF-8 bytes in ONE crossing — no Python
    per-token work at all (measured ~85% of the 10k-pair corpus cost before
    this path existed). Fallback: host tokenization + `_edit_distance_corpus`.
    """
    if unit not in ("chars", "words"):
        raise ValueError(f"unit must be 'chars' or 'words', got {unit!r}")
    from metrics_tpu import native

    try:
        out = native.text_dist_batch(list(preds), list(target), unit)
    except UnicodeEncodeError:  # lone surrogates: not UTF-8-encodable
        out = None
    if out is not None:
        dists, cnt_p, cnt_t = out
        return dists, cnt_p, cnt_t
    if unit == "chars":
        preds_tok: List[List[str]] = [list(p) for p in preds]
        tgt_tok: List[List[str]] = [list(t) for t in target]
    else:
        preds_tok = [p.split() for p in preds]
        tgt_tok = [t.split() for t in target]
    dists = np.asarray(_edit_distance_corpus(preds_tok, tgt_tok), dtype=np.int64)
    cnt_p = np.fromiter((len(p) for p in preds_tok), dtype=np.int64, count=len(preds_tok))
    cnt_t = np.fromiter((len(t) for t in tgt_tok), dtype=np.int64, count=len(tgt_tok))
    return dists, cnt_p, cnt_t


def _normalize_corpus(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
) -> Tuple[Sequence[str], Sequence[str]]:
    """Promote single strings to one-element corpora (ref ``wer.py:38-41``)."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    return preds, target


def _validate_inputs(
    hypothesis_corpus: Union[str, Sequence[str]],
    ref_corpus: Union[Sequence[str], Sequence[Sequence[str]]],
) -> Tuple[Sequence[str], Sequence[Sequence[str]]]:
    """Check and normalize (hypothesis, multi-reference) corpora shapes.

    Behavioral equivalent of reference ``functional/text/helper.py:298-330``:
    a single hypothesis string is promoted to a one-element corpus, and a flat
    reference list is promoted to per-hypothesis singleton reference lists.
    """
    if isinstance(hypothesis_corpus, str):
        hypothesis_corpus = [hypothesis_corpus]
    # flat list of strings + single hypothesis -> all references of that one hypothesis
    if all(isinstance(ref, str) for ref in ref_corpus):
        if len(hypothesis_corpus) == 1:
            ref_corpus = [ref_corpus]  # type: ignore[list-item]
        else:
            ref_corpus = [[ref] for ref in ref_corpus]  # type: ignore[misc]
    if hypothesis_corpus and len(ref_corpus) != len(hypothesis_corpus):
        raise ValueError(f"Corpus has different size {len(ref_corpus)} != {len(hypothesis_corpus)}")
    return hypothesis_corpus, ref_corpus
