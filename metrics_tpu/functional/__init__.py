from metrics_tpu.functional.audio.pesq import perceptual_evaluation_speech_quality  # noqa: F401
from metrics_tpu.functional.audio.pit import permutation_invariant_training, pit_permutate  # noqa: F401
from metrics_tpu.functional.audio.sdr import (  # noqa: F401
    scale_invariant_signal_distortion_ratio,
    signal_distortion_ratio,
)
from metrics_tpu.functional.audio.snr import (  # noqa: F401
    scale_invariant_signal_noise_ratio,
    signal_noise_ratio,
)
from metrics_tpu.functional.audio.stoi import short_time_objective_intelligibility  # noqa: F401
from metrics_tpu.functional.classification.accuracy import accuracy  # noqa: F401
from metrics_tpu.functional.classification.auc import auc  # noqa: F401
from metrics_tpu.functional.classification.auroc import auroc  # noqa: F401
from metrics_tpu.functional.classification.average_precision import average_precision  # noqa: F401
from metrics_tpu.functional.classification.calibration_error import calibration_error  # noqa: F401
from metrics_tpu.functional.classification.cohen_kappa import cohen_kappa  # noqa: F401
from metrics_tpu.functional.classification.confusion_matrix import confusion_matrix  # noqa: F401
from metrics_tpu.functional.classification.dice import dice_score  # noqa: F401
from metrics_tpu.functional.classification.f_beta import f1_score, fbeta_score  # noqa: F401
from metrics_tpu.functional.classification.hamming import hamming_distance  # noqa: F401
from metrics_tpu.functional.classification.hinge import hinge_loss  # noqa: F401
from metrics_tpu.functional.classification.jaccard import jaccard_index  # noqa: F401
from metrics_tpu.functional.classification.kl_divergence import kl_divergence  # noqa: F401
from metrics_tpu.functional.classification.matthews_corrcoef import matthews_corrcoef  # noqa: F401
from metrics_tpu.functional.classification.precision_recall import precision, precision_recall, recall  # noqa: F401
from metrics_tpu.functional.classification.precision_recall_curve import precision_recall_curve  # noqa: F401
from metrics_tpu.functional.classification.ranking import (  # noqa: F401
    coverage_error,
    label_ranking_average_precision,
    label_ranking_loss,
)
from metrics_tpu.functional.classification.roc import roc  # noqa: F401
from metrics_tpu.functional.classification.specificity import specificity  # noqa: F401
from metrics_tpu.functional.classification.stat_scores import stat_scores  # noqa: F401
from metrics_tpu.functional.detection.box_ops import box_area, box_convert, box_iou  # noqa: F401
from metrics_tpu.functional.image.d_lambda import spectral_distortion_index  # noqa: F401
from metrics_tpu.functional.image.ergas import error_relative_global_dimensionless_synthesis  # noqa: F401
from metrics_tpu.functional.image.gradients import image_gradients  # noqa: F401
from metrics_tpu.functional.image.psnr import peak_signal_noise_ratio  # noqa: F401
from metrics_tpu.functional.image.sam import spectral_angle_mapper  # noqa: F401
from metrics_tpu.functional.image.ssim import (  # noqa: F401
    multiscale_structural_similarity_index_measure,
    structural_similarity_index_measure,
)
from metrics_tpu.functional.image.uqi import universal_image_quality_index  # noqa: F401
from metrics_tpu.functional.pairwise.cosine import pairwise_cosine_similarity  # noqa: F401
from metrics_tpu.functional.pairwise.euclidean import pairwise_euclidean_distance  # noqa: F401
from metrics_tpu.functional.pairwise.linear import pairwise_linear_similarity  # noqa: F401
from metrics_tpu.functional.pairwise.manhattan import pairwise_manhattan_distance  # noqa: F401
from metrics_tpu.functional.regression.cosine_similarity import cosine_similarity  # noqa: F401
from metrics_tpu.functional.regression.explained_variance import explained_variance  # noqa: F401
from metrics_tpu.functional.regression.log_mse import mean_squared_log_error  # noqa: F401
from metrics_tpu.functional.regression.mae import mean_absolute_error  # noqa: F401
from metrics_tpu.functional.regression.mape import mean_absolute_percentage_error  # noqa: F401
from metrics_tpu.functional.regression.mse import mean_squared_error  # noqa: F401
from metrics_tpu.functional.regression.pearson import pearson_corrcoef  # noqa: F401
from metrics_tpu.functional.regression.r2 import r2_score  # noqa: F401
from metrics_tpu.functional.regression.spearman import spearman_corrcoef  # noqa: F401
from metrics_tpu.functional.regression.symmetric_mape import symmetric_mean_absolute_percentage_error  # noqa: F401
from metrics_tpu.functional.regression.tweedie_deviance import tweedie_deviance_score  # noqa: F401
from metrics_tpu.functional.regression.wmape import weighted_mean_absolute_percentage_error  # noqa: F401
from metrics_tpu.functional.retrieval.average_precision import retrieval_average_precision  # noqa: F401
from metrics_tpu.functional.retrieval.fall_out import retrieval_fall_out  # noqa: F401
from metrics_tpu.functional.retrieval.hit_rate import retrieval_hit_rate  # noqa: F401
from metrics_tpu.functional.retrieval.ndcg import retrieval_normalized_dcg  # noqa: F401
from metrics_tpu.functional.retrieval.precision import retrieval_precision  # noqa: F401
from metrics_tpu.functional.retrieval.r_precision import retrieval_r_precision  # noqa: F401
from metrics_tpu.functional.retrieval.recall import retrieval_recall  # noqa: F401
from metrics_tpu.functional.retrieval.reciprocal_rank import retrieval_reciprocal_rank  # noqa: F401
from metrics_tpu.functional.text.bert import bert_score  # noqa: F401
from metrics_tpu.functional.text.bleu import bleu_score  # noqa: F401
from metrics_tpu.functional.text.cer import char_error_rate  # noqa: F401
from metrics_tpu.functional.text.chrf import chrf_score  # noqa: F401
from metrics_tpu.functional.text.eed import extended_edit_distance  # noqa: F401
from metrics_tpu.functional.text.mer import match_error_rate  # noqa: F401
from metrics_tpu.functional.text.rouge import rouge_score  # noqa: F401
from metrics_tpu.functional.text.sacre_bleu import sacre_bleu_score  # noqa: F401
from metrics_tpu.functional.text.squad import squad  # noqa: F401
from metrics_tpu.functional.text.ter import translation_edit_rate  # noqa: F401
from metrics_tpu.functional.text.wer import word_error_rate  # noqa: F401
from metrics_tpu.functional.text.wil import word_information_lost  # noqa: F401
from metrics_tpu.functional.text.wip import word_information_preserved  # noqa: F401

__all__ = [
    "cosine_similarity",
    "error_relative_global_dimensionless_synthesis",
    "image_gradients",
    "multiscale_structural_similarity_index_measure",
    "peak_signal_noise_ratio",
    "spectral_angle_mapper",
    "spectral_distortion_index",
    "structural_similarity_index_measure",
    "universal_image_quality_index",
    "explained_variance",
    "mean_absolute_error",
    "mean_absolute_percentage_error",
    "mean_squared_error",
    "mean_squared_log_error",
    "pairwise_cosine_similarity",
    "pairwise_euclidean_distance",
    "pairwise_linear_similarity",
    "pairwise_manhattan_distance",
    "pearson_corrcoef",
    "r2_score",
    "spearman_corrcoef",
    "symmetric_mean_absolute_percentage_error",
    "tweedie_deviance_score",
    "weighted_mean_absolute_percentage_error",
    "accuracy",
    "auc",
    "auroc",
    "average_precision",
    "box_area",
    "box_convert",
    "box_iou",
    "calibration_error",
    "cohen_kappa",
    "confusion_matrix",
    "coverage_error",
    "dice_score",
    "f1_score",
    "fbeta_score",
    "hamming_distance",
    "hinge_loss",
    "jaccard_index",
    "kl_divergence",
    "label_ranking_average_precision",
    "label_ranking_loss",
    "matthews_corrcoef",
    "precision",
    "precision_recall",
    "precision_recall_curve",
    "recall",
    "retrieval_average_precision",
    "retrieval_fall_out",
    "retrieval_hit_rate",
    "retrieval_normalized_dcg",
    "retrieval_precision",
    "retrieval_r_precision",
    "retrieval_recall",
    "retrieval_reciprocal_rank",
    "roc",
    "specificity",
    "stat_scores",
    "bert_score",
    "bleu_score",
    "char_error_rate",
    "chrf_score",
    "extended_edit_distance",
    "match_error_rate",
    "rouge_score",
    "sacre_bleu_score",
    "squad",
    "translation_edit_rate",
    "word_error_rate",
    "word_information_lost",
    "word_information_preserved",
    "perceptual_evaluation_speech_quality",
    "permutation_invariant_training",
    "pit_permutate",
    "scale_invariant_signal_distortion_ratio",
    "scale_invariant_signal_noise_ratio",
    "short_time_objective_intelligibility",
    "signal_distortion_ratio",
    "signal_noise_ratio",
]
