from metrics_tpu.functional.classification.accuracy import accuracy  # noqa: F401
from metrics_tpu.functional.classification.stat_scores import stat_scores  # noqa: F401

__all__ = [
    "accuracy",
    "stat_scores",
]
