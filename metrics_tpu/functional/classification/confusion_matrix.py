"""Confusion matrix kernel (multiclass and multilabel).

Behavioral equivalent of reference
``torchmetrics/functional/classification/confusion_matrix.py`` (186 LoC):
``_confusion_matrix_update`` :25 (bincount of ``target*C + pred``; on TPU a
length-static ``jnp.bincount`` — always deterministic, no CUDA fallback
needed), ``_confusion_matrix_compute`` :57 (true/pred/all normalization).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _input_format_classification
from metrics_tpu.utilities.data import _bincount
from metrics_tpu.utilities.enums import DataType
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _confusion_matrix_update(
    preds: Array, target: Array, num_classes: int, threshold: float = 0.5, multilabel: bool = False
) -> Array:
    """Unnormalized confusion matrix: ``(C, C)``, or ``(C, 2, 2)`` when multilabel."""
    preds, target, mode = _input_format_classification(preds, target, threshold)
    if mode not in (DataType.BINARY, DataType.MULTILABEL):
        preds = preds.argmax(axis=1)
        target = target.argmax(axis=1)
    if multilabel:
        unique_mapping = ((2 * target + preds) + 4 * jnp.arange(num_classes)).reshape(-1)
        bins = _bincount(unique_mapping, minlength=4 * num_classes)
        return bins.reshape(num_classes, 2, 2)
    if jax.default_backend() == "tpu" or num_classes > 64:
        # The count matrix factors as onehot(target)^T @ onehot(preds): on
        # TPU the ops/confusion_bincount pallas tile keeps the (C, C) block
        # VMEM-resident while sample tiles stream through (one input pass,
        # no C^2-bin scatter); elsewhere the chunk-scanned MXU contraction
        # takes over past the one-hot bincount's C^2 work bound.
        from metrics_tpu.ops.confusion_bincount import confusion_counts

        return confusion_counts(preds.reshape(-1), target.reshape(-1), num_classes)
    unique_mapping = (target.reshape(-1) * num_classes + preds.reshape(-1)).astype(jnp.int32)
    bins = _bincount(unique_mapping, minlength=num_classes**2)
    return bins.reshape(num_classes, num_classes)


def _confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    """Apply 'true' | 'pred' | 'all' | none normalization (reference :57)."""
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument average needs to one of the following: {allowed_normalize}")
    if normalize is not None and normalize != "none":
        confmat = confmat.astype(jnp.float32)
        if normalize == "true":
            confmat = confmat / confmat.sum(axis=1, keepdims=True)
        elif normalize == "pred":
            confmat = confmat / confmat.sum(axis=0, keepdims=True)
        elif normalize == "all":
            confmat = confmat / confmat.sum()
        nan_elements = int(jnp.isnan(confmat).sum())
        if nan_elements:
            confmat = jnp.nan_to_num(confmat)
            rank_zero_warn(f"{nan_elements} nan values found in confusion matrix have been replaced with zeros.")
    return confmat


def confusion_matrix(
    preds: Array,
    target: Array,
    num_classes: int,
    normalize: Optional[str] = None,
    threshold: float = 0.5,
    multilabel: bool = False,
) -> Array:
    """Compute the confusion matrix (reference ``confusion_matrix`` :120).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import confusion_matrix
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> confusion_matrix(preds, target, num_classes=2)
        Array([[2, 0],
               [1, 1]], dtype=int32)
    """
    confmat = _confusion_matrix_update(preds, target, num_classes, threshold, multilabel)
    return _confusion_matrix_compute(confmat, normalize)
