"""Confusion matrix kernel (multiclass and multilabel).

Behavioral equivalent of reference
``torchmetrics/functional/classification/confusion_matrix.py`` (186 LoC):
``_confusion_matrix_update`` :25 (bincount of ``target*C + pred``; on TPU a
length-static ``jnp.bincount`` — always deterministic, no CUDA fallback
needed), ``_confusion_matrix_compute`` :57 (true/pred/all normalization).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _input_format_classification
from metrics_tpu.utilities.data import _bincount
from metrics_tpu.utilities.enums import DataType
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def _confusion_matrix_update(
    preds: Array, target: Array, num_classes: int, threshold: float = 0.5, multilabel: bool = False
) -> Array:
    """Unnormalized confusion matrix: ``(C, C)``, or ``(C, 2, 2)`` when multilabel."""
    preds, target, mode = _input_format_classification(preds, target, threshold)
    if mode not in (DataType.BINARY, DataType.MULTILABEL):
        preds = preds.argmax(axis=1)
        target = target.argmax(axis=1)
    if multilabel:
        unique_mapping = ((2 * target + preds) + 4 * jnp.arange(num_classes)).reshape(-1)
        bins = _bincount(unique_mapping, minlength=4 * num_classes)
        return bins.reshape(num_classes, 2, 2)
    if num_classes > 64:
        # C^2 bins exceed the one-hot bincount's work bound, but the count
        # matrix factors as onehot(target)^T @ onehot(preds) — an MXU matmul
        # with f32 accumulation, ~2x faster than TPU scatter and flat in C.
        # Chunked over samples so peak memory stays O(chunk * C), not O(N * C).
        t_flat = target.reshape(-1).astype(jnp.int32)
        p_flat = preds.reshape(-1).astype(jnp.int32)
        chunk = 65536
        pad = -t_flat.shape[0] % chunk
        # pad with out-of-range index -1: one_hot maps it to the zero row
        t_flat = jnp.pad(t_flat, (0, pad), constant_values=-1).reshape(-1, chunk)
        p_flat = jnp.pad(p_flat, (0, pad), constant_values=-1).reshape(-1, chunk)

        def body(acc, batch):
            t_c, p_c = batch
            oh_t = jax.nn.one_hot(t_c, num_classes, dtype=jnp.bfloat16)
            oh_p = jax.nn.one_hot(p_c, num_classes, dtype=jnp.bfloat16)
            return acc + jax.lax.dot(oh_t.T, oh_p, preferred_element_type=jnp.float32), None

        confmat, _ = jax.lax.scan(body, jnp.zeros((num_classes, num_classes), jnp.float32), (t_flat, p_flat))
        return confmat.astype(jnp.int32)
    unique_mapping = (target.reshape(-1) * num_classes + preds.reshape(-1)).astype(jnp.int32)
    bins = _bincount(unique_mapping, minlength=num_classes**2)
    return bins.reshape(num_classes, num_classes)


def _confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    """Apply 'true' | 'pred' | 'all' | none normalization (reference :57)."""
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument average needs to one of the following: {allowed_normalize}")
    if normalize is not None and normalize != "none":
        confmat = confmat.astype(jnp.float32)
        if normalize == "true":
            confmat = confmat / confmat.sum(axis=1, keepdims=True)
        elif normalize == "pred":
            confmat = confmat / confmat.sum(axis=0, keepdims=True)
        elif normalize == "all":
            confmat = confmat / confmat.sum()
        nan_elements = int(jnp.isnan(confmat).sum())
        if nan_elements:
            confmat = jnp.nan_to_num(confmat)
            rank_zero_warn(f"{nan_elements} nan values found in confusion matrix have been replaced with zeros.")
    return confmat


def confusion_matrix(
    preds: Array,
    target: Array,
    num_classes: int,
    normalize: Optional[str] = None,
    threshold: float = 0.5,
    multilabel: bool = False,
) -> Array:
    """Compute the confusion matrix (reference ``confusion_matrix`` :120).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import confusion_matrix
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> confusion_matrix(preds, target, num_classes=2)
        Array([[2, 0],
               [1, 1]], dtype=int32)
    """
    confmat = _confusion_matrix_update(preds, target, num_classes, threshold, multilabel)
    return _confusion_matrix_compute(confmat, normalize)
