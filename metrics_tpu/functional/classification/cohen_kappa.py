"""Cohen's kappa kernel.

Behavioral equivalent of reference
``torchmetrics/functional/classification/cohen_kappa.py`` (110 LoC):
``_cohen_kappa_update`` == confusion-matrix update, ``_cohen_kappa_compute``
:28 (observed vs expected agreement, optional linear/quadratic weighting).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.confusion_matrix import _confusion_matrix_compute, _confusion_matrix_update

Array = jax.Array

_cohen_kappa_update = _confusion_matrix_update


def _cohen_kappa_compute(confmat: Array, weights: Optional[str] = None) -> Array:
    """kappa = 1 - sum(w * observed) / sum(w * expected) (reference :28)."""
    confmat = _confusion_matrix_compute(confmat)
    confmat = confmat.astype(jnp.float32)
    n_classes = confmat.shape[0]
    sum0 = confmat.sum(axis=0, keepdims=True)
    sum1 = confmat.sum(axis=1, keepdims=True)
    expected = jnp.matmul(sum1, sum0, precision="float32") / sum0.sum()

    if weights is None:
        w_mat = jnp.ones((n_classes, n_classes), dtype=confmat.dtype)
        w_mat = w_mat - jnp.eye(n_classes, dtype=confmat.dtype)
    elif weights in ("linear", "quadratic"):
        w_mat = jnp.broadcast_to(jnp.arange(n_classes, dtype=confmat.dtype), (n_classes, n_classes))
        diff = w_mat - w_mat.T
        w_mat = jnp.abs(diff) if weights == "linear" else jnp.power(diff, 2.0)
    else:
        raise ValueError(f"Received {weights} for argument ``weights`` but should be either None, 'linear' or 'quadratic'")

    k = jnp.sum(w_mat * confmat) / jnp.sum(w_mat * expected)
    return 1 - k


def cohen_kappa(
    preds: Array,
    target: Array,
    num_classes: int,
    weights: Optional[str] = None,
    threshold: float = 0.5,
) -> Array:
    """Compute Cohen's kappa (reference :66).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import cohen_kappa
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> preds = jnp.asarray([0, 1, 0, 0])
        >>> cohen_kappa(preds, target, num_classes=2)
        Array(0.5, dtype=float32)
    """
    confmat = _cohen_kappa_update(preds, target, num_classes, threshold)
    return _cohen_kappa_compute(confmat, weights)
