"""Jaccard index (IoU) kernel.

Behavioral equivalent of reference
``torchmetrics/functional/classification/jaccard.py`` (129 LoC):
``_jaccard_from_confmat`` :25, ``jaccard_index`` :70.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.confusion_matrix import _confusion_matrix_update
from metrics_tpu.utilities.distributed import reduce

Array = jax.Array


def _jaccard_from_confmat(
    confmat: Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
    absent_score: float = 0.0,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """Per-class intersection-over-union from a confusion matrix (reference :25)."""
    if ignore_index is not None and 0 <= ignore_index < num_classes:
        # scatter value must match the confmat dtype (int counts unless the
        # caller normalized) — a float literal here becomes a hard error on
        # future JAX under standard dtype promotion
        confmat = confmat.at[ignore_index].set(jnp.zeros((), dtype=confmat.dtype))

    intersection = jnp.diag(confmat)
    union = confmat.sum(axis=0) + confmat.sum(axis=1) - intersection

    scores = intersection.astype(jnp.float32) / union.astype(jnp.float32)
    scores = jnp.where(union == 0, absent_score, scores)

    if ignore_index is not None and 0 <= ignore_index < num_classes:
        scores = jnp.concatenate([scores[:ignore_index], scores[ignore_index + 1:]])

    return reduce(scores, reduction=reduction)


def jaccard_index(
    preds: Array,
    target: Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
    absent_score: float = 0.0,
    threshold: float = 0.5,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """Compute the Jaccard index (reference :70).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import jaccard_index
        >>> target = jnp.asarray([[0, 1, 1], [1, 1, 0]])
        >>> preds = jnp.asarray([[0, 1, 0], [1, 1, 1]])
        >>> jaccard_index(preds, target, num_classes=2)
        Array(0.4666667, dtype=float32)
    """
    confmat = _confusion_matrix_update(preds, target, num_classes, threshold)
    return _jaccard_from_confmat(confmat, num_classes, ignore_index, absent_score, reduction)
