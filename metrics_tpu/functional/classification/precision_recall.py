"""Precision / Recall kernels.

Behavioral equivalent of reference
``torchmetrics/functional/classification/precision_recall.py`` (552 LoC):
``_precision_compute`` :23, ``precision`` :76, ``_recall_compute`` :209,
``recall`` :262, ``precision_recall`` :397. Class-presence filtering is
where-masked (jit-safe) instead of boolean-indexed.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import _reduce_stat_scores, _stat_scores_update
from metrics_tpu.utilities.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


def _mask_absent_classes(
    tp: Array, fp: Array, fn: Array, numerator: Array, denominator: Array, average: Optional[str], mdmc_average: Optional[str]
) -> Tuple[Array, Array]:
    """Exclude classes absent from preds AND target (no tp/fp/fn).

    Jit-safe replacement for the reference's boolean-index dropping
    (precision_recall.py:55-65): the ignore sentinel (-1) routes through
    ``_reduce_stat_scores``'s ignore mask.
    """
    if mdmc_average == MDMCAverageMethod.SAMPLEWISE:
        return numerator, denominator
    if average == AverageMethod.MACRO:
        absent = (tp + fp + fn) == 0
        denominator = jnp.where(absent, -1, denominator)
    elif average == AverageMethod.NONE:
        absent = (tp + fp + fn) == 0
        numerator = jnp.where(absent, -1, numerator)
        denominator = jnp.where(absent, -1, denominator)
    return numerator, denominator


def _precision_compute(
    tp: Array,
    fp: Array,
    fn: Array,
    average: Optional[str],
    mdmc_average: Optional[str],
) -> Array:
    """precision = tp / (tp + fp), averaged (reference :23)."""
    numerator, denominator = _mask_absent_classes(tp, fp, fn, tp, tp + fp, average, mdmc_average)
    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != AverageMethod.WEIGHTED else tp + fn,
        average=average,
        mdmc_average=mdmc_average,
    )


def _recall_compute(
    tp: Array,
    fp: Array,
    fn: Array,
    average: Optional[str],
    mdmc_average: Optional[str],
) -> Array:
    """recall = tp / (tp + fn), averaged (reference :209)."""
    numerator, denominator = _mask_absent_classes(tp, fp, fn, tp, tp + fn, average, mdmc_average)
    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != AverageMethod.WEIGHTED else tp + fn,
        average=average,
        mdmc_average=mdmc_average,
    )


def _check_average_arg(average: Optional[str], mdmc_average: Optional[str], num_classes: Optional[int], ignore_index: Optional[int]) -> None:
    allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")
    allowed_mdmc_average = (None, "samplewise", "global")
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")
    if average in ("macro", "weighted", "none", None) and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")


def precision(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """Compute precision (reference :76).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import precision
        >>> preds  = jnp.asarray([2, 0, 2, 1])
        >>> target = jnp.asarray([1, 1, 2, 0])
        >>> precision(preds, target, average='macro', num_classes=3)
        Array(0.16666667, dtype=float32)
        >>> precision(preds, target, average='micro')
        Array(0.25, dtype=float32)
    """
    _check_average_arg(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _precision_compute(tp, fp, fn, average, mdmc_average)


def recall(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """Compute recall (reference :262).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import recall
        >>> preds  = jnp.asarray([2, 0, 2, 1])
        >>> target = jnp.asarray([1, 1, 2, 0])
        >>> recall(preds, target, average='macro', num_classes=3)
        Array(0.33333334, dtype=float32)
        >>> recall(preds, target, average='micro')
        Array(0.25, dtype=float32)
    """
    _check_average_arg(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _recall_compute(tp, fp, fn, average, mdmc_average)


def precision_recall(
    preds: Array,
    target: Array,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Tuple[Array, Array]:
    """Compute precision and recall together (reference :397)."""
    _check_average_arg(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return (
        _precision_compute(tp, fp, fn, average, mdmc_average),
        _recall_compute(tp, fp, fn, average, mdmc_average),
    )
