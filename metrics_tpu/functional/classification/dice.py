"""Dice score kernel.

Behavioral equivalent of reference
``torchmetrics/functional/classification/dice.py`` (113 LoC) — but the
reference's per-class Python loop (:103-112) is vectorized into one
class-parallel computation (jit-friendly, MXU-sized reductions).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.data import to_categorical
from metrics_tpu.utilities.distributed import reduce

Array = jax.Array


def dice_score(
    preds: Array,
    target: Array,
    bg: bool = False,
    nan_score: float = 0.0,
    no_fg_score: float = 0.0,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """Compute the dice score per class, then reduce (reference :63).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import dice_score
        >>> pred = jnp.asarray([[0.85, 0.05, 0.05, 0.05],
        ...                     [0.05, 0.85, 0.05, 0.05],
        ...                     [0.05, 0.05, 0.85, 0.05],
        ...                     [0.05, 0.05, 0.05, 0.85]])
        >>> target = jnp.asarray([0, 1, 3, 2])
        >>> dice_score(pred, target)
        Array(0.33333334, dtype=float32)
    """
    num_classes = preds.shape[1]
    if preds.ndim == target.ndim + 1:
        preds = to_categorical(preds, argmax_dim=1)

    bg_inv = 1 - int(bg)
    classes = jnp.arange(bg_inv, num_classes)

    # vectorized per-class tp/fp/fn (reference loops classes in Python)
    p_onehot = preds[..., None] == classes  # (..., C')
    t_onehot = target[..., None] == classes
    reduce_axes = tuple(range(p_onehot.ndim - 1))
    tp = jnp.sum(p_onehot & t_onehot, axis=reduce_axes)
    fp = jnp.sum(p_onehot & ~t_onehot, axis=reduce_axes)
    fn = jnp.sum(~p_onehot & t_onehot, axis=reduce_axes)

    denom = (2 * tp + fp + fn).astype(jnp.float32)
    scores = jnp.where(denom == 0, nan_score, (2 * tp).astype(jnp.float32) / jnp.where(denom == 0, 1.0, denom))
    has_fg = jnp.sum(t_onehot, axis=reduce_axes) > 0
    scores = jnp.where(has_fg, scores, no_fg_score)

    return reduce(scores, reduction=reduction)
