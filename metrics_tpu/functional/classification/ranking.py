"""Multilabel ranking kernels: coverage error, LRAP, label ranking loss.

Behavioral equivalent of reference
``torchmetrics/functional/classification/ranking.py`` (242 LoC). The
reference's per-sample Python loop for LRAP (:139-155) is vectorized into one
(N, L, L) pairwise-rank computation — class-parallel, jit-friendly.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _check_ranking_input(preds: Array, target: Array, sample_weight: Optional[Array] = None) -> None:
    """Validate [N, C] ranking inputs (reference :30)."""
    if preds.ndim != 2 or target.ndim != 2:
        raise ValueError(
            "Expected both predictions and target to matrices of shape `[N,C]`"
            f" but got {preds.ndim} and {target.ndim}"
        )
    if preds.shape != target.shape:
        raise ValueError("Expected both predictions and target to have same shape")
    if sample_weight is not None:
        if sample_weight.ndim != 1 or sample_weight.shape[0] != preds.shape[0]:
            raise ValueError(
                "Expected sample weights to be 1 dimensional and have same size"
                f" as the first dimension of preds and target but got {sample_weight.shape}"
            )


def _weighted_mean(value, n_elements, sample_weight):
    """value / sum(weights), falling back to / n_elements when the weight sum
    is zero (or no weights were given) — trace-safe, no host pull."""
    n_elements = jnp.asarray(n_elements, dtype=jnp.float32)  # gathered int counts
    if sample_weight is None:
        return value / n_elements
    safe = jnp.where(sample_weight != 0.0, sample_weight, 1.0)
    return jnp.where(sample_weight != 0.0, value / safe, value / n_elements)


def _coverage_error_update(
    preds: Array, target: Array, sample_weight: Optional[Array] = None
) -> Tuple[Array, int, Optional[Array]]:
    """How far down the ranking to go to cover all true labels (reference :48)."""
    _check_ranking_input(preds, target, sample_weight)
    offset = jnp.where(target == 0, jnp.abs(preds.min()) + 10, 0.0)
    preds_mod = preds + offset
    preds_min = preds_mod.min(axis=1)
    coverage = (preds >= preds_min[:, None]).sum(axis=1).astype(jnp.float32)
    if sample_weight is not None:
        coverage = coverage * sample_weight
        sample_weight = sample_weight.sum()
    return coverage.sum(), coverage.size, sample_weight


def _coverage_error_compute(coverage: Array, n_elements: int, sample_weight: Optional[Array] = None) -> Array:
    return _weighted_mean(coverage, n_elements, sample_weight)


def coverage_error(preds: Array, target: Array, sample_weight: Optional[Array] = None) -> Array:
    """Compute multilabel coverage error (reference ``coverage_error`` :77)."""
    coverage, n_elements, sample_weight = _coverage_error_update(preds, target, sample_weight)
    return _coverage_error_compute(coverage, n_elements, sample_weight)


def _label_ranking_average_precision_update(
    preds: Array, target: Array, sample_weight: Optional[Array] = None
) -> Tuple[Array, int, Optional[Array]]:
    """LRAP accumulation, vectorized over samples (reference :108-131).

    For each relevant label j of sample i the reference computes
    (rank among relevant) / (rank among all), with max-rank tie handling —
    equivalent to counting labels with score >= score_j.
    """
    _check_ranking_input(preds, target, sample_weight)
    neg_preds = -preds
    n_preds, n_labels = neg_preds.shape
    relevant = target == 1
    n_rel = relevant.sum(axis=1)

    # pairwise[i, j, k] = neg_preds[i, k] <= neg_preds[i, j]
    pairwise = neg_preds[:, None, :] <= neg_preds[:, :, None]
    rank_all = pairwise.sum(axis=2).astype(jnp.float32)  # (N, L)
    rank_rel = (pairwise & relevant[:, None, :]).sum(axis=2).astype(jnp.float32)

    ratio = jnp.where(relevant, rank_rel / rank_all, 0.0)
    per_sample = jnp.where(
        (n_rel > 0) & (n_rel < n_labels),
        ratio.sum(axis=1) / jnp.maximum(n_rel, 1).astype(jnp.float32),
        1.0,
    )
    if sample_weight is not None:
        per_sample = per_sample * sample_weight
        sample_weight = sample_weight.sum()
    return per_sample.sum(), n_preds, sample_weight


def _label_ranking_average_precision_compute(
    score: Array, n_elements: int, sample_weight: Optional[Array] = None
) -> Array:
    return _weighted_mean(score, n_elements, sample_weight)


def label_ranking_average_precision(preds: Array, target: Array, sample_weight: Optional[Array] = None) -> Array:
    """Compute label ranking average precision (reference :160)."""
    score, n_elements, sample_weight = _label_ranking_average_precision_update(preds, target, sample_weight)
    return _label_ranking_average_precision_compute(score, n_elements, sample_weight)


def _label_ranking_loss_update(
    preds: Array, target: Array, sample_weight: Optional[Array] = None
) -> Tuple[Array, int, Optional[Array]]:
    """Average fraction of incorrectly ordered label pairs (reference :174-206)."""
    _check_ranking_input(preds, target, sample_weight)
    n_preds, n_labels = preds.shape
    relevant = target == 1
    n_rel = relevant.sum(axis=1)
    mask = (n_rel > 0) & (n_rel < n_labels)

    inverse = jnp.argsort(jnp.argsort(preds, axis=1), axis=1)
    per_label_loss = ((n_labels - inverse) * relevant.astype(jnp.int32)).astype(jnp.float32)
    n_rel_f = n_rel.astype(jnp.float32)
    correction = 0.5 * n_rel_f * (n_rel_f + 1.0)
    denom = n_rel_f * (n_labels - n_rel_f)
    loss = jnp.where(mask, (per_label_loss.sum(axis=1) - correction) / jnp.maximum(denom, 1.0), 0.0)
    if sample_weight is not None:
        loss = loss * jnp.where(mask, sample_weight, 0.0)
        sample_weight = sample_weight.sum()
    # no early-out for an all-false mask: loss is already zero there, and
    # 0 / n_preds == 0 / 1 — keeping it branch-free is trace-safe
    return loss.sum(), n_preds, sample_weight


def _label_ranking_loss_compute(loss: Array, n_elements: int, sample_weight: Optional[Array] = None) -> Array:
    return _weighted_mean(loss, n_elements, sample_weight)


def label_ranking_loss(preds: Array, target: Array, sample_weight: Optional[Array] = None) -> Array:
    """Compute the label ranking loss (reference ``label_ranking_loss`` :216)."""
    loss, n_elements, sample_weight = _label_ranking_loss_update(preds, target, sample_weight)
    return _label_ranking_loss_compute(loss, n_elements, sample_weight)
