"""Average precision kernel.

Behavioral equivalent of reference
``torchmetrics/functional/classification/average_precision.py`` (235 LoC).
"""
import warnings
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.precision_recall_curve import (
    _precision_recall_curve_compute,
    _precision_recall_curve_update,
)
from metrics_tpu.utilities.data import _bincount

Array = jax.Array


def _average_precision_update(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
) -> Tuple[Array, Array, int, Optional[int]]:
    """Format inputs; micro flattens the label-indicator matrix (reference :27)."""
    preds, target, num_classes, pos_label = _precision_recall_curve_update(preds, target, num_classes, pos_label)
    if average == "micro":
        if preds.ndim == target.ndim:
            preds = preds.reshape(-1)
            target = target.reshape(-1)
            num_classes = 1
        else:
            raise ValueError("Cannot use `micro` average with multi-class input")
    return preds, target, num_classes, pos_label


def _average_precision_compute(
    preds: Array,
    target: Array,
    num_classes: int,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    sample_weights: Optional[Sequence] = None,
) -> Union[List[Array], Array]:
    """AP from the precision-recall curve (reference :59)."""
    precision, recall, _ = _precision_recall_curve_compute(preds, target, num_classes, pos_label)
    if average == "weighted":
        if preds.ndim == target.ndim and target.ndim > 1:
            weights = target.sum(axis=0).astype(jnp.float32)
        else:
            weights = _bincount(target, minlength=num_classes).astype(jnp.float32)
        weights = weights / jnp.sum(weights)
    else:
        weights = None
    return _average_precision_compute_with_precision_recall(precision, recall, num_classes, average, weights)


def _average_precision_compute_with_precision_recall(
    precision: Union[Array, List[Array]],
    recall: Union[Array, List[Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Union[List[Array], Array]:
    """Step-function integral of the PR curve (reference :121)."""
    if num_classes == 1:
        return -jnp.sum((recall[1:] - recall[:-1]) * precision[:-1])

    res = [-jnp.sum((r[1:] - r[:-1]) * p[:-1]) for p, r in zip(precision, recall)]

    if average in ("macro", "weighted"):
        res = jnp.stack(res)
        if bool(jnp.isnan(res).any()):
            warnings.warn(
                "Average precision score for one or more classes was `nan`. Ignoring these classes in average",
                UserWarning,
            )
        if average == "macro":
            return res[~jnp.isnan(res)].mean()
        weights = jnp.ones_like(res) if weights is None else weights
        return (res * weights)[~jnp.isnan(res)].sum()
    if average is None:
        return res
    allowed_average = ("micro", "macro", "weighted", None)
    raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")


def average_precision(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    sample_weights: Optional[Sequence] = None,
) -> Union[List[Array], Array]:
    """Compute average precision (reference ``average_precision`` :178).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import average_precision
        >>> pred = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        >>> target = jnp.asarray([0, 1, 1, 1])
        >>> average_precision(pred, target, pos_label=1)
        Array(1., dtype=float32)
    """
    preds, target, num_classes, pos_label = _average_precision_update(preds, target, num_classes, pos_label, average)
    return _average_precision_compute(preds, target, num_classes, pos_label, average, sample_weights)
