"""Average precision kernel.

Behavioral equivalent of reference
``torchmetrics/functional/classification/average_precision.py`` (235 LoC).
"""
import warnings
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.precision_recall_curve import (
    _precision_recall_curve_compute,
    _precision_recall_curve_update,
)
from metrics_tpu.utilities.data import _bincount

Array = jax.Array


def _average_precision_update(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
) -> Tuple[Array, Array, int, Optional[int]]:
    """Format inputs; micro flattens the label-indicator matrix (reference :27)."""
    preds, target, num_classes, pos_label = _precision_recall_curve_update(preds, target, num_classes, pos_label)
    if average == "micro":
        if preds.ndim == target.ndim:
            preds = preds.reshape(-1)
            target = target.reshape(-1)
            num_classes = 1
        else:
            raise ValueError("Cannot use `micro` average with multi-class input")
    return preds, target, num_classes, pos_label


def _binary_average_precision_static(preds: Array, target: Array, pos_label: int = 1) -> Array:
    """Exact binary AP with static shapes (jit/vmap/shard_map-safe).

    The curve form dedups thresholds with ``jnp.nonzero`` (a dynamic shape).
    The step integral doesn't need the materialized curve: sort descending
    once, locate tie-block ends, and sum ``(R_end - R_prev_end) * P_end``
    over the block ends — exactly the deduped curve's
    ``-sum((recall[1:]-recall[:-1]) * precision[:-1])`` (each unique
    threshold contributes its END-of-block cumulative tp/fp, which is what
    the dedup keeps). Same trick as ``_binary_roc_auc_static``.
    """
    p = preds.reshape(-1)
    t = (target.reshape(-1) == pos_label).astype(jnp.int32)
    n = p.shape[0]
    neg_sorted, t_sorted = jax.lax.sort((-p, t), num_keys=1)  # descending by score
    # exact integer counts (float32 cumsum silently plateaus past 2^24)
    tp = jnp.cumsum(t_sorted).astype(jnp.float32)
    fp = jnp.cumsum(1 - t_sorted).astype(jnp.float32)
    idx = jnp.arange(n, dtype=jnp.int32)
    boundary = neg_sorted[1:] != neg_sorted[:-1]
    is_end = jnp.concatenate([boundary, jnp.ones(1, dtype=bool)])
    npos = tp[-1]
    precision_i = tp / jnp.maximum(tp + fp, 1.0)
    recall_i = tp / jnp.maximum(npos, 1.0)
    prev_end = jax.lax.cummax(
        jnp.concatenate([jnp.full((1,), -1, jnp.int32), jnp.where(is_end, idx, -1)[:-1]])
    )
    r_prev = jnp.where(prev_end >= 0, recall_i[jnp.clip(prev_end, 0)], 0.0)
    ap = jnp.sum(jnp.where(is_end, (recall_i - r_prev) * precision_i, 0.0))
    return jnp.where(npos > 0, ap, jnp.nan)


def _average_precision_compute(
    preds: Array,
    target: Array,
    num_classes: int,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    sample_weights: Optional[Sequence] = None,
) -> Union[List[Array], Array]:
    """AP from the precision-recall curve (reference :59)."""
    if num_classes == 1 and sample_weights is None:
        # static-shape fast path (fully jittable, exactly the curve integral)
        return _binary_average_precision_static(preds, target, 1 if pos_label is None else pos_label)
    if (
        sample_weights is None
        and average == "macro"
        and num_classes is not None
        and num_classes > 1
        and preds.ndim == 2
    ):
        # per-class one-vs-rest static AP, vmapped over the class axis (the
        # AUROC static multiclass pattern); classes with no positives are
        # NaN and drop out of the mean, matching the curve path's exclusion
        if target.ndim == 1:  # multiclass labels
            per_class = jax.vmap(
                lambda c: _binary_average_precision_static(preds[:, c], (target == c).astype(jnp.int32), 1)
            )(jnp.arange(num_classes))
        else:  # multilabel indicator
            per_class = jax.vmap(
                lambda c: _binary_average_precision_static(preds[:, c], target[:, c], 1)
            )(jnp.arange(num_classes))
        n_valid = jnp.sum(~jnp.isnan(per_class))
        if not isinstance(per_class, jax.core.Tracer) and bool(jnp.isnan(per_class).any()):
            # eager parity with the curve path (reference :121): absent
            # classes are excluded from the mean WITH a signal to the user
            warnings.warn(
                "Average precision score for one or more classes was `nan`. Ignoring these classes in average",
                UserWarning,
            )
        return jnp.where(n_valid > 0, jnp.nansum(per_class) / jnp.maximum(n_valid, 1).astype(per_class.dtype), jnp.nan)
    precision, recall, _ = _precision_recall_curve_compute(preds, target, num_classes, pos_label, sample_weights)
    if average == "weighted":
        if preds.ndim == target.ndim and target.ndim > 1:
            weights = target.sum(axis=0).astype(jnp.float32)
        else:
            weights = _bincount(target, minlength=num_classes).astype(jnp.float32)
        weights = weights / jnp.sum(weights)
    else:
        weights = None
    return _average_precision_compute_with_precision_recall(precision, recall, num_classes, average, weights)


def _average_precision_compute_with_precision_recall(
    precision: Union[Array, List[Array]],
    recall: Union[Array, List[Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Union[List[Array], Array]:
    """Step-function integral of the PR curve (reference :121)."""
    if num_classes == 1:
        return -jnp.sum((recall[1:] - recall[:-1]) * precision[:-1])

    res = [-jnp.sum((r[1:] - r[:-1]) * p[:-1]) for p, r in zip(precision, recall)]

    if average in ("macro", "weighted"):
        res = jnp.stack(res)
        if bool(jnp.isnan(res).any()):
            warnings.warn(
                "Average precision score for one or more classes was `nan`. Ignoring these classes in average",
                UserWarning,
            )
        if average == "macro":
            return res[~jnp.isnan(res)].mean()
        weights = jnp.ones_like(res) if weights is None else weights
        return (res * weights)[~jnp.isnan(res)].sum()
    if average is None:
        return res
    allowed_average = ("micro", "macro", "weighted", None)
    raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")


def average_precision(
    preds: Array,
    target: Array,
    num_classes: Optional[int] = None,
    pos_label: Optional[int] = None,
    average: Optional[str] = "macro",
    sample_weights: Optional[Sequence] = None,
) -> Union[List[Array], Array]:
    """Compute average precision (reference ``average_precision`` :178).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import average_precision
        >>> pred = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        >>> target = jnp.asarray([0, 1, 1, 1])
        >>> average_precision(pred, target, pos_label=1)
        Array(1., dtype=float32)
    """
    preds, target, num_classes, pos_label = _average_precision_update(preds, target, num_classes, pos_label, average)
    return _average_precision_compute(preds, target, num_classes, pos_label, average, sample_weights)
