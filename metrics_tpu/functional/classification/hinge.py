"""Hinge loss kernels.

Behavioral equivalent of reference
``torchmetrics/functional/classification/hinge.py`` (231 LoC): binary,
Crammer-Singer multiclass, and one-vs-all modes. Boolean fancy indexing is
replaced with where-masking (jit-safe).
"""
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _input_squeeze
from metrics_tpu.utilities.data import to_onehot
from metrics_tpu.utilities.enums import DataType, EnumStr

Array = jax.Array


class MulticlassMode(EnumStr):
    """Possible multiclass modes of hinge (reference :24)."""

    CRAMMER_SINGER = "crammer-singer"
    ONE_VS_ALL = "one-vs-all"


def _check_shape_and_type_consistency_hinge(preds: Array, target: Array) -> DataType:
    """Resolve binary vs multiclass from shapes (reference :36)."""
    if target.ndim > 1:
        raise ValueError(f"The `target` should be one dimensional, got `target` with shape={target.shape}.")
    if preds.ndim == 1:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        return DataType.BINARY
    if preds.ndim == 2:
        if preds.shape[0] != target.shape[0]:
            raise ValueError(
                "The `preds` and `target` should have the same shape in the first dimension,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        return DataType.MULTICLASS
    raise ValueError(f"The `preds` should be one or two dimensional, got `preds` with shape={preds.shape}.")


def _hinge_update(
    preds: Array,
    target: Array,
    squared: bool = False,
    multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
) -> Tuple[Array, Array]:
    """Sum of hinge losses + observation count (reference :76)."""
    preds, target = _input_squeeze(preds, target)
    mode = _check_shape_and_type_consistency_hinge(preds, target)

    if mode == DataType.MULTICLASS:
        target_onehot = to_onehot(target, max(2, preds.shape[1])).astype(bool)

    if mode == DataType.MULTICLASS and (multiclass_mode is None or multiclass_mode == MulticlassMode.CRAMMER_SINGER):
        # margin = score of true class - best score among other classes
        true_score = jnp.sum(jnp.where(target_onehot, preds, 0.0), axis=1)
        other_best = jnp.max(jnp.where(target_onehot, -jnp.inf, preds), axis=1)
        margin = true_score - other_best
    elif mode == DataType.BINARY or multiclass_mode == MulticlassMode.ONE_VS_ALL:
        if mode == DataType.BINARY:
            t = target.astype(bool)
        else:
            t = target_onehot
        margin = jnp.where(t, preds, -preds)
    else:
        raise ValueError(
            "The `multiclass_mode` should be either None / 'crammer-singer' / MulticlassMode.CRAMMER_SINGER"
            f"(default) or 'one-vs-all' / MulticlassMode.ONE_VS_ALL, got {multiclass_mode}."
        )

    measures = jnp.clip(1 - margin, 0, None)
    if squared:
        measures = measures**2

    total = jnp.asarray(target.shape[0])
    return measures.sum(axis=0), total


def _hinge_compute(measure: Array, total: Array) -> Array:
    return measure / total


def hinge_loss(
    preds: Array,
    target: Array,
    squared: bool = False,
    multiclass_mode: Optional[Union[str, MulticlassMode]] = None,
) -> Array:
    """Compute the mean hinge loss (reference ``hinge_loss`` :154).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import hinge_loss
        >>> target = jnp.asarray([0, 1, 1])
        >>> preds = jnp.asarray([-2.2, 2.4, 0.1])
        >>> hinge_loss(preds, target)
        Array(0.3, dtype=float32)
    """
    measure, total = _hinge_update(preds, target, squared=squared, multiclass_mode=multiclass_mode)
    return _hinge_compute(measure, total)
