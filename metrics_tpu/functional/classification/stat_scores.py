"""TP/FP/TN/FN sufficient statistics — the classification backbone.

Behavioral equivalent of the reference's
``torchmetrics/functional/classification/stat_scores.py`` (``_stat_scores``
:63, ``_stat_scores_update`` :110, ``_stat_scores_compute`` :196,
``_reduce_stat_scores`` :231, ``stat_scores`` :288), on jnp.

XLA-first notes:

* ``_stat_scores`` and ``_reduce_stat_scores`` are pure, static-shape, fully
  jittable kernels.
* Where the reference drops classes with data-dependent boolean indexing, the
  ignore sentinel (denominator < 0 -> class excluded) is used instead so
  shapes stay static under jit (see ``_reduce_stat_scores``).
* ``ignore_index`` column-deletion is a static-index slice (jit-safe);
  negative-``ignore_index`` row dropping is value-dependent and eager-only.
"""
from typing import Callable, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _input_format_classification
from metrics_tpu.utilities.enums import AverageMethod, DataType, MDMCAverageMethod

Array = jax.Array


def _del_column(data: Array, idx: int) -> Array:
    """Delete the class column at a static index (reference :23)."""
    return jnp.concatenate([data[:, :idx], data[:, (idx + 1):]], axis=1)


def _drop_negative_ignored_indices(
    preds: Array, target: Array, ignore_index: int, mode: DataType
) -> Tuple[Array, Array]:
    """Drop rows whose target equals a negative ``ignore_index`` (reference :28).

    Value-dependent output shape — eager-only (not jit-traceable).
    """
    if mode == DataType.MULTIDIM_MULTICLASS and jnp.issubdtype(preds.dtype, jnp.floating):
        num_classes = preds.shape[1]
        n_dims = preds.ndim
        preds = jnp.swapaxes(preds, 1, n_dims - 1).reshape(-1, num_classes)
        target = target.reshape(-1)
    if mode in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS):
        keep = target != ignore_index
        preds = preds[keep]
        target = target[keep]
    return preds, target


def _stat_scores(
    preds: Array,
    target: Array,
    reduce: Optional[str] = "micro",
) -> Tuple[Array, Array, Array, Array]:
    """Count tp/fp/tn/fn over binary ``(N, C)`` or ``(N, C, X)`` tensors.

    Output shapes per the reference contract (:63-107):
    ``(N, C)`` input -> micro: scalar; macro: ``(C,)``; samples: ``(N,)``.
    ``(N, C, X)`` input -> micro: ``(N,)``; macro: ``(N, C)``; samples: ``(N, X)``.
    """
    dim: Union[int, Tuple[int, ...]] = 1  # for "samples"
    if reduce == "micro":
        dim = (0, 1) if preds.ndim == 2 else (1, 2)
    elif reduce == "macro":
        dim = 0 if preds.ndim == 2 else 2

    true_pred = target == preds
    false_pred = target != preds
    pos_pred = preds == 1
    neg_pred = preds == 0

    tp = jnp.sum(true_pred & pos_pred, axis=dim)
    fp = jnp.sum(false_pred & pos_pred, axis=dim)
    tn = jnp.sum(true_pred & neg_pred, axis=dim)
    fn = jnp.sum(false_pred & neg_pred, axis=dim)
    dtype = jnp.int32
    return tp.astype(dtype), fp.astype(dtype), tn.astype(dtype), fn.astype(dtype)


def _micro_fast_path_eligible(
    preds, target, reduce, mdmc_reduce, num_classes, top_k, multiclass, ignore_index, mode, validate_args
) -> bool:
    """True when the micro-multiclass shortcut in ``_stat_scores_update``
    applies (validate_args=False, plain (N, C) float preds vs (N,) labels,
    top-1, no ignore_index)."""
    return (
        not validate_args
        and reduce == "micro"
        and mdmc_reduce is None
        and ignore_index is None
        and (top_k is None or top_k == 1)
        and multiclass is not False
        and mode is None
        and hasattr(preds, "ndim")
        and hasattr(target, "ndim")
        and preds.ndim == 2
        and target.ndim == 1
        and jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating)
        and preds.shape[1] > 1
        and (num_classes is None or num_classes == preds.shape[1])
    )


def _stat_scores_update(
    preds: Array,
    target: Array,
    reduce: Optional[str] = "micro",
    mdmc_reduce: Optional[str] = None,
    num_classes: Optional[int] = None,
    top_k: Optional[int] = None,
    threshold: float = 0.5,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
    mode: Optional[DataType] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, Array, Array]:
    """Normalize inputs and count tp/fp/tn/fn (reference :110-193)."""
    if _micro_fast_path_eligible(
        preds, target, reduce, mdmc_reduce, num_classes, top_k, multiclass, ignore_index, mode, validate_args
    ):
        # micro multiclass fast path: the one-hot binarization cancels out —
        # per sample, a correct argmax gives (tp=1, tn=C-1) and an incorrect
        # one (fp=1, fn=1, tn=C-2), so four sums collapse to one compare.
        # Only taken with validate_args=False (skips the gate's value checks).
        # The compare runs through the ops/argmax_compare streaming tile on
        # TPU (classes stay lane-resident; no argmax relayout pass).
        from metrics_tpu.ops.argmax_compare import argmax_correct_count

        n, c = preds.shape
        correct = argmax_correct_count(preds, target)
        n_arr = jnp.asarray(n, dtype=jnp.int32)
        return correct, n_arr - correct, n_arr * (c - 2) + correct, n_arr - correct

    _negative_index_dropped = False
    if ignore_index is not None and ignore_index < 0 and mode is not None:
        preds, target = _drop_negative_ignored_indices(preds, target, ignore_index, mode)
        _negative_index_dropped = True

    preds, target, _ = _input_format_classification(
        preds,
        target,
        threshold=threshold,
        num_classes=num_classes,
        multiclass=multiclass,
        top_k=top_k,
        ignore_index=ignore_index,
        validate_args=validate_args,
    )

    if ignore_index is not None and ignore_index >= preds.shape[1]:
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {preds.shape[1]} classes")
    if ignore_index is not None and preds.shape[1] == 1:
        raise ValueError("You can not use `ignore_index` with binary data.")

    if preds.ndim == 3:
        if not mdmc_reduce:
            raise ValueError(
                "When your inputs are multi-dimensional multi-class, you have to set the `mdmc_reduce` parameter"
            )
        if mdmc_reduce == "global":
            preds = jnp.swapaxes(preds, 1, 2).reshape(-1, preds.shape[1])
            target = jnp.swapaxes(target, 1, 2).reshape(-1, target.shape[1])

    if ignore_index is not None and reduce != "macro" and not _negative_index_dropped:
        preds = _del_column(preds, ignore_index)
        target = _del_column(target, ignore_index)

    tp, fp, tn, fn = _stat_scores(preds, target, reduce=reduce)

    if ignore_index is not None and reduce == "macro" and not _negative_index_dropped:
        tp = tp.at[..., ignore_index].set(-1)
        fp = fp.at[..., ignore_index].set(-1)
        tn = tn.at[..., ignore_index].set(-1)
        fn = fn.at[..., ignore_index].set(-1)

    return tp, fp, tn, fn


def _stat_scores_compute(tp: Array, fp: Array, tn: Array, fn: Array) -> Array:
    """Concatenate [tp, fp, tn, fn, support] along a new last axis (reference :196)."""
    stats = [
        tp[..., None],
        fp[..., None],
        tn[..., None],
        fn[..., None],
        tp[..., None] + fn[..., None],  # support
    ]
    outputs = jnp.concatenate(stats, axis=-1)
    return jnp.where(outputs < 0, -1, outputs)


def _reduce_stat_scores(
    numerator: Array,
    denominator: Array,
    weights: Optional[Array],
    average: Optional[str],
    mdmc_average: Optional[str],
    zero_division: int = 0,
) -> Array:
    """Reduce ``numerator/denominator`` scores by the averaging method.

    Jit-safe equivalent of reference :231-285: a negative denominator marks an
    ignored entry (class masked out of the average, or NaN when
    ``average='none'``); a zero denominator scores ``zero_division``.
    """
    numerator = numerator.astype(jnp.float32)
    denominator = denominator.astype(jnp.float32)
    zero_div_mask = denominator == 0
    ignore_mask = denominator < 0

    weights = jnp.ones_like(denominator) if weights is None else weights.astype(jnp.float32)

    numerator = jnp.where(zero_div_mask, float(zero_division), numerator)
    denominator = jnp.where(zero_div_mask | ignore_mask, 1.0, denominator)
    weights = jnp.where(ignore_mask, 0.0, weights)

    if average not in (AverageMethod.MICRO, AverageMethod.NONE, None):
        weights = weights / weights.sum(axis=-1, keepdims=True)

    scores = weights * (numerator / denominator)
    # sum(weights) == 0 (e.g. the only present class is ignored) -> 0/0 NaN
    scores = jnp.where(jnp.isnan(scores), float(zero_division), scores)

    if mdmc_average == MDMCAverageMethod.SAMPLEWISE and scores.ndim > 0:
        # (0-d scores arise when samplewise is set but inputs were not
        # multi-dim; torch's 0-d mean(dim=0) is a no-op, match that.)
        scores = scores.mean(axis=0)
        ignore_mask = ignore_mask.sum(axis=0).astype(bool)

    if average in (AverageMethod.NONE, None):
        scores = jnp.where(ignore_mask, jnp.nan, scores)
    else:
        scores = scores.sum()
    return scores


def stat_scores(
    preds: Array,
    target: Array,
    reduce: str = "micro",
    mdmc_reduce: Optional[str] = None,
    num_classes: Optional[int] = None,
    top_k: Optional[int] = None,
    threshold: float = 0.5,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """Compute ``[tp, fp, tn, fn, support]`` (reference ``stat_scores`` :288).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import stat_scores
        >>> preds  = jnp.asarray([1, 0, 2, 1])
        >>> target = jnp.asarray([1, 1, 2, 0])
        >>> stat_scores(preds, target, reduce='macro', num_classes=3)
        Array([[0, 1, 2, 1, 1],
               [1, 1, 1, 1, 2],
               [1, 0, 3, 0, 1]], dtype=int32)
        >>> stat_scores(preds, target, reduce='micro')
        Array([2, 2, 6, 2, 4], dtype=int32)
    """
    if reduce not in ["micro", "macro", "samples"]:
        raise ValueError(f"The `reduce` {reduce} is not valid.")
    if mdmc_reduce not in [None, "samplewise", "global"]:
        raise ValueError(f"The `mdmc_reduce` {mdmc_reduce} is not valid.")
    if reduce == "macro" and (not num_classes or num_classes < 1):
        raise ValueError("When you set `reduce` as 'macro', you have to provide the number of classes.")
    if num_classes and ignore_index is not None and (not 0 <= ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_reduce,
        top_k=top_k,
        threshold=threshold,
        num_classes=num_classes,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _stat_scores_compute(tp, fp, tn, fn)
