"""F-beta / F1 kernels.

Behavioral equivalent of reference
``torchmetrics/functional/classification/f_beta.py`` (354 LoC):
``_safe_divide`` :24, ``_fbeta_compute`` :30, ``fbeta_score`` :113,
``f1_score`` :225. In-place sentinel assignment is replaced with jit-safe
``where`` masking.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.precision_recall import _check_average_arg
from metrics_tpu.functional.classification.stat_scores import _reduce_stat_scores, _stat_scores_update
from metrics_tpu.utilities.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


def _safe_divide(num: Array, denom: Array) -> Array:
    """num / denom with zero denominators mapped to 1 (reference :24)."""
    denom = jnp.asarray(denom, dtype=num.dtype)  # int counts meet f32 numerators
    denom = jnp.where(denom == 0, jnp.ones((), dtype=denom.dtype), denom)
    return num / denom


def _fbeta_compute(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    beta: float,
    ignore_index: Optional[int],
    average: Optional[str],
    mdmc_average: Optional[str],
) -> Array:
    """F-beta from stat scores (reference :30-108)."""
    if average == AverageMethod.MICRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        # mask ignore sentinel entries (tp == -1 from macro ignore_index)
        mask = (tp >= 0).astype(tp.dtype)
        prec = _safe_divide((tp * mask).sum().astype(jnp.float32), ((tp + fp) * mask).sum())
        rec = _safe_divide((tp * mask).sum().astype(jnp.float32), ((tp + fn) * mask).sum())
    else:
        prec = _safe_divide(tp.astype(jnp.float32), tp + fp)
        rec = _safe_divide(tp.astype(jnp.float32), tp + fn)

    num = (1 + beta**2) * prec * rec
    denom = beta**2 * prec + rec
    denom = jnp.where(denom == 0.0, 1.0, denom)

    if average == AverageMethod.NONE and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        # classes absent from preds AND target are meaningless -> NaN
        absent = (tp + fp + fn) == 0
        num = jnp.where(absent, -1.0, num)
        denom = jnp.where(absent, -1.0, denom)

    if ignore_index is not None:
        if average not in (AverageMethod.MICRO, AverageMethod.SAMPLES) and mdmc_average == MDMCAverageMethod.SAMPLEWISE:
            num = num.at[..., ignore_index].set(jnp.asarray(-1, num.dtype))
            denom = denom.at[..., ignore_index].set(jnp.asarray(-1, denom.dtype))
        elif average not in (AverageMethod.MICRO, AverageMethod.SAMPLES):
            num = num.at[ignore_index, ...].set(jnp.asarray(-1, num.dtype))
            denom = denom.at[ignore_index, ...].set(jnp.asarray(-1, denom.dtype))

    if average == AverageMethod.MACRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        absent = ((tp + fp + fn) == 0) | ((tp + fp + fn) == -3)
        denom = jnp.where(absent, -1.0, denom)

    return _reduce_stat_scores(
        numerator=num,
        denominator=denom,
        weights=None if average != AverageMethod.WEIGHTED else tp + fn,
        average=average,
        mdmc_average=mdmc_average,
    )


def fbeta_score(
    preds: Array,
    target: Array,
    beta: float = 1.0,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """Compute F-beta (reference :113).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import fbeta_score
        >>> target = jnp.asarray([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.asarray([0, 2, 1, 0, 0, 1])
        >>> fbeta_score(preds, target, num_classes=3, beta=0.5)
        Array(0.33333334, dtype=float32)
    """
    _check_average_arg(average, mdmc_average, num_classes, ignore_index)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _fbeta_compute(tp, fp, tn, fn, beta, ignore_index, average, mdmc_average)


def f1_score(
    preds: Array,
    target: Array,
    beta: float = 1.0,
    average: str = "micro",
    mdmc_average: Optional[str] = None,
    ignore_index: Optional[int] = None,
    num_classes: Optional[int] = None,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    multiclass: Optional[bool] = None,
) -> Array:
    """F1 = F-beta with beta=1 (reference :225).

    ``beta`` is accepted and IGNORED, exactly like the reference
    (``f_beta.py:250`` documents "It is ignored" and ``:354`` hardcodes
    1.0) — use :func:`fbeta_score` for a real beta.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import f1_score
        >>> target = jnp.asarray([0, 1, 2, 0, 1, 2])
        >>> preds = jnp.asarray([0, 2, 1, 0, 0, 1])
        >>> f1_score(preds, target, num_classes=3)
        Array(0.33333334, dtype=float32)
    """
    return fbeta_score(preds, target, 1.0, average, mdmc_average, ignore_index, num_classes, threshold, top_k, multiclass)
