"""Calibration error kernels (ECE / RMSCE / MCE).

Behavioral equivalent of reference
``torchmetrics/functional/classification/calibration_error.py`` (208 LoC).
The reference's ``scatter_add_`` binning (:53-82) becomes jit-safe
``.at[idx].add`` segment accumulation (deterministic on TPU).
"""
from typing import Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _input_format_classification
from metrics_tpu.utilities.enums import DataType

Array = jax.Array


def _binning_bucketize(
    confidences: Array, accuracies: Array, bin_boundaries: Array
) -> Tuple[Array, Array, Array]:
    """Per-bin mean accuracy/confidence and bin mass (reference :52)."""
    n_bins = bin_boundaries.shape[0] - 1
    indices = jnp.clip(jnp.searchsorted(bin_boundaries, confidences, side="left") - 1, 0, n_bins - 1)
    ones = jnp.ones_like(confidences)
    count_bin = jnp.zeros(n_bins, dtype=confidences.dtype).at[indices].add(ones)
    conf_bin = jnp.zeros(n_bins, dtype=confidences.dtype).at[indices].add(confidences)
    conf_bin = jnp.nan_to_num(conf_bin / count_bin)
    acc_bin = jnp.zeros(n_bins, dtype=confidences.dtype).at[indices].add(accuracies)
    acc_bin = jnp.nan_to_num(acc_bin / count_bin)
    prop_bin = count_bin / count_bin.sum()
    return acc_bin, conf_bin, prop_bin


def _ce_compute(
    confidences: Array,
    accuracies: Array,
    bin_boundaries: Array,
    norm: str = "l1",
    debias: bool = False,
) -> Array:
    """Calibration error under the given norm (reference :85)."""
    if norm not in {"l1", "l2", "max"}:
        raise ValueError(f"Norm {norm} is not supported. Please select from l1, l2, or max. ")

    acc_bin, conf_bin, prop_bin = _binning_bucketize(confidences, accuracies, bin_boundaries)

    if norm == "l1":
        return jnp.sum(jnp.abs(acc_bin - conf_bin) * prop_bin)
    if norm == "max":
        return jnp.max(jnp.abs(acc_bin - conf_bin))
    # l2
    ce = jnp.sum(jnp.power(acc_bin - conf_bin, 2) * prop_bin)
    if debias:
        debias_bins = (acc_bin * (acc_bin - 1) * prop_bin) / (prop_bin * confidences.shape[0] - 1)
        ce = ce + jnp.sum(jnp.nan_to_num(debias_bins))
    return jnp.where(ce > 0, jnp.sqrt(jnp.maximum(ce, 0.0)), 0.0)


def _ce_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Extract top-1 confidences and correctness (reference :132)."""
    _, _, mode = _input_format_classification(preds, target)

    if mode == DataType.BINARY:
        confidences, accuracies = preds, target
    elif mode == DataType.MULTICLASS:
        confidences = preds.max(axis=1)
        predictions = preds.argmax(axis=1)
        accuracies = (predictions == target)
    elif mode == DataType.MULTIDIM_MULTICLASS:
        # reshape (N, C, ...) -> (N*..., C)
        n_classes = preds.shape[1]
        preds_flat = jnp.moveaxis(preds, 1, -1).reshape(-1, n_classes)
        target_flat = target.reshape(-1)
        confidences = preds_flat.max(axis=1)
        accuracies = (preds_flat.argmax(axis=1) == target_flat)
    else:
        raise ValueError(f"Calibration error is not well-defined for data with size {preds.shape} and targets {target.shape}.")
    return confidences.astype(jnp.float32).reshape(-1), accuracies.astype(jnp.float32).reshape(-1)


def calibration_error(preds: Array, target: Array, n_bins: int = 15, norm: str = "l1") -> Array:
    """Compute top-label calibration error (reference ``calibration_error`` :165).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import calibration_error
        >>> preds = jnp.asarray([0.1, 0.9, 0.8, 0.3])
        >>> target = jnp.asarray([0, 1, 1, 1])
        >>> float(calibration_error(preds, target, n_bins=2, norm='l1')) > 0
        True
    """
    if norm not in ("l1", "l2", "max"):
        raise ValueError(f"Argument `norm` is expected to be one of 'l1', 'l2', 'max' but got {norm}")
    if not isinstance(n_bins, int) or n_bins <= 0:
        raise ValueError(f"Expected argument `n_bins` to be a int larger than 0 but got {n_bins}")
    confidences, accuracies = _ce_update(preds, target)
    bin_boundaries = jnp.linspace(0, 1, n_bins + 1, dtype=jnp.float32)
    return _ce_compute(confidences, accuracies, bin_boundaries, norm=norm)
