"""KL divergence kernel.

Behavioral equivalent of reference
``torchmetrics/functional/classification/kl_divergence.py`` (113 LoC).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.checks import _check_same_shape

Array = jax.Array

METRIC_EPS = 1e-6


def _kld_update(p: Array, q: Array, log_prob: bool) -> Tuple[Array, int]:
    """Per-sample KL scores + count (reference :25)."""
    _check_same_shape(p, q)
    if p.ndim != 2 or q.ndim != 2:
        raise ValueError(f"Expected both p and q distribution to be 2D but got {p.ndim} and {q.ndim} respectively")

    total = p.shape[0]
    if log_prob:
        measures = jnp.sum(jnp.exp(p) * (p - q), axis=-1)
    else:
        p = p / p.sum(axis=-1, keepdims=True)
        q = q / q.sum(axis=-1, keepdims=True)
        q = jnp.clip(q, METRIC_EPS, None)
        measures = jnp.sum(p * jnp.log(p / q), axis=-1)
    return measures, total


def _kld_compute(measures: Array, total: Array, reduction: Optional[str] = "mean") -> Array:
    if reduction == "sum":
        return measures.sum()
    if reduction == "mean":
        return measures.sum() / total
    if reduction is None or reduction == "none":
        return measures
    return measures / total


def kl_divergence(p: Array, q: Array, log_prob: bool = False, reduction: Optional[str] = "mean") -> Array:
    """Compute KL(P || Q) (reference ``kl_divergence`` :82).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import kl_divergence
        >>> p = jnp.asarray([[0.36, 0.48, 0.16]])
        >>> q = jnp.asarray([[1/3, 1/3, 1/3]])
        >>> kl_divergence(p, q)
        Array(0.08530961, dtype=float32)
    """
    measures, total = _kld_update(p, q, log_prob)
    return _kld_compute(measures, total, reduction)
