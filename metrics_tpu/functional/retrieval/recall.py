"""Functional IR recall@k.

Behavioral equivalent of reference
``torchmetrics/functional/retrieval/recall.py:20``.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.retrieval._segment import (
    make_group_context,
    make_topk_context,
    recall_scores,
    recall_scores_topk,
)
from metrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_recall(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Fraction of ALL relevant documents retrieved in the top ``k``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_recall
        >>> preds = jnp.asarray([0.2, 0.3, 0.5])
        >>> target = jnp.asarray([True, False, True])
        >>> retrieval_recall(preds, target, k=2)
        Array(0.5, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if k is not None and not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")
    if k is not None and k < preds.shape[0]:
        # single-query dense top-k fast path: one lax.top_k instead of the
        # full sort (bitwise-equal; see _segment.py)
        tctx = make_topk_context(preds, target, (1, preds.shape[0]), k)
        return recall_scores_topk(tctx)[0].astype(preds.dtype)
    ctx = make_group_context(preds, target, jnp.zeros(preds.shape, dtype=jnp.int32))
    return recall_scores(ctx, k=k)[0].astype(preds.dtype)
