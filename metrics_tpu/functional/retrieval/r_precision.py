"""Functional IR R-precision.

Behavioral equivalent of reference
``torchmetrics/functional/retrieval/r_precision.py:20``.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.functional.retrieval._segment import make_group_context, r_precision_scores
from metrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_r_precision(preds: Array, target: Array) -> Array:
    """Precision at ``k`` where ``k`` is the number of relevant documents.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_r_precision
        >>> preds = jnp.asarray([0.2, 0.3, 0.5])
        >>> target = jnp.asarray([True, False, True])
        >>> retrieval_r_precision(preds, target)
        Array(0.5, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    ctx = make_group_context(preds, target, jnp.zeros(preds.shape, dtype=jnp.int32))
    return r_precision_scores(ctx)[0].astype(preds.dtype)
