"""Vectorized per-query retrieval kernels (sort + segmented scans).

TPU-native replacement for the reference's per-query Python loop
(``torchmetrics/retrieval/base.py:114-143`` + ``get_group_indexes``,
``torchmetrics/utilities/data.py:196-220``): ALL queries are scored in one
fused XLA program. The pipeline is scatter/gather-free — the pattern both
TPU scatter (serialized) and large gathers lower badly to:

* one stable multi-operand ``lax.sort`` by ``(query, -score)`` that carries
  the targets along (no argsort + gather),
* plain ``cummax``/``cummin`` scans for per-position group bounds,
* **segmented associative scans** (``lax.associative_scan`` over
  ``(boundary_flag, value)`` pairs) for every per-group reduction — sums,
  mins — with group totals broadcast per position as
  ``forward_scan + reverse_scan - x`` (no dense-by-segment scatter).

Every kernel returns a per-position ``(N,)`` vector with the group's
score broadcast to EVERY position of the group (kernels must preserve this
invariant — the single-query functional wrappers read position 0);
``ctx.nonempty`` is the end-position mask, so aggregating
``where(nonempty & valid, scores, 0)`` sums exactly one score per group. Measured ~8x faster than the previous
lexsort + ``jax.ops.segment_*`` formulation at 1M documents on v5e.
"""
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array


def _segmented_scan(x: Array, boundary: Array, op: Callable, reverse: bool = False) -> Array:
    """Inclusive scan of ``x`` with ``op``, restarting at group boundaries.

    ``boundary`` marks the first element of each group for a forward scan;
    for ``reverse=True`` pass the mask of each group's LAST element instead.
    """

    def combine(a, b):
        a_flag, a_val = a
        b_flag, b_val = b
        return a_flag | b_flag, jnp.where(b_flag, b_val, op(a_val, b_val))

    if reverse:
        _, out = jax.lax.associative_scan(combine, (boundary[::-1], x[::-1]))
        return out[::-1]
    _, out = jax.lax.associative_scan(combine, (boundary, x))
    return out


class GroupContext(NamedTuple):
    """Shared per-query machinery for all retrieval kernels.

    All arrays are per-position over the ``(group, -pred)``-sorted layout
    (stable, so ties keep input order). Group-level quantities (``count``,
    ``npos``) are broadcast to every position of their group; ``nonempty``
    is True exactly at each group's last position (the aggregation mask —
    one True per real group).
    """

    preds: Array  # (N,) sorted scores
    target: Array  # (N,) targets in the same order
    gid: Array  # (N,) dense group id, nondecreasing
    rank: Array  # (N,) 0-based within-group rank
    first: Array  # (N,) bool, first position of its group
    count: Array  # (N,) group size, broadcast per position
    npos: Array  # (N,) positive-target total per group, broadcast
    nonempty: Array  # (N,) bool, True at each group's end position
    num_segments: int  # static position count (== N)

    def group_sum(self, x: Array) -> Array:
        """Per-group total of ``x``, broadcast to every group position."""
        fwd = _segmented_scan(x, self.first, jnp.add)
        rev = _segmented_scan(x, self.nonempty, jnp.add, reverse=True)
        return fwd + rev - x

    def group_min(self, x: Array) -> Array:
        """Per-group minimum of ``x``, broadcast to every group position."""
        fwd = _segmented_scan(x, self.first, jnp.minimum)
        rev = _segmented_scan(x, self.nonempty, jnp.minimum, reverse=True)
        return jnp.minimum(fwd, rev)

    def group_cumsum(self, x: Array) -> Array:
        """Inclusive per-group cumulative sum of ``x``."""
        return _segmented_scan(x, self.first, jnp.add)


def make_group_context(preds: Array, target: Array, indexes: Array) -> GroupContext:
    """Build the shared sorted/grouped view of a flat retrieval batch."""
    n = preds.shape[0]
    sidx, sneg, starget = jax.lax.sort(
        (indexes, -preds.astype(jnp.float32), target), num_keys=2
    )
    spreds = -sneg

    boundary = sidx[1:] != sidx[:-1]
    first = jnp.concatenate([jnp.ones((1,), dtype=bool), boundary])
    is_end = jnp.concatenate([boundary, jnp.ones((1,), dtype=bool)])
    gid = jnp.cumsum(first) - 1

    pos = jnp.arange(n)
    block_start = jax.lax.cummax(jnp.where(first, pos, -1))
    block_end = jax.lax.cummin(jnp.where(is_end, pos, n), reverse=True)
    rank = pos - block_start
    count = (block_end - block_start + 1).astype(jnp.int32)

    ctx = GroupContext(
        preds=spreds,
        target=starget,
        gid=gid,
        rank=rank,
        first=first,
        count=count,
        npos=jnp.zeros_like(spreds),  # placeholder, replaced below
        nonempty=is_end,
        num_segments=n,
    )
    npos = ctx.group_sum((starget > 0).astype(jnp.float32))
    return ctx._replace(npos=npos)


def _topk_mask(ctx: GroupContext, k: Optional[int]) -> Array:
    if k is None:
        return jnp.ones_like(ctx.rank, dtype=bool)
    return ctx.rank < k


def average_precision_scores(ctx: GroupContext, k: Optional[int] = None) -> Array:
    """Per-group IR average precision, optionally @k (ref
    ``functional/retrieval/average_precision.py:20``; the ``top_k`` variant
    sums precision over the first ``k`` ranks and normalizes by
    ``min(npos, k)``, the maximum number of relevant documents that can
    appear there)."""
    t = (ctx.target > 0).astype(jnp.float32)
    hits = ctx.group_cumsum(t)  # relevant seen up to and incl. this rank
    contrib = t * hits / (ctx.rank + 1).astype(jnp.float32)
    if k is not None:
        contrib = jnp.where(_topk_mask(ctx, k), contrib, 0.0)
    total = ctx.group_sum(contrib)
    denom = ctx.npos if k is None else jnp.minimum(ctx.npos, float(k))
    return jnp.where(ctx.npos > 0, total / jnp.maximum(denom, 1.0), 0.0)


def reciprocal_rank_scores(ctx: GroupContext) -> Array:
    """Per-group reciprocal rank (ref ``functional/retrieval/reciprocal_rank.py:20``)."""
    sentinel = ctx.num_segments
    first_hit = ctx.group_min(jnp.where(ctx.target > 0, ctx.rank, sentinel))
    return jnp.where(first_hit < sentinel, 1.0 / (first_hit + 1).astype(jnp.float32), 0.0)


def precision_scores(ctx: GroupContext, k: Optional[int], adaptive_k: bool = False) -> Array:
    """Per-group precision@k (ref ``functional/retrieval/precision.py:21``)."""
    t = (ctx.target > 0).astype(jnp.float32)
    if k is None:
        k_g = ctx.count.astype(jnp.float32)
        mask = jnp.ones_like(t, dtype=bool)
    else:
        k_g = jnp.where(adaptive_k, jnp.minimum(k, ctx.count), k).astype(jnp.float32)
        mask = _topk_mask(ctx, k)
    rel = ctx.group_sum(t * mask.astype(t.dtype))
    return jnp.where(ctx.npos > 0, rel / jnp.maximum(k_g, 1.0), 0.0)


def r_precision_scores(ctx: GroupContext) -> Array:
    """Per-group R-precision (ref ``functional/retrieval/r_precision.py:20``)."""
    t = (ctx.target > 0).astype(jnp.float32)
    in_top_r = ctx.rank.astype(jnp.float32) < ctx.npos
    rel = ctx.group_sum(t * in_top_r.astype(t.dtype))
    return jnp.where(ctx.npos > 0, rel / jnp.maximum(ctx.npos, 1.0), 0.0)


def recall_scores(ctx: GroupContext, k: Optional[int]) -> Array:
    """Per-group recall@k (ref ``functional/retrieval/recall.py:20``)."""
    t = (ctx.target > 0).astype(jnp.float32)
    rel = ctx.group_sum(t * _topk_mask(ctx, k).astype(t.dtype))
    return jnp.where(ctx.npos > 0, rel / jnp.maximum(ctx.npos, 1.0), 0.0)


def fall_out_scores(ctx: GroupContext, k: Optional[int]) -> Array:
    """Per-group fall-out@k over NEGATIVE documents (ref ``functional/retrieval/fall_out.py:21``)."""
    neg = (ctx.target <= 0).astype(jnp.float32)
    nneg = ctx.group_sum(neg)
    ret_neg = ctx.group_sum(neg * _topk_mask(ctx, k).astype(neg.dtype))
    return jnp.where(nneg > 0, ret_neg / jnp.maximum(nneg, 1.0), 0.0)


def hit_rate_scores(ctx: GroupContext, k: Optional[int]) -> Array:
    """Per-group hit rate@k (ref ``functional/retrieval/hit_rate.py:20``)."""
    t = (ctx.target > 0).astype(jnp.float32)
    rel = ctx.group_sum(t * _topk_mask(ctx, k).astype(t.dtype))
    return (rel > 0).astype(jnp.float32)


def ndcg_scores(ctx: GroupContext, k: Optional[int]) -> Array:
    """Per-group normalized DCG, non-binary targets allowed (ref
    ``functional/retrieval/ndcg.py:29-74``)."""
    t = ctx.target.astype(jnp.float32)
    discount = 1.0 / jnp.log2((ctx.rank + 2).astype(jnp.float32))
    mask = _topk_mask(ctx, k)
    dcg = ctx.group_sum(t * discount * mask.astype(t.dtype))

    def _sorted_ideal(_):
        # general graded targets: ideal ordering is targets descending
        # within each group; a second stable two-key sort carries the
        # values (group layout and boundaries unchanged)
        _, t_ideal = jax.lax.sort((ctx.gid, -t), num_keys=2)
        return ctx.group_sum(-t_ideal * discount * mask.astype(t.dtype))

    def _binary_ideal(_):
        # binary targets (the common IR case): the ideal ranking is the
        # group's npos ones first, so ideal DCG is a plain segment-sum of
        # discounts over ranks < npos — no second full-length sort
        within = (ctx.rank < ctx.npos.astype(ctx.rank.dtype)) & mask
        return ctx.group_sum(jnp.where(within, discount, 0.0))

    is_binary = jnp.all((ctx.target == 0) | (ctx.target == 1))
    ideal = jax.lax.cond(is_binary, _binary_ideal, _sorted_ideal, None)
    # reference ndcg.py:70-72 zeroes only the ideal == 0 case; a negative
    # ideal (negative relevances are legal non-binary targets) still divides.
    return jnp.where(ideal != 0, dcg / jnp.where(ideal != 0, ideal, 1.0), 0.0)


# ---------------------------------------------------------------------------
# Segment-local top-k formulation
# ---------------------------------------------------------------------------
#
# When every query holds the same number of documents laid out contiguously
# (the common ranking-eval shape: ``indexes == repeat(arange(Q), D)`` up to
# group relabeling), the @k metrics don't need the full ``(query, -score)``
# multi-operand sort at all: a ``(Q, D)`` reshape plus ``jax.lax.top_k`` per
# row selects exactly the documents the metric reads, and everything else is
# a tiny ``(Q, k)`` gather plus row reductions. ``lax.top_k`` and the stable
# two-key sort share the same tie rule (equal scores -> lowest index first),
# so the two paths agree bitwise — pinned by ``tests/retrieval/test_k_grid``.
# The full-sort pipeline above remains the fallback for ragged layouts and
# for metrics that read every rank.


def dense_group_shape(indexes: Array) -> Optional[Tuple[int, int]]:
    """``(num_queries, docs_per_query)`` when ``indexes`` is nondecreasing
    with uniform contiguous group sizes; None otherwise. Host-side (eager
    inputs only) — this is a dispatch decision, not a traced computation."""
    import numpy as np

    if isinstance(indexes, jax.core.Tracer):
        return None
    idx = np.asarray(indexes)
    if idx.ndim != 1 or idx.size == 0:
        return None
    steps = np.diff(idx)
    sizes = np.diff(np.concatenate(([-1], np.flatnonzero(steps), [idx.size - 1])))
    if (steps < 0).any() or (sizes != sizes[0]).any():
        return None
    return int(sizes.size), int(sizes[0])


class TopKContext(NamedTuple):
    """Per-query machinery for the dense top-k fast path.

    ``topk_target``/``topk_preds`` hold each query's documents at ranks
    ``< min(k, docs)`` in descending-score order (ties by input position,
    matching the stable full sort); ``target2d`` is the full per-query
    target view for totals the top-k slice cannot provide (npos, graded
    ideal DCG).
    """

    topk_preds: Array  # (Q, K) scores at ranks < K
    topk_target: Array  # (Q, K) targets carried along
    target2d: Array  # (Q, D) all targets, query-major
    count: Array  # (Q,) documents per query (constant D, as an array)
    npos: Array  # (Q,) positive-target total per query
    k: int  # static effective k == min(requested k, D)


def _descending_rank_key(p: Array) -> Array:
    """int32 key whose DESCENDING order equals the full sort's ranking of
    ``p`` descending: NaN strictly below -inf (the float comparator sorts
    NaN last) and -0.0 tied with +0.0 (the comparator calls them equal, so
    ties stay stable by index). Standard sign-fold of the IEEE bits."""
    p = p + 0.0  # -0.0 -> +0.0: keep the comparator's 0-tie behavior
    bits = jax.lax.bitcast_convert_type(p, jnp.int32)
    int_min = jnp.int32(jnp.iinfo(jnp.int32).min)
    key = jnp.where(bits < 0, jnp.invert(bits) ^ int_min, bits)
    return jnp.where(jnp.isnan(p), int_min, key)


def make_topk_context(preds: Array, target: Array, shape: Tuple[int, int], k: int) -> TopKContext:
    """Build the dense per-query top-k view of a flat retrieval batch."""
    q, d = shape
    kk = min(k, d)
    p2 = preds.reshape(q, d).astype(jnp.float32)
    t2 = target.reshape(q, d)
    # rank on the order-preserving int key (NaN-last / ±0-tie parity with
    # the full sort), gather the ORIGINAL scores and targets by index
    _, top_i = jax.lax.top_k(_descending_rank_key(p2), kk)
    top_p = jnp.take_along_axis(p2, top_i, axis=1)
    top_t = jnp.take_along_axis(t2, top_i, axis=1)
    npos = jnp.sum((t2 > 0).astype(jnp.float32), axis=1)
    count = jnp.full((q,), d, dtype=jnp.int32)
    return TopKContext(
        topk_preds=top_p, topk_target=top_t, target2d=t2, count=count, npos=npos, k=kk
    )


def precision_scores_topk(tctx: TopKContext, k: int, adaptive_k: bool = False) -> Array:
    """Per-query precision@k on the dense top-k view (parity:
    :func:`precision_scores`)."""
    rel = jnp.sum((tctx.topk_target > 0).astype(jnp.float32), axis=1)
    k_g = jnp.where(adaptive_k, jnp.minimum(k, tctx.count), k).astype(jnp.float32)
    return jnp.where(tctx.npos > 0, rel / jnp.maximum(k_g, 1.0), 0.0)


def recall_scores_topk(tctx: TopKContext) -> Array:
    """Per-query recall@k on the dense top-k view (parity: :func:`recall_scores`)."""
    rel = jnp.sum((tctx.topk_target > 0).astype(jnp.float32), axis=1)
    return jnp.where(tctx.npos > 0, rel / jnp.maximum(tctx.npos, 1.0), 0.0)


def hit_rate_scores_topk(tctx: TopKContext) -> Array:
    """Per-query hit rate@k on the dense top-k view (parity: :func:`hit_rate_scores`)."""
    rel = jnp.sum((tctx.topk_target > 0).astype(jnp.float32), axis=1)
    return (rel > 0).astype(jnp.float32)


def fall_out_scores_topk(tctx: TopKContext) -> Array:
    """Per-query fall-out@k on the dense top-k view (parity: :func:`fall_out_scores`)."""
    ret_neg = jnp.sum((tctx.topk_target <= 0).astype(jnp.float32), axis=1)
    nneg = tctx.count.astype(jnp.float32) - tctx.npos
    return jnp.where(nneg > 0, ret_neg / jnp.maximum(nneg, 1.0), 0.0)


def average_precision_scores_topk(tctx: TopKContext, k: int) -> Array:
    """Per-query average precision@k on the dense top-k view (parity:
    :func:`average_precision_scores` with ``k``)."""
    t = (tctx.topk_target > 0).astype(jnp.float32)
    hits = jnp.cumsum(t, axis=1)
    ranks = jnp.arange(1, tctx.k + 1, dtype=jnp.float32)[None, :]
    total = jnp.sum(t * hits / ranks, axis=1)
    denom = jnp.minimum(tctx.npos, float(k))
    return jnp.where(tctx.npos > 0, total / jnp.maximum(denom, 1.0), 0.0)


def ndcg_scores_topk(tctx: TopKContext) -> Array:
    """Per-query normalized DCG@k on the dense top-k view (parity:
    :func:`ndcg_scores`; non-binary targets allowed)."""
    t = tctx.topk_target.astype(jnp.float32)
    discount = 1.0 / jnp.log2(jnp.arange(2, tctx.k + 2, dtype=jnp.float32))[None, :]
    dcg = jnp.sum(t * discount, axis=1)

    def _binary_ideal(_):
        # ideal ranking packs the npos ones first: sum discounts over
        # ranks < min(npos, k) — no per-query target sort
        within = jnp.arange(tctx.k, dtype=jnp.float32)[None, :] < tctx.npos[:, None]
        return jnp.sum(jnp.where(within, discount, 0.0), axis=1)

    def _sorted_ideal(_):
        # graded targets: per-query top-k of the targets themselves
        t_ideal, _ = jax.lax.top_k(tctx.target2d.astype(jnp.float32), tctx.k)
        return jnp.sum(t_ideal * discount, axis=1)

    is_binary = jnp.all((tctx.target2d == 0) | (tctx.target2d == 1))
    ideal = jax.lax.cond(is_binary, _binary_ideal, _sorted_ideal, None)
    return jnp.where(ideal != 0, dcg / jnp.where(ideal != 0, ideal, 1.0), 0.0)
