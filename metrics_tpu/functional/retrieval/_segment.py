"""Vectorized per-query retrieval kernels (lexsort + segment ops).

TPU-native replacement for the reference's per-query Python loop
(``torchmetrics/retrieval/base.py:114-143`` + ``get_group_indexes``,
``torchmetrics/utilities/data.py:196-220``): ALL queries are scored in one
fused XLA program — a single stable lexsort by ``(query, -score)`` followed by
``jax.ops.segment_*`` reductions with ``num_segments = N`` (a static upper
bound on the number of queries, so shapes stay static under jit). Empty
segments are masked out at aggregation time.

Every kernel returns a dense ``(N,)`` vector of per-group scores; entries for
empty segments are meaningless and must be masked with ``ctx.nonempty``.
"""
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

Array = jax.Array


class GroupContext(NamedTuple):
    """Shared per-query machinery for all retrieval kernels.

    All arrays are sorted by ``(group, -pred)`` (stable, so ties keep input
    order). ``gid`` is a dense 0-based group id, ``rank`` the 0-based position
    of each document within its group's score-descending ordering.
    """

    preds: Array  # (N,) sorted scores
    target: Array  # (N,) targets in the same order
    gid: Array  # (N,) dense group id, nondecreasing
    rank: Array  # (N,) 0-based within-group rank
    start: Array  # (N,) flat position of each group's first document
    count: Array  # (N,) documents per group (dense over segments)
    npos: Array  # (N,) positive-target total per group
    nonempty: Array  # (N,) bool, segment is a real group
    num_segments: int  # static segment count (== N)


def make_group_context(preds: Array, target: Array, indexes: Array) -> GroupContext:
    """Build the shared sorted/grouped view of a flat retrieval batch."""
    n = preds.shape[0]
    order = jnp.lexsort((-preds, indexes))
    sidx = indexes[order]
    spreds = preds[order]
    starget = target[order]

    first = jnp.concatenate([jnp.ones((1,), dtype=bool), sidx[1:] != sidx[:-1]])
    gid = jnp.cumsum(first) - 1

    pos = jnp.arange(n)
    start = jax.ops.segment_min(pos, gid, num_segments=n)
    rank = pos - start[gid]

    ones = jnp.ones((n,), dtype=jnp.int32)
    count = jax.ops.segment_sum(ones, gid, num_segments=n)
    npos = jax.ops.segment_sum((starget > 0).astype(jnp.float32), gid, num_segments=n)
    nonempty = count > 0
    return GroupContext(spreds, starget, gid, rank, start, count, npos, nonempty, n)


def _group_cumsum(x: Array, ctx: GroupContext) -> Array:
    """Inclusive cumulative sum of ``x`` restarting at each group boundary."""
    cs = jnp.cumsum(x)
    before = jnp.where(ctx.start > 0, cs[jnp.maximum(ctx.start - 1, 0)], 0.0)
    return cs - before[ctx.gid]


def _topk_mask(ctx: GroupContext, k: Optional[int]) -> Array:
    if k is None:
        return jnp.ones_like(ctx.rank, dtype=bool)
    return ctx.rank < k


def average_precision_scores(ctx: GroupContext) -> Array:
    """Per-group IR average precision (ref ``functional/retrieval/average_precision.py:20``)."""
    t = (ctx.target > 0).astype(jnp.float32)
    hits = _group_cumsum(t, ctx)  # relevant seen up to and incl. this rank
    contrib = t * hits / (ctx.rank + 1.0)
    total = jax.ops.segment_sum(contrib, ctx.gid, num_segments=ctx.num_segments)
    return jnp.where(ctx.npos > 0, total / jnp.maximum(ctx.npos, 1.0), 0.0)


def reciprocal_rank_scores(ctx: GroupContext) -> Array:
    """Per-group reciprocal rank (ref ``functional/retrieval/reciprocal_rank.py:20``)."""
    sentinel = ctx.num_segments
    first_hit = jax.ops.segment_min(
        jnp.where(ctx.target > 0, ctx.rank, sentinel), ctx.gid, num_segments=ctx.num_segments
    )
    return jnp.where(first_hit < sentinel, 1.0 / (first_hit + 1.0), 0.0)


def precision_scores(ctx: GroupContext, k: Optional[int], adaptive_k: bool = False) -> Array:
    """Per-group precision@k (ref ``functional/retrieval/precision.py:21``)."""
    t = (ctx.target > 0).astype(jnp.float32)
    if k is None:
        k_g = ctx.count.astype(jnp.float32)
        mask = jnp.ones_like(t, dtype=bool)
    else:
        k_g = jnp.where(adaptive_k, jnp.minimum(k, ctx.count), k).astype(jnp.float32)
        mask = _topk_mask(ctx, k)
    rel = jax.ops.segment_sum(t * mask, ctx.gid, num_segments=ctx.num_segments)
    return jnp.where(ctx.npos > 0, rel / jnp.maximum(k_g, 1.0), 0.0)


def r_precision_scores(ctx: GroupContext) -> Array:
    """Per-group R-precision (ref ``functional/retrieval/r_precision.py:20``)."""
    t = (ctx.target > 0).astype(jnp.float32)
    in_top_r = ctx.rank < ctx.npos[ctx.gid]
    rel = jax.ops.segment_sum(t * in_top_r, ctx.gid, num_segments=ctx.num_segments)
    return jnp.where(ctx.npos > 0, rel / jnp.maximum(ctx.npos, 1.0), 0.0)


def recall_scores(ctx: GroupContext, k: Optional[int]) -> Array:
    """Per-group recall@k (ref ``functional/retrieval/recall.py:20``)."""
    t = (ctx.target > 0).astype(jnp.float32)
    rel = jax.ops.segment_sum(t * _topk_mask(ctx, k), ctx.gid, num_segments=ctx.num_segments)
    return jnp.where(ctx.npos > 0, rel / jnp.maximum(ctx.npos, 1.0), 0.0)


def fall_out_scores(ctx: GroupContext, k: Optional[int]) -> Array:
    """Per-group fall-out@k over NEGATIVE documents (ref ``functional/retrieval/fall_out.py:21``)."""
    neg = (ctx.target <= 0).astype(jnp.float32)
    nneg = jax.ops.segment_sum(neg, ctx.gid, num_segments=ctx.num_segments)
    ret_neg = jax.ops.segment_sum(neg * _topk_mask(ctx, k), ctx.gid, num_segments=ctx.num_segments)
    return jnp.where(nneg > 0, ret_neg / jnp.maximum(nneg, 1.0), 0.0)


def hit_rate_scores(ctx: GroupContext, k: Optional[int]) -> Array:
    """Per-group hit rate@k (ref ``functional/retrieval/hit_rate.py:20``)."""
    t = (ctx.target > 0).astype(jnp.float32)
    rel = jax.ops.segment_sum(t * _topk_mask(ctx, k), ctx.gid, num_segments=ctx.num_segments)
    return (rel > 0).astype(jnp.float32)


def ndcg_scores(ctx: GroupContext, k: Optional[int]) -> Array:
    """Per-group normalized DCG, non-binary targets allowed (ref
    ``functional/retrieval/ndcg.py:29-74``)."""
    t = ctx.target.astype(jnp.float32)
    discount = 1.0 / jnp.log2(ctx.rank + 2.0)
    mask = _topk_mask(ctx, k)
    dcg = jax.ops.segment_sum(t * discount * mask, ctx.gid, num_segments=ctx.num_segments)

    # ideal ordering: targets descending within each group; gid is already
    # nondecreasing so one more stable lexsort preserves the group layout.
    ideal_order = jnp.lexsort((-t, ctx.gid))
    t_ideal = t[ideal_order]
    ideal = jax.ops.segment_sum(t_ideal * discount * mask, ctx.gid, num_segments=ctx.num_segments)
    # reference ndcg.py:70-72 zeroes only the ideal == 0 case; a negative
    # ideal (negative relevances are legal non-binary targets) still divides.
    return jnp.where(ideal != 0, dcg / jnp.where(ideal != 0, ideal, 1.0), 0.0)
