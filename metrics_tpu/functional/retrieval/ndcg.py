"""Functional IR normalized discounted cumulative gain.

Behavioral equivalent of reference
``torchmetrics/functional/retrieval/ndcg.py:29``.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.retrieval._segment import (
    make_group_context,
    make_topk_context,
    ndcg_scores,
    ndcg_scores_topk,
)
from metrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_normalized_dcg(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Normalized DCG of a single query; non-binary targets allowed.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_normalized_dcg
        >>> preds = jnp.asarray([0.1, 0.2, 0.3, 4.0, 70.0])
        >>> target = jnp.asarray([10, 0, 0, 1, 5])
        >>> retrieval_normalized_dcg(preds, target)
        Array(0.6956907, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target, allow_non_binary_target=True)
    if k is not None and not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")
    if k is not None and k < preds.shape[0]:
        # single-query dense top-k fast path: one lax.top_k instead of the
        # full sort (bitwise-equal; see _segment.py)
        tctx = make_topk_context(preds, target, (1, preds.shape[0]), k)
        return ndcg_scores_topk(tctx)[0].astype(preds.dtype)
    ctx = make_group_context(preds, target, jnp.zeros(preds.shape, dtype=jnp.int32))
    return ndcg_scores(ctx, k=k)[0].astype(preds.dtype)
