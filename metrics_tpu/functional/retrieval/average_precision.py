"""Functional IR average precision.

Behavioral equivalent of reference
``torchmetrics/functional/retrieval/average_precision.py:20``.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.functional.retrieval._segment import average_precision_scores, make_group_context
from metrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_average_precision(preds: Array, target: Array) -> Array:
    """Average precision of a single query's ranked documents.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_average_precision
        >>> preds = jnp.asarray([0.2, 0.3, 0.5])
        >>> target = jnp.asarray([True, False, True])
        >>> retrieval_average_precision(preds, target)
        Array(0.8333334, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    ctx = make_group_context(preds, target, jnp.zeros(preds.shape, dtype=jnp.int32))
    return average_precision_scores(ctx)[0].astype(preds.dtype)
