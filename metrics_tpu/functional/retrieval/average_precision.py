"""Functional IR average precision.

Behavioral equivalent of reference
``torchmetrics/functional/retrieval/average_precision.py:20``; ``top_k``
follows the reference's later cutoff semantics (precision summed over the
first ``k`` ranks, normalized by ``min(npos, k)``).
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.retrieval._segment import (
    average_precision_scores,
    average_precision_scores_topk,
    make_group_context,
    make_topk_context,
)
from metrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_average_precision(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Average precision of a single query's ranked documents, optionally @k.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_average_precision
        >>> preds = jnp.asarray([0.2, 0.3, 0.5])
        >>> target = jnp.asarray([True, False, True])
        >>> retrieval_average_precision(preds, target)
        Array(0.8333334, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if top_k is not None and not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")
    if top_k is not None and top_k < preds.shape[0]:
        # single-query dense top-k fast path: one lax.top_k instead of the
        # full sort (bitwise-equal; see _segment.py)
        tctx = make_topk_context(preds, target, (1, preds.shape[0]), top_k)
        return average_precision_scores_topk(tctx, k=top_k)[0].astype(preds.dtype)
    ctx = make_group_context(preds, target, jnp.zeros(preds.shape, dtype=jnp.int32))
    return average_precision_scores(ctx, k=top_k)[0].astype(preds.dtype)
