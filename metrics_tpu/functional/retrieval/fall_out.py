"""Functional IR fall-out@k.

Behavioral equivalent of reference
``torchmetrics/functional/retrieval/fall_out.py:21``.
"""
from typing import Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.retrieval._segment import (
    fall_out_scores,
    fall_out_scores_topk,
    make_group_context,
    make_topk_context,
)
from metrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_fall_out(preds: Array, target: Array, k: Optional[int] = None) -> Array:
    """Fraction of non-relevant documents retrieved among all non-relevant.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_fall_out
        >>> preds = jnp.asarray([0.2, 0.3, 0.5])
        >>> target = jnp.asarray([True, False, True])
        >>> retrieval_fall_out(preds, target, k=2)
        Array(1., dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if k is not None and not (isinstance(k, int) and k > 0):
        raise ValueError("`k` has to be a positive integer or None")
    if k is not None and k < preds.shape[0]:
        # single-query dense top-k fast path: one lax.top_k instead of the
        # full sort (bitwise-equal; see _segment.py)
        tctx = make_topk_context(preds, target, (1, preds.shape[0]), k)
        return fall_out_scores_topk(tctx)[0].astype(preds.dtype)
    ctx = make_group_context(preds, target, jnp.zeros(preds.shape, dtype=jnp.int32))
    return fall_out_scores(ctx, k=k)[0].astype(preds.dtype)
