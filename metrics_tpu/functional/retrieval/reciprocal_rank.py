"""Functional IR reciprocal rank.

Behavioral equivalent of reference
``torchmetrics/functional/retrieval/reciprocal_rank.py:20``.
"""
import jax
import jax.numpy as jnp

from metrics_tpu.functional.retrieval._segment import make_group_context, reciprocal_rank_scores
from metrics_tpu.utilities.checks import _check_retrieval_functional_inputs

Array = jax.Array


def retrieval_reciprocal_rank(preds: Array, target: Array) -> Array:
    """Reciprocal rank of the first relevant document.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional import retrieval_reciprocal_rank
        >>> preds = jnp.asarray([0.2, 0.3, 0.5])
        >>> target = jnp.asarray([False, True, False])
        >>> retrieval_reciprocal_rank(preds, target)
        Array(0.5, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    ctx = make_group_context(preds, target, jnp.zeros(preds.shape, dtype=jnp.int32))
    return reciprocal_rank_scores(ctx)[0].astype(preds.dtype)
