"""Streaming perplexity over an unbounded token stream.

Perplexity is a pure function of two scalar sums — total log-probability
and total token count — so the metric state is an EXACT commutative
monoid: merges are float additions of integer-weighted partial sums, and
the serve tree / mesh / scan fold order can never change the result
beyond float addition order (the platform ships per-client states through
the pow-2 stacked fold, so the reduction order is itself deterministic —
the fleet bitwise oracle in ``tests/integrations/experiment_smoke.py``
pins root state == flat offline merge).
"""
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.obs.registry import inc as _obs_inc

Array = jax.Array

__all__ = ["StreamingPerplexity"]

_LN2 = math.log(2.0)


class StreamingPerplexity(Metric):
    """Corpus perplexity from summed token log-probabilities, O(1) state.

    ``update`` takes per-token **natural-log** probabilities (the shape is
    free — ``(N,)``, ``(B, T)``, anything; an optional ``mask`` of the
    same shape excludes padding) and folds them into three scalar sums:
    ``log_prob_sum``, ``token_count`` and optionally ``byte_count`` for
    the tokenizer-independent bits-per-byte variant. The update is pure
    ``jnp`` arithmetic on fixed-shape state, so the metric is a valid
    ``jit``/``scan``/``vmap`` carry and rides
    :func:`~metrics_tpu.steps.make_stream_step` unchanged.

    ``compute`` returns ``exp(-log_prob_sum / token_count)``;
    :meth:`bits_per_byte` returns ``-log_prob_sum / (ln 2 * byte_count)``
    (report ``num_bytes`` in ``update`` to enable it). Both are EXACT
    functions of the stream — :meth:`error_bound` is identically zero,
    which is what lets an experiment's sequential test treat perplexity
    evidence at face value (zero envelope half-width).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.llm import StreamingPerplexity
        >>> m = StreamingPerplexity()
        >>> m.update(jnp.log(jnp.asarray([0.5, 0.25, 0.5, 0.25])))
        >>> float(jnp.round(m.compute(), 4))  # geometric mean prob ~ 0.3536
        2.8284
    """

    is_differentiable = False
    higher_is_better = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("log_prob_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("token_count", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("byte_count", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(
        self,
        log_probs: Array,
        mask: Optional[Array] = None,
        num_bytes: Optional[Array] = None,
    ) -> None:
        """Fold a batch of per-token natural-log probabilities.

        Args:
            log_probs: per-token ``log p(token)`` values, any shape.
            mask: optional same-shape mask; tokens with a zero/False mask
                contribute nothing (padding convention).
            num_bytes: optional total byte count of the decoded text this
                batch scored (scalar or array; summed) — enables
                :meth:`bits_per_byte`.
        """
        lp = jnp.ravel(jnp.asarray(log_probs)).astype(jnp.float32)
        if mask is None:
            m = jnp.ones_like(lp)
        else:
            m = jnp.ravel(jnp.asarray(mask)).astype(jnp.float32)
        self.log_prob_sum = self.log_prob_sum + (lp * m).sum()
        self.token_count = self.token_count + m.sum()
        if num_bytes is not None:
            self.byte_count = self.byte_count + jnp.sum(jnp.asarray(num_bytes)).astype(jnp.float32)

    def compute(self) -> Array:
        """``exp(-log_prob_sum / token_count)`` — NaN before any token."""
        count = self.token_count
        return jnp.where(count > 0, jnp.exp(-self.log_prob_sum / jnp.maximum(count, 1.0)), jnp.nan)

    def bits_per_byte(self) -> Array:
        """Tokenizer-independent ``-log2-prob per byte`` (needs
        ``num_bytes`` reported in ``update``); NaN before any byte."""
        _obs_inc("llm.perplexity_queries")
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            nbytes = self.byte_count
            return jnp.where(
                nbytes > 0, -self.log_prob_sum / (_LN2 * jnp.maximum(nbytes, 1.0)), jnp.nan
            )

    def bounds(self) -> Tuple[Array, Array]:
        """Degenerate (lower, upper) interval: the sums are exact, so the
        envelope collapses to the value itself."""
        _obs_inc("llm.perplexity_queries")
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            value = self.compute()
        return value, value

    def error_bound(self) -> Array:
        """Identically zero — perplexity is an exact function of exact
        sum states (no sketch approximation anywhere)."""
        lo, hi = self.bounds()
        return (hi - lo) / 2.0


# gather-free mesh compute: the three scalars psum over the axis; no
# materialized full-state gather is ever needed for pure sum states
from metrics_tpu.utilities.sharding import (  # noqa: E402
    register_sharded_compute as _register_sharded_compute,
)


def _streaming_perplexity_sharded(
    worker: StreamingPerplexity, state: dict, axis_name: Any
) -> Array:
    lp = jax.lax.psum(state["log_prob_sum"], axis_name)
    count = jax.lax.psum(state["token_count"], axis_name)
    return jnp.where(count > 0, jnp.exp(-lp / jnp.maximum(count, 1.0)), jnp.nan)


_register_sharded_compute(StreamingPerplexity, _streaming_perplexity_sharded)
