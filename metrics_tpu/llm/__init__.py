"""LLM-evaluation tenants: streaming perplexity, QA overlap, RAG quality.

The serving platform's north-star workload (ROADMAP open item 2) is
millions of inference workers emitting eval traffic. Every metric here is
therefore built on the platform's two aggregation primitives:

* **exact sum monoids** — token-level perplexity and SQuAD-convention
  token-F1/exact-match decompose into a handful of scalar sums, so
  thousands of workers aggregate BITWISE through the elastic serve tree
  (fold order can never change state), and
* **mergeable sketches** — :class:`StreamingRAGQuality` carries a
  :class:`~metrics_tpu.streaming.sketches.QuantileSketch` of per-query
  NDCG beside its exact means, so a 1M–1B-document eval's score
  *distribution* survives the tree with a documented error envelope.

All classes are ordinary :class:`~metrics_tpu.metric.Metric` subclasses:
they ride ``MetricCollection``, ``make_step``/``make_stream_step`` (pure
fixed-shape states), the wire schema + dedup, epoch fusion, mesh
``sharded_state=True`` where sketch-backed, history rings, and
kill-resume bitwise — the same contracts the classification tenants pin.
See ``docs/llm_eval.md`` for the monoid/envelope arguments and a worked
RAG example.
"""
from metrics_tpu.llm.perplexity import StreamingPerplexity
from metrics_tpu.llm.qa import StreamingExactMatch, StreamingTokenF1
from metrics_tpu.llm.rag import StreamingRAGQuality

__all__ = [
    "StreamingExactMatch",
    "StreamingPerplexity",
    "StreamingRAGQuality",
    "StreamingTokenF1",
]
