"""Streaming SQuAD-convention QA overlap: token-F1 and exact match.

Both metrics follow the official SQuAD v1.1 evaluation semantics by
reusing the normalization/overlap helpers of
``metrics_tpu/functional/text/squad.py`` (lowercase, strip
punctuation/articles, token-level F1, max over ground truths). Strings
are normalized HOST-side — text never touches the device — and only the
two scalar sums ``(score_sum, count)`` live as device state, so the
metric is an exact sum monoid that aggregates bitwise through the serve
tree like every other sum-reduced tenant.
"""
from typing import Any, List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.squad import _exact_match_score, _f1_score
from metrics_tpu.metric import Metric
from metrics_tpu.obs.registry import inc as _obs_inc

Array = jax.Array

__all__ = ["StreamingExactMatch", "StreamingTokenF1"]

TEXT = Union[str, Sequence[str]]
TARGETS = Union[str, Sequence[str], Sequence[Sequence[str]]]


def _as_list(text: TEXT) -> List[str]:
    return [text] if isinstance(text, str) else list(text)


def _target_lists(target: TARGETS, n: int) -> List[List[str]]:
    """Per-prediction ground-truth lists (one answer or many per item)."""
    if isinstance(target, str):
        groups: List[List[str]] = [[target]]
    else:
        groups = [[t] if isinstance(t, str) else list(t) for t in target]
    if len(groups) != n:
        raise ValueError(f"got {n} predictions but {len(groups)} target groups")
    for i, g in enumerate(groups):
        if not g:
            raise ValueError(f"target group {i} is empty — every question needs >= 1 answer")
    return groups


class _StreamingOverlap(Metric):
    """Shared host-scored / device-summed machinery for the QA pair."""

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("score_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("count", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    @staticmethod
    def _score(prediction: str, ground_truth: str) -> float:
        raise NotImplementedError

    def update(self, preds: TEXT, target: TARGETS) -> None:
        """Score prediction strings against their ground truth(s) —
        SQuAD convention: max over a question's ground truths."""
        pred_list = _as_list(preds)
        groups = _target_lists(target, len(pred_list))
        total = 0.0
        for pred, answers in zip(pred_list, groups):
            total += max(self._score(pred, answer) for answer in answers)
        self.score_sum = self.score_sum + jnp.asarray(total, jnp.float32)
        self.count = self.count + jnp.asarray(float(len(pred_list)), jnp.float32)

    def compute(self) -> Array:
        """Mean score over every question streamed so far (NaN before
        the first question)."""
        return jnp.where(self.count > 0, self.score_sum / jnp.maximum(self.count, 1.0), jnp.nan)

    def bounds(self) -> Tuple[Array, Array]:
        """Degenerate interval — the sums are exact."""
        _obs_inc("llm.qa_queries")
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            value = self.compute()
        return value, value

    def error_bound(self) -> Array:
        """Identically zero (exact sum states, no sketch)."""
        lo, hi = self.bounds()
        return (hi - lo) / 2.0


class StreamingTokenF1(_StreamingOverlap):
    """Mean SQuAD token-overlap F1 over an unbounded QA stream, O(1) state.

    Example:
        >>> from metrics_tpu.llm import StreamingTokenF1
        >>> m = StreamingTokenF1()
        >>> m.update("the cat sat", [["a cat sat", "the dog ran"]])
        >>> float(m.compute())
        1.0
    """

    @staticmethod
    def _score(prediction: str, ground_truth: str) -> float:
        return _f1_score(prediction, ground_truth)


class StreamingExactMatch(_StreamingOverlap):
    """Mean SQuAD exact-match rate over an unbounded QA stream, O(1) state.

    Example:
        >>> from metrics_tpu.llm import StreamingExactMatch
        >>> m = StreamingExactMatch()
        >>> m.update(["An Answer!"], ["an answer"])
        >>> float(m.compute())
        1.0
    """

    @staticmethod
    def _score(prediction: str, ground_truth: str) -> float:
        return _exact_match_score(prediction, ground_truth)


from metrics_tpu.utilities.sharding import (  # noqa: E402
    register_sharded_compute as _register_sharded_compute,
)


def _streaming_overlap_sharded(worker: _StreamingOverlap, state: dict, axis_name: Any) -> Array:
    total = jax.lax.psum(state["score_sum"], axis_name)
    count = jax.lax.psum(state["count"], axis_name)
    return jnp.where(count > 0, total / jnp.maximum(count, 1.0), jnp.nan)


_register_sharded_compute(_StreamingOverlap, _streaming_overlap_sharded)
