"""Streaming RAG retrieval quality: hit-rate / MRR / NDCG @k at fleet scale.

Per-query scores come from the segment-local ``lax.top_k`` fast path
(``functional/retrieval/_segment.py``, PR 1) when the batch is dense
(every query the same contiguous document count) and from the full
sort + segmented-scan pipeline otherwise — both agree bitwise on the
dense layout. The metric then ships ONLY monoid state:

* exact scalar sums (``hit_sum``, ``mrr_sum``, ``ndcg_sum``,
  ``query_count``) — the three means are exact functions of the stream,
  so the serve tree aggregates them losslessly from 1M to 1B documents;
* a :class:`~metrics_tpu.streaming.sketches.QuantileSketch` over the
  per-query NDCG scores — the score *distribution* (tail quality, drift)
  survives aggregation with a documented error envelope, and backs the
  ``mean`` family of :class:`metrics_tpu.experiment.SequentialTest`.
"""
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.retrieval._segment import (
    dense_group_shape,
    hit_rate_scores,
    hit_rate_scores_topk,
    make_group_context,
    make_topk_context,
    ndcg_scores,
    ndcg_scores_topk,
)
from metrics_tpu.metric import Metric
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.streaming.sketches import QuantileSketch

Array = jax.Array

__all__ = ["StreamingRAGQuality"]


class StreamingRAGQuality(Metric):
    """Hit-rate / MRR / NDCG @k over an unbounded stream of retrieval
    queries, in fixed device memory.

    ``update(preds, target, indexes)`` takes the flat retrieval-batch
    layout every in-tree retrieval metric uses (scores, relevances and a
    query id per document). Each query is scored once — hit-rate@k,
    reciprocal-rank@k and NDCG@k — and folds into exact sums plus a
    per-query NDCG :class:`~metrics_tpu.streaming.sketches.QuantileSketch`.

    :meth:`compute` returns a shape-``(3,)`` array
    ``[hit_rate@k, mrr@k, ndcg@k]`` (means over all queries; NaN before
    the first query). The means are EXACT — :meth:`error_bound` is zero —
    while :meth:`ndcg_quantile` answers distributional queries from the
    sketch with the sketch's rigorous envelope
    (:meth:`ndcg_quantile_bounds`).

    MRR here is reciprocal rank **@k**: a query whose first relevant
    document ranks below ``k`` scores 0, matching what a top-``k``
    retrieval stack can actually surface (the unbounded variant is
    :class:`metrics_tpu.retrieval.RetrievalMRR`).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.llm import StreamingRAGQuality
        >>> m = StreamingRAGQuality(k=2)
        >>> m.update(
        ...     jnp.asarray([0.9, 0.3, 0.1, 0.8, 0.6, 0.2]),
        ...     jnp.asarray([1, 0, 0, 0, 1, 0]),
        ...     jnp.asarray([0, 0, 0, 1, 1, 1]),
        ... )
        >>> [float(x) for x in m.compute()]  # hit@2, mrr@2, ndcg@2
        [1.0, 0.75, 0.8154648542404175]
    """

    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        k: int = 10,
        num_bins: int = 128,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if k < 1:
            raise ValueError(f"`k` must be >= 1, got {k}")
        self.k = int(k)
        self.num_bins = int(num_bins)
        self.add_state("hit_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("mrr_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("ndcg_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("query_count", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state(
            "ndcg_sketch", default=QuantileSketch(num_bins, 0.0, 1.0), dist_reduce_fx="sketch"
        )

    # -- per-query scoring ----------------------------------------------

    def _dense_scores(
        self, preds: Array, target: Array, shape: Tuple[int, int]
    ) -> Tuple[Array, Array, Array]:
        tctx = make_topk_context(preds, target, shape, self.k)
        hit = hit_rate_scores_topk(tctx)
        ndcg = ndcg_scores_topk(tctx)
        t = tctx.topk_target > 0
        first_hit = jnp.argmax(t, axis=1)
        rr = jnp.where(t.any(axis=1), 1.0 / (first_hit + 1).astype(jnp.float32), 0.0)
        return hit, rr, ndcg

    def _ragged_scores(
        self, preds: Array, target: Array, indexes: Array
    ) -> Tuple[Array, Array, Array, Array]:
        ctx = make_group_context(preds, target, indexes)
        hit = hit_rate_scores(ctx, self.k)
        ndcg = ndcg_scores(ctx, self.k)
        sentinel = ctx.num_segments
        in_k = (ctx.target > 0) & (ctx.rank < self.k)
        first_hit = ctx.group_min(jnp.where(in_k, ctx.rank, sentinel))
        rr = jnp.where(first_hit < sentinel, 1.0 / (first_hit + 1).astype(jnp.float32), 0.0)
        return hit, rr, ndcg, ctx.nonempty

    def update(self, preds: Array, target: Array, indexes: Array) -> None:
        """Fold a flat retrieval batch: one score triple per query.

        Args:
            preds: per-document retrieval scores, ``(N,)``.
            target: per-document relevances (binary or graded), ``(N,)``.
            indexes: per-document query ids, ``(N,)`` — grouping key.
        """
        preds = jnp.ravel(jnp.asarray(preds)).astype(jnp.float32)
        target = jnp.ravel(jnp.asarray(target))
        indexes = jnp.ravel(jnp.asarray(indexes))
        shape = dense_group_shape(indexes)
        if shape is not None:
            hit, rr, ndcg = self._dense_scores(preds, target, shape)
            weights = jnp.ones_like(ndcg)
            n = jnp.asarray(float(shape[0]), jnp.float32)
        else:
            hit, rr, ndcg, mask = self._ragged_scores(preds, target, indexes)
            weights = mask.astype(jnp.float32)
            hit, rr, ndcg = hit * weights, rr * weights, ndcg * weights
            n = weights.sum()
        self.hit_sum = self.hit_sum + hit.sum()
        self.mrr_sum = self.mrr_sum + rr.sum()
        self.ndcg_sum = self.ndcg_sum + ndcg.sum()
        self.query_count = self.query_count + n
        self.ndcg_sketch = self.ndcg_sketch.fold(ndcg, weights=weights)

    # -- queries ---------------------------------------------------------

    def compute(self) -> Array:
        """``[hit_rate@k, mrr@k, ndcg@k]`` means (shape ``(3,)``)."""
        n = self.query_count
        sums = jnp.stack([self.hit_sum, self.mrr_sum, self.ndcg_sum])
        return jnp.where(n > 0, sums / jnp.maximum(n, 1.0), jnp.nan)

    def bounds(self) -> Tuple[Array, Array]:
        """Degenerate per-component interval — the means are exact sums
        (the sketch only serves distributional queries)."""
        _obs_inc("llm.rag_queries")
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            value = self.compute()
        return value, value

    def error_bound(self) -> Array:
        """Identically zero for the three means."""
        lo, hi = self.bounds()
        return (hi - lo) / 2.0

    def ndcg_quantile(self, q: Any) -> Array:
        """Quantile(s) of the per-query NDCG distribution — sketch
        midpoint, accurate to :meth:`ndcg_quantile_bounds`' half-width."""
        _obs_inc("llm.rag_queries")
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            return self.ndcg_sketch.quantile(jnp.asarray(q))

    def ndcg_quantile_bounds(self, q: Any) -> Tuple[Array, Array]:
        """Rigorous (lower, upper) envelope for :meth:`ndcg_quantile`."""
        _obs_inc("llm.rag_queries")
        with self.sync_context(should_sync=self._to_sync, should_unsync=True):
            return self.ndcg_sketch.quantile_bounds(jnp.asarray(q))


# gather-free mesh compute: scalar sums psum; the NDCG sketch stays
# reduce-scattered (its quantile queries go through the sharded kernel
# in utilities/sharding.py when asked for — the headline triple needs
# only the exact scalars)
from metrics_tpu.utilities.sharding import (  # noqa: E402
    register_sharded_compute as _register_sharded_compute,
)


def _streaming_rag_sharded(worker: StreamingRAGQuality, state: dict, axis_name: Any) -> Array:
    n = jax.lax.psum(state["query_count"], axis_name)
    sums = jnp.stack(
        [
            jax.lax.psum(state["hit_sum"], axis_name),
            jax.lax.psum(state["mrr_sum"], axis_name),
            jax.lax.psum(state["ndcg_sum"], axis_name),
        ]
    )
    return jnp.where(n > 0, sums / jnp.maximum(n, 1.0), jnp.nan)


_register_sharded_compute(StreamingRAGQuality, _streaming_rag_sharded)
