"""StatScores metric class — tp/fp/tn/fn accumulation.

Behavioral equivalent of reference ``torchmetrics/classification/
stat_scores.py:126-249``: sum-reduced array states for micro/macro, cat-list
states for samples/samplewise.
"""
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.stat_scores import _stat_scores_compute, _stat_scores_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.enums import AverageMethod, MDMCAverageMethod

Array = jax.Array


class StatScores(Metric):
    """Accumulate true/false positives/negatives and support.

    Args:
        threshold: probability/logit threshold for binary & multilabel preds.
        top_k: top-k binarization for (mdmc) multi-class probabilities.
        reduce: "micro" | "macro" | "samples".
        num_classes: required for "macro".
        ignore_index: class index excluded from the scores.
        mdmc_reduce: "global" | "samplewise" for multi-dim multi-class input.
        multiclass: input-type override.
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        threshold: float = 0.5,
        top_k: Optional[int] = None,
        reduce: str = "micro",
        num_classes: Optional[int] = None,
        ignore_index: Optional[int] = None,
        mdmc_reduce: Optional[str] = None,
        multiclass: Optional[bool] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.reduce = reduce
        self.mdmc_reduce = mdmc_reduce
        self.num_classes = num_classes
        self.threshold = threshold
        self.multiclass = multiclass
        self.ignore_index = ignore_index
        self.top_k = top_k

        if reduce not in ["micro", "macro", "samples"]:
            raise ValueError(f"The `reduce` {reduce} is not valid.")
        if mdmc_reduce not in [None, "samplewise", "global"]:
            raise ValueError(f"The `mdmc_reduce` {mdmc_reduce} is not valid.")
        if reduce == "macro" and (not num_classes or num_classes < 1):
            raise ValueError("When you set `reduce` as 'macro', you have to provide the number of classes.")
        if num_classes and ignore_index is not None and (not ignore_index < num_classes or num_classes == 1):
            raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

        if mdmc_reduce != "samplewise" and reduce != "samples":
            zeros_shape = [] if reduce == "micro" else [num_classes]
            for s in ("tp", "fp", "tn", "fn"):
                self.add_state(s, default=jnp.zeros(zeros_shape, dtype=jnp.int32), dist_reduce_fx="sum")
        else:
            for s in ("tp", "fp", "tn", "fn"):
                self.add_state(s, default=[], dist_reduce_fx=None)

    def update(self, preds: Array, target: Array) -> None:
        """Accumulate stat scores from a batch of predictions and targets."""
        tp, fp, tn, fn = _stat_scores_update(
            preds,
            target,
            reduce=self.reduce,
            mdmc_reduce=self.mdmc_reduce,
            threshold=self.threshold,
            num_classes=self.num_classes,
            top_k=self.top_k,
            multiclass=self.multiclass,
            ignore_index=self.ignore_index,
        )
        self._accumulate(tp, fp, tn, fn)

    def _accumulate(self, tp: Array, fp: Array, tn: Array, fn: Array) -> None:
        """Merge batch stats into state (sum for array states, append for lists)."""
        if self.reduce != AverageMethod.SAMPLES and self.mdmc_reduce != MDMCAverageMethod.SAMPLEWISE:
            self.tp = self.tp + tp
            self.fp = self.fp + fp
            self.tn = self.tn + tn
            self.fn = self.fn + fn
        else:
            self.tp.append(tp)
            self.fp.append(fp)
            self.tn.append(tn)
            self.fn.append(fn)

    def _get_final_stats(self) -> Tuple[Array, Array, Array, Array]:
        """Concatenate list states if necessary."""
        tp = dim_zero_cat(self.tp) if isinstance(self.tp, list) else self.tp
        fp = dim_zero_cat(self.fp) if isinstance(self.fp, list) else self.fp
        tn = dim_zero_cat(self.tn) if isinstance(self.tn, list) else self.tn
        fn = dim_zero_cat(self.fn) if isinstance(self.fn, list) else self.fn
        return tp, fp, tn, fn

    def compute(self) -> Array:
        """Return ``[..., (tp, fp, tn, fn, support)]``."""
        tp, fp, tn, fn = self._get_final_stats()
        return _stat_scores_compute(tp, fp, tn, fn)
