"""AUROC metric class (reference ``torchmetrics/classification/auroc.py``, 183 LoC)."""
from typing import Any, Optional

import jax

from metrics_tpu.functional.classification.auroc import _auroc_compute, _auroc_update
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.buffers import _cat_state_default
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.enums import AverageMethod, DataType

Array = jax.Array


class AUROC(Metric):
    """Streaming area under the ROC curve.

    ``sample_capacity`` switches the unbounded cat-list states to a
    pre-allocated fixed-capacity HBM buffer of that many samples (static
    shapes, jit-friendly streaming). Overflow raises eagerly; inside a
    traced update excess samples silently clamp into the buffer tail.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AUROC
        >>> preds = jnp.asarray([0.13, 0.26, 0.08, 0.19, 0.34])
        >>> target = jnp.asarray([0, 0, 1, 1, 1])
        >>> auroc = AUROC(pos_label=1)
        >>> auroc(preds, target)
        Array(0.5, dtype=float32)
    """

    _aux_attrs = ('mode', 'num_classes')
    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        max_fpr: Optional[float] = None,
        sample_capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        self.average = average
        self.max_fpr = max_fpr

        allowed_average = (None, AverageMethod.MACRO, AverageMethod.WEIGHTED, AverageMethod.MICRO, AverageMethod.NONE)
        if average not in allowed_average:
            raise ValueError(
                f"Argument `average` expected to be one of the following: {allowed_average} but got {average}"
            )
        if max_fpr is not None and (not isinstance(max_fpr, float) or not 0 < max_fpr <= 1):
            raise ValueError(f"`max_fpr` should be a float in range (0, 1], got: {max_fpr}")

        self.mode: Optional[DataType] = None
        self.add_state("preds", default=_cat_state_default(sample_capacity), dist_reduce_fx="cat")
        self.add_state("target", default=_cat_state_default(sample_capacity), dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target, mode = _auroc_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)
        if self.mode and self.mode != mode:
            raise ValueError(
                "The mode of data (binary, multi-label, multi-class) should be constant, but changed"
                f" between batches from {self.mode} to {mode}"
            )
        self.mode = mode

    def compute(self) -> Array:
        if not self.mode:
            raise RuntimeError("You have to have determined mode.")
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _auroc_compute(
            preds, target, self.mode, self.num_classes, self.pos_label, self.average, self.max_fpr
        )


# ---------------------------------------------------------------------------
# Sharded (gather-free) compute — make_step(..., sharded_state=True)
# ---------------------------------------------------------------------------
# Binary AUROC over mesh-RESIDENT sample shards: instead of the replicated
# path's materialized buffer gather (O(n_dev * capacity) HBM on every
# device before the exact sort), a lax.ppermute ring pass counts
# discordant pairs against each visiting shard's sorted negatives — same
# total bytes as one all-gather, peak HBM stays O(capacity), and the value
# matches the exact sorted path's trapezoidal/tie-half convention to f32
# summation order. See utilities/sharding.sharded_sample_auroc.
from metrics_tpu.utilities.buffers import CapacityBuffer as _CapacityBuffer  # noqa: E402
from metrics_tpu.utilities.sharding import (  # noqa: E402
    register_sharded_compute as _register_sharded_compute,
    sharded_sample_auroc as _sharded_sample_auroc,
)


def _auroc_sharded(worker: AUROC, state: dict, axis_name: Any) -> Array:
    if worker.mode != DataType.BINARY:
        raise ValueError(
            "sharded_state AUROC supports binary mode only (the ring pair count is a"
            f" binary-score kernel); detected mode {worker.mode!r}. Use the replicated"
            " gather sync (sharded_state=False) for multiclass/multilabel."
        )
    if not isinstance(state.get("preds"), _CapacityBuffer):
        raise ValueError(
            "sharded_state AUROC needs sample_capacity= (fixed-capacity buffers): unbounded"
            " list states cannot be mesh-resident."
        )
    if worker.max_fpr is not None:
        raise ValueError("sharded_state AUROC does not support max_fpr=; use the replicated sync.")
    if worker.pos_label not in (None, 1):
        raise ValueError(
            f"sharded_state AUROC assumes pos_label=1 (got {worker.pos_label}); relabel the"
            " targets or use the replicated sync."
        )
    return _sharded_sample_auroc(state["preds"], state["target"], axis_name)


_register_sharded_compute(AUROC, _auroc_sharded)
