"""Binned (fixed-threshold) precision-recall metrics — the TPU-native curve template.

Behavioral equivalent of reference
``torchmetrics/classification/binned_precision_recall.py`` (317 LoC):
``BinnedPrecisionRecallCurve`` :45, ``BinnedAveragePrecision`` :186,
``BinnedRecallAtFixedPrecision`` :242.

Unlike the exact curve metrics (unbounded cat-list states, eager compute),
these keep O(1) fixed-shape ``(C, T)`` count states and a fully jittable
update — the design SURVEY.md §7 recommends for all curve metrics on TPU.
The reference iterates thresholds one at a time in Python "to conserve
memory" (:164-169); here the whole ``(N, C, T)`` comparison is one fused XLA
computation.
"""
from typing import Any, List, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute_with_precision_recall,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import to_onehot

Array = jax.Array

METRIC_EPS = 1e-6


def _recall_at_precision(
    precision: Array, recall: Array, thresholds: Array, min_precision: float
) -> Tuple[Array, Array]:
    """Max recall whose precision >= min_precision (reference :25-42).

    The reference takes ``max((r, p, t))`` over valid triples — lexicographic
    on recall, then precision, then threshold; reproduced here with jit-safe
    masked argmax passes (thresholds has one fewer entry than p/r, so the
    appended (1, 0) end point is excluded, like the reference's zip).
    """
    n_t = thresholds.shape[0]
    precision, recall = precision[:n_t], recall[:n_t]
    valid = precision >= min_precision
    best_r = jnp.max(jnp.where(valid, recall, -jnp.inf))
    cand = valid & (recall == best_r)
    best_p = jnp.max(jnp.where(cand, precision, -jnp.inf))
    cand = cand & (precision == best_p)
    idx = jnp.argmax(jnp.where(cand, jnp.arange(n_t), -1))
    any_valid = valid.any()
    max_recall = jnp.where(any_valid, recall[idx], 0.0)
    best_threshold = jnp.where(any_valid & (max_recall > 0), thresholds[idx], 1e6)
    return max_recall.astype(recall.dtype), best_threshold.astype(thresholds.dtype)


class BinnedPrecisionRecallCurve(Metric):
    """Precision-recall pairs at fixed thresholds with O(1) state.

    Example (binary case):
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import BinnedPrecisionRecallCurve
        >>> pred = jnp.asarray([0.0, 0.1, 0.8, 0.4])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> pr_curve = BinnedPrecisionRecallCurve(num_classes=1, thresholds=5)
        >>> precision, recall, thresholds = pr_curve(pred, target)
        >>> precision
        Array([0.5      , 0.5      , 1.       , 1.       , 0.99999  , 1.       ],      dtype=float32)
        >>> recall
        Array([1. , 0.5, 0.5, 0.5, 0. , 0. ], dtype=float32)
    """

    is_differentiable = False
    higher_is_better = None
    full_state_update = False

    def __init__(
        self,
        num_classes: int,
        thresholds: Union[int, Array, List[float], None] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        if isinstance(thresholds, int):
            self.num_thresholds = thresholds
            self.thresholds = jnp.linspace(0, 1.0, thresholds)
        elif thresholds is not None:
            if not isinstance(thresholds, (list, jnp.ndarray, jax.Array)):
                raise ValueError("Expected argument `thresholds` to either be an integer, list of floats or a tensor")
            self.thresholds = jnp.asarray(thresholds)
            self.num_thresholds = self.thresholds.size
        else:
            self.num_thresholds = 100
            self.thresholds = jnp.linspace(0, 1.0, 100)

        for name in ("TPs", "FPs", "FNs"):
            self.add_state(
                name=name,
                default=jnp.zeros((num_classes, self.num_thresholds), dtype=jnp.float32),
                dist_reduce_fx="sum",
            )

    def update(self, preds: Array, target: Array) -> None:
        """Vectorized over all thresholds via the fused binning kernel
        (pallas on TPU, one (N, C, T) XLA comparison elsewhere)."""
        from metrics_tpu.ops import binned_counts

        if preds.ndim == target.ndim == 1:
            preds = preds.reshape(-1, 1)
            target = target.reshape(-1, 1)
        if preds.ndim == target.ndim + 1:
            target = to_onehot(target, num_classes=self.num_classes)
        # binned_counts binarizes with a strict `== 1` itself (bool-safe under
        # strict promotion); pass target through so the rule lives in one place
        tps, fps, fns = binned_counts(preds, target, self.thresholds)
        self.TPs = self.TPs + tps
        self.FPs = self.FPs + fps
        self.FNs = self.FNs + fns

    def _compute_curve(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        precisions = (self.TPs + METRIC_EPS) / (self.TPs + self.FPs + METRIC_EPS)
        recalls = self.TPs / (self.TPs + self.FNs + METRIC_EPS)
        # guarantee the curve ends at precision=1, recall=0
        t_ones = jnp.ones((self.num_classes, 1), dtype=precisions.dtype)
        precisions = jnp.concatenate([precisions, t_ones], axis=1)
        t_zeros = jnp.zeros((self.num_classes, 1), dtype=recalls.dtype)
        recalls = jnp.concatenate([recalls, t_zeros], axis=1)
        if self.num_classes == 1:
            return precisions[0, :], recalls[0, :], self.thresholds
        return list(precisions), list(recalls), [self.thresholds for _ in range(self.num_classes)]

    def compute(self) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
        return self._compute_curve()


class BinnedAveragePrecision(BinnedPrecisionRecallCurve):
    """Average precision from the binned curve (reference :186).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import BinnedAveragePrecision
        >>> pred = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        >>> target = jnp.asarray([0, 1, 1, 1])
        >>> average_precision = BinnedAveragePrecision(num_classes=1, thresholds=10)
        >>> average_precision(pred, target)
        Array(1., dtype=float32)
    """

    def compute(self) -> Union[List[Array], Array]:
        precisions, recalls, _ = self._compute_curve()
        return _average_precision_compute_with_precision_recall(precisions, recalls, self.num_classes, average=None)


class BinnedRecallAtFixedPrecision(BinnedPrecisionRecallCurve):
    """Highest recall at a minimum precision (reference :242).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import BinnedRecallAtFixedPrecision
        >>> pred = jnp.asarray([0.0, 0.2, 0.5, 0.8])
        >>> target = jnp.asarray([0, 1, 1, 0])
        >>> average_precision = BinnedRecallAtFixedPrecision(num_classes=1, thresholds=10, min_precision=0.5)
        >>> average_precision(pred, target)
        (Array(1., dtype=float32), Array(0.11111111, dtype=float32))
    """

    def __init__(
        self,
        num_classes: int,
        min_precision: float,
        thresholds: Union[int, Array, List[float], None] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(num_classes=num_classes, thresholds=thresholds, **kwargs)
        self.min_precision = min_precision

    def compute(self) -> Tuple[Array, Array]:
        precisions, recalls, thresholds = self._compute_curve()
        if self.num_classes == 1:
            return _recall_at_precision(precisions, recalls, thresholds, self.min_precision)
        out = [
            _recall_at_precision(precisions[i], recalls[i], thresholds[i], self.min_precision)
            for i in range(self.num_classes)
        ]
        recalls_at_p = jnp.stack([o[0] for o in out])
        thresholds_at_p = jnp.stack([o[1] for o in out])
        return recalls_at_p, thresholds_at_p
