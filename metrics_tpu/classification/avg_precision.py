"""AveragePrecision metric class (reference ``torchmetrics/classification/avg_precision.py``, 141 LoC)."""
from typing import Any, List, Optional, Union

import jax

from metrics_tpu.functional.classification.average_precision import (
    _average_precision_compute,
    _average_precision_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.buffers import _cat_state_default
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class AveragePrecision(Metric):
    """Streaming average precision.

    ``sample_capacity`` switches the unbounded cat-list states to a
    pre-allocated fixed-capacity HBM buffer of that many samples (static
    shapes, jit-friendly streaming). Overflow raises eagerly; inside a
    traced update excess samples silently clamp into the buffer tail.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import AveragePrecision
        >>> pred = jnp.asarray([0.0, 1.0, 2.0, 3.0])
        >>> target = jnp.asarray([0, 1, 1, 1])
        >>> average_precision = AveragePrecision(pos_label=1)
        >>> average_precision(pred, target)
        Array(1., dtype=float32)
    """

    _aux_attrs = ('num_classes', 'pos_label')
    is_differentiable = False
    higher_is_better = True
    full_state_update = False

    def __init__(
        self,
        num_classes: Optional[int] = None,
        pos_label: Optional[int] = None,
        average: Optional[str] = "macro",
        sample_capacity: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.pos_label = pos_label
        allowed_average = ("micro", "macro", "weighted", "none", None)
        if average not in allowed_average:
            raise ValueError(f"Expected argument `average` to be one of {allowed_average} but got {average}")
        self.average = average
        self.add_state("preds", default=_cat_state_default(sample_capacity), dist_reduce_fx="cat")
        self.add_state("target", default=_cat_state_default(sample_capacity), dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target, num_classes, pos_label = _average_precision_update(
            preds, target, self.num_classes, self.pos_label, self.average
        )
        self.preds.append(preds)
        self.target.append(target)
        self.num_classes = num_classes
        self.pos_label = pos_label

    def compute(self) -> Union[Array, List[Array]]:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _average_precision_compute(preds, target, self.num_classes, self.pos_label, self.average)
