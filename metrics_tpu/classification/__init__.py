from metrics_tpu.classification.accuracy import Accuracy  # noqa: F401
from metrics_tpu.classification.stat_scores import StatScores  # noqa: F401
