"""Multilabel ranking metric classes (reference ``torchmetrics/classification/ranking.py``, 192 LoC)."""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.classification.ranking import (
    _coverage_error_compute,
    _coverage_error_update,
    _label_ranking_average_precision_compute,
    _label_ranking_average_precision_update,
    _label_ranking_loss_compute,
    _label_ranking_loss_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class _RankingMetricBase(Metric):
    """Shared sum-state machinery for the ranking metrics."""

    is_differentiable = False
    full_state_update = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("n_elements", jnp.asarray(0), dist_reduce_fx="sum")
        self.add_state("sample_weight", jnp.asarray(0.0), dist_reduce_fx="sum")
        self._weighted = False

    def _accumulate(self, score: Array, n_elements: int, sample_weight: Optional[Array]) -> None:
        self.score = self.score + score
        self.n_elements = self.n_elements + n_elements
        if sample_weight is not None:
            self._weighted = True
            self.sample_weight = self.sample_weight + sample_weight


class CoverageError(_RankingMetricBase):
    """How far down the label ranking to go to cover all true labels."""

    higher_is_better = False

    def update(self, preds: Array, target: Array, sample_weight: Optional[Array] = None) -> None:
        score, n, sw = _coverage_error_update(preds, target, sample_weight)
        self._accumulate(score, n, sw)

    def compute(self) -> Array:
        return _coverage_error_compute(self.score, self.n_elements, self.sample_weight if self._weighted else None)


class LabelRankingAveragePrecision(_RankingMetricBase):
    """Label ranking average precision for multilabel data."""

    higher_is_better = True

    def update(self, preds: Array, target: Array, sample_weight: Optional[Array] = None) -> None:
        score, n, sw = _label_ranking_average_precision_update(preds, target, sample_weight)
        self._accumulate(score, n, sw)

    def compute(self) -> Array:
        return _label_ranking_average_precision_compute(
            self.score, self.n_elements, self.sample_weight if self._weighted else None
        )


class LabelRankingLoss(_RankingMetricBase):
    """Average fraction of incorrectly ordered label pairs."""

    higher_is_better = False

    def update(self, preds: Array, target: Array, sample_weight: Optional[Array] = None) -> None:
        score, n, sw = _label_ranking_loss_update(preds, target, sample_weight)
        self._accumulate(score, n, sw)

    def compute(self) -> Array:
        return _label_ranking_loss_compute(self.score, self.n_elements, self.sample_weight if self._weighted else None)
