"""InceptionScore metric class.

Behavioral equivalent of reference ``torchmetrics/image/inception.py:28``
(feature cat-list state :138, shuffled split-KL ``compute`` :149-175).
TPU-first: the split loop is one reshaped batched KL computation; the
shuffle uses an explicit stored PRNG key.
"""
from typing import Any, Callable, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


class InceptionScore(Metric):
    """Inception Score (reference ``image/inception.py:28``).

    Args:
        feature: int/str in ``("logits_unbiased", 64, 192, 768, 2048)``
            selecting an in-repo Flax InceptionV3 tap (uint8 image inputs;
            weights via ``weights_path=``/discovery, refusing without a
            checkpoint unless ``allow_random_weights=True``), or a callable
            ``images -> (N, num_classes)`` logits extractor.
        splits: number of splits for the mean/std estimate.
        rng_seed: seed for the pre-split shuffle.
        weights_path: optional local InceptionV3 checkpoint for the str/int
            ``feature`` path.

    Example:
        >>> import jax
        >>> from metrics_tpu import InceptionScore
        >>> logits = lambda imgs: imgs.reshape(imgs.shape[0], -1)[:, :10]
        >>> inception = InceptionScore(feature=logits, splits=2)
        >>> imgs = jax.random.uniform(jax.random.PRNGKey(0), (32, 3, 4, 4))
        >>> inception.update(imgs)
        >>> score_mean, score_std = inception.compute()
        >>> bool(score_mean >= 1.0)
        True
    """

    higher_is_better = True
    is_differentiable = False

    def __init__(
        self,
        feature: Union[str, int, Callable] = "logits_unbiased",
        splits: int = 10,
        rng_seed: int = 42,
        weights_path: str = None,
        allow_random_weights: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        rank_zero_warn(
            "Metric `InceptionScore` will save all extracted features in buffer."
            " For large datasets this may lead to large memory footprint.",
            UserWarning,
        )
        if isinstance(feature, (str, int)):
            valid_int_input = ("logits_unbiased", 64, 192, 768, 2048)
            if feature not in valid_int_input:
                raise ValueError(
                    f"Integer input to argument `feature` must be one of {valid_int_input}, but got {feature}."
                )
            from metrics_tpu.image.backbones import NoTrainInceptionV3

            self.inception = NoTrainInceptionV3(
                [str(feature)], weights_path=weights_path, allow_random_weights=allow_random_weights
            )
        elif callable(feature):
            self.inception = feature
        else:
            raise TypeError(f"Got unknown input to argument `feature`: {feature}")
        self.splits = splits
        self.rng_seed = rng_seed
        self.add_state("features", default=[], dist_reduce_fx=None)

    def update(self, imgs: Array) -> None:
        features = jnp.asarray(self.inception(imgs))
        self.features.append(features)

    def compute(self) -> Tuple[Array, Array]:
        features = dim_zero_cat(self.features)
        idx = jax.random.permutation(jax.random.PRNGKey(self.rng_seed), features.shape[0])
        features = features[idx]

        prob = jax.nn.softmax(features, axis=1)
        log_prob = jax.nn.log_softmax(features, axis=1)

        # torch.chunk sizing (reference inception.py:160): ceil(N/splits)-size
        # chunks, possibly fewer than `splits` of them
        n = features.shape[0]
        chunk = -(-n // self.splits)
        bounds = [(i * chunk, min((i + 1) * chunk, n)) for i in range(-(-n // chunk))]

        kl_scores = []
        for lo, hi in bounds:
            p, lp = prob[lo:hi], log_prob[lo:hi]
            mean_prob = p.mean(axis=0, keepdims=True)
            kl = p * (lp - jnp.log(mean_prob))
            kl_scores.append(jnp.exp(kl.sum(axis=1).mean()))
        kl_arr = jnp.stack(kl_scores)
        # unbiased std (reference returns torch's default ddof=1 std)
        std = kl_arr.std(ddof=1) if kl_arr.shape[0] > 1 else jnp.zeros_like(kl_arr[0])
        return kl_arr.mean(), std
