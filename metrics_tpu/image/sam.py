"""SpectralAngleMapper metric class.

Behavioral equivalent of reference ``torchmetrics/image/sam.py:25`` (image
cat-lists, :73-74). TPU-first: SAM is a per-pixel angle map independent
across images, so mean/sum reductions stream a score-sum + count (O(1),
psum-reducible); ``none`` keeps per-image angle maps.
"""
from typing import Any, Optional

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.sam import _sam_check_inputs, _sam_compute
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.distributed import reduce

Array = jax.Array


class SpectralAngleMapper(Metric):
    """Spectral Angle Mapper (reference ``image/sam.py:25``).

    Example:
        >>> import jax
        >>> from metrics_tpu import SpectralAngleMapper
        >>> preds = jax.random.uniform(jax.random.PRNGKey(42), (8, 3, 16, 16))
        >>> target = jax.random.uniform(jax.random.PRNGKey(123), (8, 3, 16, 16))
        >>> sam = SpectralAngleMapper()
        >>> bool(sam(preds, target) > 0)
        True
    """

    higher_is_better = False
    is_differentiable = True

    def __init__(self, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.reduction = reduction

        self._streaming = reduction in ("elementwise_mean", "sum")
        if self._streaming:
            self.add_state("score_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
            self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")
        else:
            self.add_state("scores", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _sam_check_inputs(preds, target)
        scores = _sam_compute(preds, target, reduction="none")
        if self._streaming:
            self.score_sum = self.score_sum + scores.sum()
            self.total = self.total + scores.size
        else:
            self.scores.append(scores)

    def compute(self) -> Array:
        if self._streaming:
            if self.reduction == "sum":
                return self.score_sum
            return self.score_sum / jnp.asarray(self.total, dtype=self.score_sum.dtype)
        return reduce(dim_zero_cat(self.scores), self.reduction)
