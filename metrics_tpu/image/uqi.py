"""UniversalImageQualityIndex metric class.

Behavioral equivalent of reference ``torchmetrics/image/uqi.py:25`` (which
keeps full image cat-lists, :80-81). TPU-first: UQI has no global-data
dependence (per-window statistic, no data-range constants), so for mean/sum
reductions the state is a running score-sum + element count — O(1), psum-
reducible. ``none`` reduction keeps the reference's buffer semantics.
"""
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.uqi import _uqi_check_inputs, _uqi_compute
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class UniversalImageQualityIndex(Metric):
    """Universal Image Quality Index (reference ``image/uqi.py:25``).

    Example:
        >>> import jax
        >>> from metrics_tpu import UniversalImageQualityIndex
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (8, 3, 16, 16))
        >>> target = preds * 0.75
        >>> uqi = UniversalImageQualityIndex()
        >>> float(uqi(preds, target)) > 0.9
        True
    """

    higher_is_better = True
    is_differentiable = True

    def __init__(
        self,
        kernel_size: Sequence[int] = (11, 11),
        sigma: Sequence[float] = (1.5, 1.5),
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[float] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.kernel_size = kernel_size
        self.sigma = sigma
        self.reduction = reduction
        self.data_range = data_range

        self._streaming = reduction in ("elementwise_mean", "sum")
        if self._streaming:
            self.add_state("score_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
            self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")
        else:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _uqi_check_inputs(preds, target)
        if self._streaming:
            scores = _uqi_compute(preds, target, self.kernel_size, self.sigma, reduction="none")
            self.score_sum = self.score_sum + scores.sum()
            self.total = self.total + scores.size
        else:
            self.preds.append(preds)
            self.target.append(target)

    def compute(self) -> Array:
        if self._streaming:
            if self.reduction == "sum":
                return self.score_sum
            return self.score_sum / jnp.asarray(self.total, dtype=self.score_sum.dtype)
        return _uqi_compute(
            dim_zero_cat(self.preds), dim_zero_cat(self.target), self.kernel_size, self.sigma, self.reduction
        )
