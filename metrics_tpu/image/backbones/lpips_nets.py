"""Flax LPIPS perceptual-similarity networks (VGG16 / AlexNet / SqueezeNet).

Behavioral equivalent of the reference's ``NoTrainLpips``
(``torchmetrics/image/lpip.py:33-42``), which wraps the ``lpips`` package:
an ImageNet feature stack sliced at the canonical relu taps, unit-normalized
per channel, squared-differenced, passed through per-layer 1x1 linear heads,
and spatially averaged (Zhang et al. 2018).

TPU-first: NHWC layout, the full two-tower forward + heads in one jitted XLA
program, optional bfloat16 conv compute. Weights are random-initialized by
default (pretrained checkpoints cannot be downloaded here; exact
architecture + documented warning); ``weights_path=`` loads a locally
converted ``.npz``/``.msgpack`` checkpoint in the same format as
``inception.save_variables_npz``.
"""
import functools
from typing import Any, List, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from metrics_tpu.image.backbones.inception import _fast_init_variables, _load_variables
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

# ImageNet scaling layer constants (lpips.LPIPS.ScalingLayer).
_SHIFT = (-0.030, -0.088, -0.188)
_SCALE = (0.458, 0.448, 0.450)

def _conv(features: int, kernel: int, stride: int = 1, pad: int = None, name: str = None) -> nn.Conv:
    if pad is None:
        pad = kernel // 2
    return nn.Conv(features, (kernel, kernel), strides=(stride, stride), padding=((pad, pad), (pad, pad)), name=name)


def _max_pool(x: Array, kernel: int = 2, stride: int = 2, ceil_mode: bool = False) -> Array:
    if ceil_mode:
        # torch MaxPool2d(ceil_mode=True) semantics: when (dim - kernel) is
        # not a stride multiple, one extra window starting inside the input
        # is emitted; high-side -inf padding (nn.max_pool's pad value)
        # reproduces it exactly since max ignores the padded cells
        pads = tuple((0, (stride - (size - kernel) % stride) % stride) for size in x.shape[1:3])
        return nn.max_pool(x, (kernel, kernel), strides=(stride, stride), padding=pads)
    return nn.max_pool(x, (kernel, kernel), strides=(stride, stride))


class _VGG16Slices(nn.Module):
    """VGG16 conv stack, returning (relu1_2, relu2_2, relu3_3, relu4_3, relu5_3)."""

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, ...]:
        taps: List[Array] = []
        plan = ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3))
        for block, (width, n_convs) in enumerate(plan):
            if block > 0:
                x = _max_pool(x)
            for i in range(n_convs):
                x = nn.relu(_conv(width, 3, name=f"conv{block + 1}_{i + 1}")(x))
            taps.append(x)
        return tuple(taps)


class _AlexNetSlices(nn.Module):
    """AlexNet conv stack, returning the 5 relu taps used by LPIPS."""

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, ...]:
        r1 = nn.relu(_conv(64, 11, stride=4, pad=2, name="conv1")(x))
        r2 = nn.relu(_conv(192, 5, name="conv2")(_max_pool(r1, 3, 2)))
        r3 = nn.relu(_conv(384, 3, name="conv3")(_max_pool(r2, 3, 2)))
        r4 = nn.relu(_conv(256, 3, name="conv4")(r3))
        r5 = nn.relu(_conv(256, 3, name="conv5")(r4))
        return (r1, r2, r3, r4, r5)


class _Fire(nn.Module):
    squeeze: int
    expand: int

    @nn.compact
    def __call__(self, x: Array) -> Array:
        s = nn.relu(_conv(self.squeeze, 1, name="squeeze")(x))
        e1 = nn.relu(_conv(self.expand, 1, name="expand1x1")(s))
        e3 = nn.relu(_conv(self.expand, 3, name="expand3x3")(s))
        return jnp.concatenate([e1, e3], axis=-1)


class _SqueezeNetSlices(nn.Module):
    """SqueezeNet 1.1 conv stack, returning the 7 taps used by LPIPS.

    torchvision's SqueezeNet 1.1 pools with ``MaxPool2d(3, 2,
    ceil_mode=True)``, so odd-sized feature maps keep the extra edge window.
    """

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, ...]:
        r1 = nn.relu(_conv(64, 3, stride=2, pad=0, name="conv1")(x))
        x = _max_pool(r1, 3, 2, ceil_mode=True)
        x = _Fire(16, 64, name="fire2")(x)
        r2 = _Fire(16, 64, name="fire3")(x)
        x = _max_pool(r2, 3, 2, ceil_mode=True)
        x = _Fire(32, 128, name="fire4")(x)
        r3 = _Fire(32, 128, name="fire5")(x)
        x = _max_pool(r3, 3, 2, ceil_mode=True)
        r4 = _Fire(48, 192, name="fire6")(x)
        r5 = _Fire(48, 192, name="fire7")(r4)
        r6 = _Fire(64, 256, name="fire8")(r5)
        r7 = _Fire(64, 256, name="fire9")(r6)
        return (r1, r2, r3, r4, r5, r6, r7)


_BACKBONES = {"vgg": _VGG16Slices, "alex": _AlexNetSlices, "squeeze": _SqueezeNetSlices}


class LPIPSNetwork(nn.Module):
    """Full LPIPS: scaling layer -> two-tower feature stack -> unit-normalize
    -> squared diff -> per-layer 1x1 linear head -> spatial mean -> sum."""

    net_type: str = "alex"

    @nn.compact
    def __call__(self, img0: Array, img1: Array) -> Array:  # NHWC in [-1, 1]
        shift = jnp.asarray(_SHIFT)
        scale = jnp.asarray(_SCALE)
        backbone = _BACKBONES[self.net_type](name="net")
        feats0 = backbone((img0 - shift) / scale)
        feats1 = backbone((img1 - shift) / scale)

        def unit_normalize(v: Array) -> Array:
            return v / (jnp.linalg.norm(v, axis=-1, keepdims=True) + 1e-10)

        total = 0.0
        for k, (f0, f1) in enumerate(zip(feats0, feats1)):
            diff = (unit_normalize(f0) - unit_normalize(f1)) ** 2
            head = nn.Conv(1, (1, 1), use_bias=False, name=f"lin{k}")
            total = total + head(diff).mean(axis=(1, 2))  # spatial average, (N, 1)
        return total.squeeze(-1)


@functools.partial(jax.jit, static_argnums=0)
def _lpips_forward(module: LPIPSNetwork, variables: Any, img0: Array, img1: Array) -> Array:
    # Module-level + static module arg: all instances of the same net_type
    # share one compiled executable per input shape.
    return module.apply(variables, jnp.transpose(img0, (0, 2, 3, 1)), jnp.transpose(img1, (0, 2, 3, 1)))


class NoTrainLpips:
    """Frozen LPIPS distance — the default ``net`` backend for
    ``LearnedPerceptualImagePatchSimilarity`` (reference ``image/lpip.py:33-42``).

    Callable ``(img0, img1) -> (N,)`` with ``(N, 3, H, W)`` float inputs in
    [-1, 1]; transposes to NHWC and runs both towers + heads in one jitted
    program.

    Args:
        net_type: ``"vgg" | "alex" | "squeeze"``.
        weights_path: local checkpoint (``.npz``/``.msgpack``). When omitted,
            a converted checkpoint is DISCOVERED via
            ``$METRICS_TPU_WEIGHTS_DIR`` / the user cache dir (see
            :mod:`.weights`); with nothing found, construction refuses unless
            ``allow_random_weights=True``. The LPIPS linear heads are
            non-negative in the pretrained nets, so random heads are clamped
            to their absolute value to keep distances >= 0.
        allow_random_weights: FORCE seeded random initialization
            (architecture-only smoke mode) — skips discovery so the result
            does not depend on what happens to sit in the cache.
        rng_seed: seed for random initialization.
    """

    def __init__(
        self,
        net_type: str = "alex",
        weights_path: str = None,
        rng_seed: int = 0,
        allow_random_weights: bool = False,
    ) -> None:
        from metrics_tpu.image.backbones.weights import resolve_weights

        if net_type not in _BACKBONES:
            raise ValueError(f"Argument `net_type` must be one of {tuple(_BACKBONES)}, but got {net_type}.")
        self.net_type = net_type
        self.module = LPIPSNetwork(net_type=net_type)
        dummy = jnp.zeros((1, 16, 16, 3), jnp.float32)
        weights_path = resolve_weights(f"lpips-{net_type}", weights_path, allow_random_weights)
        if weights_path is not None:
            template = jax.eval_shape(self.module.init, jax.random.PRNGKey(0), dummy, dummy)
            self.variables = _load_variables(template, weights_path)
        else:
            rank_zero_warn(
                "NoTrainLpips is running with RANDOM weights (allow_random_weights=True). Architecture"
                " is exact but distances are not comparable to the pretrained LPIPS; convert a checkpoint"
                " with `python -m metrics_tpu.image.backbones.convert` for real evaluations.",
                UserWarning,
            )
            variables = _fast_init_variables(self.module, (dummy, dummy), rng_seed)
            variables = jax.tree_util.tree_map_with_path(
                lambda path, v: jnp.abs(v)
                if any(str(getattr(p, "key", "")).startswith("lin") for p in path)
                else v,
                variables,
            )
            self.variables = variables

    def __call__(self, img0: Array, img1: Array) -> Array:
        return _lpips_forward(
            self.module, self.variables, jnp.asarray(img0, jnp.float32), jnp.asarray(img1, jnp.float32)
        )
