"""Flax InceptionV3 (FID variant) feature extractor.

Behavioral equivalent of the reference's ``NoTrainInceptionV3``
(``torchmetrics/image/fid.py:40-57``), which wraps torch-fidelity's
``FeatureExtractorInceptionV3`` — the TensorFlow-slim FID InceptionV3 with
feature taps named ``'64' | '192' | '768' | '2048' | 'logits_unbiased' |
'logits'`` and a 1008-way legacy-TF classifier head.

TPU-first design:

* **NHWC layout** end to end (the TPU-native conv layout); the public wrapper
  accepts the reference's ``(N, 3, H, W)`` uint8 contract and transposes once.
* **Whole forward under one ``jax.jit``** — resize, normalize, every Inception
  block, and the feature taps fuse into a single XLA program; conv+BN+relu are
  folded by XLA, convs land on the MXU.
* **Static early exit**: ``features_list`` is a static module attribute, so
  blocks after the last requested tap are never traced (requesting only
  ``'64'`` compiles a 4-layer program, not the full network).
* **No training mode exists at all** — batch norm always uses stored running
  statistics, which is the frozen-``eval()`` guarantee the reference enforces
  by overriding ``train()`` (``image/fid.py:51-53``).

Weights: pretrained checkpoints cannot be downloaded here, so initialization
is random by default (exact architecture, documented warning); pass
``weights_path=`` to load a locally converted checkpoint — either a flax
``.msgpack`` of the variables pytree or an ``.npz`` flat dict keyed by
``'/'.join(path)`` (e.g. ``"params/Conv2d_1a_3x3/conv/kernel"``).
"""
import functools
import os
import zlib
from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array

_VALID_FEATURES = ("64", "192", "768", "2048", "logits_unbiased", "logits")
_FEATURE_DIM = {"64": 64, "192": 192, "768": 768, "2048": 2048, "logits_unbiased": 1008, "logits": 1008}


class BasicConv2d(nn.Module):
    """Conv (no bias) + frozen BatchNorm (eps=1e-3) + ReLU."""

    features: int
    kernel_size: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "VALID"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        x = nn.Conv(
            self.features,
            self.kernel_size,
            strides=self.strides,
            padding=self.padding,
            use_bias=False,
            dtype=self.dtype,
            name="conv",
        )(x)
        x = nn.BatchNorm(use_running_average=True, epsilon=1e-3, dtype=self.dtype, name="bn")(x)
        return nn.relu(x)


def _avg_pool_3x3_same(x: Array) -> Array:
    # count_include_pad=False semantics (TF-slim / torch-fidelity AvgPool).
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)), count_include_pad=False)


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        d = self.dtype
        b1 = BasicConv2d(64, (1, 1), dtype=d, name="branch1x1")(x)
        b5 = BasicConv2d(48, (1, 1), dtype=d, name="branch5x5_1")(x)
        b5 = BasicConv2d(64, (5, 5), padding=((2, 2), (2, 2)), dtype=d, name="branch5x5_2")(b5)
        b3 = BasicConv2d(64, (1, 1), dtype=d, name="branch3x3dbl_1")(x)
        b3 = BasicConv2d(96, (3, 3), padding=((1, 1), (1, 1)), dtype=d, name="branch3x3dbl_2")(b3)
        b3 = BasicConv2d(96, (3, 3), padding=((1, 1), (1, 1)), dtype=d, name="branch3x3dbl_3")(b3)
        bp = BasicConv2d(self.pool_features, (1, 1), dtype=d, name="branch_pool")(_avg_pool_3x3_same(x))
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        d = self.dtype
        b3 = BasicConv2d(384, (3, 3), strides=(2, 2), dtype=d, name="branch3x3")(x)
        bd = BasicConv2d(64, (1, 1), dtype=d, name="branch3x3dbl_1")(x)
        bd = BasicConv2d(96, (3, 3), padding=((1, 1), (1, 1)), dtype=d, name="branch3x3dbl_2")(bd)
        bd = BasicConv2d(96, (3, 3), strides=(2, 2), dtype=d, name="branch3x3dbl_3")(bd)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        d, c7 = self.dtype, self.channels_7x7
        pad_17 = ((0, 0), (3, 3))
        pad_71 = ((3, 3), (0, 0))
        b1 = BasicConv2d(192, (1, 1), dtype=d, name="branch1x1")(x)
        b7 = BasicConv2d(c7, (1, 1), dtype=d, name="branch7x7_1")(x)
        b7 = BasicConv2d(c7, (1, 7), padding=pad_17, dtype=d, name="branch7x7_2")(b7)
        b7 = BasicConv2d(192, (7, 1), padding=pad_71, dtype=d, name="branch7x7_3")(b7)
        bd = BasicConv2d(c7, (1, 1), dtype=d, name="branch7x7dbl_1")(x)
        bd = BasicConv2d(c7, (7, 1), padding=pad_71, dtype=d, name="branch7x7dbl_2")(bd)
        bd = BasicConv2d(c7, (1, 7), padding=pad_17, dtype=d, name="branch7x7dbl_3")(bd)
        bd = BasicConv2d(c7, (7, 1), padding=pad_71, dtype=d, name="branch7x7dbl_4")(bd)
        bd = BasicConv2d(192, (1, 7), padding=pad_17, dtype=d, name="branch7x7dbl_5")(bd)
        bp = BasicConv2d(192, (1, 1), dtype=d, name="branch_pool")(_avg_pool_3x3_same(x))
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        d = self.dtype
        b3 = BasicConv2d(192, (1, 1), dtype=d, name="branch3x3_1")(x)
        b3 = BasicConv2d(320, (3, 3), strides=(2, 2), dtype=d, name="branch3x3_2")(b3)
        b7 = BasicConv2d(192, (1, 1), dtype=d, name="branch7x7x3_1")(x)
        b7 = BasicConv2d(192, (1, 7), padding=((0, 0), (3, 3)), dtype=d, name="branch7x7x3_2")(b7)
        b7 = BasicConv2d(192, (7, 1), padding=((3, 3), (0, 0)), dtype=d, name="branch7x7x3_3")(b7)
        b7 = BasicConv2d(192, (3, 3), strides=(2, 2), dtype=d, name="branch7x7x3_4")(b7)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """Final-stage block; ``pool='avg'`` is Mixed_7b, ``pool='max'`` Mixed_7c.

    (The FID variant's E_1/E_2 split — torch-fidelity uses avg pooling with
    count_include_pad=False in the first E block and max pooling in the last.)
    """

    pool: str = "avg"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        d = self.dtype
        pad_13 = ((0, 0), (1, 1))
        pad_31 = ((1, 1), (0, 0))
        b1 = BasicConv2d(320, (1, 1), dtype=d, name="branch1x1")(x)
        b3 = BasicConv2d(384, (1, 1), dtype=d, name="branch3x3_1")(x)
        b3 = jnp.concatenate(
            [
                BasicConv2d(384, (1, 3), padding=pad_13, dtype=d, name="branch3x3_2a")(b3),
                BasicConv2d(384, (3, 1), padding=pad_31, dtype=d, name="branch3x3_2b")(b3),
            ],
            axis=-1,
        )
        bd = BasicConv2d(448, (1, 1), dtype=d, name="branch3x3dbl_1")(x)
        bd = BasicConv2d(384, (3, 3), padding=((1, 1), (1, 1)), dtype=d, name="branch3x3dbl_2")(bd)
        bd = jnp.concatenate(
            [
                BasicConv2d(384, (1, 3), padding=pad_13, dtype=d, name="branch3x3dbl_3a")(bd),
                BasicConv2d(384, (3, 1), padding=pad_31, dtype=d, name="branch3x3dbl_3b")(bd),
            ],
            axis=-1,
        )
        if self.pool == "avg":
            pooled = _avg_pool_3x3_same(x)
        else:
            pooled = nn.max_pool(x, (3, 3), strides=(1, 1), padding=((1, 1), (1, 1)))
        bp = BasicConv2d(192, (1, 1), dtype=d, name="branch_pool")(pooled)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class FIDInceptionV3(nn.Module):
    """FID-variant InceptionV3 returning the requested feature taps.

    Input: ``(N, 299, 299, 3)`` float in [-1, 1] (NHWC). Output: tuple of
    arrays, one per ``features_list`` entry, in order. Blocks beyond the last
    requested tap are not traced.
    """

    features_list: Tuple[str, ...] = ("2048",)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Tuple[Array, ...]:
        for f in self.features_list:
            if f not in _VALID_FEATURES:
                raise ValueError(f"Invalid feature {f!r}; valid: {_VALID_FEATURES}")
        remaining = set(self.features_list)
        out: Dict[str, Array] = {}
        d = self.dtype

        def spatial_mean(v: Array) -> Array:  # adaptive_avg_pool2d(·, 1) then flatten
            return v.mean(axis=(1, 2))

        x = BasicConv2d(32, (3, 3), strides=(2, 2), dtype=d, name="Conv2d_1a_3x3")(x)
        x = BasicConv2d(32, (3, 3), dtype=d, name="Conv2d_2a_3x3")(x)
        x = BasicConv2d(64, (3, 3), padding=((1, 1), (1, 1)), dtype=d, name="Conv2d_2b_3x3")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        if "64" in remaining:
            out["64"] = spatial_mean(x)
            remaining.discard("64")
        if remaining:
            x = BasicConv2d(80, (1, 1), dtype=d, name="Conv2d_3b_1x1")(x)
            x = BasicConv2d(192, (3, 3), dtype=d, name="Conv2d_4a_3x3")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2))
            if "192" in remaining:
                out["192"] = spatial_mean(x)
                remaining.discard("192")
        if remaining:
            x = InceptionA(32, dtype=d, name="Mixed_5b")(x)
            x = InceptionA(64, dtype=d, name="Mixed_5c")(x)
            x = InceptionA(64, dtype=d, name="Mixed_5d")(x)
            x = InceptionB(dtype=d, name="Mixed_6a")(x)
            x = InceptionC(128, dtype=d, name="Mixed_6b")(x)
            x = InceptionC(160, dtype=d, name="Mixed_6c")(x)
            x = InceptionC(160, dtype=d, name="Mixed_6d")(x)
            x = InceptionC(192, dtype=d, name="Mixed_6e")(x)
            if "768" in remaining:
                out["768"] = spatial_mean(x)
                remaining.discard("768")
        if remaining:
            x = InceptionD(dtype=d, name="Mixed_7a")(x)
            x = InceptionE("avg", dtype=d, name="Mixed_7b")(x)
            x = InceptionE("max", dtype=d, name="Mixed_7c")(x)
            x = spatial_mean(x)
            if "2048" in remaining:
                out["2048"] = x
                remaining.discard("2048")
        if remaining:  # logits / logits_unbiased (1008-way legacy-TF head)
            kernel = self.param("fc_kernel", nn.initializers.lecun_normal(), (2048, 1008), jnp.float32)
            bias = self.param("fc_bias", nn.initializers.zeros_init(), (1008,), jnp.float32)
            unbiased = jnp.matmul(x.astype(jnp.float32), kernel)
            out["logits_unbiased"] = unbiased
            out["logits"] = unbiased + bias
        return tuple(out[f] for f in self.features_list)


def _fast_init_variables(module: nn.Module, dummy_args: Tuple, rng_seed: int) -> Any:
    """Random-initialize a frozen network's variables from shapes alone.

    ``module.init`` runs the full forward pass eagerly, which on the XLA CPU
    backend compiles every op individually (minutes for InceptionV3).
    These backbones are frozen — pretrained weights are the real contract and
    random init only needs plausible magnitudes — so initialize each leaf
    directly from its ``jax.eval_shape`` shape: conv/dense kernels get fan-in
    scaled normals, batch-norm scale/var get ones, everything else zeros.
    """
    shapes = jax.eval_shape(module.init, jax.random.PRNGKey(0), *dummy_args)
    key = jax.random.PRNGKey(rng_seed)

    def init_leaf(path: Tuple, sds: Any) -> Array:
        names = [str(getattr(p, "key", p)) for p in path]
        # crc32, not hash(): Python string hashing is salted per process, and
        # identical rng_seed must give identical weights on every host
        leaf_key = jax.random.fold_in(key, zlib.crc32("/".join(names).encode()) & 0x7FFFFFFF)
        name = names[-1]
        if name == "scale" or name == "var":
            return jnp.ones(sds.shape, sds.dtype)
        if "kernel" in name:
            fan_in = int(np.prod(sds.shape[:-1])) or 1
            return jax.random.normal(leaf_key, sds.shape, sds.dtype) * np.sqrt(1.0 / fan_in)
        return jnp.zeros(sds.shape, sds.dtype)

    return jax.tree_util.tree_map_with_path(init_leaf, shapes)


def _load_variables(template: Any, weights_path: str) -> Any:
    """Load a variables pytree from a local ``.msgpack`` or ``.npz`` checkpoint."""
    if not os.path.exists(weights_path):
        raise FileNotFoundError(f"weights_path {weights_path!r} does not exist")
    if weights_path.endswith(".npz"):
        flat = dict(np.load(weights_path))
        leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
        rebuilt = []
        for path, leaf in leaves:
            key = "/".join(getattr(p, "key", str(p)) for p in path)
            if key not in flat:
                raise KeyError(f"checkpoint {weights_path!r} is missing entry {key!r}")
            arr = jnp.asarray(flat[key])
            if arr.shape != leaf.shape:
                raise ValueError(f"checkpoint entry {key!r} has shape {arr.shape}, expected {leaf.shape}")
            rebuilt.append(arr)
        return jax.tree_util.tree_unflatten(treedef, rebuilt)
    from flax import serialization

    with open(weights_path, "rb") as fh:
        return serialization.from_bytes(template, fh.read())


def save_variables_npz(variables: Any, path: str) -> None:
    """Save a variables pytree as the flat ``.npz`` format ``weights_path`` loads."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(variables)
    flat = {"/".join(getattr(p, "key", str(p)) for p in path): np.asarray(v) for path, v in leaves}
    np.savez(path, **flat)


@functools.partial(jax.jit, static_argnums=0)
def _inception_forward(module: FIDInceptionV3, variables: Any, imgs: Array) -> Tuple[Array, ...]:
    """Resize + normalize + backbone in one XLA program.

    Module-level and keyed on the (hashable, frozen-dataclass) module so all
    extractor instances with the same ``features_list``/dtype share one
    compiled executable per input shape.
    """
    x = jnp.transpose(imgs, (0, 2, 3, 1)).astype(jnp.float32)
    x = jax.image.resize(x, (x.shape[0], 299, 299, x.shape[3]), method="bilinear")
    x = (x - 128.0) / 128.0
    feats = module.apply(variables, x)
    return tuple(f.astype(jnp.float32) for f in feats)


class NoTrainInceptionV3:
    """Frozen InceptionV3 extractor — the default ``feature`` backend for
    FID/KID/IS (reference ``torchmetrics/image/fid.py:40-57``).

    Callable ``(N, 3, H, W) uint8 -> (N, D)`` features: transposes to NHWC,
    bilinear-resizes to 299x299 (half-pixel centers, matching
    ``F.interpolate(align_corners=False)``), normalizes ``(x - 128) / 128``,
    and runs the requested tap — all inside one jitted XLA program.

    Args:
        features_list: taps to compute, e.g. ``["2048"]`` (the wrapper returns
            the first tap flattened, like the reference's ``out[0].reshape``).
        weights_path: local checkpoint (``.npz`` flat dict or flax
            ``.msgpack``). When omitted, a converted checkpoint is DISCOVERED
            via ``$METRICS_TPU_WEIGHTS_DIR`` / the user cache dir (see
            :mod:`.weights`); with nothing found, construction refuses unless
            ``allow_random_weights=True`` explicitly opts into
            random-initialized architecture-only mode (with a warning).
        allow_random_weights: FORCE seeded random initialization
            (architecture-only smoke mode) — skips discovery so the result
            does not depend on what happens to sit in the cache.
        rng_seed: seed for random initialization.
        dtype: compute dtype for the conv stack (``jnp.bfloat16`` roughly
            doubles MXU throughput; taps are cast back to float32).
    """

    def __init__(
        self,
        features_list: Sequence[str],
        weights_path: str = None,
        rng_seed: int = 0,
        dtype: Any = jnp.float32,
        allow_random_weights: bool = False,
    ) -> None:
        from metrics_tpu.image.backbones.weights import resolve_weights

        self.features_list = tuple(str(f) for f in features_list)
        for f in self.features_list:
            if f not in _VALID_FEATURES:
                raise ValueError(f"Invalid feature {f!r}; valid: {_VALID_FEATURES}")
        self.module = FIDInceptionV3(features_list=self.features_list, dtype=dtype)
        init_input = jnp.zeros((1, 299, 299, 3), jnp.float32)
        weights_path = resolve_weights("inception", weights_path, allow_random_weights)
        if weights_path is not None:
            template = jax.eval_shape(self.module.init, jax.random.PRNGKey(0), init_input)
            self.variables = _load_variables(template, weights_path)
        else:
            rank_zero_warn(
                "NoTrainInceptionV3 is running with RANDOM weights (allow_random_weights=True)."
                " Feature shapes and architecture are exact, but metric values are not comparable to"
                " pretrained-InceptionV3 results; convert a checkpoint with"
                " `python -m metrics_tpu.image.backbones.convert` for real evaluations.",
                UserWarning,
            )
            self.variables = _fast_init_variables(self.module, (init_input,), rng_seed)

    @property
    def num_features(self) -> int:
        """Output dimensionality of the first requested tap."""
        return _FEATURE_DIM.get(self.features_list[0], 1008)

    def __call__(self, imgs: Array) -> Array:
        imgs = jnp.asarray(imgs)
        if imgs.ndim != 4 or imgs.shape[1] != 3:
            raise ValueError(f"Expected input of shape (N, 3, H, W), got {imgs.shape}")
        if imgs.dtype != jnp.uint8:
            raise TypeError(f"Expected uint8 images in [0, 255], got dtype {imgs.dtype}")
        out = _inception_forward(self.module, self.variables, imgs)
        return out[0].reshape(imgs.shape[0], -1)
