"""Pretrained-checkpoint discovery for the image backbones.

The reference obtains its backbone weights by download at first use
(torch-fidelity for the FID InceptionV3, ``torchmetrics/image/fid.py:40-57``;
the ``lpips`` package for the LPIPS towers+heads, ``image/lpip.py:33-42``).
Downloads are not assumed here; instead a converted checkpoint (see
``python -m metrics_tpu.image.backbones.convert``) is DISCOVERED at
construction time:

1. an explicit ``weights_path=`` argument wins;
2. ``$METRICS_TPU_WEIGHTS_DIR/<canonical name>`` if the env var is set;
3. the user cache dir: ``$XDG_CACHE_HOME/metrics_tpu/weights/<name>`` (or
   ``~/.cache/metrics_tpu/weights/<name>``).

Canonical file names: ``inception_fid.npz`` for the FID/KID/IS InceptionV3,
``lpips_{vgg,alex,squeeze}.npz`` for the LPIPS nets. ``convert --install``
writes straight into the cache dir under these names.

When nothing is found, construction REFUSES by default — a metric silently
running on random weights produces plausible-looking numbers that are
meaningless against the literature. Passing ``allow_random_weights=True``
opts into the random-initialized architecture (useful for smoke tests and
pipeline development), still with a warning.
"""
import os
from typing import Optional

from metrics_tpu.utilities.exceptions import MetricsTPUUserError

WEIGHTS_DIR_ENV = "METRICS_TPU_WEIGHTS_DIR"

#: canonical checkpoint file names, keyed by backbone id
CANONICAL_NAMES = {
    "inception": "inception_fid.npz",
    "lpips-vgg": "lpips_vgg.npz",
    "lpips-alex": "lpips_alex.npz",
    "lpips-squeeze": "lpips_squeeze.npz",
}

# one-line recipes shown in the refusal error, per backbone id
_CONVERT_HINTS = {
    "inception": (
        "python -m metrics_tpu.image.backbones.convert inception"
        " <torch-fidelity-or-torchvision inception .pth> --install"
    ),
    "lpips-vgg": (
        "python -m metrics_tpu.image.backbones.convert lpips-vgg"
        " <torchvision vgg16 .pth> <lpips weights/v0.1/vgg.pth> --install"
    ),
    "lpips-alex": (
        "python -m metrics_tpu.image.backbones.convert lpips-alex"
        " <torchvision alexnet .pth> <lpips weights/v0.1/alex.pth> --install"
    ),
    "lpips-squeeze": (
        "python -m metrics_tpu.image.backbones.convert lpips-squeeze"
        " <torchvision squeezenet1_1 .pth> <lpips weights/v0.1/squeeze.pth> --install"
    ),
}


def weights_cache_dir() -> str:
    """The directory ``convert --install`` writes to and discovery reads from."""
    env = os.environ.get(WEIGHTS_DIR_ENV)
    if env:
        return env
    xdg = os.environ.get("XDG_CACHE_HOME") or os.path.join(os.path.expanduser("~"), ".cache")
    return os.path.join(xdg, "metrics_tpu", "weights")


def discover_weights(backbone: str) -> Optional[str]:
    """The discovered checkpoint path for a backbone id, or None."""
    name = CANONICAL_NAMES[backbone]
    candidate = os.path.join(weights_cache_dir(), name)
    return candidate if os.path.exists(candidate) else None


def resolve_weights(
    backbone: str, weights_path: Optional[str], allow_random_weights: bool
) -> Optional[str]:
    """Resolve the checkpoint a backbone should load.

    Returns a path (explicit or discovered), or ``None`` when random
    initialization was explicitly requested. Raises
    :class:`MetricsTPUUserError` otherwise — the honest default: no real
    weights, no silently-meaningless metric values.

    ``allow_random_weights=True`` FORCES random init (unless an explicit
    ``weights_path`` was also given): it must stay reproducible and
    machine-independent, so a checkpoint that happens to sit in the
    discovery cache does not override it.
    """
    if weights_path is not None:
        return weights_path
    if allow_random_weights:
        return None
    found = discover_weights(backbone)
    if found is not None:
        return found
    raise MetricsTPUUserError(
        f"No pretrained weights found for backbone {backbone!r}: no `weights_path=` was given and"
        f" {os.path.join(weights_cache_dir(), CANONICAL_NAMES[backbone])!r} does not exist."
        " Metric values computed on RANDOM weights are meaningless against published results, so"
        " construction refuses by default. Either convert a locally available torch checkpoint —\n"
        f"    {_CONVERT_HINTS[backbone]}\n"
        f" (or set ${WEIGHTS_DIR_ENV} to a directory containing {CANONICAL_NAMES[backbone]!r}) —"
        " or opt in explicitly with `allow_random_weights=True` (architecture-only smoke mode)."
    )
