"""Convert pretrained torch checkpoints into ``weights_path`` format.

Pretrained backbone weights cannot be downloaded in this environment, but
users migrating from the reference already have them on disk: torch-fidelity
caches its FID InceptionV3 (``pt_inception-2015-12-05``-style state dicts,
the torchvision naming convention) and the ``lpips`` package ships
VGG16/AlexNet/SqueezeNet towers + linear heads. This module maps those
state dicts onto the Flax trees of :mod:`.inception` / :mod:`.lpips_nets`:

* conv ``weight (O, I, kH, kW)`` -> ``kernel (kH, kW, I, O)``
* batchnorm ``weight/bias`` -> ``scale/bias`` (params),
  ``running_mean/running_var`` -> ``mean/var`` (batch_stats)
* final fc ``weight (num_classes, 2048)`` -> ``fc_kernel (2048, num_classes)``
* LPIPS ``lin{k}`` 1x1 heads ``(1, C, 1, 1)`` -> ``kernel (1, 1, C, 1)``

Output is the flat ``{"/".join(path): array}`` dict that
``save_variables_npz`` writes and ``weights_path=`` loads. CLI::

    python -m metrics_tpu.image.backbones.convert inception weights.pth out.npz
    python -m metrics_tpu.image.backbones.convert lpips-alex lpips.pth out.npz

Conversion itself is pure numpy — torch is only needed to ``torch.load``
a ``.pt``/``.pth`` input file.
"""
from typing import Any, Dict, Mapping

import numpy as np

__all__ = ["convert_inception_state_dict", "convert_lpips_state_dict", "save_flat_npz"]


def _np(t: Any) -> np.ndarray:
    # torch tensor or array-like -> numpy without importing torch
    return np.asarray(t.detach().cpu().numpy() if hasattr(t, "detach") else t)


def _conv_kernel(w: Any) -> np.ndarray:
    return _np(w).transpose(2, 3, 1, 0)  # (O, I, H, W) -> (H, W, I, O)


def convert_inception_state_dict(state_dict: Mapping[str, Any]) -> Dict[str, np.ndarray]:
    """torch(-fidelity/vision) InceptionV3 state dict -> flat flax dict.

    Handles the standard names (``Conv2d_1a_3x3.conv.weight``,
    ``Mixed_5b.branch1x1.bn.running_mean``, ``fc.weight``, ...); torchvision's
    ``AuxLogits`` head and bookkeeping buffers are skipped.
    """
    flat: Dict[str, np.ndarray] = {}
    for key, value in state_dict.items():
        if key.startswith("AuxLogits") or key.endswith("num_batches_tracked"):
            continue
        if key == "fc.weight":
            flat["params/fc_kernel"] = _np(value).T
            continue
        if key == "fc.bias":
            flat["params/fc_bias"] = _np(value)
            continue
        parts = key.split(".")
        module_path, layer, param = parts[:-2], parts[-2], parts[-1]
        prefix = "/".join(module_path)
        if layer == "conv" and param == "weight":
            flat[f"params/{prefix}/conv/kernel"] = _conv_kernel(value)
        elif layer == "bn":
            dest = {
                "weight": "params/{}/bn/scale",
                "bias": "params/{}/bn/bias",
                "running_mean": "batch_stats/{}/bn/mean",
                "running_var": "batch_stats/{}/bn/var",
            }.get(param)
            if dest is None:
                raise KeyError(f"Unrecognized batchnorm entry {key!r}")
            flat[dest.format(prefix)] = _np(value)
        else:
            raise KeyError(f"Unrecognized InceptionV3 entry {key!r}")
    return flat


# absolute torchvision `features` indices -> our layer names (the lpips
# package keeps absolute indices when slicing the towers)
_LPIPS_LAYER_MAPS = {
    "vgg": {
        0: "conv1_1", 2: "conv1_2", 5: "conv2_1", 7: "conv2_2",
        10: "conv3_1", 12: "conv3_2", 14: "conv3_3",
        17: "conv4_1", 19: "conv4_2", 21: "conv4_3",
        24: "conv5_1", 26: "conv5_2", 28: "conv5_3",
    },
    "alex": {0: "conv1", 3: "conv2", 6: "conv3", 8: "conv4", 10: "conv5"},
    "squeeze": {
        0: "conv1", 3: "fire2", 4: "fire3", 6: "fire4", 7: "fire5",
        9: "fire6", 10: "fire7", 11: "fire8", 12: "fire9",
    },
}


def convert_lpips_state_dict(net_type: str, state_dict: Mapping[str, Any]) -> Dict[str, np.ndarray]:
    """``lpips.LPIPS`` state dict (tower + ``lin`` heads) -> flat flax dict.

    Accepts the full LPIPS module state dict: ``net.slice{S}.{idx}...``
    tower entries (absolute torchvision indices) and ``lin{k}.model.1.weight``
    / ``lins.{k}.model.1.weight`` heads. A bare torchvision backbone state
    dict (``features.{idx}...``) also converts — heads are then absent.
    """
    if net_type not in _LPIPS_LAYER_MAPS:
        raise ValueError(f"net_type must be one of {tuple(_LPIPS_LAYER_MAPS)}, got {net_type!r}")
    layer_map = _LPIPS_LAYER_MAPS[net_type]
    flat: Dict[str, np.ndarray] = {}
    for key, value in state_dict.items():
        if key.startswith("scaling_layer"):
            continue  # constants, baked into LPIPSNetwork
        if key.startswith("classifier.") or key.endswith("num_batches_tracked"):
            continue  # torchvision hub files ship the unused classifier head
        parts = key.split(".")
        if parts[0].startswith("lin") or parts[0] == "lins":
            k = int(parts[1]) if parts[0] == "lins" else int(parts[0][3:])
            if parts[-1] == "weight":
                flat[f"params/lin{k}/kernel"] = _conv_kernel(value)
            continue
        if parts[0] == "net" or parts[0] == "features":
            idx_pos = 2 if parts[0] == "net" else 1  # net.sliceS.<idx> / features.<idx>
            idx = int(parts[idx_pos])
            name = layer_map.get(idx)
            if name is None:
                raise KeyError(f"{key!r}: torchvision index {idx} is not a parametrized layer of {net_type}")
            rest = parts[idx_pos + 1 : -1]  # e.g. [] for plain convs, ['squeeze'] for fire
            param = parts[-1]
            prefix = "/".join(["params", "net", name] + rest)
            if param == "weight":
                flat[f"{prefix}/kernel"] = _conv_kernel(value)
            elif param == "bias":
                flat[f"{prefix}/bias"] = _np(value)
            else:
                raise KeyError(f"Unrecognized tower entry {key!r}")
            continue
        raise KeyError(f"Unrecognized LPIPS entry {key!r}")
    return flat


def expected_lpips_keys(net_type: str) -> set:
    """Every flat key a loadable LPIPS checkpoint must contain."""
    keys = set()
    for name in _LPIPS_LAYER_MAPS[net_type].values():
        subs = ("squeeze", "expand1x1", "expand3x3") if name.startswith("fire") else ("",)
        for sub in subs:
            prefix = f"params/net/{name}" + (f"/{sub}" if sub else "")
            keys.add(f"{prefix}/kernel")
            keys.add(f"{prefix}/bias")
    n_heads = 7 if net_type == "squeeze" else 5
    keys.update(f"params/lin{k}/kernel" for k in range(n_heads))
    return keys


def validate_lpips_flat(net_type: str, flat: Dict[str, np.ndarray]) -> None:
    """Fail fast (with the fix) instead of at load time.

    No single cached artifact has everything: the ``lpips`` package's
    ``weights/v0.1/{net}.pth`` holds only the lin heads, while torchvision
    hub files hold only the tower — the CLI merges multiple inputs for
    exactly this reason.
    """
    missing = expected_lpips_keys(net_type) - set(flat)
    if missing:
        tower_missing = sorted(k for k in missing if "/net/" in k)
        head_missing = sorted(k for k in missing if "/lin" in k)
        hint = []
        if tower_missing:
            hint.append(
                f"{len(tower_missing)} tower entries (e.g. {tower_missing[0]}) — also pass the torchvision"
                f" backbone checkpoint ({net_type} features)"
            )
        if head_missing:
            hint.append(
                f"{len(head_missing)} linear-head entries (e.g. {head_missing[0]}) — also pass the lpips"
                f" package's weights/v0.1/{net_type}.pth"
            )
        raise ValueError("Converted LPIPS checkpoint is incomplete: missing " + "; ".join(hint))


def save_flat_npz(flat: Dict[str, np.ndarray], path: str) -> None:
    np.savez(path, **flat)


def main(argv=None) -> None:
    import argparse
    import os

    from metrics_tpu.image.backbones.weights import CANONICAL_NAMES, weights_cache_dir

    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("kind", choices=["inception", "lpips-vgg", "lpips-alex", "lpips-squeeze"])
    parser.add_argument(
        "paths",
        nargs="+",
        help=".pt/.pth input state dict(s) — LPIPS usually needs TWO, the torchvision tower plus"
        " the lpips package's lin-head file, merged here — followed by the output .npz path"
        " (the output is omitted when --install is given: everything is then an input)",
    )
    parser.add_argument(
        "--install",
        action="store_true",
        help=f"write to the discovery cache dir ({weights_cache_dir()}) under the canonical name"
        " so FID/KID/IS/LPIPS find the weights automatically",
    )
    parser.add_argument(
        "--allow-partial", action="store_true", help="skip the completeness check (LPIPS kinds only)"
    )
    args = parser.parse_args(argv)
    if args.install:
        inputs, out_npz = args.paths, None
    else:
        if len(args.paths) < 2:
            parser.error(
                "give input checkpoint(s) followed by the output .npz path, or pass --install"
            )
        inputs, out_npz = args.paths[:-1], args.paths[-1]

    import torch

    flat: Dict[str, np.ndarray] = {}
    for ckpt in inputs:
        sd = torch.load(ckpt, map_location="cpu", weights_only=True)
        sd = sd.get("state_dict", sd) if isinstance(sd, dict) else sd
        if args.kind == "inception":
            flat.update(convert_inception_state_dict(sd))
        else:
            flat.update(convert_lpips_state_dict(args.kind.split("-")[1], sd))
    if args.kind != "inception" and not args.allow_partial:
        validate_lpips_flat(args.kind.split("-")[1], flat)
    outputs = []
    if out_npz is not None:
        outputs.append(out_npz)
    if args.install:
        os.makedirs(weights_cache_dir(), exist_ok=True)
        outputs.append(os.path.join(weights_cache_dir(), CANONICAL_NAMES[args.kind]))
    for out in outputs:
        save_flat_npz(flat, out)
        print(f"wrote {len(flat)} arrays to {out}")
    if args.install:
        print(
            "installed: FID/KID/IS/LPIPS will discover these weights automatically"
            " (override the directory with $METRICS_TPU_WEIGHTS_DIR)"
        )


if __name__ == "__main__":
    main()
