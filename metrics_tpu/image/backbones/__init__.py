"""In-repo Flax feature-extractor backbones for model-in-the-metric metrics.

The reference ships frozen torch backbones (``torchmetrics/image/fid.py:40-57``
``NoTrainInceptionV3`` via torch-fidelity; ``torchmetrics/image/lpip.py:33-42``
``NoTrainLpips`` via the ``lpips`` package). Here the equivalents are Flax
``linen`` modules compiled by XLA for the TPU MXU: NHWC layout internally,
conv+batchnorm+relu fused by XLA, optional bfloat16 compute.

Pretrained weight *files* cannot be downloaded in this environment, so every
backbone constructs with random initialization (architecture and shapes are
exact) and documents a ``weights_path=`` hook that loads a locally converted
checkpoint (``.npz`` flat dict or flax ``.msgpack``).
"""
from metrics_tpu.image.backbones.inception import FIDInceptionV3, NoTrainInceptionV3
from metrics_tpu.image.backbones.lpips_nets import LPIPSNetwork, NoTrainLpips

__all__ = ["FIDInceptionV3", "NoTrainInceptionV3", "LPIPSNetwork", "NoTrainLpips"]
