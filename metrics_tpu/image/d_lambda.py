"""SpectralDistortionIndex metric class.

Behavioral equivalent of reference ``torchmetrics/image/d_lambda.py:30``.
D-lambda's UQI channel-pair matrices are computed over the ENTIRE accumulated
batch (non-separable across batches), so the cat-list buffer semantics of the
reference are kept (:79-80).
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.image.d_lambda import (
    _spectral_distortion_index_check_inputs,
    _spectral_distortion_index_compute,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


class SpectralDistortionIndex(Metric):
    """Spectral Distortion Index / D-lambda (reference ``image/d_lambda.py:30``).

    Example:
        >>> import jax
        >>> from metrics_tpu import SpectralDistortionIndex
        >>> preds = jax.random.uniform(jax.random.PRNGKey(42), (4, 3, 16, 16))
        >>> target = jax.random.uniform(jax.random.PRNGKey(123), (4, 3, 16, 16))
        >>> sdi = SpectralDistortionIndex()
        >>> bool(sdi(preds, target) >= 0)
        True
    """

    higher_is_better = False
    is_differentiable = True

    def __init__(self, p: int = 1, reduction: Optional[str] = "elementwise_mean", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        rank_zero_warn(
            "Metric `SpectralDistortionIndex` will save all targets and predictions in buffer. For large datasets"
            " this may lead to large memory footprint."
        )
        if not isinstance(p, int) or p <= 0:
            raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
        self.p = p
        if reduction not in ("elementwise_mean", "sum", "none", None):
            raise ValueError(f"Expected argument `reduction` be one of ['elementwise_mean', 'sum', 'none']")
        self.reduction = reduction
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _spectral_distortion_index_check_inputs(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spectral_distortion_index_compute(preds, target, self.p, self.reduction)
