"""KernelInceptionDistance metric class.

Behavioral equivalent of reference ``torchmetrics/image/kid.py:67``
(``maximum_mean_discrepancy`` :29-47, ``poly_kernel`` :50-55, ``poly_mmd``
:58-64, feature cat-list states :230-231, subset-sampled ``compute``
:247-273). TPU-first: the ``subsets`` loop is a single ``vmap`` over a
``(subsets, subset_size)`` gather — one batched kernel-matrix contraction on
the MXU instead of a Python loop; subset sampling uses an explicit, stored
PRNG key (``rng_seed``) instead of global RNG state so compute is
reproducible and jittable.
"""
from typing import Any, Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.prints import rank_zero_warn

Array = jax.Array


def poly_kernel(f1: Array, f2: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0) -> Array:
    """Polynomial kernel matrix (reference ``kid.py:50``)."""
    if gamma is None:
        gamma = 1.0 / f1.shape[1]
    return (jnp.matmul(f1, f2.T, precision="float32") * gamma + coef) ** degree


def maximum_mean_discrepancy(k_xx: Array, k_xy: Array, k_yy: Array) -> Array:
    """Unbiased MMD^2 estimate from kernel matrices (reference ``kid.py:29``)."""
    m = k_xx.shape[0]
    kt_xx_sum = k_xx.sum() - jnp.trace(k_xx)
    kt_yy_sum = k_yy.sum() - jnp.trace(k_yy)
    k_xy_sum = k_xy.sum()
    value = (kt_xx_sum + kt_yy_sum) / (m * (m - 1))
    return value - 2 * k_xy_sum / (m**2)


def poly_mmd(
    f_real: Array, f_fake: Array, degree: int = 3, gamma: Optional[float] = None, coef: float = 1.0
) -> Array:
    """Polynomial-kernel MMD between two feature sets (reference ``kid.py:58``)."""
    k_11 = poly_kernel(f_real, f_real, degree, gamma, coef)
    k_22 = poly_kernel(f_fake, f_fake, degree, gamma, coef)
    k_12 = poly_kernel(f_real, f_fake, degree, gamma, coef)
    return maximum_mean_discrepancy(k_11, k_12, k_22)


class KernelInceptionDistance(Metric):
    """Kernel Inception Distance (reference ``image/kid.py:67``).

    Args:
        feature: int/str in ``("logits_unbiased", 64, 192, 768, 2048)``
            selecting an in-repo Flax InceptionV3 tap (uint8 image inputs;
            weights via ``weights_path=``/discovery, refusing without a
            checkpoint unless ``allow_random_weights=True``), or a callable
            ``images -> (N, D)`` feature extractor.
        subsets: number of random feature subsets per compute.
        subset_size: samples per subset.
        degree / gamma / coef: polynomial-kernel parameters.
        reset_real_features: whether ``reset()`` clears the real feature set.
        rng_seed: seed of the subset-sampling PRNG key.

    Example:
        >>> import jax
        >>> from metrics_tpu import KernelInceptionDistance
        >>> extract = lambda imgs: imgs.reshape(imgs.shape[0], -1)[:, :8]
        >>> kid = KernelInceptionDistance(feature=extract, subsets=3, subset_size=16)
        >>> real = jax.random.uniform(jax.random.PRNGKey(0), (32, 3, 4, 4))
        >>> fake = jax.random.uniform(jax.random.PRNGKey(1), (32, 3, 4, 4))
        >>> kid.update(real, real=True)
        >>> kid.update(fake, real=False)
        >>> kid_mean, kid_std = kid.compute()
        >>> bool(kid_std >= 0)
        True
    """

    higher_is_better = False
    is_differentiable = False

    def __init__(
        self,
        feature: Union[str, int, Callable] = 2048,
        subsets: int = 100,
        subset_size: int = 1000,
        degree: int = 3,
        gamma: Optional[float] = None,
        coef: float = 1.0,
        reset_real_features: bool = True,
        rng_seed: int = 42,
        weights_path: str = None,
        allow_random_weights: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        rank_zero_warn(
            "Metric `KernelInceptionDistance` will save all extracted features in buffer."
            " For large datasets this may lead to large memory footprint.",
            UserWarning,
        )
        if isinstance(feature, (str, int)):
            valid_int_input = ("logits_unbiased", 64, 192, 768, 2048)
            if feature not in valid_int_input:
                raise ValueError(
                    f"Integer input to argument `feature` must be one of {valid_int_input}, but got {feature}."
                )
            from metrics_tpu.image.backbones import NoTrainInceptionV3

            self.inception = NoTrainInceptionV3(
                [str(feature)], weights_path=weights_path, allow_random_weights=allow_random_weights
            )
        elif callable(feature):
            self.inception = feature
        else:
            raise TypeError(f"Got unknown input to argument `feature`: {feature}")

        if not (isinstance(subsets, int) and subsets > 0):
            raise ValueError("Argument `subsets` expected to be integer larger than 0")
        self.subsets = subsets
        if not (isinstance(subset_size, int) and subset_size > 0):
            raise ValueError("Argument `subset_size` expected to be integer larger than 0")
        self.subset_size = subset_size
        if not (isinstance(degree, int) and degree > 0):
            raise ValueError("Argument `degree` expected to be integer larger than 0")
        self.degree = degree
        if gamma is not None and not (isinstance(gamma, float) and gamma > 0):
            raise ValueError("Argument `gamma` expected to be `None` or float larger than 0")
        self.gamma = gamma
        if not (isinstance(coef, float) and coef > 0):
            raise ValueError("Argument `coef` expected to be float larger than 0")
        self.coef = coef
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features
        self.rng_seed = rng_seed

        self.add_state("real_features", default=[], dist_reduce_fx=None)
        self.add_state("fake_features", default=[], dist_reduce_fx=None)

    def update(self, imgs: Array, real: bool) -> None:
        features = jnp.asarray(self.inception(imgs))
        if real:
            self.real_features.append(features)
        else:
            self.fake_features.append(features)

    def compute(self) -> Tuple[Array, Array]:
        real_features = dim_zero_cat(self.real_features)
        fake_features = dim_zero_cat(self.fake_features)

        n_samples_real = real_features.shape[0]
        if n_samples_real < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")
        n_samples_fake = fake_features.shape[0]
        if n_samples_fake < self.subset_size:
            raise ValueError("Argument `subset_size` should be smaller than the number of samples")

        key = jax.random.PRNGKey(self.rng_seed)
        keys = jax.random.split(key, 2 * self.subsets)
        real_idx = jnp.stack(
            [jax.random.permutation(k, n_samples_real)[: self.subset_size] for k in keys[: self.subsets]]
        )
        fake_idx = jnp.stack(
            [jax.random.permutation(k, n_samples_fake)[: self.subset_size] for k in keys[self.subsets :]]
        )

        def one_subset(ri: Array, fi: Array) -> Array:
            return poly_mmd(real_features[ri], fake_features[fi], self.degree, self.gamma, self.coef)

        kid_scores = jax.vmap(one_subset)(real_idx, fake_idx)
        return kid_scores.mean(), kid_scores.std()

    def reset(self) -> None:
        if not self.reset_real_features:
            real = self.real_features
            super().reset()
            self.real_features = real
        else:
            super().reset()
