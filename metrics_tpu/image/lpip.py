"""LearnedPerceptualImagePatchSimilarity metric class.

Behavioral equivalent of reference ``torchmetrics/image/lpip.py:44``
(``NoTrainLpips`` wrapper :33, sum/total states :79-80, [-1,1] input check
:88-92). ``net_type`` selects the in-repo Flax LPIPS network
(``image/backbones/lpips_nets.py``: VGG16 / AlexNet / SqueezeNet feature
stacks + per-layer linear heads, one jitted two-tower XLA program) —
weights from ``weights_path=`` or the discovery path (refusing without a
checkpoint unless ``allow_random_weights=True``), loaded from a locally converted
checkpoint. A callable ``net`` ``(img1, img2) -> (N,) distances`` stays
injectable.
"""
from typing import Any, Callable, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric

Array = jax.Array


class LearnedPerceptualImagePatchSimilarity(Metric):
    """LPIPS (reference ``image/lpip.py:44``).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu import LearnedPerceptualImagePatchSimilarity
        >>> dist = lambda a, b: jnp.abs(a - b).mean(axis=(1, 2, 3))
        >>> lpips = LearnedPerceptualImagePatchSimilarity(net=dist)
        >>> img1 = jax.random.uniform(jax.random.PRNGKey(0), (8, 3, 16, 16), minval=-1, maxval=1)
        >>> img2 = jax.random.uniform(jax.random.PRNGKey(1), (8, 3, 16, 16), minval=-1, maxval=1)
        >>> bool(lpips(img1, img2) >= 0)
        True
    """

    higher_is_better = False
    is_differentiable = True

    def __init__(
        self,
        net_type: str = "alex",
        reduction: str = "mean",
        net: Union[Callable, None] = None,
        weights_path: str = None,
        allow_random_weights: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        valid_net_type = ("vgg", "alex", "squeeze")
        if net_type not in valid_net_type:
            raise ValueError(f"Argument `net_type` must be one of {valid_net_type}, but got {net_type}.")
        if net is None:
            from metrics_tpu.image.backbones import NoTrainLpips

            net = NoTrainLpips(
                net_type=net_type, weights_path=weights_path, allow_random_weights=allow_random_weights
            )
        self.net = net

        valid_reduction = ("mean", "sum")
        if reduction not in valid_reduction:
            raise ValueError(f"Argument `reduction` must be one of {valid_reduction}, but got {reduction}")
        self.reduction = reduction

        self.add_state("sum_scores", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, img1: Array, img2: Array) -> None:
        if img1.ndim != 4 or img2.ndim != 4 or img1.shape[1] != 3 or img2.shape[1] != 3:
            raise ValueError("Expected both input arguments to be 4D tensors of shape (N, 3, H, W)")
        if bool(jnp.abs(img1).max() > 1) or bool(jnp.abs(img2).max() > 1):
            raise ValueError("Expected both input arguments to be normalized tensors (all values in range [-1,1])")
        loss = jnp.asarray(self.net(img1, img2)).squeeze()
        self.sum_scores = self.sum_scores + loss.sum()
        self.total = self.total + img1.shape[0]

    def compute(self) -> Array:
        if self.reduction == "mean":
            return self.sum_scores / self.total
        return self.sum_scores
