"""ErrorRelativeGlobalDimensionlessSynthesis metric class.

Behavioral equivalent of reference ``torchmetrics/image/ergas.py:26`` (image
cat-lists, :77-78). TPU-first: ERGAS is a per-image score, so mean/sum
reductions stream a score-sum + count (O(1), psum-reducible) and ``none``
keeps a per-image score buffer — scores, not raw images.
"""
from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.ergas import _ergas_check_inputs, _ergas_compute
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat
from metrics_tpu.utilities.distributed import reduce

Array = jax.Array


class ErrorRelativeGlobalDimensionlessSynthesis(Metric):
    """ERGAS (reference ``image/ergas.py:26``).

    Example:
        >>> import jax
        >>> from metrics_tpu import ErrorRelativeGlobalDimensionlessSynthesis
        >>> preds = jax.random.uniform(jax.random.PRNGKey(42), (4, 3, 16, 16))
        >>> target = preds * 0.75
        >>> ergas = ErrorRelativeGlobalDimensionlessSynthesis()
        >>> bool(ergas(preds, target) > 0)
        True
    """

    higher_is_better = False
    is_differentiable = True

    def __init__(
        self,
        ratio: Union[int, float] = 4,
        reduction: Optional[str] = "elementwise_mean",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.ratio = ratio
        self.reduction = reduction

        self._streaming = reduction in ("elementwise_mean", "sum")
        if self._streaming:
            self.add_state("score_sum", default=jnp.asarray(0.0), dist_reduce_fx="sum")
            self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")
        else:
            self.add_state("scores", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _ergas_check_inputs(preds, target)
        scores = _ergas_compute(preds, target, self.ratio, reduction="none")
        if self._streaming:
            self.score_sum = self.score_sum + scores.sum()
            self.total = self.total + scores.shape[0]
        else:
            self.scores.append(scores)

    def compute(self) -> Array:
        if self._streaming:
            if self.reduction == "sum":
                return self.score_sum
            return self.score_sum / jnp.asarray(self.total, dtype=self.score_sum.dtype)
        return reduce(dim_zero_cat(self.scores), self.reduction)
