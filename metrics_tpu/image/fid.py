"""FrechetInceptionDistance metric class.

Behavioral equivalent of reference ``torchmetrics/image/fid.py:127``
(``NoTrainInceptionV3`` :40, scipy sqrtm round-trip :60-94, ``_compute_fid``
:97-124, feature cat-list states :251-252, ``reset_real_features`` :289-295).

TPU-first differences:

* **Streaming moments instead of feature buffers.** FID depends only on the
  mean and covariance of the feature sets, which stream exactly: states are
  ``(sum, outer-product-sum, count)`` per distribution — O(D^2) and
  psum-reducible over the mesh, vs the reference's unbounded cat-lists.
* **On-device sqrtm.** ``tr(sqrtm(S1 S2))`` via two ``eigh`` calls in XLA
  (``functional/image/fid.py``), replacing the scipy CPU round-trip.
* **In-repo Flax InceptionV3 default.** Passing an int (the reference's
  pretrained-InceptionV3 layer selector, ``image/fid.py:228-250``) builds the
  in-repo ``NoTrainInceptionV3`` (``image/backbones/inception.py``) at that
  feature tap — weights from ``weights_path=`` or the discovery path
  (``$METRICS_TPU_WEIGHTS_DIR`` / user cache; see ``backbones/weights.py``),
  loaded from a
  locally converted checkpoint (downloads are unavailable here). A callable
  ``images -> (N, D)`` extractor stays injectable (the reference's
  user-supplied ``torch.nn.Module`` path).
"""
from typing import Any, Callable, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.fid import _compute_fid, _mean_cov_from_moments
from metrics_tpu.metric import Metric

Array = jax.Array


class FrechetInceptionDistance(Metric):
    """Frechet Inception Distance (reference ``image/fid.py:127``).

    Args:
        feature: int in ``(64, 192, 768, 2048)`` selecting an in-repo Flax
            InceptionV3 feature tap (uint8 image inputs), or a callable
            ``images -> (N, D)`` feature extractor.
        feature_dim: dimensionality D of the extractor output (required when
            ``feature`` is a callable, to pre-allocate moment states).
        reset_real_features: whether ``reset()`` clears the real-set moments.
        weights_path: optional local InceptionV3 checkpoint for the int
            ``feature`` path (``.npz`` flat dict or flax ``.msgpack``);
            discovered via the weights cache otherwise. With no checkpoint
            found, construction refuses unless ``allow_random_weights=True``
            (architecture-only smoke mode, warned).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu import FrechetInceptionDistance
        >>> fid = FrechetInceptionDistance(feature=64, allow_random_weights=True)
        >>> key1, key2 = jax.random.split(jax.random.PRNGKey(0))
        >>> real = jax.random.randint(key1, (8, 3, 32, 32), 0, 200, dtype=jnp.uint8)
        >>> fake = jax.random.randint(key2, (8, 3, 32, 32), 100, 255, dtype=jnp.uint8)
        >>> fid.update(real, real=True)
        >>> fid.update(fake, real=False)
        >>> bool(fid.compute() >= 0)
        True
    """

    higher_is_better = False
    is_differentiable = False

    def __init__(
        self,
        feature: Union[int, Callable] = 2048,
        reset_real_features: bool = True,
        feature_dim: int = None,
        weights_path: str = None,
        allow_random_weights: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if isinstance(feature, int):
            valid_int_input = (64, 192, 768, 2048)
            if feature not in valid_int_input:
                raise ValueError(
                    f"Integer input to argument `feature` must be one of {valid_int_input}, but got {feature}."
                )
            from metrics_tpu.image.backbones import NoTrainInceptionV3

            self.inception = NoTrainInceptionV3(
                [str(feature)], weights_path=weights_path, allow_random_weights=allow_random_weights
            )
            feature_dim = feature
        elif callable(feature):
            if feature_dim is None:
                raise ValueError("`feature_dim` (the extractor output dimensionality) must be given")
            self.inception = feature
        else:
            raise TypeError(f"Got unknown input to argument `feature`: {feature}")
        self.feature_dim = int(feature_dim)
        if not isinstance(reset_real_features, bool):
            raise ValueError("Argument `reset_real_features` expected to be a bool")
        self.reset_real_features = reset_real_features

        d = self.feature_dim
        self.add_state("real_features_sum", default=jnp.zeros(d, dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("real_features_cov_sum", default=jnp.zeros((d, d), dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("real_features_num_samples", default=jnp.asarray(0), dist_reduce_fx="sum")
        self.add_state("fake_features_sum", default=jnp.zeros(d, dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("fake_features_cov_sum", default=jnp.zeros((d, d), dtype=jnp.float32), dist_reduce_fx="sum")
        self.add_state("fake_features_num_samples", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, imgs: Array, real: bool) -> None:
        """Extract features and fold them into the streaming moments."""
        features = jnp.asarray(self.inception(imgs), dtype=jnp.float32)
        if features.ndim != 2 or features.shape[1] != self.feature_dim:
            raise ValueError(
                f"Expected extractor output of shape (N, {self.feature_dim}), got {features.shape}"
            )
        feat_sum = features.sum(axis=0)
        outer_sum = jnp.matmul(features.T, features, precision="float32")
        n = features.shape[0]
        if real:
            self.real_features_sum = self.real_features_sum + feat_sum
            self.real_features_cov_sum = self.real_features_cov_sum + outer_sum
            self.real_features_num_samples = self.real_features_num_samples + n
        else:
            self.fake_features_sum = self.fake_features_sum + feat_sum
            self.fake_features_cov_sum = self.fake_features_cov_sum + outer_sum
            self.fake_features_num_samples = self.fake_features_num_samples + n

    def compute(self) -> Array:
        mu1, sigma1 = _mean_cov_from_moments(
            self.real_features_sum, self.real_features_cov_sum, self.real_features_num_samples
        )
        mu2, sigma2 = _mean_cov_from_moments(
            self.fake_features_sum, self.fake_features_cov_sum, self.fake_features_num_samples
        )
        return _compute_fid(mu1, sigma1, mu2, sigma2)

    def reset(self) -> None:
        """Reset, optionally preserving real-set moments (reference :289-295)."""
        if not self.reset_real_features:
            real = (
                self.real_features_sum,
                self.real_features_cov_sum,
                self.real_features_num_samples,
            )
            super().reset()
            self.real_features_sum, self.real_features_cov_sum, self.real_features_num_samples = real
        else:
            super().reset()
