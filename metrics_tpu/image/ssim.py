"""SSIM / Multi-Scale SSIM metric classes.

Behavioral equivalents of reference ``torchmetrics/image/ssim.py`` (``SSIM``
:25 / ``MultiScaleSSIM`` :138; both keep full ``preds``/``target`` image
cat-lists, :96-97/:219-220). TPU-first difference: when ``data_range`` is
given and no per-image output is requested, per-batch scores are computable
at ``update`` time, so the state collapses to two O(1) psum-reducible sums —
no unbounded HBM growth. The reference's buffer semantics are kept only for
the cases that truly need global data (``data_range=None`` or the
full-image/``none``-reduction outputs).
"""
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.image.ssim import (
    _multiscale_ssim_compute,
    _multiscale_ssim_from_scale_stats,
    _multiscale_ssim_per_image,
    _ssim_check_inputs,
    _ssim_compute,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import dim_zero_cat

Array = jax.Array


class StructuralSimilarityIndexMeasure(Metric):
    """Structural Similarity Index Measure (reference ``image/ssim.py:25``).

    Example:
        >>> import jax
        >>> from metrics_tpu import StructuralSimilarityIndexMeasure
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (8, 3, 16, 16))
        >>> target = preds * 0.75
        >>> ssim = StructuralSimilarityIndexMeasure(data_range=1.0)
        >>> float(ssim(preds, target)) > 0.9
        True
    """

    higher_is_better = True
    is_differentiable = True

    def __init__(
        self,
        gaussian_kernel: bool = True,
        sigma: Union[float, Sequence[float]] = 1.5,
        kernel_size: Union[int, Sequence[int]] = 11,
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[float] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        return_full_image: bool = False,
        return_contrast_sensitivity: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.gaussian_kernel = gaussian_kernel
        self.sigma = sigma
        self.kernel_size = kernel_size
        self.reduction = reduction
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.return_full_image = return_full_image
        self.return_contrast_sensitivity = return_contrast_sensitivity

        self._streaming = (
            data_range is not None
            and reduction in ("elementwise_mean", "sum")
            and not return_full_image
            and not return_contrast_sensitivity
        )
        if self._streaming:
            self.add_state("similarity", default=jnp.asarray(0.0), dist_reduce_fx="sum")
            self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")
        else:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _ssim_check_inputs(preds, target)
        if self._streaming:
            batch_scores = _ssim_compute(
                preds,
                target,
                self.gaussian_kernel,
                self.sigma,
                self.kernel_size,
                "none",
                self.data_range,
                self.k1,
                self.k2,
            )
            self.similarity = self.similarity + batch_scores.sum()
            self.total = self.total + batch_scores.shape[0]
        else:
            self.preds.append(preds)
            self.target.append(target)

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        if self._streaming:
            if self.reduction == "sum":
                return self.similarity
            return self.similarity / jnp.asarray(self.total, dtype=self.similarity.dtype)
        return _ssim_compute(
            dim_zero_cat(self.preds),
            dim_zero_cat(self.target),
            self.gaussian_kernel,
            self.sigma,
            self.kernel_size,
            self.reduction,
            self.data_range,
            self.k1,
            self.k2,
            self.return_full_image,
            self.return_contrast_sensitivity,
        )


class MultiScaleStructuralSimilarityIndexMeasure(Metric):
    """Multi-Scale SSIM (reference ``image/ssim.py:138``).

    Example:
        >>> import jax
        >>> from metrics_tpu import MultiScaleStructuralSimilarityIndexMeasure
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (2, 3, 180, 180))
        >>> target = preds * 0.75
        >>> ms_ssim = MultiScaleStructuralSimilarityIndexMeasure(data_range=1.0)
        >>> float(ms_ssim(preds, target)) > 0.7
        True
    """

    higher_is_better = True
    is_differentiable = True

    def __init__(
        self,
        gaussian_kernel: bool = True,
        kernel_size: Union[int, Sequence[int]] = 11,
        sigma: Union[float, Sequence[float]] = 1.5,
        reduction: Optional[str] = "elementwise_mean",
        data_range: Optional[float] = None,
        k1: float = 0.01,
        k2: float = 0.03,
        betas: Tuple[float, ...] = (0.0448, 0.2856, 0.3001, 0.2363, 0.1333),
        normalize: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not (isinstance(kernel_size, (Sequence, int))):
            raise ValueError("Argument `kernel_size` expected to be an sequence or an int")
        if not isinstance(betas, tuple) or not all(isinstance(beta, float) for beta in betas):
            raise ValueError("Argument `betas` is expected to be of a type tuple of floats.")
        if normalize and normalize not in ("relu", "simple"):
            raise ValueError("Argument `normalize` to be expected either `None` or one of 'relu' or 'simple'")
        self.gaussian_kernel = gaussian_kernel
        self.sigma = sigma
        self.kernel_size = kernel_size
        self.reduction = reduction
        self.data_range = data_range
        self.k1 = k1
        self.k2 = k2
        self.betas = betas
        self.normalize = normalize

        # The reference reduces (sim, cs) over the batch PER SCALE before the
        # beta-weighted product (ssim.py:386-414), so the sufficient state is
        # one per-scale (sim_sum, cs_sum) pair + a count — O(n_scales), not a
        # growing image buffer, whenever data_range is fixed.
        self._streaming = data_range is not None and reduction in ("elementwise_mean", "sum")
        if self._streaming:
            self.add_state("sim_sum", default=jnp.zeros(len(betas)), dist_reduce_fx="sum")
            self.add_state("cs_sum", default=jnp.zeros(len(betas)), dist_reduce_fx="sum")
            self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")
        else:
            self.add_state("preds", default=[], dist_reduce_fx="cat")
            self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _ssim_check_inputs(preds, target)
        if self._streaming:
            sim, cs = _multiscale_ssim_per_image(
                preds,
                target,
                self.gaussian_kernel,
                self.sigma,
                self.kernel_size,
                self.data_range,
                self.k1,
                self.k2,
                n_scales=len(self.betas),
            )
            self.sim_sum = self.sim_sum + sim.sum(axis=1)
            self.cs_sum = self.cs_sum + cs.sum(axis=1)
            self.total = self.total + sim.shape[1]
        else:
            self.preds.append(preds)
            self.target.append(target)

    def compute(self) -> Array:
        if self._streaming:
            if self.reduction == "sum":
                sim_stat, cs_stat = self.sim_sum, self.cs_sum
            else:
                total = jnp.asarray(self.total, dtype=self.sim_sum.dtype)
                sim_stat, cs_stat = self.sim_sum / total, self.cs_sum / total
            return _multiscale_ssim_from_scale_stats(sim_stat, cs_stat, self.betas, self.normalize)
        return _multiscale_ssim_compute(
            dim_zero_cat(self.preds),
            dim_zero_cat(self.target),
            self.gaussian_kernel,
            self.sigma,
            self.kernel_size,
            self.reduction,
            self.data_range,
            self.k1,
            self.k2,
            self.betas,
            self.normalize,
        )
