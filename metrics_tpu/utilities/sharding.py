"""Mesh-native sharded metric state: declarative specs + gather-free compute.

The replicated in-jit sync model (``sync_reduce_in_context`` /
``sync_sketch_in_context`` / ``sync_buffer_in_context``) ends every
``compute()`` with a FULL copy of each state on every device — a psum
all-reduce for sketch bins (2x payload on an ICI ring), a materialized
all-gather for sample buffers (n_dev x HBM at the root of the sort). At pod
scale that is exactly the wrong shape: the state should stay RESIDENT
across the mesh, and ``compute()`` should reduce in place.

This module is the sharded-state execution path:

* :class:`StateShardSpec` — the declarative per-state sharding spec
  consumed by :meth:`metrics_tpu.Metric.add_state`. Sketches declare
  per-leaf shard dims (``Sketch._shard_dims``); ``CapacityBuffer`` rows
  shard along dim 0 by construction.
* :func:`state_named_shardings` — the pjit surface: the spec lowered to a
  ``NamedSharding`` pytree matching ``Metric.state_pytree()``, so a pjit
  program (or ``jax.device_put``) keeps buffer rows and sketch bins
  mesh-resident between folds with no code change to the metric.
* :func:`shard_sketch_in_context` — the sharded in-jit sync: ``sum``
  leaves **reduce-scatter** over the mesh axis (1x ring payload, each
  device left holding its 1/n bin slice; a psum all-reduce would move 2x
  and replicate), extremes psum-family as before.
* sharded compute kernels (:func:`sharded_sketch_auroc`,
  :func:`sharded_sketch_average_precision`, :func:`sharded_sketch_quantile`,
  :func:`sharded_sample_auroc`) — segment-local partial computation plus
  scalar-sized collectives, so no full state is ever materialized on one
  device. The sample-buffer AUROC replaces the gather with a
  ``lax.ppermute`` ring pass: each device's buffer transits the ring once
  (same total bytes as an all-gather) but peak HBM stays O(capacity), not
  O(n_dev * capacity).
* :func:`register_sharded_compute` — the registry ``make_step(...,
  sharded_state=True)`` resolves a metric's gather-free compute from
  (built-ins registered by ``streaming/metrics.py`` and
  ``classification/auroc.py``).

Correctness contract: every kernel consumes the SAME folded states as the
replicated path — the reduce-scatter of integer-valued bin counts equals
the psum slice-for-slice bitwise (the sketch monoid's fold-order
invariance), which ``tests/bases/test_sharded_state.py`` pins across mesh
sizes and device permutations. Metric VALUES agree with the replicated
compute to f32 summation order (exactly, while partial products stay
integer-representable).
"""
from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from metrics_tpu.streaming.distinct import DistinctCountSketch, _hll_estimate
from metrics_tpu.streaming.hashing import bucket_index, pack_bits
from metrics_tpu.streaming.heavy import CoOccurrenceSketch, HeavyHitterSketch, _rank_candidates
from metrics_tpu.streaming.sketches import QuantileSketch, ScoreLabelSketch, Sketch
from metrics_tpu.utilities.buffers import CapacityBuffer
from metrics_tpu.utilities.distributed import (
    _all_gather,
    _axis_size,
    _obs_count_collective,
    reduce_scatter_in_context,
    replicate_typed,
    sync_reduce_in_context,
)

Array = jax.Array

__all__ = [
    "StateShardSpec",
    "REPLICATED",
    "get_sharded_compute",
    "register_sharded_compute",
    "shard_sketch_in_context",
    "sharded_sample_auroc",
    "sharded_sketch_auroc",
    "sharded_sketch_average_precision",
    "sharded_sketch_cooccur_top_cells",
    "sharded_sketch_distinct",
    "sharded_sketch_quantile",
    "sharded_sketch_topk",
    "state_named_shardings",
]


class StateShardSpec:
    """Declarative per-state sharding: leaves shard along ``dim`` over the
    sync mesh axis.

    Passed to :meth:`metrics_tpu.Metric.add_state(shard_spec=...)`. The
    spec is LAYOUT, not protocol: it declares which dimension of the
    state's arrays is distributable, and both consumers derive from it —
    :func:`state_named_shardings` builds the pjit ``NamedSharding`` that
    keeps the state mesh-resident, and the ``sharded_state=True`` compute
    path reduce-scatters along it. ``dim=None`` (:data:`REPLICATED`)
    declares the state must stay a full replica (the default for states
    without a spec).
    """

    __slots__ = ("dim",)

    def __init__(self, dim: Optional[int] = 0) -> None:
        if dim is not None and (not isinstance(dim, int) or dim < 0):
            raise ValueError(f"`dim` must be a non-negative int or None, got {dim!r}")
        self.dim = dim

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, StateShardSpec) and other.dim == self.dim

    def __hash__(self) -> int:
        return hash((StateShardSpec, self.dim))

    def __repr__(self) -> str:
        return f"StateShardSpec(dim={self.dim})"


REPLICATED = StateShardSpec(dim=None)


def _scatter_axis(axis_name: Union[str, Tuple[str, ...]]) -> str:
    """The axis the state scatters over.

    Convention: for a hierarchical multi-axis sync the FIRST axis is the
    fast/ICI one (reduced first, see ``hierarchical_reduce_in_context``);
    the sharded state scatters over that same first axis so the resident
    slices live within the fast fabric, and the remaining (DCN) axes
    combine by plain psum of the already-scattered slices.
    """
    if isinstance(axis_name, (tuple, list)):
        return axis_name[0]
    return axis_name


def _rest_axes(axis_name: Union[str, Tuple[str, ...]]) -> Tuple[str, ...]:
    if isinstance(axis_name, (tuple, list)):
        return tuple(axis_name[1:])
    return ()


# ---------------------------------------------------------------------------
# pjit surface: spec -> NamedSharding pytree
# ---------------------------------------------------------------------------


def _axis_total(mesh: Any, axis_name: Union[str, Tuple[str, ...]]) -> int:
    names = axis_name if isinstance(axis_name, (tuple, list)) else (axis_name,)
    total = 1
    for n in names:
        total *= int(mesh.shape[n])
    return total


def state_named_shardings(
    metric: Any, mesh: Any, axis_name: Union[str, Tuple[str, ...]]
) -> Dict[str, Any]:
    """Lower a metric's declarative shard specs to a ``NamedSharding``
    pytree matching ``state_pytree()``.

    Use it as a pjit program's ``in_shardings``/``out_shardings`` (or with
    ``jax.device_put``) so ``CapacityBuffer`` rows and sketch bins stay
    RESIDENT across the mesh between folds — the state never exists as a
    single-device array. States without a spec (and leaves whose shard dim
    does not divide by the mesh axis) come back replicated.

    Example::

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        shardings = state_named_shardings(metric, mesh, "dp")
        state = jax.device_put(metric.state_pytree(), shardings)
        epoch = jax.jit(raw_epoch, donate_argnums=0,
                        in_shardings=(shardings, ...), out_shardings=(shardings, ...))
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = _axis_total(mesh, axis_name)
    replicated = NamedSharding(mesh, P())

    def _dim_sharding(leaf: Any, dim: Optional[int]) -> Any:
        if (
            dim is None
            or not hasattr(leaf, "ndim")
            or leaf.ndim <= dim
            or leaf.shape[dim] % n != 0
        ):
            return replicated
        spec = [None] * leaf.ndim
        spec[dim] = axis_name if isinstance(axis_name, str) else tuple(axis_name)
        return NamedSharding(mesh, P(*spec))

    out: Dict[str, Any] = {}
    for name, default in metric._defaults.items():
        value = getattr(metric, name, default)
        spec_obj = getattr(metric, "_shard_specs", {}).get(name)
        # an EXPLICIT spec overrides the structural defaults everywhere:
        # REPLICATED (dim=None) pins a full replica even for buffer rows /
        # sketch bins, an explicit dim overrides the declared one
        if isinstance(value, Sketch):
            dims = type(value)._shard_dims
            children, aux = value.tree_flatten()
            shardings = tuple(
                _dim_sharding(
                    child,
                    spec_obj.dim
                    if spec_obj is not None and dims.get(lname) is not None
                    else dims.get(lname),
                )
                for (lname, _red), child in zip(value._leaf_fields, children)
            )
            out[name] = type(value).tree_unflatten(aux, shardings)
        elif isinstance(value, CapacityBuffer):
            children, aux = value.tree_flatten()
            # children = (count,) [+ (data,)] [+ (overflowed,)]: rows shard
            # along the declared axis (add_state stores the buffer's
            # SHARD_DIM spec; an explicit spec overrides), the fill counter
            # and overflow flags replicate
            row_dim = spec_obj.dim if spec_obj is not None else CapacityBuffer.SHARD_DIM
            shardings = tuple(
                _dim_sharding(child, row_dim) if child is value.data else replicated
                for child in children
            )
            out[name] = CapacityBuffer.tree_unflatten(aux, shardings)
        elif isinstance(value, list):
            out[name] = [replicated for _ in value]
        elif spec_obj is not None:
            out[name] = _dim_sharding(value, spec_obj.dim)
        else:
            out[name] = replicated
    return out


# ---------------------------------------------------------------------------
# Sharded in-jit sketch sync: reduce-scatter instead of all-reduce
# ---------------------------------------------------------------------------


def shard_sketch_in_context(
    sketch: Sketch, axis_name: Union[str, Tuple[str, ...]]
) -> Sketch:
    """Merge per-device sketches over the mesh, leaving each device its SLICE.

    The sharded-state arm of the sketch sync: ``sum`` leaves with a
    declared shard dim **reduce-scatter** over the (first) mesh axis — the
    merged leaf never exists in full on any device; device ``i`` holds
    rows ``[i*L, (i+1)*L)`` of it (padded up to a multiple of the axis
    size with zero-count rows, which are massless and thus invisible to
    every query). Extreme leaves (scalars) and undeclared leaves psum-family
    as in the replicated sync. Remaining (DCN) axes of a multi-axis sync
    combine the already-scattered slices by plain psum — the ICI-first
    hierarchical order by construction.

    Returns the sharded view: a sketch whose sharded ``sum`` leaves hold
    only the local slice, zero-padded up to a multiple of the axis size
    with massless rows (NOT a valid full sketch — consume it with the
    ``sharded_sketch_*`` kernels below). Because bin counts are
    integer-valued f32, the scattered slices equal the corresponding
    slices of the replicated psum BITWISE — the monoid fold-order
    invariance the tests pin across mesh permutations.
    """
    scatter_ax = _scatter_axis(axis_name)
    rest = _rest_axes(axis_name)
    n = _axis_size(scatter_ax)
    dims = type(sketch)._shard_dims
    out: Dict[str, Any] = {}
    for name, red in sketch._leaf_fields:
        leaf = getattr(sketch, name)
        dim = dims.get(name)
        if red == "sum" and dim is not None and hasattr(leaf, "ndim") and leaf.ndim > dim:
            pad = (-leaf.shape[dim]) % n
            if pad:
                widths = [(0, 0)] * leaf.ndim
                widths[dim] = (0, pad)
                leaf = jnp.pad(leaf, widths)
            leaf = reduce_scatter_in_context(leaf, scatter_ax, dim=dim)
            for ax in rest:
                leaf = sync_reduce_in_context(leaf, "sum", ax)
            out[name] = leaf
        else:
            out[name] = sync_reduce_in_context(
                leaf, red, tuple([scatter_ax, *rest]) if rest else scatter_ax
            )
    return sketch._replace_leaves(**out)


def _shard_exclusive_above(local_total: Array, axis_name: str) -> Tuple[Array, Array]:
    """(sum over shards with HIGHER index, sum over LOWER index) of a
    per-shard scalar — the segment-boundary terms of a sharded suffix/prefix
    sum. One tiny all-gather of ``n`` scalars; never the state itself."""
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    totals = _all_gather(jnp.reshape(local_total, ()), axis_name, "varying")  # (n,)
    ranks = jnp.arange(n)
    above = jnp.where(ranks > idx, totals, jnp.zeros((), totals.dtype)).sum()
    below = jnp.where(ranks < idx, totals, jnp.zeros((), totals.dtype)).sum()
    return above, below


def _psum_all(x: Array, axis_name: Union[str, Tuple[str, ...]]) -> Array:
    names = axis_name if isinstance(axis_name, (tuple, list)) else (axis_name,)
    return lax.psum(x, tuple(names))


# ---------------------------------------------------------------------------
# Sharded sketch computes: segment-local math + scalar collectives
# ---------------------------------------------------------------------------


def sharded_sketch_auroc(
    sketch: ScoreLabelSketch, axis_name: Union[str, Tuple[str, ...]]
) -> Tuple[Array, Array]:
    """AUROC envelope ``(lo, hi)`` with the merged bins left SHARDED.

    ``shard_sketch_in_context`` reduce-scatters the pos/neg histograms;
    each device computes its slice's contribution to ``cross = sum_b
    neg_b * pos_above_b`` (local suffix sums plus the higher-shard totals
    from one n-scalar gather) and the cross/same/total terms psum as
    scalars. Equivalent to ``ScoreLabelSketch.auroc_bounds()`` on the full
    merged sketch — exactly, while the partial products stay
    integer-representable in f32.
    """
    view = shard_sketch_in_context(sketch, axis_name)
    scatter_ax = _scatter_axis(axis_name)
    pos_l, neg_l = view.pos, view.neg  # local bin slices, ascending score
    p_shard = pos_l.sum()
    pos_above_shards, _ = _shard_exclusive_above(p_shard, scatter_ax)
    # positives strictly above each LOCAL bin: local suffix + higher shards
    local_above = jnp.concatenate(
        [jnp.cumsum(pos_l[::-1])[::-1][1:], jnp.zeros((1,), pos_l.dtype)]
    )
    pos_above = local_above + pos_above_shards
    # the scattered slices are GLOBAL sums (already combined over any
    # non-scatter axes and replicated there), so the scalar partials sum
    # over the SCATTER axis only — a full-tuple psum would multiply every
    # term by the replication factor
    cross = lax.psum((neg_l * pos_above).sum(), scatter_ax)
    same = lax.psum((neg_l * pos_l).sum(), scatter_ax)
    p_total = lax.psum(p_shard, scatter_ax)
    n_total = lax.psum(neg_l.sum(), scatter_ax)
    pn = jnp.maximum(p_total * n_total, 1.0)
    lo = jnp.where(p_total * n_total > 0, cross / pn, jnp.nan)
    hi = jnp.where(p_total * n_total > 0, (cross + same) / pn, jnp.nan)
    return lo, hi


def sharded_sketch_average_precision(
    sketch: ScoreLabelSketch, axis_name: Union[str, Tuple[str, ...]]
) -> Tuple[Array, Array]:
    """Average-precision envelope ``(lo, hi)`` from sharded bins.

    Same decomposition as :func:`sharded_sketch_auroc`: per-bin Jensen /
    chord terms (``ScoreLabelSketch.average_precision_bounds``) are local
    math once each bin knows the positives/negatives strictly above it —
    local suffix sums plus the higher-shard totals. Scalar psums finish.
    """
    view = shard_sketch_in_context(sketch, axis_name)
    scatter_ax = _scatter_axis(axis_name)
    p, n = view.pos, view.neg
    pos_above_shards, _ = _shard_exclusive_above(p.sum(), scatter_ax)
    neg_above_shards, _ = _shard_exclusive_above(n.sum(), scatter_ax)
    pos_above = (
        jnp.concatenate([jnp.cumsum(p[::-1])[::-1][1:], jnp.zeros((1,), p.dtype)])
        + pos_above_shards
    )
    neg_above = (
        jnp.concatenate([jnp.cumsum(n[::-1])[::-1][1:], jnp.zeros((1,), n.dtype)])
        + neg_above_shards
    )
    # identical per-bin terms to average_precision_bounds, on the local slice
    has = p > 0
    safe_p = jnp.where(has, p, 1.0)
    j_mid = (safe_p + 1.0) / 2.0
    upper_terms = safe_p * (pos_above + j_mid) / jnp.maximum(pos_above + neg_above + j_mid, 1.0)
    denom0 = jnp.maximum(pos_above + neg_above + n + 1.0, 1.0)
    denom1 = jnp.maximum(pos_above + neg_above + n + safe_p, 1.0)
    lower_terms = safe_p * ((pos_above + 1.0) / denom0 + (pos_above + safe_p) / denom1) / 2.0
    zero = jnp.zeros((), jnp.float32)
    hi_local = jnp.where(has, upper_terms, zero).sum()
    lo_local = jnp.where(has, lower_terms, zero).sum()
    # scatter-axis-only psums: see sharded_sketch_auroc
    p_total = jnp.maximum(lax.psum(p.sum(), scatter_ax), 1.0)
    hi = lax.psum(hi_local, scatter_ax) / p_total
    lo = lax.psum(lo_local, scatter_ax) / p_total
    nanless = lax.psum(p.sum(), scatter_ax) > 0
    return (
        jnp.where(nanless, jnp.clip(lo, 0.0, 1.0), jnp.nan),
        jnp.where(nanless, jnp.clip(hi, 0.0, 1.0), jnp.nan),
    )


def sharded_sketch_quantile(
    sketch: QuantileSketch,
    q: Union[float, Sequence[float], Array],
    axis_name: Union[str, Tuple[str, ...]],
) -> Array:
    """Quantile envelope midpoints from sharded bins, bitwise-equal to
    ``QuantileSketch.quantile`` on the full merged sketch.

    The merged counts reduce-scatter (padded to a multiple of the axis
    size with massless zero rows); the rank search runs segment-locally on
    ``exclusive_prefix + local_cumsum`` — the same integer-valued partial
    sums the replicated global cumsum produces, so EXACTLY one shard
    claims each query's bin, and the claimed bin index (hence the edge
    arithmetic, identical expression for expression) matches the
    replicated ``searchsorted`` result exactly.
    """
    view = shard_sketch_in_context(sketch, axis_name)
    scatter_ax = _scatter_axis(axis_name)
    counts_l = view.counts  # local slice of the merged (num_bins + 2 [+ pad]) counts
    minv, maxv = view.minv, view.maxv  # replicated synced extremes
    local_len = counts_l.shape[0]
    shard = lax.axis_index(scatter_ax)
    q_arr = jnp.atleast_1d(jnp.asarray(q, jnp.float32))

    local_total = counts_l.sum()
    _above, below = _shard_exclusive_above(local_total, scatter_ax)
    local_cum = below + jnp.cumsum(counts_l)
    total = lax.psum(local_total, scatter_ax)  # scatter-axis only: see sharded_sketch_auroc
    rank = jnp.clip(q_arr, 0.0, 1.0) * total
    target = jnp.maximum(rank, jnp.finfo(jnp.float32).tiny)
    # first global bin whose cumulative mass reaches the target: exactly one
    # shard has below < target <= its last cumulative value
    j = jnp.searchsorted(local_cum, target, side="left")  # (Q,), == local_len when not here
    claim = (j < local_len) & (below < target)
    g = shard * local_len + jnp.clip(j, 0, local_len - 1)  # global bin index
    g = jnp.clip(g, 0, sketch.num_bins + 1)
    # edge arithmetic identical to QuantileSketch._bin_edges on index g
    width = (sketch.hi - sketch.lo) / sketch.num_bins
    lo_edge = jnp.where(
        g == 0, -jnp.inf, sketch.lo + width * (g - 1).astype(jnp.float32)
    )
    hi_edge = jnp.where(
        g >= sketch.num_bins + 1, jnp.inf, sketch.lo + width * g.astype(jnp.float32)
    )
    lo_edge = jnp.clip(lo_edge, minv, maxv)
    hi_edge = jnp.clip(hi_edge, minv, maxv)
    zero = jnp.zeros((), jnp.float32)
    lo_v = lax.psum(jnp.where(claim, lo_edge, zero), scatter_ax)
    hi_v = lax.psum(jnp.where(claim, hi_edge, zero), scatter_ax)
    # exact extremes at the endpoints, NaN on an empty sketch — the
    # replicated quantile()'s exact semantics
    lo_v = jnp.where(q_arr <= 0.0, minv, jnp.where(q_arr >= 1.0, maxv, lo_v))
    hi_v = jnp.where(q_arr <= 0.0, minv, jnp.where(q_arr >= 1.0, maxv, hi_v))
    out = jnp.where(total > 0, (lo_v + hi_v) / 2.0, jnp.nan)
    return out[0] if jnp.ndim(q) == 0 else out


# ---------------------------------------------------------------------------
# Sharded linear-sketch computes: heavy hitters / co-occurrence / distinct
# ---------------------------------------------------------------------------
# The heavy-hitter family's merged state reduce-scatters bucket-wise
# (shard dim 1 of counts[D, W] and bitsums[D, W, B]); the full merged
# tables never exist on one device. Decode + bounds then split as:
#   * each device majority-decodes the candidates of ITS bucket slice
#     (the scattered slices are exact global sums for those buckets);
#   * candidate ids — KB-sized, never the state — all-gather once;
#   * per-(candidate, row) bound terms are owned by exactly one device
#     (whoever holds the bucket that candidate hashes to in that row),
#     so the min-over-rows upper / max-over-rows lower finish with one
#     pmin/pmax over the candidate vector. min/max are exact, the owned
#     terms are the same f32 values the replicated decode computes, and
#     _rank_candidates' (estimate desc, id asc) total order is
#     enumeration-invariant — the reported arrays match the replicated
#     topk() BITWISE.


def _sharded_linear_candidates(
    counts_l: Array, bitsums_l: Array, width: int, scatter_ax: str
) -> Tuple[Array, Array, Array, Array]:
    """Decode + bound candidates from bucket-sharded linear-sketch slices.

    Returns replicated flat ``(ids int32[M], valid bool[M], lower f32[M],
    upper f32[M])`` over all ``M = n_dev * depth * local_width`` candidate
    slots (padded slots are massless -> invalid).
    """
    depth, local_len = counts_l.shape
    shard = lax.axis_index(scatter_ax)
    cols_global = shard * local_len + jnp.arange(local_len, dtype=jnp.int32)
    # local decode: majority bits per owned cell + home-bucket self-check
    maj = (2.0 * bitsums_l) > counts_l[..., None]
    ids_local = pack_bits(maj)  # uint32 [D, local]
    valid_local = counts_l > 0
    for r in range(depth):
        valid_local = valid_local.at[r].set(
            valid_local[r] & (bucket_index(ids_local[r], r, width) == cols_global)
        )
    # candidate gather: KB of ids, never the state; the gathered vectors
    # are device-identical but varying-typed — re-type them (pmax identity,
    # exact for ints) so the ranked outputs satisfy out_specs=P()
    ids = _all_gather(ids_local.reshape(-1), scatter_ax, "varying").reshape(-1)
    ids = replicate_typed(ids, scatter_ax)
    valid = _all_gather(valid_local.reshape(-1).astype(jnp.int32), scatter_ax, "varying")
    valid = replicate_typed(valid.reshape(-1), scatter_ax) > 0
    # owned per-(candidate, row) bound terms, then pmin/pmax to finish
    num_bits = bitsums_l.shape[-1]
    bits = ((ids[:, None] >> jnp.arange(num_bits, dtype=jnp.uint32)) & jnp.uint32(1)) > 0
    uppers, lowers = [], []
    for r in range(depth):
        b = bucket_index(ids, r, width)  # global bucket, [M]
        mine = (b // local_len) == shard
        lb = jnp.clip(b - shard * local_len, 0, local_len - 1)
        c = counts_l[r, lb]
        bs = bitsums_l[r, lb, :]
        agree = jnp.where(bits, bs, c[:, None] - bs)
        up_r = jnp.minimum(agree.min(axis=-1), c)
        lo_r = c - (c[:, None] - agree).sum(axis=-1)
        uppers.append(jnp.where(mine, up_r, jnp.inf))
        lowers.append(jnp.where(mine, lo_r, -jnp.inf))
    upper = lax.pmin(jnp.stack(uppers).min(axis=0), scatter_ax)
    lower = jnp.clip(lax.pmax(jnp.stack(lowers).max(axis=0), scatter_ax), 0.0, None)
    return ids, valid, jnp.minimum(lower, upper), upper


def sharded_sketch_topk(
    sketch: HeavyHitterSketch, k: int, axis_name: Union[str, Tuple[str, ...]]
) -> Tuple[Array, Array, Array]:
    """``HeavyHitterSketch.topk(k)`` with the merged tables left SHARDED —
    bitwise-equal ``(ids, counts, overestimates)`` to the replicated
    condensation (see the block comment above for the decomposition)."""
    view = shard_sketch_in_context(sketch, axis_name)
    scatter_ax = _scatter_axis(axis_name)
    ids, valid, lo, up = _sharded_linear_candidates(
        view.counts, view.bitsums, sketch.capacity, scatter_ax
    )
    return _rank_candidates(ids, valid, lo, up, int(k))


def sharded_sketch_cooccur_top_cells(
    sketch: CoOccurrenceSketch, k: int, axis_name: Union[str, Tuple[str, ...]]
) -> Tuple[Array, Array, Array, Array]:
    """``CoOccurrenceSketch.top_cells(k)`` from bucket-sharded cell tables.

    Same candidate decomposition as :func:`sharded_sketch_topk`; the exact
    marginals carry no shard dim, so the shard view holds them fully
    synced (psum — replicated) and the marginal clamp is local math."""
    view = shard_sketch_in_context(sketch, axis_name)
    scatter_ax = _scatter_axis(axis_name)
    ids, valid, lo, up = _sharded_linear_candidates(
        view.cells, view.bitsums, sketch.capacity, scatter_ax
    )
    in_space = ids < jnp.uint32(sketch.num_rows * sketch.num_cols)
    safe = jnp.where(in_space, ids, 0)
    r_idx, c_idx = sketch._unpack(safe)
    up = jnp.minimum(up, jnp.minimum(view.row_marg[r_idx], view.col_marg[c_idx]))
    lo = jnp.minimum(lo, up)
    pair_ids, counts, over = _rank_candidates(ids, valid & in_space, lo, up, int(k))
    got = pair_ids >= 0
    rr, cc = sketch._unpack(jnp.where(got, pair_ids, 0))
    return (
        jnp.where(got, rr, -1).astype(jnp.int32),
        jnp.where(got, cc, -1).astype(jnp.int32),
        counts,
        over,
    )


def sharded_sketch_distinct(
    sketch: DistinctCountSketch, axis_name: Union[str, Tuple[str, ...]]
) -> Array:
    """``DistinctCountSketch.estimate()`` under the sharded-state path.

    HLL registers carry the ``max`` reduction, so the shard view syncs
    them by pmax (idempotent — the one collective whose "reduce-scatter"
    IS its all-reduce payload-wise) and the corrected estimator runs
    locally on the full register array: at 2^p int32 registers (16KB at
    p=12) the state is smaller than one candidate gather of the
    heavy-hitter family, and evaluating it whole keeps the estimate
    bitwise-equal to the replicated compute (a segmented harmonic sum
    would reorder f32 addition)."""
    view = shard_sketch_in_context(sketch, axis_name)
    return _hll_estimate(view.regs, sketch.precision)


# ---------------------------------------------------------------------------
# Sharded sample-buffer compute: ring pair counting (no gather, O(cap) HBM)
# ---------------------------------------------------------------------------


def sharded_sample_auroc(
    preds_buf: CapacityBuffer,
    target_buf: CapacityBuffer,
    axis_name: Union[str, Tuple[str, ...]],
) -> Array:
    """Exact binary AUROC over mesh-resident sample shards — NO gather.

    The replicated path all-gathers every device's ``CapacityBuffer`` and
    sorts the concatenation: O(n_dev * capacity) HBM on every device. This
    kernel keeps each device's rows RESIDENT and counts discordant pairs
    with a ``lax.ppermute`` ring pass (the ring-attention schedule): each
    hop rotates only the visiting shard's sorted negative scores one
    neighbour around the ring, the local positives count against them with
    two ``searchsorted`` passes (strictly-below and ties), and after
    ``n - 1`` hops every ordered shard pair has been counted exactly once.
    Total bytes moved equal one all-gather; peak HBM stays O(capacity).

        AUROC = (#[s_pos > s_neg] + 0.5 * #[s_pos == s_neg]) / (P * N)

    which is exactly the trapezoidal/tie-half convention of the exact
    sorted path, so the value matches ``AUROC.compute()`` on the gathered
    samples to f32 summation order. Pair counts accumulate in f32 (the
    exact path's own cumsums are f32 too); scores must be finite.

    Multi-axis syncs ring over the flattened axis tuple — one ring over
    every participating device, so cross-slice pairs are counted too.
    """
    names = axis_name if isinstance(axis_name, (tuple, list)) else (axis_name,)
    if preds_buf.data is None or target_buf.data is None:
        # SPMD-symmetric empty buffers: no samples anywhere
        return jnp.asarray(jnp.nan, jnp.float32)
    cap = preds_buf.capacity
    scores = preds_buf.data.astype(jnp.float32).reshape(cap)
    labels = target_buf.data.reshape(cap)
    valid = jnp.arange(cap) < preds_buf.count
    pos_mask = valid & (labels == 1)
    neg_mask = valid & (labels != 1)
    # padded sorted negatives: invalid/positive slots to +inf so they sort
    # last and never count as "below" any finite positive score
    neg_sorted = jnp.sort(jnp.where(neg_mask, scores, jnp.inf))
    pos_w = pos_mask.astype(jnp.float32)

    def count_against(visiting_neg_sorted: Array) -> Tuple[Array, Array]:
        below = jnp.searchsorted(visiting_neg_sorted, scores, side="left")
        at_or_below = jnp.searchsorted(visiting_neg_sorted, scores, side="right")
        gt = (below.astype(jnp.float32) * pos_w).sum()
        ties = ((at_or_below - below).astype(jnp.float32) * pos_w).sum()
        return gt, ties

    # +inf doubles as the padding sentinel, so a NON-FINITE real score
    # would silently corrupt the pair counts (the replicated sort path
    # handles it); poison the result to NaN instead — loud, not wrong
    finite_ok = jnp.where(valid, jnp.isfinite(scores), True).all()
    gt_acc, tie_acc = count_against(neg_sorted)  # hop 0: local pos vs local neg
    # one flat ring over every participating device (multi-axis syncs ride
    # the flattened axis tuple; lax.axis_index over a tuple is the
    # row-major linear index, matching a tuple-axis ppermute's numbering)
    n = 1
    for ax in names:
        n = n * _axis_size(ax)
    if n > 1:
        _obs_count_collective(
            "ring_permute", int(neg_sorted.size * neg_sorted.dtype.itemsize) * (n - 1)
        )
        perm = [(j, (j + 1) % n) for j in range(n)]

        def body(_h: Array, carry: Tuple[Array, Array, Array]) -> Tuple[Array, Array, Array]:
            gt, ties, buf = carry
            buf = lax.ppermute(buf, tuple(names), perm)
            g, t = count_against(buf)
            return gt + g, ties + t, buf

        gt_acc, tie_acc, _ = lax.fori_loop(0, n - 1, body, (gt_acc, tie_acc, neg_sorted))
    p_total = _psum_all(pos_w.sum(), axis_name)
    n_total = _psum_all(neg_mask.astype(jnp.float32).sum(), axis_name)
    gt_total = _psum_all(gt_acc, axis_name)
    tie_total = _psum_all(tie_acc, axis_name)
    pn = p_total * n_total
    bad = _psum_all(1.0 - finite_ok.astype(jnp.float32), axis_name)
    auroc = jnp.where(pn > 0, (gt_total + 0.5 * tie_total) / jnp.maximum(pn, 1.0), jnp.nan)
    return jnp.where(bad > 0, jnp.nan, auroc)


# ---------------------------------------------------------------------------
# Registry: metric class -> gather-free sharded compute
# ---------------------------------------------------------------------------

_SHARDED_COMPUTES: Dict[type, Callable] = {}


def register_sharded_compute(metric_cls: type, fn: Callable) -> None:
    """Register the gather-free compute for a metric class.

    ``fn(worker, state, axis_name) -> value`` runs INSIDE the mesh program
    in place of the replicated sync + ``compute()``: ``worker`` is the
    loaded metric instance (for static config — ``q``, ``mode``, bins),
    ``state`` the UNSYNCED per-device state pytree, and the contract is
    that ``fn`` reduces over ``axis_name`` itself using only
    scatter/segment/scalar collectives — never a materialized full-state
    gather. Resolution walks the MRO, so a subclass inherits its base's
    kernel unless it registers its own.

    Built-ins are registered by the modules that own the metric classes
    (``streaming/metrics.py``, ``classification/auroc.py``).
    """
    if not isinstance(metric_cls, type):
        raise ValueError(f"metric_cls must be a class, got {metric_cls!r}")
    if not callable(fn):
        raise ValueError("`fn` must be callable")
    _SHARDED_COMPUTES[metric_cls] = fn


def get_sharded_compute(metric_cls: type) -> Optional[Callable]:
    """The registered sharded compute for ``metric_cls`` (MRO-resolved), or
    ``None``."""
    for cls in metric_cls.__mro__:
        fn = _SHARDED_COMPUTES.get(cls)
        if fn is not None:
            return fn
    return None
