"""Optional-dependency probes gating extras.

Equivalent surface to the reference's ``torchmetrics/utilities/imports.py``
(``_package_available`` :25, flags :94-120). Flags cover the packages this
framework can optionally use; anything absent degrades to a clear error at
metric construction time, never at import time.
"""
import importlib.util


def _package_available(package_name: str) -> bool:
    """Check (without importing) whether a package is installed."""
    try:
        return importlib.util.find_spec(package_name) is not None
    except (ImportError, ModuleNotFoundError, ValueError):
        return False


_JAX_AVAILABLE = _package_available("jax")
_FLAX_AVAILABLE = _package_available("flax")
_ORBAX_AVAILABLE = _package_available("orbax")
_TRANSFORMERS_AVAILABLE = _package_available("transformers")
_NLTK_AVAILABLE = _package_available("nltk")
_REGEX_AVAILABLE = _package_available("regex")
_SCIPY_AVAILABLE = _package_available("scipy")
_SKLEARN_AVAILABLE = _package_available("sklearn")
_TORCH_AVAILABLE = _package_available("torch")
_PESQ_AVAILABLE = _package_available("pesq")
_PYSTOI_AVAILABLE = _package_available("pystoi")
_FAST_BSS_EVAL_AVAILABLE = _package_available("fast_bss_eval")
_TORCHVISION_AVAILABLE = _package_available("torchvision")
_PYCOCOTOOLS_AVAILABLE = _package_available("pycocotools")
