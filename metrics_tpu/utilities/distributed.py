"""Mesh-aware distributed synchronization for metric states.

TPU-native replacement for the reference's ``torchmetrics/utilities/
distributed.py`` (``gather_all_tensors`` :102, ``reduce`` :22, ``class_reduce``
:44). Instead of ``torch.distributed.all_gather`` over NCCL/gloo process
groups, synchronization lowers to XLA collectives over a ``jax.sharding.Mesh``:

* **in-jit (SPMD)**: per-state reduction specs lower to ``lax.psum`` /
  ``lax.pmin`` / ``lax.pmax`` / ``lax.all_gather`` over named mesh axes inside
  ``shard_map`` / ``pmap`` — collectives ride ICI.
* **eager multi-process (DCN)**: host-side states are exchanged with
  ``jax.experimental.multihost_utils.process_allgather``, with the reference's
  pad-to-max/trim trick (distributed.py:128-151) for uneven shapes.

The reference's ``process_group`` argument maps to a tuple of mesh axis names.
"""
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import get_config as _obs_get_config
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.obs.registry import observe as _obs_observe
from metrics_tpu.obs.registry import set_gauge as _obs_gauge

Array = jax.Array

# Reduction spec vocabulary shared with Metric.add_state's dist_reduce_fx.
_SUM_LIKE = ("sum", "mean")


def _obs_count_collective(op: str, nbytes: int) -> None:
    """Count one collective + its per-device payload bytes.

    For the in-jit SPMD helpers this fires at TRACE time (the only moment
    Python runs under jit): the counters read "collectives emitted into the
    program, with their static payload" — one increment per compiled
    program, not per execution. The eager DCN gather counts per call.
    """
    if _obs_enabled():
        _obs_inc("sync.collectives", op=op)
        _obs_inc("sync.payload_bytes", float(nbytes), op=op)


# ---------------------------------------------------------------------------
# Trace-time seam for in-jit collectives
# ---------------------------------------------------------------------------

_COLLECTIVE_SEAM: Optional[Callable[[Array, str, Any], Array]] = None


def set_collective_seam(seam: Optional[Callable[[Array, str, Any], Array]]) -> Optional[Callable]:
    """Install a trace-time hook around every in-jit sync collective.

    ``seam(x, op, axis_name) -> x`` runs at TRACE time on the operand of
    each :func:`sync_reduce_in_context` collective (``op`` is the lowered
    collective's name: ``psum``/``pmean``/``pmax``/``pmin``/``all_gather``;
    sketch states pass through leafwise). Whatever the seam returns is what
    the collective consumes, so health tooling can thread extra in-graph
    measurement through the sync point — e.g. an ``lax.pmax`` over a
    device-local timestamp-ish counter to measure in-jit arrival spread, or
    a ``jax.debug.callback`` marker — without the sync code knowing about
    it. The seam only applies while the obs layer is ENABLED, so disabled-
    mode programs stay byte-identical regardless of what is installed.

    Pass ``None`` to uninstall; returns the previously installed seam.
    """
    global _COLLECTIVE_SEAM
    previous = _COLLECTIVE_SEAM
    _COLLECTIVE_SEAM = seam
    return previous


def _apply_seam(x: Array, op: str, axis_name: Any) -> Array:
    if _COLLECTIVE_SEAM is not None and _obs_enabled():
        return _COLLECTIVE_SEAM(x, op, axis_name)
    return x


def record_arrival_skew() -> bool:
    """One tiny barrier collective at a LOGICAL sync point: the time this
    host spends blocked in it is (last peer's arrival - this host's
    arrival) + transfer — an upper bound on this host's LEAD over the
    slowest peer, measured without comparing cross-host clocks. Lands in
    the ``sync.arrival_skew_ms`` gauge (latest) and the
    ``sync.arrival_wait_ms`` histogram (distribution; a distinct family so
    gauge and histogram types never collide in one Prometheus family). A
    host that is itself the straggler reads ~0, so straggler hunting means
    comparing the gauge ACROSS hosts (high = far ahead of the fleet,
    consistently ~0 = the straggler). Returns True when a sample was
    recorded.

    Called by :meth:`metrics_tpu.Metric.sync` once per metric sync — NOT
    per state-leaf gather, where the first barrier would align the hosts
    and every later probe would overwrite the gauge with ~0. Call it
    yourself at the top of any custom sync protocol. Gated on the obs
    layer, the ``arrival_skew_probe`` config knob (default OFF) and a
    multi-process runtime, so an unconditional call site stays free when
    any of those is off. The knob defaults off because the probe is a
    COLLECTIVE: arm it — and the obs layer — IDENTICALLY on every
    process, or the barrier on the armed hosts pairs against the payload
    gather on the others and the sync hangs or corrupts in a way no retry
    policy can see.

    Best-effort: the probe is telemetry and must never take down a sync
    the retry policy could have saved — a failing barrier only counts
    under ``sync.arrival_skew_probe_failures`` (the payload gather that
    follows will surface a genuinely dead fleet through the retry path).
    """
    if not _obs_enabled() or not _obs_get_config("arrival_skew_probe"):
        return False
    try:
        if jax.process_count() == 1:
            return False
        from jax.experimental import multihost_utils

        t0 = time.perf_counter()
        multihost_utils.process_allgather(jnp.zeros((), jnp.int32))
        wait_ms = (time.perf_counter() - t0) * 1000.0
    except Exception:  # noqa: BLE001 — see docstring
        _obs_inc("sync.arrival_skew_probe_failures")
        return False
    _obs_gauge("sync.arrival_skew_ms", wait_ms)
    _obs_observe("sync.arrival_wait_ms", wait_ms)
    return True


def reduce(x: Array, reduction: str) -> Array:
    """Reduce a tensor: 'elementwise_mean' | 'sum' | 'none'.

    Parity with reference ``utilities/distributed.py:22``.
    """
    if reduction == "elementwise_mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction in ("none", None):
        return x
    raise ValueError("Reduction parameter unknown.")


def class_reduce(num: Array, denom: Array, weights: Array, class_reduction: str = "none") -> Array:
    """Class-wise score reduction: 'micro' | 'macro' | 'weighted' | 'none'.

    Parity with reference ``utilities/distributed.py:44``.
    """
    valid_reduction = ("micro", "macro", "weighted", "none", None)
    fraction = jnp.sum(num) / jnp.sum(denom) if class_reduction == "micro" else num / denom
    fraction = jnp.where(jnp.isnan(fraction), 0.0, fraction) if class_reduction != "micro" else jnp.nan_to_num(fraction)
    if class_reduction == "micro":
        return fraction
    if class_reduction == "macro":
        return jnp.mean(fraction)
    if class_reduction == "weighted":
        return jnp.sum(fraction * (weights / jnp.sum(weights)))
    if class_reduction in ("none", None):
        return fraction
    raise ValueError(f"Reduction parameter {class_reduction} unknown. Choose between one of these: {valid_reduction}")


# ---------------------------------------------------------------------------
# In-jit collectives (SPMD over mesh axes, inside shard_map / pmap)
# ---------------------------------------------------------------------------


def _axis_size(axis_name: Union[str, Tuple[str, ...]]) -> int:
    """Static mesh-axis size; ``lax.psum(1)`` on jax releases predating
    ``lax.axis_size`` (folded to a constant under SPMD, not a collective)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def sync_reduce_in_context(
    x: Array,
    reduce_fx: Union[str, Callable, None],
    axis_name: Union[str, Tuple[str, ...]],
    typed: str = "invariant",
) -> Array:
    """Apply one state's distributed reduction inside a shard_map/pmap context.

    ``sum|mean`` -> psum (mean divides by axis size), ``max`` -> pmax,
    ``min`` -> pmin, ``cat``/None/callable -> all_gather along a new leading
    device axis (the callable / None case mirrors the reference's behaviour of
    handing the gathered per-rank stack to user code, metric.py:294-304).

    ``typed`` selects the gather's output typing under shard_map's
    varying-manual-axes system (psum-family reductions are always
    invariant-typed; this only affects the cat/None/callable gather):

    * ``"invariant"`` (default): replicated-typed output that satisfies
      ``out_specs=P()`` directly — lowered as psum of a zero-padded scatter,
      which moves ``n_dev x`` payload through an all-reduce (2x an
      all-gather's bytes on an ICI ring).
    * ``"varying"``: a native ``lax.all_gather`` at 1x payload; the output is
      device-varying-typed even though every device holds identical values.
      Restore invariant typing on the (small) final value derived from it
      with :func:`replicate_typed` before returning through
      ``out_specs=P()``.
    """
    nbytes = x.size * x.dtype.itemsize if hasattr(x, "size") else 0
    _op = {"sum": "psum", "mean": "pmean", "max": "pmax", "min": "pmin"}.get(reduce_fx, "all_gather")
    # trace-time seam (set_collective_seam): health tooling can thread
    # extra in-graph measurement through every sync point
    x = _apply_seam(x, _op, axis_name)
    if reduce_fx == "sum":
        _obs_count_collective("psum", nbytes)
        return lax.psum(x, axis_name)
    if reduce_fx == "mean":
        _obs_count_collective("pmean", nbytes)
        return lax.pmean(x, axis_name)
    if reduce_fx == "max":
        _obs_count_collective("pmax", nbytes)
        return lax.pmax(x, axis_name)
    if reduce_fx == "min":
        _obs_count_collective("pmin", nbytes)
        return lax.pmin(x, axis_name)
    _obs_count_collective("all_gather", nbytes)
    gathered = _all_gather(x, axis_name, typed)  # (n_dev, ...) leading axis
    if reduce_fx == "cat":
        return gathered.reshape((-1,) + x.shape[1:]) if x.ndim >= 1 else gathered.reshape(-1)
    if callable(reduce_fx):
        return reduce_fx(gathered)
    return gathered


def _all_gather(x: Array, axis_name: Union[str, Tuple[str, ...]], typed: str) -> Array:
    """All-gather with selectable output typing (see sync_reduce_in_context)."""
    if typed == "varying":
        return lax.all_gather(x, axis_name)
    if typed != "invariant":
        raise ValueError(f"typed must be 'invariant' or 'varying', got {typed!r}")
    return _all_gather_replicated(x, axis_name)


def _all_gather_replicated(x: Array, axis_name: Union[str, Tuple[str, ...]]) -> Array:
    """All-gather whose output is replicated-typed: psum(one-hot scatter).

    This JAX version has no invariant-typed all_gather (``lax.all_gather``
    outputs stay device-varying and fail ``out_specs=P()`` inference), so the
    replicated gather is a psum of a zero-padded scatter — an all-reduce over
    ``n_dev x`` payload. Prefer ``typed="varying"`` + :func:`replicate_typed`
    on the final value for large states.
    """
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    padded = jnp.zeros((n,) + x.shape, x.dtype).at[idx].set(x)
    return lax.psum(padded, axis_name)


def replicate_typed(x: Array, axis_name: Union[str, Tuple[str, ...]]) -> Array:
    """Restore invariant (replicated) typing of a device-identical value.

    After a ``typed="varying"`` gather, every device holds identical values
    but the type system still marks them device-varying, so
    ``out_specs=P()`` rejects them. ``pmax`` over identical replicas is the
    cheapest identity collective that re-types: exact for ints and floats
    (no division), NaN-propagating, and only the FINAL value (typically a
    scalar or small vector) pays it — not the gathered buffer.
    """
    if hasattr(x, "dtype") and x.dtype == jnp.bool_:
        return lax.pmax(x.astype(jnp.uint8), axis_name).astype(jnp.bool_)
    return lax.pmax(x, axis_name)


def reduce_scatter_in_context(
    x: Array, axis_name: Union[str, Tuple[str, ...]], dim: int = 0
) -> Array:
    """Sum-reduce ``x`` over the axis AND shard the result along ``dim``.

    ``lax.psum_scatter(tiled=True)``: device ``i`` ends holding slice ``i``
    of the axis-sum — the sharded-state alternative to ``psum``, moving 1x
    payload on an ICI ring (an all-reduce moves ~2x) and leaving each
    device with ``1/n`` of the state resident instead of a full replica.
    ``x.shape[dim]`` must divide evenly by the axis size (pad the operand
    first otherwise — see ``utilities.sharding.shard_sketch_in_context``).

    The output is device-varying by construction (each device holds a
    DIFFERENT slice); consume it with the sharded compute kernels in
    :mod:`metrics_tpu.utilities.sharding`, or restore a full replica with
    an ``all_gather`` (at which point plain ``psum`` was cheaper).
    """
    nbytes = x.size * x.dtype.itemsize if hasattr(x, "size") else 0
    x = _apply_seam(x, "psum_scatter", axis_name)
    _obs_count_collective("psum_scatter", nbytes)
    return lax.psum_scatter(x, axis_name, scatter_dimension=dim, tiled=True)


def hierarchical_reduce_in_context(
    x: Array,
    reduce_fx: Union[str, Callable, None],
    axis_names: Sequence[str],
    typed: str = "invariant",
) -> Array:
    """Topology-ordered reduction: one collective per mesh axis, in order.

    A flat ``psum(x, ("ici", "dcn"))`` leaves the reduction schedule to the
    compiler; this chain makes the topology explicit — reduce over
    ``axis_names[0]`` FIRST (pass the ICI/intra-slice axis there, so the
    fast fabric combines first and the slow DCN hop moves one
    already-reduced operand), then each following axis in order. For
    ``sum``/``max``/``min`` the chain is exactly the flat reduction (the
    monoid is associative); ``mean`` is exact on rectangular meshes (every
    sub-group the same size — true for named mesh axes by construction).

    Each hop runs through :func:`sync_reduce_in_context`, so the
    ``set_collective_seam`` hook and the ``sync.collectives`` /
    ``sync.payload_bytes`` counters observe every per-axis collective in
    issue order — the MULTICHIP harness measures the ICI-vs-DCN split
    directly. Gather-typed reductions (``cat``/None/callable) do not chain
    (concatenation order would depend on the axis split); they fall back
    to one flat gather over all the axes.
    """
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    if reduce_fx not in _SUM_LIKE and reduce_fx not in ("max", "min"):
        # gather-typed: device order of the concatenation must match the
        # flat gather's — one collective over the full axis set
        return sync_reduce_in_context(x, reduce_fx, tuple(axis_names), typed=typed)
    for axis in axis_names:
        x = sync_reduce_in_context(x, reduce_fx, axis, typed=typed)
    return x


def ring_allreduce(x: Array, axis_name: str, op: Callable[[Array, Array], Array] = jnp.add) -> Array:
    """Manual ring all-reduce via ``lax.ppermute`` (ring-attention pattern).

    Each device folds its neighbours' contributions in ``n - 1`` rotation
    steps around the ring — the communication schedule of ring attention /
    pipeline-stage state merges, exposed as a library facility so mesh
    programs can fold states along an axis without a global ``psum`` (useful
    when the axis rides a physical ring, when overlapping per-hop compute,
    or with a non-additive fold ``op``).

    The result is bitwise identical on every device but typed device-varying
    (``ppermute`` outputs vary by construction); pass it through
    :func:`replicate_typed` (or any psum-family identity) before a
    ``shard_map`` ``out_specs=P()`` boundary.

    Args:
        x: the local contribution on each device.
        axis_name: mesh axis to ring-reduce over (a single named axis).
        op: associative+commutative binary fold, default ``jnp.add``.
            (Commutativity matters: hop ``k`` folds neighbour ``(i - k) %% n``,
            so contributions arrive in a different order on each device.)
    """
    n = _axis_size(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(_, carry):
        acc, buf = carry
        buf = lax.ppermute(buf, axis_name, perm)
        return op(acc, buf), buf

    acc, _ = lax.fori_loop(0, n - 1, body, (x, x))
    return acc


def sync_sketch_in_context(
    sketch: Any,
    axis_name: Union[str, Tuple[str, ...]],
    typed: str = "invariant",
    hierarchical: bool = False,
) -> Any:
    """Merge per-device sketch summaries inside shard_map/pmap.

    The in-jit arm of the ``dist_reduce_fx="sketch"`` registry entry: every
    leaf of a :class:`metrics_tpu.streaming.sketches.Sketch` declares its
    own reduction (``sum``/``min``/``max``), so the mesh merge is leafwise
    :func:`sync_reduce_in_context` — count vectors psum, extremes
    pmin/pmax. Because the sketch merge is that exact monoid, the result
    equals folding every device's sketch with ``merge`` in any order, and
    the payload is the fixed sketch size (a few KB) — never a gather of
    samples. psum-family collectives are invariant-typed on every path, so
    ``typed`` only matters if a future sketch declares a gather-typed leaf.
    ``hierarchical=True`` with a multi-axis ``axis_name`` reduces each leaf
    one axis at a time in the given order (ICI-first — see
    :func:`hierarchical_reduce_in_context`); the merged state is identical
    by the monoid's associativity.
    """
    reduce_one = (
        (lambda leaf, red: hierarchical_reduce_in_context(leaf, red, axis_name, typed=typed))
        if hierarchical
        else (lambda leaf, red: sync_reduce_in_context(leaf, red, axis_name, typed=typed))
    )
    reduced = {name: reduce_one(getattr(sketch, name), red) for name, red in sketch._leaf_fields}
    return sketch._replace_leaves(**reduced)


def sync_buffer_in_context(buf: Any, axis_name: Union[str, Tuple[str, ...]], typed: str = "invariant") -> Any:
    """Merge per-device :class:`CapacityBuffer` sample states inside shard_map.

    The in-graph analogue of the reference's uneven cat-state gather
    (``torchmetrics/utilities/distributed.py:128-151``): all-gather each
    device's ``(capacity, *item)`` buffer plus its fill count, then
    concatenate the filled prefixes into one merged buffer of capacity
    ``n_devices * capacity``.

    Two regimes:

    * **static counts** (the fill count's trace-time host mirror survived —
      true whenever ``init``/``step``/``compute`` run in ONE traced program
      with unrolled steps, since SPMD gives every device the same static
      count): the filled prefixes are sliced and reshaped directly; the
      merged buffer keeps a static count, so any downstream ``compute``
      (exact AUROC sort, retrieval segmentation) runs unmodified.
    * **traced counts** (state crossed a ``lax.scan`` carry or jit boundary):
      a masked scatter-concat — slot ``j`` of device ``d`` lands at
      ``cumsum(counts)[d-1] + j`` when ``j < counts[d]``, out-of-bounds
      (dropped) otherwise. The merged count is traced; consumers either need
      a mask-aware compute or must restore the known total via
      ``CapacityBuffer.declare_count``.

    ``typed`` selects the gather typing exactly as in
    :func:`sync_reduce_in_context`: ``"varying"`` moves 1x payload via
    ``lax.all_gather`` (restore invariance on the final computed value with
    :func:`replicate_typed`); ``"invariant"`` (default) pays the
    ``n_dev x`` psum-of-scatter but satisfies ``out_specs=P()`` directly.

    .. warning::
        If a device's buffer OVERFLOWED under traced counts (appends past
        ``capacity`` inside a scan), its tail rows were overwritten in place
        by the clamped ``dynamic_update_slice`` writes — the merged buffer's
        count is clamped to honest totals, but the surviving rows from that
        device may be CORRUPTED samples (later appends overwrote earlier
        rows), not merely a truncated prefix. The merged buffer carries
        per-device flags in ``merged.overflowed`` (bool ``(n_devices,)``,
        in-graph, free to read) so production code can detect this without
        ``debug_checks``; checkify under ``debug_checks`` still hard-fails
        at the append site, and sizing ``capacity`` for the worst case
        remains the real fix.
    """
    from metrics_tpu.utilities.buffers import CapacityBuffer

    n = _axis_size(axis_name)
    cap = buf.capacity
    merged = CapacityBuffer(n * cap, buf.dtype)
    if buf.data is None:  # SPMD symmetry: no device appended anything
        return merged
    item_shape = buf.data.shape[1:]
    _obs_count_collective("buffer_gather", buf.data.size * buf.data.dtype.itemsize)
    if buf._host_count is not None:
        # static count: gather only the filled prefix — the collective moves
        # n*c rows, not n*capacity
        c = buf._host_count
        filled = _all_gather(buf.data[:c], axis_name, typed).reshape((n * c,) + item_shape)
        merged.data = jnp.zeros((n * cap,) + item_shape, buf.data.dtype).at[: n * c].set(filled)
        merged.count = jnp.asarray(n * c, jnp.int32)
        merged._host_count = n * c
        return merged
    data = _all_gather(buf.data, axis_name, typed)  # (n, cap, *item)
    counts = _all_gather(buf.count, axis_name, typed)  # (n,)
    # a traced overflow (append past capacity inside a scan) leaves count >
    # capacity while the data writes were clamped; clamp here too so the
    # merge stays dense (no phantom zero rows) and the total stays honest —
    # and surface WHICH devices overflowed so production code can react
    # without arming debug_checks (see CapacityBuffer.overflowed)
    overflow = counts > cap
    counts = jnp.minimum(counts, cap)
    # dense concat as n contiguous whole-buffer writes at dynamic offsets,
    # ascending: device d's stale tail [offset_d + count_d, offset_d + cap)
    # is exactly covered by device d+1's write (offset_{d+1} = offset_d +
    # count_d, same cap-row extent), and no later write reaches an earlier
    # device's real rows — so only the LAST device's tail needs masking to
    # zeros before its write. Contiguous dynamic_update_slice lowers near
    # memcpy speed, unlike the masked scatter (39.6ms) or row gather (202ms)
    # it replaces — measured 1M x 8dev: ~12ms, ~1.2x the static-count path.
    offsets = (jnp.cumsum(counts) - counts).astype(jnp.int32)
    zero = jnp.asarray(0, jnp.int32)
    slot = jnp.arange(cap, dtype=jnp.int32).reshape((cap,) + (1,) * len(item_shape))
    out = jnp.zeros((n * cap,) + item_shape, buf.data.dtype)
    if n <= 16:  # unrolled: only the final device's tail needs the mask
        for d in range(n):
            rows = data[d]
            if d == n - 1:
                rows = jnp.where(slot < counts[d], rows, jnp.zeros((), buf.data.dtype))
            out = lax.dynamic_update_slice(out, rows, (offsets[d],) + (zero,) * len(item_shape))
    else:  # pod-scale axes: rolled loop, program size O(1) in n; masking
        # every device's tail (not just the last) keeps the body uniform
        if typed == "varying":
            # the loop carry must already hold the body output's
            # device-varying type (fori_loop requires equal carry types)
            out = lax.pvary(out, axis_name)

        def body(d, acc):
            rows = lax.dynamic_index_in_dim(data, d, keepdims=False)
            rows = jnp.where(slot < counts[d], rows, jnp.zeros((), buf.data.dtype))
            return lax.dynamic_update_slice(acc, rows, (offsets[d],) + (zero,) * len(item_shape))

        out = lax.fori_loop(0, n, body, out)
    merged.data = out
    merged.count = counts.sum().astype(jnp.int32)
    merged._host_count = None
    merged.overflowed = overflow
    return merged


# ---------------------------------------------------------------------------
# Eager cross-process gather (DCN / multi-host, host-side states)
# ---------------------------------------------------------------------------

# One eager DCN collective above this payload is split into dim-0 chunks:
# a monolithic multi-hundred-MB process_allgather holds the host network's
# buffers (and any retry policy's timeout budget) hostage to its slowest
# fragment, while chunked gathers bound each collective, keep peak host
# staging memory at chunk size x world, and give the retry watchdog a
# meaningful per-collective unit. Chunk boundaries derive from the
# (already-gathered) agreed shapes, so every process issues the same
# collective sequence.
_GATHER_CHUNK_BYTES: Optional[int] = 64 * 1024 * 1024


def configure_gather_chunking(max_bytes: Optional[int] = 64 * 1024 * 1024) -> Optional[int]:
    """Set the eager DCN gather's per-collective payload cap (bytes).

    Payloads above the cap are gathered as multiple dim-0 chunks (counted
    under ``sync.gather_chunks``; per-chunk bytes under
    ``sync.payload_bytes{op=process_allgather_chunk}``). Pass ``None`` to
    disable chunking (the monolithic pre-round-15 behaviour). Returns the
    previous cap. Must be set identically on every process — the chunk
    schedule is part of the collective sequence.
    """
    global _GATHER_CHUNK_BYTES
    if max_bytes is not None and (not isinstance(max_bytes, int) or max_bytes <= 0):
        raise ValueError(f"max_bytes must be a positive int or None, got {max_bytes!r}")
    previous = _GATHER_CHUNK_BYTES
    _GATHER_CHUNK_BYTES = max_bytes
    return previous


def _process_allgather_chunked(x: Array) -> Array:
    """``multihost_utils.process_allgather`` with the >cap payload split
    into dim-0 chunks (see :func:`configure_gather_chunking`).

    The chunk count is a pure function of the operand's shape/dtype and the
    cap — both identical on every process by the time this runs (equal
    shapes, or the pad-to-max path already agreed on ``max_size``) — so all
    processes issue matching collectives. Returns the stacked ``(P, *shape)``
    result either way.
    """
    from jax.experimental import multihost_utils

    limit = _GATHER_CHUNK_BYTES
    nbytes = x.size * x.dtype.itemsize
    if limit is None or nbytes <= limit or x.ndim == 0 or x.shape[0] <= 1:
        return multihost_utils.process_allgather(x)
    n_chunks = min(x.shape[0], -(-nbytes // limit))  # ceil-div, capped by rows
    bounds = [round(i * x.shape[0] / n_chunks) for i in range(n_chunks + 1)]
    if _obs_enabled():
        _obs_inc("sync.gather_chunks", float(n_chunks))
    parts = []
    for lo, hi in zip(bounds, bounds[1:]):
        chunk = x[lo:hi]
        if _obs_enabled():
            _obs_inc(
                "sync.payload_bytes",
                float(chunk.size * chunk.dtype.itemsize),
                op="process_allgather_chunk",
            )
        parts.append(multihost_utils.process_allgather(chunk))
    return jnp.concatenate(parts, axis=1)  # parts are (P, chunk_rows, ...)


def gather_all_tensors(result: Array, group: Optional[Any] = None) -> List[Array]:
    """All-gather an array across JAX processes, handling uneven dim-0 shapes.

    Parity with reference ``utilities/distributed.py:102-151``: gathers local
    shapes first, pads dim 0 to the max, gathers, then trims. Returns a list
    with one entry per process (single-process: ``[result]``). ``group`` is
    accepted for API parity and ignored (mesh axes handle grouping in-jit).

    Failure handling (the reference has none — one ``all_gather``, hang or
    raise): the whole gather runs under the :mod:`metrics_tpu.ft.retry`
    policy. Transient failures are retried with backoff (``ft.retries``
    counter); exhausting the policy degrades to the local per-host partial
    ``[result]`` with a one-shot warning and an ``ft.degraded_syncs`` bump,
    so a flaky peer degrades this host's metric values instead of hanging
    the fleet. Set ``configure_retries(degraded_fallback=False)`` to make
    exhaustion raise :class:`~metrics_tpu.ft.retry.DegradedSyncError`
    instead.

    Retries are per-host best-effort, not fleet-coordinated: a retried
    gather only succeeds if the peers reach their matching collective
    (give every process the same policy), a timed-out attempt is NOT
    retried (the abandoned call could mis-pair with a fresh one — it
    degrades immediately), and without ``timeout_s`` a hard-hung peer is
    not detected. Once any attempt in this process has failed or timed
    out, every gather is additionally **self-echo fenced**: the gathered
    slot for this process must equal its local contribution bitwise, so a
    retried collective that mis-paired with a neighbouring collective (a
    failed attempt can have partially executed on peers) is detected and
    treated as a failure rather than returned as silently misaligned
    "global" state; healthy processes never pay the fence. The degraded
    fallback bounds the damage.
    """
    if jax.process_count() == 1:
        return [result]
    from metrics_tpu.ft.retry import active_scope_degraded, call_with_retries

    if active_scope_degraded():
        # an earlier gather of this sync already degraded and the enclosing
        # scope will discard this result in favour of local state — skip
        # the doomed retry/backoff cycle entirely
        return [result]
    health_armed = _obs_enabled()
    t0 = time.perf_counter()
    out = call_with_retries(
        lambda: _checked_gather_all_tensors(result),
        op="gather_all_tensors",
        # degraded mode: this host's own shard only — the per-host partial
        # result shape every consumer already handles (single-process case)
        fallback=lambda _err: [result],
    )
    if health_armed:
        # end-to-end logical gather latency (retries + backoff included:
        # that IS what the training loop paid) into the p50/p95/p99-able
        # histogram the HealthMonitor's sync_latency condition reads
        _obs_observe("sync.latency_ms", (time.perf_counter() - t0) * 1000.0, op="gather_all_tensors")
    return out


def _checked_gather_all_tensors(result: Array) -> List[Array]:
    """One gather attempt plus the self-echo fence (see gather_all_tensors).

    The fence arms only after some retry attempt in this process has
    failed or timed out — before that no ghost collective can exist, so
    healthy fleets skip the per-gather payload compare + host sync."""
    from metrics_tpu.ft.retry import collective_fence_armed

    out = _gather_all_tensors_impl(result)
    if collective_fence_armed():
        own = out[jax.process_index()]
        equal_nan = bool(jnp.issubdtype(jnp.asarray(result).dtype, jnp.inexact))
        if tuple(own.shape) != tuple(result.shape) or not bool(
            jnp.array_equal(own, result, equal_nan=equal_nan)
        ):
            raise RuntimeError(
                "gather_all_tensors self-echo mismatch: the gathered slot for this"
                " process does not match its local contribution — a retried"
                " collective likely mis-paired with a neighbouring collective."
                " Treating the attempt as failed."
            )
    # count one LOGICAL gather, after the fence accepts it: failed or
    # fence-rejected attempts must not inflate the traffic counters the
    # incident analysis correlates with ft.degraded_syncs
    if _obs_enabled():
        _obs_inc("sync.gathers")
        _obs_inc("sync.payload_bytes", float(result.size * result.dtype.itemsize), op="process_allgather")
    return out


def _gather_all_tensors_impl(result: Array) -> List[Array]:
    from jax.experimental import multihost_utils

    local_size = jnp.asarray(result.shape, dtype=jnp.int32)
    all_sizes = multihost_utils.process_allgather(local_size)  # (P, ndim)
    max_size = tuple(int(s) for s in all_sizes.max(axis=0))
    all_equal = bool((all_sizes == all_sizes[0]).all())
    if all_equal:
        gathered = _process_allgather_chunked(result)
        return [gathered[i] for i in range(gathered.shape[0])]
    pad_width = [(0, m - s) for m, s in zip(max_size, result.shape)]
    padded = jnp.pad(result, pad_width)
    gathered = _process_allgather_chunked(padded)
    out = []
    for i in range(gathered.shape[0]):
        slices = tuple(slice(0, int(d)) for d in all_sizes[i])
        out.append(gathered[i][slices])
    return out


def distributed_available() -> bool:
    """True when more than one JAX process participates (DCN case)."""
    try:
        return jax.process_count() > 1
    except Exception:
        return False
