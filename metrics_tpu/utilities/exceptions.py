"""User-facing exception types.

Equivalent surface to the reference's ``torchmetrics/utilities/exceptions.py``.
"""


class MetricsTPUUserError(Exception):
    """Error raised on misuse of the metrics API (lifecycle violations etc.)."""


# Alias kept so code reading like the reference's name still works.
TorchMetricsUserError = MetricsTPUUserError
