"""Fixed-capacity HBM sample buffers for ``cat`` states.

The reference's unbounded-memory answer to cat-list states is host offload
(``compute_on_cpu``, reference ``metric.py:313-323``). The TPU-native
answer (SURVEY.md §7 hard part 1): a **pre-allocated device buffer plus a
fill counter**, so streamed samples stay HBM-resident with a static shape —
the state pytree never changes structure, `jit`-compiled accumulation
doesn't retrace, and the distributed gather sees one contiguous array.

:class:`CapacityBuffer` is list-API-compatible (mutating ``append``, same
``dim_zero_cat`` consumption), so curve metrics switch between unbounded
Python lists and bounded device buffers with a single ``sample_capacity``
constructor argument. The item shape is discovered on first append, since
metrics like AUROC only learn the class count from data.
"""
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

__all__ = ["CapacityBuffer", "_cat_state_default"]


def _cat_state_default(sample_capacity: Optional[int]):
    """Default for a ``cat`` state: unbounded Python list, or an HBM-resident
    fixed-capacity buffer when ``sample_capacity`` is given."""
    return [] if sample_capacity is None else CapacityBuffer(sample_capacity)


@jax.tree_util.register_pytree_node_class
class CapacityBuffer:
    """A ``(capacity, *item)`` device array with a fill counter.

    ``append`` writes at the current count via ``lax.dynamic_update_slice``
    (jit-safe, static shapes). The fill count is mirrored as a plain Python
    int on the eager path, so appends never block on a device round-trip;
    eager overflow raises (naming capacity, current count and the offending
    append length). Inside a trace the mirror is unavailable and the
    caller owns the capacity contract — ``dynamic_update_slice`` clamps the
    start index, so excess samples silently overwrite the buffer tail
    (a linear buffer, not ring wraparound).

    A capacity buffer still keeps *samples* — memory is O(capacity) and a
    stream longer than the capacity cannot fit. For always-on monitoring
    over unbounded streams, the bounded-memory alternative is a mergeable
    sketch state (:mod:`metrics_tpu.streaming.sketches`): a few KB of
    summary regardless of stream length, with a documented error bound vs
    this exact-sample path (``docs/streaming.md``).

    Sharding: buffer ROWS (``SHARD_DIM`` = the sample axis) distribute
    over a mesh — ``Metric.add_state`` derives a dim-0
    :class:`~metrics_tpu.utilities.sharding.StateShardSpec` for every
    buffer state automatically, so ``state_shardings()`` keeps the rows
    mesh-resident under pjit and ``make_step(sharded_state=True)``
    computes over the resident shards with a ring pass instead of the
    materialized ``sync_buffer_in_context`` gather
    (:func:`metrics_tpu.utilities.sharding.sharded_sample_auroc`).
    """

    #: the dimension that distributes over a mesh axis (samples/rows)
    SHARD_DIM = 0

    def __init__(self, capacity: int, dtype: Any = None) -> None:
        if capacity <= 0:
            raise ValueError(f"`capacity` must be positive, got {capacity}")
        self.capacity = int(capacity)
        self.dtype = dtype
        self.data: Optional[Array] = None  # allocated on first append
        self.count: Array = jnp.asarray(0, dtype=jnp.int32)
        self._host_count: Optional[int] = 0  # None when count came from a trace
        # set by sync_buffer_in_context on the MERGED buffer: per-device bool
        # flags, True where that device appended past capacity under traced
        # counts (its surviving rows may be overwritten samples). None when
        # not a mesh-merge product / overflow is statically impossible.
        self.overflowed: Optional[Array] = None

    # -- list-compatible mutating API -----------------------------------

    def append(self, batch: Array) -> None:
        batch = jnp.atleast_1d(jnp.asarray(batch))
        if self.dtype is not None:
            batch = batch.astype(self.dtype)
        if self.data is None:
            self.data = jnp.zeros((self.capacity,) + batch.shape[1:], dtype=batch.dtype)
        n = batch.shape[0]
        if self._host_count is not None:
            if self._host_count + n > self.capacity:
                from metrics_tpu.obs.registry import enabled as _obs_enabled
                from metrics_tpu.obs.registry import inc as _obs_inc

                if _obs_enabled():
                    _obs_inc("capacity_buffer.eager_overflows")
                raise ValueError(
                    f"CapacityBuffer overflow: appending {n} sample(s) to a buffer already"
                    f" holding {self._host_count} of capacity {self.capacity} would exceed it"
                    f" by {self._host_count + n - self.capacity}. Raise `sample_capacity`,"
                    " switch to unbounded list states, or — for endless streams — use a"
                    " bounded-memory sketch metric (metrics_tpu.streaming: StreamingAUROC/"
                    "StreamingAveragePrecision/StreamingQuantile keep a fixed-size mergeable"
                    " summary instead of samples)."
                )
            self._host_count += n
        else:
            # post-boundary traced count: overflow silently clamps to the
            # tail. debug_checks arms a checkify guard for exactly this
            # (SURVEY §7 hard part 4) — surfaced by checkify.checkify(step).
            # The obs layer counts every such clamp-RISK site (overflow is
            # data-dependent and unknowable at trace time; the counter says
            # how many appends ran without the host-count guard).
            from metrics_tpu.obs.registry import enabled as _obs_enabled
            from metrics_tpu.obs.registry import inc as _obs_inc
            from metrics_tpu.utilities.debug import debug_checks_enabled

            if _obs_enabled():
                _obs_inc("capacity_buffer.clamp_risk_appends")
            if debug_checks_enabled():
                if _obs_enabled():
                    _obs_inc("capacity_buffer.checkify_guards_armed")
                from jax.experimental import checkify

                checkify.check(
                    self.count + n <= self.capacity,
                    "CapacityBuffer overflow under trace: count {c} + "
                    f"{n} > capacity {self.capacity} (excess samples would overwrite the buffer tail)",
                    c=self.count,
                )
        start = (self.count,) + (jnp.asarray(0, jnp.int32),) * (batch.ndim - 1)
        self.data = jax.lax.dynamic_update_slice(self.data, batch, start)
        self.count = self.count + n

    def _concrete_count(self) -> int:
        if self._host_count is None:
            if isinstance(self.count, jax.core.Tracer):
                raise ValueError(
                    "CapacityBuffer fill count is a tracer (the state crossed a lax.scan carry or jit"
                    " boundary), so the filled prefix has no static shape. Either keep init/step/compute"
                    " in one traced program with unrolled steps, or restore the known total with"
                    " `buffer.declare_count(n)` after the scan."
                )
            self._host_count = int(self.count)  # one sync, then cached
        return self._host_count

    def declare_count(self, n: int) -> "CapacityBuffer":
        """Assert the fill count after it was lost to a scan/jit boundary.

        A ``lax.scan`` carry re-enters as tracers, dropping the trace-time
        host mirror even though the caller usually knows the exact fill of
        THIS buffer (``n_batches * batch_size``; under ``shard_map`` that is
        the PER-DEVICE count — the per-shard batch size times steps — since
        the mesh sync multiplies by the axis size when merging). Declaring
        it restores the static filled-prefix shape so ``materialize`` (and
        any downstream exact compute) works inside the same traced program.
        The caller owns the assertion's correctness.
        """
        n = int(n)
        if not 0 <= n <= self.capacity:
            raise ValueError(f"declared count {n} outside [0, capacity={self.capacity}]")
        self._host_count = n
        if not isinstance(self.count, jax.core.Tracer):
            self.count = jnp.asarray(n, dtype=jnp.int32)
        return self

    @property
    def overflow(self) -> Array:
        """Traced bool: whether appends ran past ``capacity`` on THIS device.

        Under traced counts the clamped ``dynamic_update_slice`` writes keep
        incrementing ``count`` past capacity, so ``count > capacity`` is an
        exact overflow indicator that costs nothing to read in-graph — the
        production-path alternative to the ``debug_checks`` checkify guard.
        """
        return self.count > self.capacity

    def materialize(self) -> Array:
        """The filled prefix ``data[:count]`` (eager; count must be concrete)."""
        if self.data is None:
            raise ValueError("No samples to concatenate")
        return self.data[: self._concrete_count()]

    def __len__(self) -> int:
        return self._concrete_count()

    def __bool__(self) -> bool:
        return self._concrete_count() > 0

    def copy_empty(self) -> "CapacityBuffer":
        return CapacityBuffer(self.capacity, self.dtype)

    def __deepcopy__(self, memo: dict) -> "CapacityBuffer":
        new = CapacityBuffer(self.capacity, self.dtype)
        new.data = self.data  # jnp arrays are immutable
        new.count = self.count
        new._host_count = self._host_count
        new.overflowed = self.overflowed
        return new

    def __repr__(self) -> str:
        shape = None if self.data is None else tuple(self.data.shape)
        return f"CapacityBuffer(capacity={self.capacity}, count={self.count}, data_shape={shape})"

    # -- pytree protocol -------------------------------------------------

    def tree_flatten(self) -> Tuple[tuple, tuple]:
        children = (self.count,) + (() if self.data is None else (self.data,))
        if self.overflowed is not None:
            children = children + (self.overflowed,)
        return children, (self.capacity, self.dtype, self.data is not None, self.overflowed is not None)

    @classmethod
    def tree_unflatten(cls, aux: tuple, children: tuple) -> "CapacityBuffer":
        capacity, dtype, allocated, has_overflow = aux
        new = cls.__new__(cls)
        new.capacity = capacity
        new.dtype = dtype
        new.count = children[0]
        new.data = children[1] if allocated else None
        new.overflowed = children[-1] if has_overflow else None
        # Only adopt a host mirror from leaves that are free to read: a plain
        # Python/numpy int. int() on a tracer raises, on a ShapeDtypeStruct
        # (eval_shape / orbax restore targets) is a TypeError, and on a live
        # device array it BLOCKS until the dispatch finishes — which would
        # kill async dispatch on every jitted-step output. Those recover
        # lazily through _concrete_count() when first needed.
        if isinstance(new.count, int) or type(new.count).__module__ == "numpy":
            new._host_count = int(new.count)
        else:
            new._host_count = None
        return new
