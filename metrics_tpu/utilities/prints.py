"""Rank-zero-gated logging helpers.

Equivalent surface to the reference's ``torchmetrics/utilities/prints.py``,
with rank resolution via ``jax.process_index()`` (falling back to the
``LOCAL_RANK`` env var when JAX distributed is not initialised).
"""
import logging
import os
import warnings
from functools import partial, wraps
from typing import Any, Callable

_logger = logging.getLogger("metrics_tpu")


def _get_rank() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:
        return int(os.environ.get("LOCAL_RANK", 0))


def rank_zero_only(fn: Callable) -> Callable:
    """Run ``fn`` only on process 0."""

    @wraps(fn)
    def wrapped(*args: Any, **kwargs: Any) -> Any:
        if _get_rank() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped


@rank_zero_only
def rank_zero_warn(message: str, *args: Any, stacklevel: int = 3, **kwargs: Any) -> None:
    warnings.warn(message, *args, stacklevel=stacklevel, **kwargs)


rank_zero_info = rank_zero_only(partial(_logger.info))
rank_zero_debug = rank_zero_only(partial(_logger.debug))
