"""Rank-zero-gated logging helpers.

Equivalent surface to the reference's ``torchmetrics/utilities/prints.py``,
with rank resolution via ``jax.process_index()`` (falling back to the
``LOCAL_RANK`` env var when JAX distributed is not initialised).
"""
import logging
import os
import warnings
from functools import partial, wraps
from typing import Any, Callable

_logger = logging.getLogger("metrics_tpu")


def _jax_distributed_initialized() -> bool:
    """True when ``jax.distributed.initialize`` has run (DCN world exists)."""
    try:
        import jax

        if hasattr(jax.distributed, "is_initialized"):  # jax >= 0.4.34
            return bool(jax.distributed.is_initialized())
        from jax._src import distributed

        return getattr(distributed.global_state, "client", None) is not None
    except Exception:
        return False


def _backend_already_initialized() -> bool:
    """True when an XLA backend is ALREADY live — without creating one.

    ``jax.process_index()`` initializes the backend as a side effect, which
    an early log line must never trigger (it would pin the platform before
    user code gets to configure it, e.g. conftest's 8-virtual-device mesh).
    """
    try:
        from jax._src import xla_bridge

        if hasattr(xla_bridge, "backends_are_initialized"):
            return bool(xla_bridge.backends_are_initialized())
        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return False


def _get_rank() -> int:
    # only consult jax when doing so cannot initialize the backend as a side
    # effect: either the distributed runtime is up (process_index is then
    # authoritative) or a backend already exists. Otherwise fall back to the
    # launcher-provided env var.
    try:
        if _jax_distributed_initialized() or _backend_already_initialized():
            import jax

            return jax.process_index()
    except Exception:
        pass
    return int(os.environ.get("LOCAL_RANK", 0))


def rank_zero_only(fn: Callable) -> Callable:
    """Run ``fn`` only on process 0."""

    @wraps(fn)
    def wrapped(*args: Any, **kwargs: Any) -> Any:
        if _get_rank() == 0:
            return fn(*args, **kwargs)
        return None

    return wrapped


@rank_zero_only
def rank_zero_warn(message: str, *args: Any, stacklevel: int = 3, **kwargs: Any) -> None:
    warnings.warn(message, *args, stacklevel=stacklevel, **kwargs)


rank_zero_info = rank_zero_only(partial(_logger.info))
rank_zero_debug = rank_zero_only(partial(_logger.debug))
