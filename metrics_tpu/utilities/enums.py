"""Case-insensitive string enums for metric options.

Equivalent surface to the reference's ``torchmetrics/utilities/enums.py``
(``DataType``/``AverageMethod``/``MDMCAverageMethod``).
"""
from enum import Enum
from typing import Optional


class EnumStr(str, Enum):
    """String enum with case-insensitive ``from_str`` lookup."""

    @classmethod
    def from_str(cls, value: str) -> Optional["EnumStr"]:
        try:
            return cls[value.replace("-", "_").upper()]
        except KeyError:
            return None

    def __eq__(self, other: object) -> bool:
        if isinstance(other, str):
            return self.value.lower() == other.lower()
        return NotImplemented

    def __hash__(self) -> int:
        return hash(self.value.lower())


class DataType(EnumStr):
    """Classification input-case taxonomy."""

    BINARY = "binary"
    MULTILABEL = "multi-label"
    MULTICLASS = "multi-class"
    MULTIDIM_MULTICLASS = "multi-dim multi-class"


class AverageMethod(EnumStr):
    """Class-reduction method."""

    MICRO = "micro"
    MACRO = "macro"
    WEIGHTED = "weighted"
    NONE = "none"
    SAMPLES = "samples"


class MDMCAverageMethod(EnumStr):
    """Multi-dim multi-class reduction method."""

    GLOBAL = "global"
    SAMPLEWISE = "samplewise"
