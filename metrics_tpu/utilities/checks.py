"""Input normalization gate for classification & retrieval metrics.

Behavioral equivalent of the reference's ``torchmetrics/utilities/checks.py``
(`_input_format_classification` :310-449, `_check_classification_inputs` :203,
retrieval checks :501-606), re-designed for the XLA compilation model:

* **Case resolution is trace-time static.** The input case (binary /
  multi-class / multi-label / multi-dim multi-class) is decided from shapes
  and dtypes, which are static under jit. The only value-dependent decision in
  the reference — inferring ``num_classes`` from ``max(label)`` — is done
  eagerly (host peek) and should be avoided under jit by passing
  ``num_classes`` explicitly.
* **Value validation is eager-only.** Range checks (targets non-negative,
  probabilities in [0,1], binary targets) pull scalars to host; they run in
  the eager class API and are skipped inside jit (guard with
  ``validate_args=False``).

The normalized output contract matches the reference: binary int tensors of
shape ``(N, C)`` or ``(N, C, X)`` plus the resolved ``DataType`` case.
"""
import threading
from contextlib import contextmanager
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.utilities.data import select_topk, to_onehot
from metrics_tpu.utilities.enums import DataType

Array = jax.Array

# ---------------------------------------------------------------------------
# Shared input-format memo (collection fusion)
# ---------------------------------------------------------------------------

_FORMAT_SCOPE = threading.local()


@contextmanager
def shared_input_format_scope():
    """Memoize :func:`_input_format_classification` for the enclosed block.

    A ``MetricCollection`` hands the SAME ``preds``/``target`` objects to
    every member, and each member's ``update`` re-runs the whole input
    normalization/format-check pass. Inside this scope the pass is keyed by
    the input identities plus every normalization parameter, so N members
    sharing one parameterization pay for it once — under a trace this also
    guarantees ONE normalization subgraph per parameterization by
    construction, instead of relying on XLA CSE to merge N copies.

    Yields a stats dict (``{"hits": int, "misses": int}``) so callers and
    tests can assert the reuse. Reentrant: a nested scope shares the outer
    cache (and the outer scope's stats keep counting). Outputs are consumed
    read-only by every caller, which is what makes sharing them safe.
    """
    cache = getattr(_FORMAT_SCOPE, "cache", None)
    created = cache is None
    if created:
        cache = _FORMAT_SCOPE.cache = {}
        stats = _FORMAT_SCOPE.stats = {"hits": 0, "misses": 0}
    else:
        stats = _FORMAT_SCOPE.stats
    try:
        yield stats
    finally:
        if created:
            _FORMAT_SCOPE.cache = None
            _FORMAT_SCOPE.stats = None


def _format_cache_lookup(key):
    cache = getattr(_FORMAT_SCOPE, "cache", None)
    if cache is None:
        return None, None
    hit = cache.get(key)
    if hit is not None:
        _FORMAT_SCOPE.stats["hits"] += 1
        from metrics_tpu.obs.registry import enabled as _obs_enabled
        from metrics_tpu.obs.registry import inc as _obs_inc

        if _obs_enabled():
            _obs_inc("collection.format_reuse")
    return cache, hit


def _is_floating(x: Array) -> bool:
    return jnp.issubdtype(x.dtype, jnp.floating)


def _concrete(*arrays: Array) -> bool:
    """True when every array holds concrete values (eager mode).

    Value-level validation (range/label checks that pull scalars to host)
    only runs eagerly; while tracing under ``jit`` these checks are skipped
    and only the static shape/dtype checks apply — the trace-time analogue of
    the reference resolving input cases from tensor values at runtime
    (``utilities/checks.py:65-119``).
    """
    return not any(isinstance(a, jax.core.Tracer) for a in arrays)


def _check_for_empty_tensors(preds: Array, target: Array) -> bool:
    return preds.size == 0 and target.size == 0


def _check_same_shape(preds: Array, target: Array) -> None:
    """Raise if predictions and targets have different shapes."""
    if preds.shape != target.shape:
        raise RuntimeError(
            f"Predictions and targets are expected to have the same shape, got {preds.shape} and {target.shape}."
        )


def _basic_input_validation(
    preds: Array, target: Array, threshold: float, multiclass: Optional[bool], ignore_index: Optional[int]
) -> None:
    """Value-level validation (eager only — pulls scalars to host)."""
    if _check_for_empty_tensors(preds, target):
        return
    if _is_floating(target):
        raise ValueError("The `target` has to be an integer tensor.")
    preds_float = _is_floating(preds)
    if preds.shape[0] != target.shape[0]:
        raise ValueError("The `preds` and `target` should have the same first dimension.")
    if not _concrete(preds, target):
        return  # tracing: value-level checks below are eager-only
    # A negative ignore_index legitimizes negative padding labels (dropped
    # upstream by _drop_negative_ignored_indices); mirror reference :46-49.
    if (ignore_index is None or ignore_index >= 0) and target.min() < 0:
        raise ValueError("The `target` has to be a non-negative tensor.")
    if not preds_float and preds.min() < 0:
        raise ValueError("If `preds` are integers, they have to be non-negative.")
    if multiclass is False and target.max() > 1:
        raise ValueError("If you set `multiclass=False`, then `target` should not exceed 1.")
    if multiclass is False and not preds_float and preds.max() > 1:
        raise ValueError("If you set `multiclass=False` and `preds` are integers, then `preds` should not exceed 1.")


def _check_shape_and_type_consistency(preds: Array, target: Array) -> Tuple[DataType, int]:
    """Resolve the input case from shapes/dtypes only (static under jit)."""
    preds_float = _is_floating(preds)

    if preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape, "
                f"got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        if preds.ndim == 1 and preds_float:
            case = DataType.BINARY
        elif preds.ndim == 1 and not preds_float:
            case = DataType.MULTICLASS
        elif preds.ndim > 1 and preds_float:
            case = DataType.MULTILABEL
        else:
            case = DataType.MULTIDIM_MULTICLASS
        implied_classes = int(jnp.size(preds[0])) if preds.size > 0 else 0
    elif preds.ndim == target.ndim + 1:
        if not preds_float:
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
        implied_classes = preds.shape[1] if preds.size > 0 else 0
        case = DataType.MULTICLASS if preds.ndim == 2 else DataType.MULTIDIM_MULTICLASS
    else:
        raise ValueError(
            "Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be (N, ...)"
            " and `preds` should be (N, C, ...)."
        )
    return case, implied_classes


def _check_num_classes_binary(num_classes: int, multiclass: Optional[bool]) -> None:
    if num_classes > 2:
        raise ValueError("Your data is binary, but `num_classes` is larger than 2.")
    if num_classes == 2 and not multiclass:
        raise ValueError(
            "Your data is binary and `num_classes=2`, but `multiclass` is not True."
            " Set it to True if you want to transform binary data to multi-class format."
        )
    if num_classes == 1 and multiclass:
        raise ValueError(
            "You have binary data and have set `multiclass=True`, but `num_classes` is 1."
            " Either set `multiclass=None` (default) or set `num_classes=2`"
            " to transform binary data to multi-class format."
        )


def _check_num_classes_mc(
    preds: Array, target: Array, num_classes: int, multiclass: Optional[bool], implied_classes: int
) -> None:
    if num_classes == 1 and multiclass is not False:
        raise ValueError(
            "You have set `num_classes=1`, but predictions are integers."
            " If you want to convert (multi-dimensional) multi-class data with 2 classes"
            " to binary/multi-label, set `multiclass=False`."
        )
    if num_classes > 1:
        if multiclass is False and implied_classes != num_classes:
            raise ValueError(
                "You have set `multiclass=False`, but the implied number of classes"
                " (from shape of inputs) does not match `num_classes`."
            )
        if target.size > 0 and _concrete(target) and num_classes <= int(target.max()):
            raise ValueError("The highest label in `target` should be smaller than `num_classes`.")
        if preds.shape != target.shape and num_classes != implied_classes:
            raise ValueError("The size of C dimension of `preds` does not match `num_classes`.")


def _check_num_classes_ml(num_classes: int, multiclass: Optional[bool], implied_classes: int) -> None:
    if multiclass and num_classes != 2:
        raise ValueError(
            "Your have set `multiclass=True`, but `num_classes` is not equal to 2."
            " If you are trying to transform multi-label data to 2 class multi-dimensional"
            " multi-class, you should set `num_classes` to either 2 or None."
        )
    if not multiclass and num_classes != implied_classes:
        raise ValueError("The implied number of classes (from shape of inputs) does not match num_classes.")


def _check_top_k(top_k: int, case: DataType, implied_classes: int, multiclass: Optional[bool], preds_float: bool) -> None:
    if case == DataType.BINARY:
        raise ValueError("You can not use `top_k` parameter with binary data.")
    if not isinstance(top_k, int) or top_k <= 0:
        raise ValueError("The `top_k` has to be an integer larger than 0.")
    if not preds_float:
        raise ValueError("You have set `top_k`, but you do not have probability predictions.")
    if multiclass is False:
        raise ValueError("If you set `multiclass=False`, you can not set `top_k`.")
    if case == DataType.MULTILABEL and multiclass:
        raise ValueError(
            "If you want to transform multi-label data to 2 class multi-dimensional"
            " multi-class data using `multiclass=True`, you can not use `top_k`."
        )
    if top_k >= implied_classes:
        raise ValueError("The `top_k` has to be strictly smaller than the `C` dimension of `preds`.")


def _check_classification_inputs(
    preds: Array,
    target: Array,
    threshold: float,
    num_classes: Optional[int],
    multiclass: Optional[bool],
    top_k: Optional[int],
    ignore_index: Optional[int] = None,
) -> DataType:
    """Full input validation; returns the resolved case.

    Mirrors reference ``utilities/checks.py:203-295``.
    """
    _basic_input_validation(preds, target, threshold, multiclass, ignore_index)
    case, implied_classes = _check_shape_and_type_consistency(preds, target)

    # Value-level check kept out of _check_shape_and_type_consistency so the
    # validate_args=False path stays free of host peeks (jit-safe).
    if (
        preds.ndim == target.ndim
        and _is_floating(preds)
        and target.size > 0
        and _concrete(target)
        and int(target.max()) > 1
    ):
        raise ValueError(
            "If `preds` and `target` are of shape (N, ...) and `preds` are floats, `target` should be binary."
        )

    if preds.shape != target.shape:
        if multiclass is False and implied_classes != 2:
            raise ValueError(
                "You have set `multiclass=False`, but have more than 2 classes in your data,"
                " based on the C dimension of `preds`."
            )
        if target.size > 0 and _concrete(target) and int(target.max()) >= implied_classes:
            raise ValueError(
                "The highest label in `target` should be smaller than the size of the `C` dimension of `preds`."
            )

    if num_classes:
        if case == DataType.BINARY:
            _check_num_classes_binary(num_classes, multiclass)
        elif case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS):
            _check_num_classes_mc(preds, target, num_classes, multiclass, implied_classes)
        elif case == DataType.MULTILABEL:
            _check_num_classes_ml(num_classes, multiclass, implied_classes)

    if top_k is not None:
        _check_top_k(top_k, case, implied_classes, multiclass, _is_floating(preds))

    return case


def _input_squeeze(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Remove all size-1 dims except the leading batch dim."""
    if preds.shape and preds.shape[0] == 1:
        preds = jnp.expand_dims(jnp.squeeze(preds), 0)
        target = jnp.expand_dims(jnp.squeeze(target), 0)
    else:
        preds, target = jnp.squeeze(preds), jnp.squeeze(target)
    return preds, target


def _input_format_classification(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, DataType]:
    """Normalize classification inputs to binary ``(N, C)``/``(N, C, X)`` int tensors.

    Behavioral parity with reference ``utilities/checks.py:310-449``. Output
    contract per case:

    * binary: preds thresholded, both ``(N, 1)`` (``multiclass=True`` -> one-hot ``(N, 2)``)
    * multi-class: one-hot/top-k binarized, both ``(N, C)`` (``multiclass=False`` -> ``(N, 1)``)
    * multi-label: thresholded/top-k, both ``(N, C)`` with trailing dims flattened
      (``multiclass=True`` -> ``(N, 2, C)``)
    * multi-dim multi-class: both ``(N, C, X)`` (``multiclass=False`` -> ``(N, X)``)

    Inside :func:`shared_input_format_scope` the whole pass is memoized by
    input identity + parameters, so a collection's members sharing one
    parameterization normalize once.
    """
    key = (id(preds), id(target), threshold, top_k, num_classes, multiclass, ignore_index, validate_args)
    cache, hit = _format_cache_lookup(key)
    if hit is not None:
        return hit[0]
    if cache is not None:
        _FORMAT_SCOPE.stats["misses"] += 1
        raw_preds, raw_target = preds, target

    preds, target = _input_squeeze(preds, target)
    if preds.dtype in (jnp.float16, jnp.bfloat16):
        preds = preds.astype(jnp.float32)

    if validate_args:
        case = _check_classification_inputs(
            preds, target, threshold=threshold, num_classes=num_classes,
            multiclass=multiclass, top_k=top_k, ignore_index=ignore_index,
        )
    else:
        case, _ = _check_shape_and_type_consistency(preds, target)

    if case in (DataType.BINARY, DataType.MULTILABEL) and not top_k:
        preds = (preds >= threshold).astype(jnp.int32)
        num_classes = num_classes if not multiclass else 2

    if case == DataType.MULTILABEL and top_k:
        preds = select_topk(preds, top_k)

    if case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) or multiclass:
        if _is_floating(preds):
            num_classes = preds.shape[1]
            preds = select_topk(preds, top_k or 1)
        else:
            if not num_classes:
                if not _concrete(preds, target):
                    raise ValueError(
                        "`num_classes` must be given explicitly when tracing under `jit`:"
                        " inferring it from the label values is a data-dependent shape."
                    )
                # Value-dependent inference — eager host peek, mirrors reference :429.
                num_classes = int(max(int(preds.max()), int(target.max()))) + 1
            preds = to_onehot(preds, max(2, num_classes))
        target = to_onehot(target, max(2, num_classes))
        if multiclass is False:
            preds, target = preds[:, 1, ...], target[:, 1, ...]

    if not _check_for_empty_tensors(preds, target):
        if (case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) and multiclass is not False) or multiclass:
            target = target.reshape(target.shape[0], target.shape[1], -1)
            preds = preds.reshape(preds.shape[0], preds.shape[1], -1)
        else:
            target = target.reshape(target.shape[0], -1)
            preds = preds.reshape(preds.shape[0], -1)

    if preds.ndim > 2 and preds.shape[-1] == 1:
        preds, target = jnp.squeeze(preds, -1), jnp.squeeze(target, -1)

    out = (preds.astype(jnp.int32), target.astype(jnp.int32), case)
    if cache is not None:
        # the raw inputs ride in the entry to pin their ids for the scope's
        # life (the foreign_coercion_scope trick)
        cache[key] = (out, raw_preds, raw_target)
    return out


# ---------------------------------------------------------------------------
# Retrieval input checks (reference utilities/checks.py:501-606)
# ---------------------------------------------------------------------------


def _check_retrieval_target_and_prediction_types(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    if not (jnp.issubdtype(target.dtype, jnp.integer) or jnp.issubdtype(target.dtype, jnp.bool_) or _is_floating(target)):
        raise ValueError("`target` must be a tensor of booleans, integers or floats")
    if not _is_floating(preds):
        raise ValueError("`preds` must be a tensor of floats")
    if not allow_non_binary_target and _concrete(target) and (target.max() > 1 or target.min() < 0):
        raise ValueError("`target` must contain `binary` values")
    target = target.astype(jnp.float32) if _is_floating(target) else target.astype(jnp.int32)
    preds = preds.astype(jnp.float32)
    return preds.reshape(-1), target.reshape(-1)


def _check_retrieval_functional_inputs(
    preds: Array, target: Array, allow_non_binary_target: bool = False
) -> Tuple[Array, Array]:
    if preds.shape != target.shape:
        raise ValueError("`preds` and `target` must be of the same shape")
    if preds.size == 0 or preds.ndim == 0:
        raise ValueError("`preds` and `target` must be non-empty and non-scalar tensors")
    return _check_retrieval_target_and_prediction_types(preds, target, allow_non_binary_target)


def _check_retrieval_inputs(
    indexes: Array,
    preds: Array,
    target: Array,
    allow_non_binary_target: bool = False,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array]:
    if indexes.shape != preds.shape or preds.shape != target.shape:
        raise ValueError("`indexes`, `preds` and `target` must be of the same shape")
    if not jnp.issubdtype(indexes.dtype, jnp.integer):
        raise ValueError("`indexes` must be a tensor of long integers")
    if ignore_index is not None:
        valid = target != ignore_index
        indexes, preds, target = indexes[valid], preds[valid], target[valid]
    if indexes.size == 0 or indexes.ndim == 0:
        raise ValueError("`indexes`, `preds` and `target` must be non-empty and non-scalar tensors")
    preds, target = _check_retrieval_target_and_prediction_types(preds, target, allow_non_binary_target)
    return indexes.astype(jnp.int32).reshape(-1), preds, target
