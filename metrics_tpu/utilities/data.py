"""Tensor utilities: dim-0 reductions, one-hot/top-k encoders, collection maps.

Equivalent surface to the reference's ``torchmetrics/utilities/data.py``
(``dim_zero_*`` at data.py:22-48, ``to_onehot`` :68, ``select_topk`` :102,
``to_categorical`` :128, ``apply_to_collection`` :146, ``get_group_indexes``
:196, ``_bincount`` :231) — re-designed on jnp. All kernels here are pure and
jittable; ``apply_to_collection`` / ``get_group_indexes`` are host-side
structural helpers.
"""
import sys
import threading
from collections import namedtuple
from contextlib import contextmanager
from typing import Any, Callable, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array


def dim_zero_cat(x: Union[Array, List[Array]]) -> Array:
    """Concatenate a (possibly list- or buffer-valued) state along dim 0."""
    if isinstance(x, (jnp.ndarray, jax.Array)) and not isinstance(x, (list, tuple)):
        return x
    if hasattr(x, "materialize"):  # CapacityBuffer
        return x.materialize()
    x = [jnp.atleast_1d(y) for y in x]
    if not x:
        raise ValueError("No samples to concatenate")
    return jnp.concatenate(x, axis=0)


def dim_zero_sum(x: Array) -> Array:
    return jnp.sum(x, axis=0)


def dim_zero_mean(x: Array) -> Array:
    return jnp.mean(x, axis=0)


def dim_zero_max(x: Array) -> Array:
    return jnp.max(x, axis=0)


def dim_zero_min(x: Array) -> Array:
    return jnp.min(x, axis=0)


def _flatten(x: Sequence) -> list:
    """Flatten one level of nesting."""
    return [item for sublist in x for item in sublist]


def _to_float(x: Array) -> Array:
    """Cast integer/bool arrays to float32, pass floats through unchanged."""
    x = jnp.asarray(x)
    return x if jnp.issubdtype(x.dtype, jnp.floating) else x.astype(jnp.float32)


def to_onehot(label_tensor: Array, num_classes: Optional[int] = None) -> Array:
    """Convert a dense label tensor ``(N, ...)`` to one-hot ``(N, C, ...)``.

    Mirrors reference ``utilities/data.py:68`` but uses a static
    ``num_classes`` under jit (falls back to a value peek when eager).
    """
    if num_classes is None:
        num_classes = int(label_tensor.max()) + 1
    if not jnp.issubdtype(label_tensor.dtype, jnp.integer):
        # bool / float labels are valid in the reference (tensor.scatter on
        # a long cast); one_hot requires an integer index tensor
        label_tensor = label_tensor.astype(jnp.int32)
    onehot = jax.nn.one_hot(label_tensor, num_classes, dtype=jnp.int32)
    # Move the new class axis to dim 1: (N, ..., C) -> (N, C, ...)
    return jnp.moveaxis(onehot, -1, 1)


def select_topk(prob_tensor: Array, topk: int = 1, dim: int = 1) -> Array:
    """Binarize a probability tensor by its top-k entries along ``dim``.

    Mirrors reference ``utilities/data.py:102``; implemented with
    ``jax.lax.top_k`` + scatter-free one-hot sum so it stays jittable.
    """
    if topk == 1:  # cheap fast-path
        idx = jnp.argmax(prob_tensor, axis=dim, keepdims=True)
        mask = jnp.zeros_like(prob_tensor, dtype=jnp.int32)
        return jnp.put_along_axis(mask, idx, 1, axis=dim, inplace=False)
    moved = jnp.moveaxis(prob_tensor, dim, -1)
    _, idx = jax.lax.top_k(moved, topk)
    onehots = jax.nn.one_hot(idx, moved.shape[-1], dtype=jnp.int32).sum(axis=-2)
    return jnp.moveaxis(jnp.minimum(onehots, 1), -1, dim)


def to_categorical(x: Array, argmax_dim: int = 1) -> Array:
    """Convert probability tensor to dense labels via argmax."""
    return jnp.argmax(x, axis=argmax_dim)


def apply_to_collection(
    data: Any,
    dtype: Union[type, tuple],
    function: Callable,
    *args: Any,
    prune: Optional[Callable[[Any], bool]] = None,
    **kwargs: Any,
) -> Any:
    """Recursively apply ``function`` to all ``dtype`` leaves of a collection.

    Mirrors reference ``utilities/data.py:146``. A ``prune`` predicate stops
    the walk at any node it accepts (the node is returned unchanged).
    """
    if prune is not None and prune(data):
        return data
    if isinstance(data, dtype):
        return function(data, *args, **kwargs)
    if isinstance(data, Mapping):
        return type(data)(
            {k: apply_to_collection(v, dtype, function, *args, prune=prune, **kwargs) for k, v in data.items()}
        )
    if isinstance(data, tuple) and hasattr(data, "_fields"):  # namedtuple
        return type(data)(*(apply_to_collection(d, dtype, function, *args, prune=prune, **kwargs) for d in data))
    if isinstance(data, (list, tuple)):
        return type(data)(apply_to_collection(d, dtype, function, *args, prune=prune, **kwargs) for d in data)
    return data


_COERCION_SCOPE = threading.local()


@contextmanager
def foreign_coercion_scope(*coerced: Any):
    """Mark containers whose elements were ALREADY coerced.

    ``MetricCollection.forward`` → ``Metric.forward`` → ``update`` each
    coerce defensively (each is a public entry point); registering the
    already-converted containers here lets the nested
    :func:`coerce_foreign_tensors` calls prune their walk at exactly those
    objects, so one call converts the (possibly deeply nested) input
    collection exactly once.

    Suppression is scoped to the IDENTITY of the registered elements — not
    the whole thread — so a composite metric whose ``update`` builds fresh
    torch tensors and feeds them to a nested metric still gets those
    converted (they are new objects, never registered).
    """
    ids = getattr(_COERCION_SCOPE, "ids", None)
    if ids is None:
        ids = _COERCION_SCOPE.ids = {}
    added = []
    for container in coerced:
        if isinstance(container, Mapping):
            items = container.values()
        elif isinstance(container, (list, tuple)):
            items = container
        else:
            items = (container,)
        for item in items:
            key = id(item)
            if key not in ids:
                ids[key] = item  # strong ref pins the id for the scope's life
                added.append(key)
    try:
        yield
    finally:
        for key in added:
            del ids[key]


def coerce_foreign_tensors(data: Any) -> Any:
    """Convert torch tensors nested anywhere in ``data`` to jax arrays.

    Migration affordance for users of the reference (whose pipelines hand
    metrics ``torch.Tensor`` batches — reference ``metric.py:229`` consumes
    them natively): ``update``/``forward`` accept them transparently.
    Conversion goes through numpy on host (zero-copy for CPU tensors except
    bfloat16, which numpy cannot represent — that round-trips via float32
    and re-casts to ``jnp.bfloat16``). No-op when torch was never imported
    by the process; jax/numpy inputs pass through untouched. Objects
    registered by an enclosing :func:`foreign_coercion_scope` (already
    coerced once) prune the walk.
    """
    torch = sys.modules.get("torch")  # cheap gate: no torch, no torch tensors
    if torch is None or not hasattr(torch, "Tensor"):
        # None is the standard sys.modules placeholder for "import blocked"
        return data

    def _convert(t: Any) -> Array:
        # resolve lazy conj/neg views: .numpy() refuses tensors with those
        # bits set and detach() does not clear them
        t = t.detach().resolve_conj().resolve_neg()
        if t.device.type != "cpu":
            t = t.cpu()
        if t.dtype == torch.bfloat16:
            return jnp.asarray(t.to(torch.float32).numpy()).astype(jnp.bfloat16)
        return jnp.asarray(t.numpy())

    ids = getattr(_COERCION_SCOPE, "ids", None)
    if not ids:
        return apply_to_collection(data, torch.Tensor, _convert)
    # prune at objects an enclosing scope already coerced (torch-free subtrees)
    return apply_to_collection(data, torch.Tensor, _convert, prune=lambda d: id(d) in ids)


def get_group_indexes(indexes: Array) -> List[Array]:
    """Group positions by query id; returns one index array per group.

    API-parity helper for the reference's ``utilities/data.py:196``. Note the
    retrieval metrics in this package do NOT use this Python loop on the hot
    path — they use sort + segment ops (`functional/retrieval`) — this exists
    for user code parity and host-side tooling.
    """
    import numpy as np

    idx = np.asarray(indexes)
    order = np.argsort(idx, kind="stable")
    sorted_idx = idx[order]
    boundaries = np.flatnonzero(np.diff(sorted_idx)) + 1
    return [jnp.asarray(g) for g in np.split(order, boundaries)]


_BINCOUNT_ONEHOT_MAX = 4096


def _bincount(x: Array, minlength: int) -> Array:
    """Deterministic bincount with a static length (jit-safe).

    Replaces reference ``utilities/data.py:231``'s CUDA-deterministic fallback.
    TPU scatter-add is slow (serialized updates); for moderate bin counts a
    one-hot sum is a fused compare+reduce that runs ~3x faster at N=1M and is
    deterministic by construction. Work is O(N * minlength), so large bin
    counts fall back to the scatter path.
    """
    x = x.reshape(-1)
    if jax.default_backend() == "tpu" and 0 < x.shape[0] and minlength <= 2048:
        # streaming pallas tile: bin block VMEM-resident, one input pass
        # (ops/confusion_bincount; same drop-out-of-range contract)
        from metrics_tpu.ops.confusion_bincount import bincount_counts

        return bincount_counts(x, minlength)
    if minlength <= _BINCOUNT_ONEHOT_MAX:
        return jnp.sum(
            x[:, None] == jnp.arange(minlength, dtype=x.dtype)[None, :], axis=0, dtype=jnp.int32
        )
    return jnp.bincount(x, length=minlength)


def _flatten_dict(x: Mapping) -> dict:
    """Flatten one level of dict nesting (dict-valued metric results inside a
    collection get spliced into the top-level result namespace)."""
    out: dict = {}
    for key, value in x.items():
        if isinstance(value, Mapping):
            out.update(value)
        else:
            out[key] = value
    return out


def allclose(a: Array, b: Array, rtol: float = 1e-5, atol: float = 1e-8) -> bool:
    """Host-level allclose over two arrays (dtype-promoting, shape-strict)."""
    if a.shape != b.shape:
        return False
    return bool(jnp.allclose(a, b, rtol=rtol, atol=atol))


def _squeeze_scalar_element_tensor(x: Array) -> Array:
    return x.reshape(()) if x.size == 1 else x


def _squeeze_if_scalar(data: Any) -> Any:
    return apply_to_collection(data, jax.Array, _squeeze_scalar_element_tensor)
