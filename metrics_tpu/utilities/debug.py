"""Opt-in traced value checks (SURVEY §7 hard part 4).

The reference resolves input/state validity from tensor *values* at runtime;
under ``jit`` those checks cannot raise on data, so a handful of guarded
conditions become silent (a ``CapacityBuffer`` overflowing inside a scan
clamps to the tail; ``nan_strategy="error"`` cannot error on traced NaNs).
``debug_checks(True)`` arms :func:`jax.experimental.checkify.check` guards
at exactly those points — run the jitted step under
``checkify.checkify(...)`` and call ``err.throw()`` to surface them. When
off (the default) no check is emitted into the trace: the compiled program
is bit-identical to the unguarded one, so the debug mode is cost-free in
production.

    import metrics_tpu
    from jax.experimental import checkify

    metrics_tpu.debug_checks(True)
    err, (state, value) = checkify.checkify(jax.jit(step))(state, preds, target)
    err.throw()  # raises on traced CapacityBuffer overflow / NaN-on-error

Also togglable via ``METRICS_TPU_DEBUG_CHECKS=1`` in the environment.
"""
import os

__all__ = ["debug_checks", "debug_checks_enabled"]

_ENABLED = os.environ.get("METRICS_TPU_DEBUG_CHECKS", "").strip().lower() not in ("", "0", "false", "no", "off")


def debug_checks(enable: bool = True) -> bool:
    """Arm (or disarm) traced checkify guards; returns the previous state."""
    global _ENABLED
    previous = _ENABLED
    _ENABLED = bool(enable)
    # mirror the toggle into the obs registry so a snapshot records whether
    # the traced guards were armed during the run it describes
    from metrics_tpu.obs.registry import enabled as _obs_enabled
    from metrics_tpu.obs.registry import set_gauge as _obs_gauge

    if _obs_enabled():
        _obs_gauge("debug.checks_enabled", 1.0 if _ENABLED else 0.0)
    return previous


def debug_checks_enabled() -> bool:
    return _ENABLED
