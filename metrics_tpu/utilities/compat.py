"""Version compatibility for the supported JAX range.

The package targets current JAX, where ``shard_map`` is a top-level
``jax.shard_map``; on older installs (<= 0.4.x) the same function lives at
``jax.experimental.shard_map.shard_map`` with a matching keyword signature.
Every public example, benchmark and test in this repo addresses the stable
spelling, so on old installs we alias it once at import — a no-op wherever
``jax.shard_map`` already exists.
"""
import jax

__all__ = ["install_jax_compat"]


def install_jax_compat() -> None:
    """Backfill ``jax.shard_map`` / ``lax.pcast`` / ``lax.pvary`` on older
    JAX releases (idempotent)."""
    if not hasattr(jax, "shard_map"):
        try:
            from jax.experimental.shard_map import shard_map
        except ImportError:  # pragma: no cover - very old jax; nothing to do
            return
        jax.shard_map = shard_map
    # releases predating the varying-manual-axes type system have no
    # replicated/varying distinction, so the casts are identities there
    if not hasattr(jax.lax, "pcast"):
        jax.lax.pcast = lambda x, axis_name=None, *, to=None: x
    if not hasattr(jax.lax, "pvary"):
        jax.lax.pvary = lambda x, axis_name=None: x
