"""Checkpoint / resume for metric states via orbax.

The reference checkpoints metric state through ``nn.Module.state_dict``
(reference ``metric.py:571-609``; DDP-correct checkpointing by saving inside
``sync_context``, ``tests/bases/test_ddp.py:226-234``). The TPU-native
equivalent: metric state is already a pytree (``Metric.state_pytree``), so
persistence is orbax save/restore of that pytree. List (cat) states are
stored as dicts keyed by position with a ``__list_len`` sentinel so
arbitrary-length accumulations — including empty ones — round-trip; scalar
bookkeeping (``_update_count``) rides along so a restored metric continues
streaming where it left off.

``save_state``/``restore_state`` accept a single :class:`Metric` or a
:class:`MetricCollection` (saved as one composite keyed by metric name).
Writes are atomic (:func:`atomic_dir_swap`): the tree is staged in a
sibling temp directory and published with one ``os.replace``, so a crash
mid-save can never leave a half-written "latest" checkpoint. Rotation,
manifests, async saves and resume cursors live one level up in
:class:`metrics_tpu.ft.manager.CheckpointManager`.
"""
import json
import os
import shutil
import tempfile
from contextlib import contextmanager
from enum import Enum
from typing import Any, Dict, Iterator, Union

import jax.numpy as jnp
import numpy as np

__all__ = [
    "atomic_dir_swap",
    "save_state",
    "restore_state",
    "metric_state_to_tree",
    "load_metric_state_tree",
]

_LIST_LEN_KEY = "__list_len"


def _maybe_inject(point: str) -> None:
    # deferred import: ft.manager imports this module at its top level, so
    # a module-level import here would cycle. At call time the package is
    # fully initialized and this is a sys.modules hit; maybe_fail itself is
    # one dict read when nothing is armed.
    from metrics_tpu.ft import faults

    faults.maybe_fail(point)


@contextmanager
def atomic_dir_swap(final_path: Union[str, os.PathLike]) -> Iterator[str]:
    """Stage a directory write, then atomically publish it at ``final_path``.

    Yields a staging path (inside a sibling scratch dir, same filesystem);
    on clean exit the staged directory becomes ``final_path`` via
    ``os.replace`` — readers see either the complete old version or the
    complete new one, never a partial write. On error the stage is
    discarded and any existing ``final_path`` is untouched. Leftover
    ``.tmp.*`` scratch dirs from a hard kill are inert (hidden from
    checkpoint discovery) and cleaned by the next
    :class:`~metrics_tpu.ft.manager.CheckpointManager` save.

    Overwriting an existing ``final_path`` needs two renames (directories
    cannot be exchanged in one syscall), so a kill between them would
    otherwise lose the old version: it is parked at the VISIBLE sibling
    ``<final>.prev`` for the window and removed after the publish.
    :func:`restore_state` falls back to ``<final>.prev`` when
    ``final_path`` is missing, so even that instant is recoverable; a
    stale ``.prev`` orphaned by such a kill is removed once the next save
    publishes a newer complete version.
    """
    final = os.fspath(os.path.abspath(final_path))
    parent = os.path.dirname(final) or "."
    os.makedirs(parent, exist_ok=True)
    scratch = tempfile.mkdtemp(prefix=".tmp.", dir=parent)
    stage = os.path.join(scratch, "stage")
    try:
        yield stage
        if not os.path.isdir(stage):
            raise FileNotFoundError(f"atomic_dir_swap: nothing was staged at {stage}")
        _maybe_inject("checkpoint.pre_rename")
        prev = final + ".prev"
        if os.path.lexists(final):
            if os.path.lexists(prev):
                shutil.rmtree(prev, ignore_errors=True)
            os.replace(final, prev)
            _maybe_inject("checkpoint.mid_swap")
            os.replace(stage, final)
        else:
            os.replace(stage, final)
        # only AFTER the new complete version is published: until then a
        # .prev (possibly orphaned by a kill in the window above, with
        # final missing) is the sole recovery copy
        shutil.rmtree(prev, ignore_errors=True)
    finally:
        shutil.rmtree(scratch, ignore_errors=True)


def _pack(value: Any) -> Any:
    """Lists/buffers/sketches become plain dicts (orbax trees need stable
    structure built from standard containers)."""
    from metrics_tpu.streaming.sketches import Sketch
    from metrics_tpu.utilities.buffers import CapacityBuffer

    if isinstance(value, Sketch):
        # leaves + a JSON-in-uint8 meta blob naming the sketch class and
        # its static config, so restore can rebuild without a target
        return value.to_pack_tree()
    if isinstance(value, CapacityBuffer):
        packed = {"__capbuf_capacity": jnp.asarray(value.capacity, jnp.int32), "__capbuf_count": value.count}
        if value.data is not None:
            packed["__capbuf_data"] = value.data
        return packed
    if isinstance(value, list):
        # explicit length sentinel: an EMPTY list still packs to a non-empty
        # dict, so unpacking never has to guess from key shapes alone
        packed = {f"__list_{i}": v for i, v in enumerate(value)}
        packed[_LIST_LEN_KEY] = jnp.asarray(len(value), jnp.int32)
        return packed
    return value


def _unpack(value: Any) -> Any:
    from metrics_tpu.utilities.buffers import CapacityBuffer

    if isinstance(value, dict) and "__sketch_meta" in value:
        from metrics_tpu.streaming.sketches import sketch_from_pack_tree

        return sketch_from_pack_tree(value)
    if isinstance(value, dict) and "__capbuf_capacity" in value:
        buf = CapacityBuffer(int(value["__capbuf_capacity"]))
        if "__capbuf_data" in value:
            buf.data = jnp.asarray(value["__capbuf_data"])
        buf.count = jnp.asarray(value["__capbuf_count"], jnp.int32)
        buf._host_count = None  # concretized lazily on first use
        return buf
    if isinstance(value, dict) and _LIST_LEN_KEY in value:
        return [value[f"__list_{i}"] for i in range(int(value[_LIST_LEN_KEY]))]
    # legacy packing (pre-__list_len checkpoints): positional keys only. The
    # non-empty requirement matters — an empty dict satisfies the all()
    # vacuously and would silently round-trip a non-list state as []
    if isinstance(value, dict) and value and all(k.startswith("__list_") for k in value):
        return [value[f"__list_{i}"] for i in range(len(value))]
    return value


def metric_state_to_tree(metric: Any) -> Dict[str, Any]:
    """Serializable pytree for a Metric or MetricCollection."""
    if hasattr(metric, "items") and not hasattr(metric, "state_pytree"):  # MetricCollection
        if getattr(metric, "_groups_checked", False):
            # with compute groups only the representative accumulates between
            # computes; materialize real state onto every member first
            metric._compute_groups_create_state_ref(copy=True)
            metric._state_is_copy = False
        return {name: metric_state_to_tree(m) for name, m in metric.items()}
    tree = {name: _pack(value) for name, value in metric.state_pytree().items()}
    tree["__update_count"] = jnp.asarray(metric._update_count, dtype=jnp.int32)
    aux = {}
    for name in metric._aux_attrs:
        value = getattr(metric, name, None)
        aux[name] = value.value if isinstance(value, Enum) else value
    if aux:
        # JSON-in-uint8 so non-numeric aux (e.g. detected input mode) rides
        # in the same orbax tree; EnumStr values restore as plain strings,
        # which compare equal to the enum
        tree["__aux"] = np.frombuffer(json.dumps(aux).encode(), dtype=np.uint8).copy()
    return tree


def load_metric_state_tree(metric: Any, tree: Dict[str, Any]) -> None:
    """Restore a Metric or MetricCollection from :func:`metric_state_to_tree`."""
    if hasattr(metric, "items") and not hasattr(metric, "state_pytree"):  # MetricCollection
        for name, m in metric.items():
            if name in tree:
                load_metric_state_tree(m, tree[name])
        # members now hold individually-restored real state; the group
        # state-ref bookkeeping (representative aliasing, _state_is_copy)
        # must be re-established or the next update can clobber restored
        # non-representative state (see collections.py)
        if hasattr(metric, "_resync_compute_groups_after_restore"):
            metric._resync_compute_groups_after_restore()
        return
    metric._update_count = int(tree.get("__update_count", metric._update_count))
    if "__aux" in tree:
        aux = json.loads(bytes(np.asarray(tree["__aux"]).astype(np.uint8)).decode())
        for name, value in aux.items():
            setattr(metric, name, value)
    state: Dict[str, Any] = {}
    for key, value in tree.items():
        if key in ("__update_count", "__aux"):
            continue
        unpacked = _unpack(value)
        if isinstance(unpacked, dict) and not unpacked and isinstance(metric._defaults.get(key), list):
            # legacy pre-__list_len checkpoints packed an EMPTY cat list as
            # {}; _unpack can't tell that from a genuine empty dict, but the
            # state's declared default can
            unpacked = []
        state[key] = unpacked
    metric.load_state_pytree(state)
    metric._computed = None


def save_state(path: Union[str, os.PathLike], metric: Any) -> None:
    """Write the metric/collection state to ``path`` with orbax, atomically.

    The orbax tree is staged in a sibling temp dir and published with one
    rename (:func:`atomic_dir_swap`), so a crash mid-save leaves any
    previous checkpoint at ``path`` intact rather than a corrupt partial
    write. In a distributed setting call inside ``sync_context`` (mirroring
    the reference's DDP checkpoint recipe) so the saved state is the global
    one.
    """
    import orbax.checkpoint as ocp

    tree = metric_state_to_tree(metric)
    with atomic_dir_swap(path) as stage:
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(stage, tree)


def restore_state(path: Union[str, os.PathLike], metric: Any) -> Any:
    """Restore state saved by :func:`save_state` into ``metric``; returns it.

    When ``path`` is missing but ``<path>.prev`` exists — a kill landed in
    :func:`atomic_dir_swap`'s two-rename overwrite window — the parked
    previous checkpoint is restored instead (nothing is ever lost).
    """
    import orbax.checkpoint as ocp

    target = os.fspath(os.path.abspath(path))
    if not os.path.exists(target) and os.path.isdir(target + ".prev"):
        target = target + ".prev"
    with ocp.PyTreeCheckpointer() as ckptr:
        tree = ckptr.restore(target)
    load_metric_state_tree(metric, tree)
    return metric
