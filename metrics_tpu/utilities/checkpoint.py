"""Checkpoint / resume for metric states via orbax.

The reference checkpoints metric state through ``nn.Module.state_dict``
(reference ``metric.py:571-609``; DDP-correct checkpointing by saving inside
``sync_context``, ``tests/bases/test_ddp.py:226-234``). The TPU-native
equivalent: metric state is already a pytree (``Metric.state_pytree``), so
persistence is orbax save/restore of that pytree. List (cat) states are
stored as dicts keyed by position so arbitrary-length accumulations
round-trip; scalar bookkeeping (``_update_count``) rides along so a restored
metric continues streaming where it left off.

``save_state``/``restore_state`` accept a single :class:`Metric` or a
:class:`MetricCollection` (saved as one composite keyed by metric name).
"""
import json
import os
from enum import Enum
from typing import Any, Dict, Union

import jax.numpy as jnp
import numpy as np

__all__ = ["save_state", "restore_state", "metric_state_to_tree", "load_metric_state_tree"]


def _pack(value: Any) -> Any:
    """Lists/buffers become plain dicts (orbax trees need stable structure
    built from standard containers)."""
    from metrics_tpu.utilities.buffers import CapacityBuffer

    if isinstance(value, CapacityBuffer):
        packed = {"__capbuf_capacity": jnp.asarray(value.capacity, jnp.int32), "__capbuf_count": value.count}
        if value.data is not None:
            packed["__capbuf_data"] = value.data
        return packed
    if isinstance(value, list):
        return {f"__list_{i}": v for i, v in enumerate(value)}
    return value


def _unpack(value: Any) -> Any:
    from metrics_tpu.utilities.buffers import CapacityBuffer

    if isinstance(value, dict) and "__capbuf_capacity" in value:
        buf = CapacityBuffer(int(value["__capbuf_capacity"]))
        if "__capbuf_data" in value:
            buf.data = jnp.asarray(value["__capbuf_data"])
        buf.count = jnp.asarray(value["__capbuf_count"], jnp.int32)
        buf._host_count = None  # concretized lazily on first use
        return buf
    if isinstance(value, dict) and all(k.startswith("__list_") for k in value):
        return [value[f"__list_{i}"] for i in range(len(value))]
    return value


def metric_state_to_tree(metric: Any) -> Dict[str, Any]:
    """Serializable pytree for a Metric or MetricCollection."""
    if hasattr(metric, "items") and not hasattr(metric, "state_pytree"):  # MetricCollection
        if getattr(metric, "_groups_checked", False):
            # with compute groups only the representative accumulates between
            # computes; materialize real state onto every member first
            metric._compute_groups_create_state_ref(copy=True)
            metric._state_is_copy = False
        return {name: metric_state_to_tree(m) for name, m in metric.items()}
    tree = {name: _pack(value) for name, value in metric.state_pytree().items()}
    tree["__update_count"] = jnp.asarray(metric._update_count, dtype=jnp.int32)
    aux = {}
    for name in metric._aux_attrs:
        value = getattr(metric, name, None)
        aux[name] = value.value if isinstance(value, Enum) else value
    if aux:
        # JSON-in-uint8 so non-numeric aux (e.g. detected input mode) rides
        # in the same orbax tree; EnumStr values restore as plain strings,
        # which compare equal to the enum
        tree["__aux"] = np.frombuffer(json.dumps(aux).encode(), dtype=np.uint8).copy()
    return tree


def load_metric_state_tree(metric: Any, tree: Dict[str, Any]) -> None:
    """Restore a Metric or MetricCollection from :func:`metric_state_to_tree`."""
    if hasattr(metric, "items") and not hasattr(metric, "state_pytree"):  # MetricCollection
        for name, m in metric.items():
            if name in tree:
                load_metric_state_tree(m, tree[name])
        return
    metric._update_count = int(tree.get("__update_count", metric._update_count))
    if "__aux" in tree:
        aux = json.loads(bytes(np.asarray(tree["__aux"]).astype(np.uint8)).decode())
        for name, value in aux.items():
            setattr(metric, name, value)
    metric.load_state_pytree(
        {k: _unpack(v) for k, v in tree.items() if k not in ("__update_count", "__aux")}
    )
    metric._computed = None


def save_state(path: Union[str, os.PathLike], metric: Any) -> None:
    """Write the metric/collection state to ``path`` with orbax.

    In a distributed setting call inside ``sync_context`` (mirroring the
    reference's DDP checkpoint recipe) so the saved state is the global one.
    """
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(os.fspath(os.path.abspath(path)), metric_state_to_tree(metric))


def restore_state(path: Union[str, os.PathLike], metric: Any) -> Any:
    """Restore state saved by :func:`save_state` into ``metric``; returns it."""
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        tree = ckptr.restore(os.fspath(os.path.abspath(path)))
    load_metric_state_tree(metric, tree)
    return metric
