"""Shared persistent-XLA-compile-cache setup.

Benches, tests and doctests compile hundreds of programs — many small, a
few (retrieval sort/segment at 1M docs, InceptionV3) taking minutes on a
cold process. One cache dir serves them all; the threshold is low enough
that the small doctest programs are cached too.
"""
import os

CACHE_DIR = os.path.join(os.path.expanduser("~"), ".cache", "metrics_tpu_xla")


def enable_persistent_cache() -> None:
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
    except Exception:  # older jax without the knob: cold compiles only
        pass
