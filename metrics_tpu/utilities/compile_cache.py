"""Shared persistent-XLA-compile-cache setup.

Benches, tests and doctests compile hundreds of programs — many small, a
few (retrieval sort/segment at 1M docs, InceptionV3) taking minutes on a
cold process. One cache dir serves them all; the min-compile-time threshold
is zero so every program — including the hundreds of sub-100ms test jits,
which in aggregate dominate suite wall-clock on a 1-core runner — is cached.
Tests that need a compile the cache could falsify (op-metadata assertions,
executable serialization) opt out via the ``isolated_compile_cache``
fixture in ``tests/conftest.py``.
"""
import os

CACHE_DIR = os.path.join(os.path.expanduser("~"), ".cache", "metrics_tpu_xla")


def enable_persistent_cache() -> None:
    """Point jax at the shared on-disk compile cache.

    Only the EXPECTED failure — a jax build without the cache knobs
    (``jax.config.update`` raises ``AttributeError``/``KeyError`` for an
    unrecognized option) — is swallowed, and even then a
    ``rank_zero_debug`` line says the cache is disabled, so a silent
    cold-compile-only run is diagnosable from the logs. Anything else
    (import failure, permission error writing the config) propagates:
    swallowing it used to hide real misconfiguration behind minutes of
    recompiles.
    """
    import jax

    from metrics_tpu.obs.registry import enabled as _obs_enabled
    from metrics_tpu.obs.registry import set_gauge as _obs_gauge

    try:
        jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except (AttributeError, KeyError) as err:  # older jax without the knob
        from metrics_tpu.utilities.prints import rank_zero_debug

        rank_zero_debug(
            f"persistent XLA compile cache disabled (jax lacks the config knob: {err});"
            " this process pays cold compiles only"
        )
        if _obs_enabled():
            _obs_gauge("compile_cache.persistent_enabled", 0.0)
        return
    if _obs_enabled():
        _obs_gauge("compile_cache.persistent_enabled", 1.0)
