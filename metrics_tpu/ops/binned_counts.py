"""Fused threshold-binning kernel: per-class TP/FP counts at T thresholds.

The hot op of every binned curve metric (BinnedPrecisionRecallCurve /
BinnedAveragePrecision / BinnedRecallAtFixedPrecision — reference
``classification/binned_precision_recall.py:45,186,242``, which loops
thresholds one at a time in Python "to conserve memory", :164-169).

The XLA formulation materializes/streams an ``(N, C, T)`` comparison; this
pallas kernel instead keeps a ``(1, T)`` count block resident in VMEM while
streaming sample tiles through, so HBM traffic is one read of ``preds``/
``target`` and one tiny write.

Layout: inputs are transposed to class-major and tiled ``(C, n_blocks, 8,
BL)`` (sublane x lane = 8 x BL satisfies the TPU (8, 128) tiling floor);
grid is ``(C, n_blocks)`` with the sample axis innermost, so each class's
``(1, T)`` count block initializes once (``pl.program_id(1) == 0``) and
accumulates across the whole stream before moving to the next class.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array

_SUBLANES = 8
_BLOCK_LANES = 1024


def _kernel(thr_ref, preds_ref, target_ref, tp_ref, fp_ref):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        tp_ref[...] = jnp.zeros_like(tp_ref)
        fp_ref[...] = jnp.zeros_like(fp_ref)

    p = preds_ref[0, 0]  # (8, BL)
    t = target_ref[0, 0]  # (8, BL) float 0/1
    thr = thr_ref[0, :]  # (T,)
    mask = (p[:, None, :] >= thr[None, :, None]).astype(jnp.float32)  # (8, T, BL)
    pred_pos = jnp.sum(mask, axis=(0, 2))  # (T,)
    tp = jnp.sum(mask * t[:, None, :], axis=(0, 2))  # (T,)
    tp_ref[0, 0, :] += tp
    fp_ref[0, 0, :] += pred_pos - tp


@functools.partial(jax.jit, static_argnames=("interpret",))
def _binned_counts_pallas(preds: Array, target: Array, thresholds: Array, interpret: bool = False) -> tuple:
    n, c = preds.shape
    t = thresholds.shape[0]
    block = _SUBLANES * _BLOCK_LANES
    n_pad = -n % block
    # pad with preds=-inf (below every threshold) and target=0: no contribution
    preds_t = jnp.pad(preds.astype(jnp.float32), ((0, n_pad), (0, 0)), constant_values=-jnp.inf)
    target_t = jnp.pad(target.astype(jnp.float32), ((0, n_pad), (0, 0)))
    n_blocks = (n + n_pad) // block
    preds_t = preds_t.T.reshape(c, n_blocks, _SUBLANES, _BLOCK_LANES)
    target_t = target_t.T.reshape(c, n_blocks, _SUBLANES, _BLOCK_LANES)

    tps, fps = pl.pallas_call(
        _kernel,
        grid=(c, n_blocks),
        in_specs=[
            pl.BlockSpec((1, t), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 1, _SUBLANES, _BLOCK_LANES), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, _SUBLANES, _BLOCK_LANES), lambda i, j: (i, j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, t), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((c, 1, t), jnp.float32),
            jax.ShapeDtypeStruct((c, 1, t), jnp.float32),
        ],
        interpret=interpret,
    )(thresholds.astype(jnp.float32).reshape(1, t), preds_t, target_t)
    tps, fps = tps[:, 0, :], fps[:, 0, :]
    total_pos = target.astype(jnp.float32).sum(axis=0)[:, None]
    return tps, fps, total_pos - tps


@jax.jit
def _binned_counts_xla(preds: Array, target: Array, thresholds: Array) -> tuple:
    """Reference XLA formulation: one (N, C, T) fused comparison."""
    tgt = target.astype(bool)[:, :, None]
    mask = preds[:, :, None] >= thresholds[None, None, :]
    tps = (tgt & mask).sum(axis=0).astype(jnp.float32)
    fps = ((~tgt) & mask).sum(axis=0).astype(jnp.float32)
    fns = (tgt & (~mask)).sum(axis=0).astype(jnp.float32)
    return tps, fps, fns


def binned_counts(preds: Array, target: Array, thresholds: Array) -> tuple:
    """``(TPs, FPs, FNs)`` each ``(C, T)`` float32.

    Args:
        preds: ``(N, C)`` scores in [0, 1].
        target: ``(N, C)`` binary labels — bool, or integers where ONLY the
            value ``1`` marks a positive (a ``-1`` ignore sentinel or any
            other non-{0,1} value counts as negative).
        thresholds: ``(T,)`` sorted thresholds.

    Uses the pallas kernel on TPU, the XLA broadcast elsewhere. The kernel's
    (8, T, BL) VMEM mask caps the threshold count (~16 MB VMEM); beyond that
    the XLA formulation takes over.
    """
    # Binarize with a strict `== 1` so non-{0,1} values (ignore sentinels,
    # multi-valued labels) count as negatives; bool targets map True -> 1.
    # Done via int32 to stay clean under strict dtype promotion.
    target = target.astype(jnp.int32) == 1
    if jax.default_backend() == "tpu" and thresholds.shape[0] <= 256:
        return _binned_counts_pallas(preds, target, thresholds)
    return _binned_counts_xla(preds, target, thresholds)
