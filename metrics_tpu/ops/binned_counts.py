"""Fused threshold-binning kernel: per-class TP/FP counts at T thresholds.

The hot op of every binned curve metric (BinnedPrecisionRecallCurve /
BinnedAveragePrecision / BinnedRecallAtFixedPrecision — reference
``classification/binned_precision_recall.py:45,186,242``, which loops
thresholds one at a time in Python "to conserve memory", :164-169).

The XLA formulation materializes/streams an ``(N, C, T)`` comparison; this
pallas kernel instead keeps a ``(1, T)`` count block resident in VMEM while
streaming sample tiles through, so HBM traffic is one read of ``preds``/
``target`` and one tiny write.

Layout: inputs are transposed to class-major and tiled ``(C, n_blocks, 8,
BL)`` (sublane x lane = 8 x BL satisfies the TPU (8, 128) tiling floor);
grid is ``(C, n_blocks)`` with the sample axis innermost, so each class's
``(1, T)`` count block initializes once (``pl.program_id(1) == 0``) and
accumulates across the whole stream before moving to the next class.

Per-block compute is ONE VPU compare per (sample, threshold) pair — the
bf16 mask — with both count reductions (tp and predicted-positive) folded
into a single MXU contraction against the stacked ``[target, ones]``
operand (0/1 values are exact in bf16; accumulation is f32 via
``preferred_element_type``). The previous formulation spent 3 further VPU
ops per pair on mask*target products and two tree-sums, which is exactly
the 44%-of-VPU-bound gap the round-5 roofline table flagged.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array

_SUBLANES = 8
_BLOCK_LANES = 1024


def _kernel(thr_ref, preds_ref, target_ref, tp_ref, fp_ref):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        tp_ref[...] = jnp.zeros_like(tp_ref)
        fp_ref[...] = jnp.zeros_like(fp_ref)

    p = preds_ref[0, 0]  # (8, BL)
    t = target_ref[0, 0]  # (8, BL) float 0/1
    thr = thr_ref[0, :]  # (T,)
    mask = (p[:, None, :] >= thr[None, :, None]).astype(jnp.bfloat16)  # (8, T, BL)
    # both reductions in one sublane-batched MXU contraction:
    # (8, T, BL) x (8, BL, 2) -> (8, T, 2) with [:, :, 0] = tp rows and
    # [:, :, 1] = predicted-positive rows; 0/1 operands are exact in bf16
    # and the f32 preferred_element_type keeps the accumulation exact
    rhs = jnp.stack([t, jnp.ones_like(t)], axis=-1).astype(jnp.bfloat16)  # (8, BL, 2)
    counts = jax.lax.dot_general(
        mask, rhs, dimension_numbers=(((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ).sum(axis=0)  # (T, 2)
    tp_ref[0, 0, :] += counts[:, 0]
    fp_ref[0, 0, :] += counts[:, 1] - counts[:, 0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _binned_counts_pallas(preds: Array, target: Array, thresholds: Array, interpret: bool = False) -> tuple:
    from metrics_tpu.obs.tracing import trace_span

    with trace_span("ops.binned_counts", category="kernel"):
        return _binned_counts_pallas_impl(preds, target, thresholds, interpret)


def _binned_counts_pallas_impl(preds: Array, target: Array, thresholds: Array, interpret: bool = False) -> tuple:
    n, c = preds.shape
    t = thresholds.shape[0]
    # bf16 mask block: (8, T, BL) x 2 bytes. Half the old f32 footprint, so
    # the sample block widens to 2048 lanes at moderate threshold counts
    # (fewer grid steps, longer MXU contractions); T > 128 keeps 1024 to
    # stay within the VMEM budget.
    block_lanes = 2 * _BLOCK_LANES if t <= 128 else _BLOCK_LANES
    block = _SUBLANES * block_lanes
    n_pad = -n % block
    # pad with preds=-inf (below every threshold) and target=0: no contribution
    preds_t = jnp.pad(preds.astype(jnp.float32), ((0, n_pad), (0, 0)), constant_values=-jnp.inf)
    target_t = jnp.pad(target.astype(jnp.float32), ((0, n_pad), (0, 0)))
    n_blocks = (n + n_pad) // block
    preds_t = preds_t.T.reshape(c, n_blocks, _SUBLANES, block_lanes)
    target_t = target_t.T.reshape(c, n_blocks, _SUBLANES, block_lanes)

    tps, fps = pl.pallas_call(
        _kernel,
        grid=(c, n_blocks),
        in_specs=[
            pl.BlockSpec((1, t), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 1, _SUBLANES, block_lanes), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((1, 1, _SUBLANES, block_lanes), lambda i, j: (i, j, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, t), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((c, 1, t), jnp.float32),
            jax.ShapeDtypeStruct((c, 1, t), jnp.float32),
        ],
        interpret=interpret,
    )(thresholds.astype(jnp.float32).reshape(1, t), preds_t, target_t)
    tps, fps = tps[:, 0, :], fps[:, 0, :]
    total_pos = target.astype(jnp.float32).sum(axis=0)[:, None]
    return tps, fps, total_pos - tps


@jax.jit
def _binned_counts_xla(preds: Array, target: Array, thresholds: Array) -> tuple:
    """Reference XLA formulation: one (N, C, T) fused comparison."""
    tgt = target.astype(bool)[:, :, None]
    mask = preds[:, :, None] >= thresholds[None, None, :]
    tps = (tgt & mask).sum(axis=0).astype(jnp.float32)
    fps = ((~tgt) & mask).sum(axis=0).astype(jnp.float32)
    fns = (tgt & (~mask)).sum(axis=0).astype(jnp.float32)
    return tps, fps, fns


# launch-timing wrappers for eager dispatches of either compiled kernel
# (same step label: the pallas/XLA choice is internal); trace-transparent,
# one predicate per eager call when obs device timing is off
from metrics_tpu.obs.profile import time_launch as _obs_time_launch  # noqa: E402

_timed_pallas = _obs_time_launch(_binned_counts_pallas, "ops.binned_counts")
_timed_xla = _obs_time_launch(_binned_counts_xla, "ops.binned_counts")


def binned_counts(preds: Array, target: Array, thresholds: Array) -> tuple:
    """``(TPs, FPs, FNs)`` each ``(C, T)`` float32.

    Args:
        preds: ``(N, C)`` scores in [0, 1].
        target: ``(N, C)`` binary labels — bool, or integers where ONLY the
            value ``1`` marks a positive (a ``-1`` ignore sentinel or any
            other non-{0,1} value counts as negative).
        thresholds: ``(T,)`` sorted thresholds.

    Uses the pallas kernel on TPU, the XLA broadcast elsewhere. The kernel's
    (8, T, BL) VMEM mask caps the threshold count (~16 MB VMEM); beyond that
    the XLA formulation takes over.
    """
    # Binarize with a strict `== 1` so non-{0,1} values (ignore sentinels,
    # multi-valued labels) count as negatives; bool targets map True -> 1.
    # Done via int32 to stay clean under strict dtype promotion.
    target = target.astype(jnp.int32) == 1
    if jax.default_backend() == "tpu" and thresholds.shape[0] <= 256:
        return _timed_pallas(preds, target, thresholds)
    return _timed_xla(preds, target, thresholds)


def binned_label_histograms(preds: Array, target: Array, num_bins: int) -> tuple:
    """Per-bin ``(positive, negative)`` label histograms over ``num_bins``
    equal score bins in [0, 1] — the sufficient statistic of the streaming
    ``ScoreLabelSketch`` — via the fused threshold kernel.

    Bin ``k`` covers ``[k/T, (k+1)/T)`` with the last bin closed at 1.0
    (scores are clipped into range first). The kernel's outputs are
    cumulative ``>= threshold`` counts, so the per-bin masses are the
    adjacent differences; keeping that layout translation HERE, beside the
    kernel that defines it, lets every consumer share one definition.

    Args:
        preds: ``(N,)`` scores (clipped to [0, 1]).
        target: ``(N,)`` binary labels (strict ``== 1`` marks a positive).
        num_bins: ``T``; the pallas path engages on TPU at ``T <= 256``.

    Returns:
        ``(pos_hist, neg_hist)``, each ``(T,)`` float32.
    """
    thresholds = jnp.arange(num_bins, dtype=jnp.float32) / num_bins
    preds = jnp.clip(jnp.ravel(preds), 0.0, 1.0)
    target = jnp.ravel(target).astype(jnp.int32)
    tps, fps, _ = binned_counts(preds[:, None], target[:, None], thresholds)
    tp_cum, fp_cum = tps[0], fps[0]  # counts with score >= k/T
    zero = jnp.zeros((1,), jnp.float32)
    pos_hist = tp_cum - jnp.concatenate([tp_cum[1:], zero])
    neg_hist = fp_cum - jnp.concatenate([fp_cum[1:], zero])
    return pos_hist, neg_hist
