"""Pallas TPU kernels for hot metric ops.

These back the performance-critical update paths where plain XLA lowering
leaves bandwidth on the table. Every kernel has an XLA fallback used on
non-TPU backends (and for oracle comparison in tests).
"""
from metrics_tpu.ops.binned_counts import binned_counts  # noqa: F401
from metrics_tpu.ops.confusion_bincount import bincount_counts, confusion_counts  # noqa: F401

__all__ = ["bincount_counts", "binned_counts", "confusion_counts"]
