"""Fused argmax-compare kernel: count of ``argmax(preds, 1) == target``.

The hot op of micro-multiclass accuracy/stat-scores at small ``C`` (the
``_stat_scores_update`` fast path): XLA lowers the ``(N, C)`` argmax as a
(value, index)-pair reduction over the minor dimension whose vectorized form
needs a relayout of the whole operand — the round-5 roofline table blames
that relayout for the accuracy row sitting at 16-24% of its HBM bound.

This pallas kernel pins the layout instead: sample tiles stream through VMEM
in their NATIVE row-major layout (``(BLOCK_N, C)`` blocks, classes on lanes),
and the first-max index is computed with a handful of lane-reduced
elementwise ops per tile — HBM traffic is ONE read of ``preds``/``target``
and a scalar write, no relayout pass.

The argmax tie/NaN contract matches ``jnp.argmax`` exactly: first index of
the maximum, with NaN ordered greatest (first NaN wins).
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array

_BLOCK_ROWS = 2048
# classes ride the 128-lane minor dim; beyond one lane tile the padded-lane
# waste stops paying for the saved relayout and XLA's argmax amortizes fine
_MAX_LANE_CLASSES = 128


def _kernel(preds_ref, target_ref, out_ref, *, num_classes: int):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    x = preds_ref[...]  # (BLOCK_N, C) float32, classes on lanes
    t = target_ref[...]  # (BLOCK_N, 1) int32
    idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    sentinel = jnp.int32(num_classes)  # "no candidate in this row"
    # jnp.argmax == first NaN index if any NaN (NaN sorts greatest), else
    # first index attaining the row max
    is_nan = jnp.isnan(x)
    nan_first = jnp.min(jnp.where(is_nan, idx, sentinel), axis=1, keepdims=True)
    row_max = jnp.max(x, axis=1, keepdims=True)
    max_first = jnp.min(jnp.where(x == row_max, idx, sentinel), axis=1, keepdims=True)
    am = jnp.where(nan_first < sentinel, nan_first, max_first)  # (BLOCK_N, 1)
    # int32 accumulation: exact for any N < 2^31 (an f32 accumulator would
    # round away +1s past 2^24 correct rows — the flattened-epoch regime)
    out_ref[0, 0] += jnp.sum((am == t).astype(jnp.int32))


@functools.partial(jax.jit, static_argnames=("interpret",))
def _argmax_correct_pallas(preds: Array, target: Array, interpret: bool = False) -> Array:
    from metrics_tpu.obs.tracing import trace_span

    with trace_span("ops.argmax_compare", category="kernel"):
        return _argmax_correct_pallas_impl(preds, target, interpret)


def _argmax_correct_pallas_impl(preds: Array, target: Array, interpret: bool = False) -> Array:
    n, c = preds.shape
    n_pad = -n % _BLOCK_ROWS
    # pad rows with preds=0 / target=-1: their argmax lands in [0, C) and
    # never matches the -1 target, so padding contributes nothing
    preds_p = jnp.pad(preds.astype(jnp.float32), ((0, n_pad), (0, 0)))
    target_p = jnp.pad(target.astype(jnp.int32), (0, n_pad), constant_values=-1)
    n_blocks = (n + n_pad) // _BLOCK_ROWS

    out = pl.pallas_call(
        functools.partial(_kernel, num_classes=c),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, c), lambda j: (j, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        interpret=interpret,
    )(preds_p, target_p.reshape(-1, 1))
    return out[0, 0]


@jax.jit
def _argmax_correct_xla(preds: Array, target: Array) -> Array:
    return jnp.sum(jnp.argmax(preds, axis=1) == target).astype(jnp.int32)


# one launch-timing wrapper per compiled dispatch target (same step label:
# the pallas/XLA choice is an internal detail of the same logical kernel);
# trace-transparent and one predicate per eager call when timing is off
from metrics_tpu.obs.profile import time_launch as _obs_time_launch  # noqa: E402

_timed_pallas = _obs_time_launch(_argmax_correct_pallas, "ops.argmax_compare")
_timed_xla = _obs_time_launch(_argmax_correct_xla, "ops.argmax_compare")


def argmax_correct_count(preds: Array, target: Array) -> Array:
    """Number of rows whose first-max class index equals ``target`` (int32).

    Args:
        preds: ``(N, C)`` float scores (any float dtype; compared exactly —
            the bf16->f32 cast is injective and order-preserving).
        target: ``(N,)`` integer labels; out-of-range labels never match.

    Uses the pallas streaming tile on TPU for lane-resident class counts,
    the XLA argmax elsewhere (and for empty inputs, which have no block to
    stream).

    With ``obs.configure(device_timing=True)`` armed, eager dispatches of
    either compiled kernel land in the ``step.latency_ms{step=
    ops.argmax_compare}`` histogram (in-jit call sites are untouched —
    the wrapper is trace-transparent).
    """
    if (
        jax.default_backend() == "tpu"
        and preds.shape[0] > 0
        and 1 < preds.shape[1] <= _MAX_LANE_CLASSES
    ):
        return _timed_pallas(preds, target)
    return _timed_xla(preds, target)
