"""Fused confusion-matrix / bincount scatter tiles.

The hot op of the confusion-matrix metric family (ConfusionMatrix /
CohenKappa / MatthewsCorrCoef / JaccardIndex — reference
``classification/confusion_matrix.py:25`` counts ``target * C + pred`` with
a bincount) and of every ``_bincount`` consumer. The PR 5 cost gauges rank
these rows bytes-bound: XLA lowers the count either as a serialized TPU
scatter-add or as the one-hot compare whose ``(N, C^2)`` (bincount) /
``(N, C)`` one-hot operands stream through HBM once per reduction pass.

These pallas kernels keep the count block resident in VMEM while sample
tiles stream through, so HBM traffic is ONE read of the index vectors and
one tiny write — the same streaming-accumulator shape as
``ops/argmax_compare`` / ``ops/binned_counts``:

* :func:`confusion_counts` — the ``(C, C)`` joint count factored as
  ``onehot(target)^T @ onehot(preds)`` per tile: two VPU compares build the
  bf16 one-hots (0/1 exact in bf16) and ONE MXU contraction per block
  accumulates into the resident ``(C, C)`` int32 block.
* :func:`bincount_counts` — the ``(M,)`` histogram as one VPU compare
  against a lane-resident bin iota plus one MXU contraction against a ones
  row per block.

Both accumulate EXACTLY for any count below 2^31: the per-block MXU
contraction is f32 but a block contributes at most its row count per cell
(far below 2^24, so the dot itself is exact), and the cross-block
accumulation is int32. Out-of-range indices are no-ops — the padding
contract, and the same semantics as ``jax.nn.one_hot`` on invalid indices.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array

_BLOCK_ROWS = 2048
# classes ride the 128-lane minor dim of the one-hot operands; the resident
# count block is (C, C) int32, so C beyond one lane tile starts paying
# padded MXU waste on both operands and the XLA one-hot matmul amortizes fine
_MAX_LANE_CLASSES = 128
# bincount streams a (BLOCK, M) mask; past 2048 bins the VMEM footprint
# stops paying for the saved streaming pass
_MAX_BINS = 2048


def _confusion_kernel(preds_ref, target_ref, out_ref, *, num_classes: int):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    p = preds_ref[...]  # (BLOCK_ROWS, 1) int32
    t = target_ref[...]  # (BLOCK_ROWS, 1) int32
    idx = jax.lax.broadcasted_iota(jnp.int32, (p.shape[0], num_classes), 1)
    # out-of-range indices (the -1 padding) match no lane -> zero row
    p_oh = (p == idx).astype(jnp.bfloat16)  # (BLOCK_ROWS, C)
    t_oh = (t == idx).astype(jnp.bfloat16)
    # contract the sample axis: (BLOCK, C)^T x (BLOCK, C) -> (C, C) with
    # [true, pred] layout; 0/1 operands are exact in bf16 and the per-block
    # f32 dot is exact (<= BLOCK counts per cell). The cross-block
    # accumulator is int32 so totals stay exact past 2^24 per cell — the
    # flattened-epoch regime feeds WHOLE epochs into one update.
    counts = jax.lax.dot_general(
        t_oh, p_oh, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    out_ref[...] += counts.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_classes", "interpret"))
def _confusion_pallas(preds: Array, target: Array, num_classes: int, interpret: bool = False) -> Array:
    from metrics_tpu.obs.tracing import trace_span

    with trace_span("ops.confusion_counts", category="kernel"):
        return _confusion_pallas_impl(preds, target, num_classes, interpret)


def _confusion_pallas_impl(preds: Array, target: Array, num_classes: int, interpret: bool) -> Array:
    n = preds.shape[0]
    n_pad = -n % _BLOCK_ROWS
    # pad with index -1: matches no one-hot lane, contributes nothing
    preds_p = jnp.pad(preds.astype(jnp.int32), (0, n_pad), constant_values=-1)
    target_p = jnp.pad(target.astype(jnp.int32), (0, n_pad), constant_values=-1)
    n_blocks = (n + n_pad) // _BLOCK_ROWS

    out = pl.pallas_call(
        functools.partial(_confusion_kernel, num_classes=num_classes),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda j: (j, 0)),
            pl.BlockSpec((_BLOCK_ROWS, 1), lambda j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((num_classes, num_classes), lambda j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((num_classes, num_classes), jnp.int32),
        interpret=interpret,
    )(preds_p.reshape(-1, 1), target_p.reshape(-1, 1))
    return out


@functools.partial(jax.jit, static_argnames=("num_classes",))
def _confusion_xla(preds: Array, target: Array, num_classes: int) -> Array:
    """XLA fallback: one-hot MXU contraction, chunk-scanned over samples so
    peak memory stays O(chunk * C), not O(N * C)."""
    chunk = min(65536, max(1, preds.shape[0]))
    pad = -preds.shape[0] % chunk
    t = jnp.pad(target.astype(jnp.int32), (0, pad), constant_values=-1).reshape(-1, chunk)
    p = jnp.pad(preds.astype(jnp.int32), (0, pad), constant_values=-1).reshape(-1, chunk)

    def body(acc, batch):
        t_c, p_c = batch
        oh_t = jax.nn.one_hot(t_c, num_classes, dtype=jnp.bfloat16)
        oh_p = jax.nn.one_hot(p_c, num_classes, dtype=jnp.bfloat16)
        counts = jax.lax.dot_general(
            oh_t, oh_p, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # per-chunk dot is exact (<= chunk counts per cell); accumulate in
        # int32 so whole-epoch totals stay exact past 2^24 per cell
        return acc + counts.astype(jnp.int32), None

    out, _ = jax.lax.scan(body, jnp.zeros((num_classes, num_classes), jnp.int32), (t, p))
    return out


def _bincount_kernel(x_ref, out_ref, *, num_bins: int):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    x = x_ref[...]  # (BLOCK, 1) int32
    idx = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], num_bins), 1)
    mask = (x == idx).astype(jnp.bfloat16)  # (BLOCK, M)
    ones = jnp.ones((1, x.shape[0]), jnp.bfloat16)
    counts = jax.lax.dot_general(
        ones, mask, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # per-block dot exact (<= BLOCK per bin); int32 cross-block accumulation
    out_ref[...] += counts.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_bins", "interpret"))
def _bincount_pallas(x: Array, num_bins: int, interpret: bool = False) -> Array:
    from metrics_tpu.obs.tracing import trace_span

    with trace_span("ops.bincount", category="kernel"):
        return _bincount_pallas_impl(x, num_bins, interpret)


def _bincount_pallas_impl(x: Array, num_bins: int, interpret: bool) -> Array:
    # keep the streamed (BLOCK, M) bf16 mask within a few MB of VMEM
    block = _BLOCK_ROWS if num_bins <= 512 else 512
    n = x.shape[0]
    n_pad = -n % block
    x_p = jnp.pad(x.astype(jnp.int32), (0, n_pad), constant_values=-1)
    n_blocks = (n + n_pad) // block

    out = pl.pallas_call(
        functools.partial(_bincount_kernel, num_bins=num_bins),
        grid=(n_blocks,),
        in_specs=[pl.BlockSpec((block, 1), lambda j: (j, 0))],
        out_specs=pl.BlockSpec((1, num_bins), lambda j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, num_bins), jnp.int32),
        interpret=interpret,
    )(x_p.reshape(-1, 1))
    return out[0]


# launch-timing wrappers for eager dispatches (same step label per logical
# kernel: the pallas/XLA choice is internal); trace-transparent, one
# predicate per eager call when obs device timing is off
from metrics_tpu.obs.profile import time_launch as _obs_time_launch  # noqa: E402

_timed_confusion_pallas = _obs_time_launch(_confusion_pallas, "ops.confusion_counts")
_timed_confusion_xla = _obs_time_launch(_confusion_xla, "ops.confusion_counts")
_timed_bincount_pallas = _obs_time_launch(_bincount_pallas, "ops.bincount")


def confusion_counts(preds: Array, target: Array, num_classes: int) -> Array:
    """Unnormalized ``(C, C)`` confusion counts, ``[target, pred]`` indexed
    (int32).

    Args:
        preds: ``(N,)`` integer predicted class ids; out-of-range ids
            contribute nothing.
        target: ``(N,)`` integer true class ids; out-of-range ids
            contribute nothing.
        num_classes: ``C``; the pallas streaming tile engages on TPU at
            ``C <= 128`` (count block resident in VMEM, one input pass),
            the one-hot MXU contraction elsewhere.
    """
    if (
        jax.default_backend() == "tpu"
        and preds.shape[0] > 0
        and num_classes <= _MAX_LANE_CLASSES
    ):
        return _timed_confusion_pallas(preds, target, num_classes)
    return _timed_confusion_xla(preds, target, num_classes)


def bincount_counts(x: Array, num_bins: int) -> Array:
    """``(M,)`` int32 histogram of integer values in ``[0, num_bins)``;
    out-of-range values are dropped (the padding contract).

    The pallas tile engages on TPU at ``num_bins <= 2048``; callers on
    other backends (or beyond the bin bound) should use their existing
    formulation — see ``utilities.data._bincount``, which routes here.
    """
    if jax.default_backend() == "tpu" and x.shape[0] > 0 and num_bins <= _MAX_BINS:
        return _timed_bincount_pallas(x, num_bins)
    # fallback: one-hot compare-sum, chunk-scanned over samples so peak
    # memory stays O(chunk * M), not O(N * M)
    x = x.reshape(-1)
    chunk = min(65536, max(1, x.shape[0]))
    pad = -x.shape[0] % chunk
    xc = jnp.pad(x.astype(jnp.int32), (0, pad), constant_values=-1).reshape(-1, chunk)
    bins = jnp.arange(num_bins, dtype=jnp.int32)

    def body(acc, x_c):
        return acc + jnp.sum(x_c[:, None] == bins[None, :], axis=0, dtype=jnp.int32), None

    out, _ = jax.lax.scan(body, jnp.zeros((num_bins,), jnp.int32), xc)
    return out
