"""MinMaxMetric — track the running min/max of a base metric's value.

Behavioral equivalent of reference ``torchmetrics/wrappers/minmax.py:23``.
``min_val``/``max_val`` are deliberately NOT registered states: they are
derived from the base metric's ``compute()`` value, which is already
cross-process synced, so every rank advances them identically — and keeping
them outside the state registry means they survive both the ``forward``
snapshot/restore cycle and ``reset`` (min/max track the whole experiment,
like the reference's buffers, which its ``Metric.reset`` never restores).
"""
from typing import Any, Dict

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.wrappers.abstract import WrapperMetric

Array = jax.Array


class MinMaxMetric(WrapperMetric):
    """Report the base metric's value plus the min/max it has reached over
    all ``compute`` calls.

    Example::

        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> from metrics_tpu.wrappers import MinMaxMetric
        >>> metric = MinMaxMetric(Accuracy())
        >>> metric.update(jnp.asarray([0, 1, 1]), jnp.asarray([0, 1, 0]))
        >>> result = metric.compute()
        >>> sorted(result)
        ['max', 'min', 'raw']
    """

    def __init__(self, base_metric: Metric, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `metrics_tpu.Metric` but received {base_metric}"
            )
        self._base_metric = base_metric
        self.min_val = jnp.asarray(jnp.inf)
        self.max_val = jnp.asarray(-jnp.inf)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._base_metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        """Base value plus updated running min/max."""
        val = self._base_metric.compute()
        if not self._is_suitable_val(val):
            raise RuntimeError(f"Returned value from base metric should be a scalar, but got {val}")
        val32 = jnp.asarray(val, dtype=jnp.float32)
        self.max_val = jnp.maximum(self.max_val, val32)
        self.min_val = jnp.minimum(self.min_val, val32)
        return {"raw": val, "max": self.max_val, "min": self.min_val}

    @staticmethod
    def _is_suitable_val(val: Any) -> bool:
        if isinstance(val, (int, float)):
            return True
        if isinstance(val, (jnp.ndarray, jax.Array)):
            return val.size == 1
        return False
