"""MetricTracker — history of metric values over time (e.g. per epoch).

Behavioral equivalent of reference ``torchmetrics/wrappers/tracker.py:25``:
a list of snapshots of a base metric (or collection); ``increment`` starts a
new timestep; ``compute_all``/``best_metric`` aggregate the history.
"""
from copy import deepcopy
from typing import Any, Dict, List, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.collections import MetricCollection
from metrics_tpu.metric import Metric
from metrics_tpu.utilities.prints import rank_zero_warn
from metrics_tpu.wrappers.abstract import WrapperMetric

Array = jax.Array


class MetricTracker(WrapperMetric):
    """Track a base metric over a sequence of timesteps.

    Args:
        metric: base ``Metric`` or ``MetricCollection`` to snapshot.
        maximize: whether higher is better (bool, or list of bool per
            collection member).

    Example::

        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> from metrics_tpu.wrappers import MetricTracker
        >>> tracker = MetricTracker(Accuracy())
        >>> for epoch in range(3):
        ...     tracker.increment()
        ...     tracker.update(jnp.asarray([0, 1, 1]), jnp.asarray([0, 1, epoch % 2]))
        >>> tracker.n_steps
        3
    """

    def __init__(self, metric: Union[Metric, MetricCollection], maximize: Union[bool, List[bool]] = True) -> None:
        super().__init__()
        if not isinstance(metric, (Metric, MetricCollection)):
            raise TypeError(
                "Metric arg need to be an instance of a metrics_tpu `Metric` or `MetricCollection`"
                f" but got {metric}"
            )
        self._base_metric = metric
        if not isinstance(maximize, (bool, list)):
            raise ValueError("Argument `maximize` should either be a single bool or list of bool")
        if isinstance(maximize, list):
            if not isinstance(metric, MetricCollection):
                raise ValueError("Argument `maximize` can only be a list when `metric` is a `MetricCollection`")
            if len(maximize) != len(metric):
                raise ValueError("The len of argument `maximize` should match the length of the metric collection")
        self.maximize = maximize
        self._metrics: List[Union[Metric, MetricCollection]] = []
        self._increment_called = False

    @property
    def n_steps(self) -> int:
        """Number of timesteps tracked."""
        return len(self._metrics)

    def increment(self) -> None:
        """Start a new timestep by snapshotting a fresh copy of the base."""
        self._increment_called = True
        self._invalidate()
        self._metrics.append(deepcopy(self._base_metric))

    def forward(self, *args: Any, **kwargs: Any) -> Any:
        self._check_for_increment("forward")
        self._invalidate()
        self._update_count += 1
        return self._metrics[-1](*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        self._check_for_increment("update")
        self._metrics[-1].update(*args, **kwargs)

    def compute(self) -> Any:
        """Value of the current (latest) timestep."""
        self._check_for_increment("compute")
        return self._metrics[-1].compute()

    def compute_all(self) -> Union[Array, Dict[str, Array]]:
        """Values of every tracked timestep, stacked."""
        self._check_for_increment("compute_all")
        vals = [metric.compute() for metric in self._metrics]
        if isinstance(vals[0], dict):  # MetricCollection or dict-returning base
            keys = vals[0].keys()
            return {k: jnp.stack([jnp.asarray(v[k]) for v in vals], axis=0) for k in keys}
        return jnp.stack([jnp.asarray(v) for v in vals], axis=0)

    def reset(self) -> None:
        """Reset the CURRENT timestep's metric."""
        self._invalidate()
        if self._metrics:
            self._metrics[-1].reset()

    def reset_all(self) -> None:
        """Reset every tracked timestep."""
        self._invalidate()
        for metric in self._metrics:
            metric.reset()

    def best_metric(
        self, return_step: bool = False
    ) -> Union[Array, Tuple[Array, int], Dict[str, Array], Tuple[Dict[str, Array], Dict[str, int]]]:
        """Best value over time (and optionally the step it occurred at)."""
        res = self.compute_all()
        if isinstance(res, dict):
            maximize = self.maximize if isinstance(self.maximize, list) else [self.maximize] * len(res)
            values: Dict[str, Array] = {}
            steps: Dict[str, int] = {}
            for (k, v), m in zip(res.items(), maximize):
                try:
                    arr = np.asarray(v)
                    idx = int(np.argmax(arr) if m else np.argmin(arr))
                    values[k], steps[k] = v[idx], idx
                except (ValueError, TypeError) as error:
                    rank_zero_warn(
                        f"Encountered the following error when trying to get the best metric for metric {k}:"
                        f" {error}. Returning `None` instead.",
                        UserWarning,
                    )
                    values[k], steps[k] = None, None  # type: ignore[assignment]
            return (values, steps) if return_step else values
        try:
            arr = np.asarray(res)
            idx = int(np.argmax(arr) if self.maximize else np.argmin(arr))
            return (res[idx], idx) if return_step else res[idx]
        except (ValueError, TypeError) as error:
            rank_zero_warn(
                f"Encountered the following error when trying to get the best metric: {error}."
                " Returning `None` instead.",
                UserWarning,
            )
            return (None, None) if return_step else None  # type: ignore[return-value]

    def _check_for_increment(self, method: str) -> None:
        if not self._increment_called:
            raise ValueError(f"`{method}` cannot be called before `.increment()` has been called")
