"""WrapperMetric — lifecycle-correct base for metrics that wrap metrics.

The reference has no such base class; its wrappers inherit the plain
``Metric.forward`` whose state cache covers only ``self._defaults``
(reference ``metric.py:258``) — child-metric state (where wrappers actually
accumulate) is reset and never restored, so a reference wrapper's ``forward``
silently drops history. Here the snapshot/restore used by both ``forward``
and ``sync_context`` recurses into wrapped child metrics, making the fused
batch-value path safe for wrappers.
"""
from typing import Any, Dict, Iterator, List, Union

import jax

from metrics_tpu.collections import MetricCollection
from metrics_tpu.metric import Metric

Array = jax.Array


class WrapperMetric(Metric):
    """Base class for wrapper metrics; children join the lifecycle snapshot.

    Child metrics are discovered on instance attributes: a ``Metric``, a
    list/tuple of metrics, or a ``MetricCollection``.
    """

    full_state_update = True

    def _wrapped_metrics(self) -> Iterator[Metric]:
        for value in self.__dict__.values():
            if isinstance(value, Metric):
                yield value
            elif isinstance(value, MetricCollection):
                yield from value.values(copy_state=False)
            elif isinstance(value, (list, tuple)):
                yield from (m for m in value if isinstance(m, Metric))

    def _snapshot_state(self) -> Dict[str, Union[Array, List]]:
        snap = super()._snapshot_state()
        snap["__children__"] = [(c._snapshot_state(), c._update_count) for c in self._wrapped_metrics()]
        return snap

    def _restore_state(self, cache: Dict[str, Union[Array, List]]) -> None:
        super()._restore_state({k: v for k, v in cache.items() if k != "__children__"})
        for child, (child_snap, child_count) in zip(self._wrapped_metrics(), cache.get("__children__", [])):
            child._restore_state(child_snap)
            child._update_count = child_count
            child._computed = None

    def reset(self) -> None:
        super().reset()
        for child in self._wrapped_metrics():
            child.reset()

    def _invalidate(self) -> None:
        """Drop the cached compute value after an out-of-band state change."""
        self._computed = None
