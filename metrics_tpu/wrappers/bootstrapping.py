"""BootStrapper — bootstrap confidence intervals around any metric.

Behavioral equivalent of reference ``torchmetrics/wrappers/bootstrapping.py:48``
(``BootStrapper``; sampler ``:25``): ``num_bootstraps`` independent bootstrap
replicates of a base metric; every ``update`` feeds each replicate a
resampled version of the batch (poisson or multinomial bootstrap);
``compute`` reports mean/std/quantile/raw over the replicates' values.

TPU-first design (SURVEY §7.4): instead of the reference's N deep copies
dispatching N updates per batch, replicate STATES are one stacked pytree
with a leading bootstrap axis and every update is ONE jitted
``jax.vmap``-ed program — a single dispatch resamples (gather) and updates
all replicates on device:

* ``"multinomial"``: a ``(B, N)`` index matrix gathers each replicate's
  resample; works for any metric whose states are fixed-shape arrays with
  sum/min/max reductions (the ``make_step`` merge contract).
* ``"poisson"``: resample sizes vary per replicate (reference semantics),
  which breaks static shapes — UNLESS the base metric supports per-sample
  weights (``supports_sample_weights``, e.g. ``MeanMetric``): a sample
  drawn ``c ~ Poisson(1)`` times is exactly a weight multiplier of ``c``,
  so the vmapped update passes poisson count vectors as weights.

Metrics outside those contracts (sample-buffer states, host-side text
metrics, poisson without weight support) fall back to the reference's
deep-copy loop with host-side index resampling.
"""
from copy import deepcopy
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.utilities.buffers import CapacityBuffer
from metrics_tpu.utilities.data import apply_to_collection
from metrics_tpu.wrappers.abstract import WrapperMetric

Array = jax.Array

_STATE_PREFIX = "_boot_"


def _bootstrap_sampler(size: int, sampling_strategy: str, rng: np.random.Generator) -> np.ndarray:
    """Draw resample row indices (reference ``wrappers/bootstrapping.py:25``)."""
    if sampling_strategy == "poisson":
        p = rng.poisson(1, size)
        return np.repeat(np.arange(size), p)
    if sampling_strategy == "multinomial":
        return rng.integers(0, size, size)
    raise ValueError("Unknown sampling strategy")


def _apply_resample(
    step: Any, boot: Dict[str, Array], matrix: Array, strategy: str, args: tuple, kwargs: dict
) -> Dict[str, Array]:
    """Fold one drawn resample matrix into the stacked replicate states.

    The single definition of the resample semantics, shared by the eager
    wrapper's vmapped update (numpy-drawn matrices) and the pure-step path
    (jax.random-drawn matrices): ``matrix`` is ``(B, N)`` gather indices for
    multinomial, or ``(B, N)`` Poisson counts applied as per-sample weight
    multipliers for poisson. Array leaves whose leading dim equals the batch
    size are resampled; everything else passes through unchanged.
    """
    keys = sorted(kwargs)
    n_pos = len(args)
    leaves = list(args) + [kwargs[k] for k in keys]
    size = matrix.shape[1]
    batch_mask = [getattr(a, "ndim", 0) >= 1 and a.shape[0] == size for a in leaves]
    if strategy == "multinomial":

        def one(state, index, *flat):
            resampled = [a[index] if m else a for a, m in zip(flat, batch_mask)]
            new_state, _ = step(state, *resampled[:n_pos], **dict(zip(keys, resampled[n_pos:])))
            return new_state

        return jax.vmap(one, in_axes=(0, 0) + (None,) * len(leaves))(boot, matrix, *leaves)
    # poisson: a sample drawn c ~ Poisson(1) times is a weight multiplier of c
    value = leaves[0]
    weight = kwargs.get("weight", args[1] if len(args) > 1 else jnp.ones(size, jnp.float32))
    weight = jnp.broadcast_to(jnp.asarray(weight, jnp.float32), (size,))

    def one_w(state, c):
        new_state, _ = step(state, value, weight * c)
        return new_state

    return jax.vmap(one_w, in_axes=(0, 0))(boot, matrix.astype(jnp.float32))


class BootStrapper(WrapperMetric):
    """Compute bootstrapped statistics of a base metric.

    Args:
        base_metric: the metric to bootstrap.
        num_bootstraps: number of independent bootstrap replicates.
        mean / std / raw: which statistics ``compute`` returns.
        quantile: optional quantile(s) of the bootstrap distribution.
        sampling_strategy: ``"poisson"`` (sample counts ~ Poisson(1)) or
            ``"multinomial"`` (sample-with-replacement to the same size).

    Example::

        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> from metrics_tpu.wrappers import BootStrapper
        >>> boot = BootStrapper(Accuracy(), num_bootstraps=20, seed=123)
        >>> boot.update(jnp.asarray([0, 1, 2, 3]), jnp.asarray([0, 1, 2, 3]))
        >>> sorted(boot.compute())
        ['mean', 'std']
    """

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Sequence[float]]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `metrics_tpu.Metric` but received {base_metric}"
            )
        self.base_metric = base_metric
        self.num_bootstraps = num_bootstraps

        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw

        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling}"
                f" but received {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy
        self._seed = seed  # make_step's pure-step factory derives its PRNG key from this
        self._rng = np.random.default_rng(seed)
        self._probe_ok: set = set()  # batch signatures that passed the trace probe

        self._vmap = self._try_build_vmap_path()
        if self._vmap:
            self.metrics: list = []  # replicate state lives in the stacked pytree
        else:
            self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]

    # ------------------------------------------------------------------
    # vmapped fast path: stacked replicate states, one dispatch per update
    # ------------------------------------------------------------------

    def _try_build_vmap_path(self) -> bool:
        poisson = self.sampling_strategy == "poisson"
        if poisson and not getattr(self.base_metric, "supports_sample_weights", False):
            return False
        try:
            from metrics_tpu.steps import make_step

            self._init, self._step, self._compute_one = make_step(self.base_metric, with_value=False)
        except ValueError:  # unbounded list states
            return False
        template = self._init()
        base = self.base_metric
        if any(isinstance(v, CapacityBuffer) for v in template.values()) or not all(
            base._reductions.get(n) in ("sum", "max", "min") for n in template
        ):
            return False
        # each leaf becomes a registered state with a leading bootstrap axis
        # and the base metric's own reduction — reset/serialization/DDP sync
        # come from the normal Metric machinery
        for name, value in template.items():
            stacked = jnp.broadcast_to(value[None], (self.num_bootstraps,) + value.shape)
            self.add_state(_STATE_PREFIX + name, default=jnp.array(stacked), dist_reduce_fx=base._reductions[name])
        self._state_names = list(template)
        return True

    def _stacked_state(self) -> Dict[str, Array]:
        return {n: getattr(self, _STATE_PREFIX + n) for n in self._state_names}

    def _set_stacked_state(self, state: Dict[str, Array]) -> None:
        for n in self._state_names:
            setattr(self, _STATE_PREFIX + n, state[n])

    def _vmap_update(self, size: int, args: tuple, kwargs: dict) -> bool:
        """One vmapped dispatch for all replicates; False -> use fallback.

        Array leaves whose leading dim is the batch size are resampled;
        everything else (scalars, config values) passes through unchanged —
        the same split the eager loop's ``apply_to_collection`` resample
        makes.
        """
        keys = sorted(kwargs)
        n_pos = len(args)
        leaves = list(args) + [kwargs[k] for k in keys]
        if not any(
            isinstance(a, (jnp.ndarray, jax.Array, np.ndarray)) and getattr(a, "ndim", 0) >= 1 and a.shape[0] == size
            for a in leaves
        ):
            return False
        step = self._step

        def run(matrix):
            return _apply_resample(step, self._stacked_state(), matrix, self.sampling_strategy, args, kwargs)

        if self.sampling_strategy == "multinomial":
            dummy = jnp.zeros((self.num_bootstraps, size), jnp.int32)
            draw = lambda: jnp.asarray(self._rng.integers(0, size, (self.num_bootstraps, size)))
        else:
            dummy = jnp.ones((self.num_bootstraps, size), jnp.float32)
            draw = lambda: jnp.asarray(self._rng.poisson(1, (self.num_bootstraps, size)), dtype=jnp.float32)

        # Probe trace-compatibility with a dummy index/count matrix BEFORE
        # consuming RNG, so a rejected batch (metric not trace-ready,
        # untraceable passthrough args, non-per-sample poisson weights) does
        # not advance the seed stream — a seeded run falls back with the
        # identical resample sequence it would have had on the fallback path
        # from the start.
        def _sig(a: Any) -> Any:
            return (getattr(a, "shape", None), str(getattr(a, "dtype", type(a).__name__)))

        signature = (self.sampling_strategy, n_pos, tuple(keys), tuple(_sig(a) for a in leaves))
        if signature not in self._probe_ok:
            try:
                jax.eval_shape(run, dummy)
            except (TypeError, ValueError):
                return False
            self._probe_ok.add(signature)
        try:
            new = run(draw())
        except (TypeError, ValueError):
            return False
        self._set_stacked_state(new)
        return True

    def _materialize_copies(self) -> List[Metric]:
        """Per-replicate metric copies loaded from the stacked states, so a
        mid-stream fallback keeps everything accumulated so far."""
        copies = []
        for b in range(self.num_bootstraps):
            copy = deepcopy(self.base_metric)
            copy.reset()
            copy.load_state_pytree({n: getattr(self, _STATE_PREFIX + n)[b] for n in self._state_names})
            copy._update_count = 1
            copies.append(copy)
        return copies

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Resample the batch once per replicate and update it (one vmapped
        dispatch on the fast path; a per-copy loop otherwise)."""
        args_sizes = apply_to_collection(args, (jnp.ndarray, jax.Array), lambda x: x.shape[0])
        kwargs_sizes = apply_to_collection(kwargs, (jnp.ndarray, jax.Array), lambda x: x.shape[0])
        if len(args_sizes) > 0:
            size = args_sizes[0]
        elif len(kwargs_sizes) > 0:
            size = next(iter(kwargs_sizes.values()))
        else:
            raise ValueError("None of the input contained tensors, so could not determine the sampling size")

        if self._vmap and self._vmap_update(size, args, kwargs):
            return
        if not self.metrics:
            # vmap path rejected this batch: materialize per-replicate copies
            # FROM the stacked states so prior vmapped updates are kept
            self.metrics = self._materialize_copies()
            self._vmap = False
        for idx in range(self.num_bootstraps):
            sample_idx = jnp.asarray(_bootstrap_sampler(size, self.sampling_strategy, self._rng))
            if sample_idx.size == 0:  # poisson can draw an empty resample
                continue
            new_args = apply_to_collection(args, (jnp.ndarray, jax.Array), jnp.take, sample_idx, axis=0)
            new_kwargs = apply_to_collection(kwargs, (jnp.ndarray, jax.Array), jnp.take, sample_idx, axis=0)
            self.metrics[idx].update(*new_args, **new_kwargs)

    def compute(self) -> Dict[str, Array]:
        """Statistics over the bootstrap replicates' computed values."""
        if self._vmap:
            computed_vals = jax.vmap(self._compute_one)(self._stacked_state())
        else:
            computed_vals = jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], axis=0)
        output: Dict[str, Array] = {}
        if self.mean:
            output["mean"] = computed_vals.mean(axis=0)
        if self.std:
            output["std"] = computed_vals.std(axis=0, ddof=1)
        if self.quantile is not None:
            output["quantile"] = jnp.quantile(computed_vals, jnp.asarray(self.quantile), axis=0)
        if self.raw:
            output["raw"] = computed_vals
        return output
