"""BootStrapper — bootstrap confidence intervals around any metric.

Behavioral equivalent of reference ``torchmetrics/wrappers/bootstrapping.py:48``
(``BootStrapper``; sampler ``:25``): keeps ``num_bootstraps`` independent
copies of a base metric; every ``update`` feeds each copy a resampled version
of the batch (poisson or multinomial bootstrap); ``compute`` reports
mean/std/quantile/raw over the copies' values.

TPU notes: resample *indices* are drawn host-side with numpy (cheap, O(batch))
so each copy's jitted ``update`` kernel still sees a static batch shape for
the ``"multinomial"`` strategy. The ``"poisson"`` strategy produces a
variable-size resample by construction (reference semantics); its gather is
built host-side and the inner metric update remains jitted per unique shape.
"""
from copy import deepcopy
from typing import Any, Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import apply_to_collection
from metrics_tpu.wrappers.abstract import WrapperMetric

Array = jax.Array


def _bootstrap_sampler(size: int, sampling_strategy: str, rng: np.random.Generator) -> np.ndarray:
    """Draw resample row indices (reference ``wrappers/bootstrapping.py:25``)."""
    if sampling_strategy == "poisson":
        p = rng.poisson(1, size)
        return np.repeat(np.arange(size), p)
    if sampling_strategy == "multinomial":
        return rng.integers(0, size, size)
    raise ValueError("Unknown sampling strategy")


class BootStrapper(WrapperMetric):
    """Compute bootstrapped statistics of a base metric.

    Args:
        base_metric: the metric to bootstrap.
        num_bootstraps: number of independent resampled copies.
        mean / std / raw: which statistics ``compute`` returns.
        quantile: optional quantile(s) of the bootstrap distribution.
        sampling_strategy: ``"poisson"`` (sample counts ~ Poisson(1)) or
            ``"multinomial"`` (sample-with-replacement to the same size).

    Example::

        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> from metrics_tpu.wrappers import BootStrapper
        >>> boot = BootStrapper(Accuracy(), num_bootstraps=20, seed=123)
        >>> boot.update(jnp.asarray([0, 1, 2, 3]), jnp.asarray([0, 1, 2, 3]))
        >>> sorted(boot.compute())
        ['mean', 'std']
    """

    def __init__(
        self,
        base_metric: Metric,
        num_bootstraps: int = 10,
        mean: bool = True,
        std: bool = True,
        quantile: Optional[Union[float, Sequence[float]]] = None,
        raw: bool = False,
        sampling_strategy: str = "poisson",
        seed: Optional[int] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(base_metric, Metric):
            raise ValueError(
                f"Expected base metric to be an instance of `metrics_tpu.Metric` but received {base_metric}"
            )
        self.base_metric = base_metric
        self.metrics = [deepcopy(base_metric) for _ in range(num_bootstraps)]
        self.num_bootstraps = num_bootstraps

        self.mean = mean
        self.std = std
        self.quantile = quantile
        self.raw = raw

        allowed_sampling = ("poisson", "multinomial")
        if sampling_strategy not in allowed_sampling:
            raise ValueError(
                f"Expected argument ``sampling_strategy`` to be one of {allowed_sampling}"
                f" but received {sampling_strategy}"
            )
        self.sampling_strategy = sampling_strategy
        self._rng = np.random.default_rng(seed)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Resample the batch once per bootstrap copy and update it."""
        args_sizes = apply_to_collection(args, (jnp.ndarray, jax.Array), lambda x: x.shape[0])
        kwargs_sizes = apply_to_collection(kwargs, (jnp.ndarray, jax.Array), lambda x: x.shape[0])
        if len(args_sizes) > 0:
            size = args_sizes[0]
        elif len(kwargs_sizes) > 0:
            size = next(iter(kwargs_sizes.values()))
        else:
            raise ValueError("None of the input contained tensors, so could not determine the sampling size")

        for idx in range(self.num_bootstraps):
            sample_idx = jnp.asarray(_bootstrap_sampler(size, self.sampling_strategy, self._rng))
            if sample_idx.size == 0:  # poisson can draw an empty resample
                continue
            new_args = apply_to_collection(args, (jnp.ndarray, jax.Array), jnp.take, sample_idx, axis=0)
            new_kwargs = apply_to_collection(kwargs, (jnp.ndarray, jax.Array), jnp.take, sample_idx, axis=0)
            self.metrics[idx].update(*new_args, **new_kwargs)

    def compute(self) -> Dict[str, Array]:
        """Statistics over the bootstrap copies' computed values."""
        computed_vals = jnp.stack([jnp.asarray(m.compute()) for m in self.metrics], axis=0)
        output: Dict[str, Array] = {}
        if self.mean:
            output["mean"] = computed_vals.mean(axis=0)
        if self.std:
            output["std"] = computed_vals.std(axis=0, ddof=1)
        if self.quantile is not None:
            output["quantile"] = jnp.quantile(computed_vals, jnp.asarray(self.quantile), axis=0)
        if self.raw:
            output["raw"] = computed_vals
        return output
