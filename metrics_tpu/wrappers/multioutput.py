"""MultioutputWrapper — evaluate a base metric per output dimension.

Behavioral equivalent of reference ``torchmetrics/wrappers/multioutput.py:23``
(``MultioutputWrapper``; NaN-row removal helper ``:11``).
"""
from copy import deepcopy
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.metric import Metric
from metrics_tpu.utilities.data import apply_to_collection
from metrics_tpu.wrappers.abstract import WrapperMetric

Array = jax.Array


def _get_nan_indices(*tensors: Array) -> Array:
    """Rows where ANY input tensor has a NaN (reference ``multioutput.py:11``)."""
    if len(tensors) == 0:
        raise ValueError("Must pass at least one tensor as argument")
    sentinel_nan_indices = None
    for tensor in tensors:
        permuted = tensor.reshape(tensor.shape[0], -1)
        nan_indices = jnp.any(jnp.isnan(permuted), axis=1)
        sentinel_nan_indices = nan_indices if sentinel_nan_indices is None else sentinel_nan_indices | nan_indices
    return sentinel_nan_indices


class MultioutputWrapper(WrapperMetric):
    """Clone a base metric per output along ``output_dim``; optionally drop
    NaN rows per output before updating.

    Example::

        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanMetric
        >>> from metrics_tpu.wrappers import MultioutputWrapper
        >>> values = jnp.asarray([[1.0, 10.0], [2.0, 20.0], [3.0, 30.0]])
        >>> mean_per_output = MultioutputWrapper(MeanMetric(), num_outputs=2)
        >>> mean_per_output.update(values)
        >>> mean_per_output.compute().shape
        (2,)
    """

    is_differentiable = False

    def __init__(
        self,
        base_metric: Metric,
        num_outputs: int,
        output_dim: int = -1,
        remove_nans: bool = True,
        squeeze_outputs: bool = True,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.metrics = [deepcopy(base_metric) for _ in range(num_outputs)]
        self.output_dim = output_dim
        self.remove_nans = remove_nans
        self.squeeze_outputs = squeeze_outputs

    def _get_args_kwargs_by_output(self, *args: Array, **kwargs: Array) -> List[Tuple]:
        """Slice inputs along ``output_dim`` per output, with NaN-row removal."""
        args_kwargs_by_output = []
        for i in range(len(self.metrics)):
            selected_args = apply_to_collection(
                args, (jnp.ndarray, jax.Array), jnp.take, indices=jnp.asarray([i]), axis=self.output_dim
            )
            selected_kwargs = apply_to_collection(
                kwargs, (jnp.ndarray, jax.Array), jnp.take, indices=jnp.asarray([i]), axis=self.output_dim
            )
            if self.remove_nans:
                tensors = list(selected_args) + list(selected_kwargs.values())
                if tensors:
                    nan_idxs = np.asarray(_get_nan_indices(*tensors))
                    keep = jnp.asarray(np.flatnonzero(~nan_idxs))
                    selected_args = [jnp.take(arg, keep, axis=0) for arg in selected_args]
                    selected_kwargs = {k: jnp.take(v, keep, axis=0) for k, v in selected_kwargs.items()}
            if self.squeeze_outputs:
                selected_args = [arg.squeeze(self.output_dim) for arg in selected_args]
                selected_kwargs = {k: v.squeeze(self.output_dim) for k, v in selected_kwargs.items()}
            args_kwargs_by_output.append((selected_args, selected_kwargs))
        return args_kwargs_by_output

    def update(self, *args: Any, **kwargs: Any) -> None:
        reshaped_args_kwargs = self._get_args_kwargs_by_output(*args, **kwargs)
        for metric, (selected_args, selected_kwargs) in zip(self.metrics, reshaped_args_kwargs):
            metric.update(*selected_args, **selected_kwargs)

    def compute(self) -> Array:
        """Stack per-output computed values."""
        return jnp.stack([m.compute() for m in self.metrics], axis=0)
