"""ClasswiseWrapper — unroll per-class results into a flat dict.

Behavioral equivalent of reference ``torchmetrics/wrappers/classwise.py:8``.
"""
from typing import Any, Dict, List, Optional

import jax

from metrics_tpu.metric import Metric
from metrics_tpu.wrappers.abstract import WrapperMetric

Array = jax.Array


class ClasswiseWrapper(WrapperMetric):
    """Wrap a per-class metric (``average=None``-style output) so ``compute``
    returns ``{"metricname_label": scalar}`` entries.

    Example::

        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy
        >>> from metrics_tpu.wrappers import ClasswiseWrapper
        >>> metric = ClasswiseWrapper(Accuracy(num_classes=3, average=None))
        >>> metric.update(jnp.asarray([0, 1, 2]), jnp.asarray([0, 1, 1]))
        >>> sorted(metric.compute())
        ['accuracy_0', 'accuracy_1', 'accuracy_2']
    """

    def __init__(self, metric: Metric, labels: Optional[List[str]] = None) -> None:
        super().__init__()
        if not isinstance(metric, Metric):
            raise ValueError(f"Expected argument `metric` to be an instance of `metrics_tpu.Metric` but got {metric}")
        if labels is not None and not (isinstance(labels, list) and all(isinstance(lab, str) for lab in labels)):
            raise ValueError(f"Expected argument `labels` to either be `None` or a list of strings but got {labels}")
        self.metric = metric
        self.labels = labels

    def _convert(self, x: Array) -> Dict[str, Array]:
        name = self.metric.__class__.__name__.lower()
        if self.labels is None:
            return {f"{name}_{i}": val for i, val in enumerate(x)}
        return {f"{name}_{lab}": val for lab, val in zip(self.labels, x)}

    def update(self, *args: Any, **kwargs: Any) -> None:
        self.metric.update(*args, **kwargs)

    def compute(self) -> Dict[str, Array]:
        return self._convert(self.metric.compute())
