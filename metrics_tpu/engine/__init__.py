"""Ahead-of-time metric programs, persistent executables, warm revival.

The execution-engine subsystem (``docs/execution_engine.md``): one metric
definition runs behind a pluggable :class:`ExecutionEngine` — eager CPU
(the reference's no-compile semantics), ``jax.jit`` (today's default), or
AOT with executables serialized through a :class:`ProgramStore` keyed by
:class:`ProgramKey` (tenant schema fingerprint x input shapes/dtypes x
static config x backend x jax version x topology). The serving tier uses
it to eliminate cold starts: ``Aggregator(engine="aot")`` pre-lowers its
per-tenant stacked-fold programs at registration, checkpoints carry a
warmup manifest, and a revived node's ``warmup()`` restores states AND
executables together — first fold, zero backend compiles.
"""
from metrics_tpu.engine.engine import (
    AotEngine,
    CompiledProgram,
    EagerEngine,
    ExecutionEngine,
    JitEngine,
    compile_program,
    configure,
    default_store,
    environment_manifest,
    get_engine,
    reset_memory_cache,
)
from metrics_tpu.engine.keys import (
    ProgramKey,
    abstractify,
    environment_mismatches,
    input_signature,
    topology_fingerprint,
)
from metrics_tpu.engine.store import ProgramStore

__all__ = [
    "AotEngine",
    "CompiledProgram",
    "EagerEngine",
    "ExecutionEngine",
    "JitEngine",
    "ProgramKey",
    "ProgramStore",
    "abstractify",
    "compile_program",
    "configure",
    "default_store",
    "environment_manifest",
    "environment_mismatches",
    "get_engine",
    "input_signature",
    "reset_memory_cache",
    "topology_fingerprint",
]
