"""The execution-engine layer: one metric definition, pluggable backends.

PAPER.md's reference runs eager torch ops — no compile step, no cold
start. The tracing/XLA pipeline buys this repo its ~800x hot-path wins but
introduces a latency class the reference never had: the first call of
every distinct program pays trace + lower + backend compile. This module
makes that cost a *managed artifact* instead of an ambient tax:

* :class:`ExecutionEngine` — the protocol. An engine takes a lowerable
  target (a jitted callable, or the ``make_epoch`` family's epoch wrapper,
  which re-exports ``.lower``) plus a :class:`~metrics_tpu.engine.keys.ProgramKey`
  and returns the callable to execute with. The split follows PAPER.md's
  L1/L2 cut: the stateful class API stays eager (L1), the pure kernels
  (L2) are what engines compile and cache.
* :class:`EagerEngine` — no compile ever: the target's Python body runs
  op-by-op. The reference semantics, for debugging and tiny-workload CPU
  serving.
* :class:`JitEngine` — today's behavior: ``jax.jit`` with its in-process
  cache. First call per signature compiles.
* :class:`AotEngine` — ahead-of-time: programs are lowered on
  ``ShapeDtypeStruct``s, compiled once, and **serialized through a
  persistent** :class:`~metrics_tpu.engine.ProgramStore`. A later process
  (a revived serving node, a fresh autoscale replica) loads the executable
  with zero backend compiles. :func:`compile_program` is the engine's
  heart and is also usable standalone.

Every :func:`compile_program` resolution is counted — ``compile.cache_hits
{step=,tier=memory|disk}`` / ``compile.cache_misses{step=}`` — through the
same registry the jax.monitoring listener feeds, so warm-start efficacy is
a first-class observable (``obs.snapshot()`` / ``/metrics``).
"""
import os
import threading
from typing import Any, Callable, Dict, Optional, Tuple

from metrics_tpu.engine.keys import ProgramKey, abstractify
from metrics_tpu.engine.store import ProgramStore
from metrics_tpu.obs.registry import inc as _obs_inc

__all__ = [
    "AotEngine",
    "CompiledProgram",
    "EagerEngine",
    "ExecutionEngine",
    "JitEngine",
    "compile_program",
    "configure",
    "default_store",
    "get_engine",
    "reset_memory_cache",
]

_ENV_STORE = "METRICS_TPU_PROGRAM_CACHE"

_lock = threading.Lock()
_config: Dict[str, Any] = {"store_dir": os.environ.get(_ENV_STORE) or None}
_default_store: Optional[ProgramStore] = None
# process-level registry of already-resolved programs: digest -> program.
# The memory tier exists so a node asks the disk exactly once per program.
_programs: Dict[str, "CompiledProgram"] = {}


def configure(store_dir: "os.PathLike | str | None" = None) -> Dict[str, Any]:
    """Set the default :class:`ProgramStore` directory (None disables the
    disk tier for engines that don't carry their own store). Returns the
    live config."""
    global _default_store
    with _lock:
        _config["store_dir"] = None if store_dir is None else os.fspath(store_dir)
        _default_store = None
    return dict(_config)


def default_store() -> Optional[ProgramStore]:
    """The configured default store (lazily constructed), or None."""
    global _default_store
    with _lock:
        if _default_store is None and _config["store_dir"] is not None:
            _default_store = ProgramStore(_config["store_dir"])
        return _default_store


def reset_memory_cache() -> int:
    """Drop every in-memory resolved program (the disk store is untouched);
    returns the number dropped. Tests and cold-vs-warm benchmarks use this
    to re-measure the disk tier inside one process."""
    with _lock:
        n = len(_programs)
        _programs.clear()
    return n


class CompiledProgram:
    """One resolved executable: ``key`` + the callable + where it came from
    (``"memory"`` / ``"disk"`` / ``"compiled"``)."""

    __slots__ = ("key", "source", "_call")

    def __init__(self, key: ProgramKey, call: Callable, source: str) -> None:
        self.key = key
        self.source = source
        self._call = call

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self._call(*args, **kwargs)

    def __repr__(self) -> str:
        return f"CompiledProgram(step={self.key.step!r}, source={self.source!r})"


def compile_program(
    target: Any,
    key: ProgramKey,
    *args: Any,
    store: Optional[ProgramStore] = None,
    use_default_store: bool = True,
    **kwargs: Any,
) -> CompiledProgram:
    """Resolve the executable for calling ``target`` with ``(args, kwargs)``.

    Resolution order — each tier counted under its own label:

    1. **memory** (``compile.cache_hits{tier=memory}``): this process
       already resolved the digest.
    2. **disk** (``compile.cache_hits{tier=disk}``): the store holds a
       valid serialized executable — deserialized straight into the
       runtime, zero backend compiles.
    3. **compile** (``compile.cache_misses``): AOT trace+lower+compile on
       ``ShapeDtypeStruct``s (concrete/donated buffers are never read),
       then serialize into the store for the next process.

    ``target`` must expose ``.lower`` (a ``jax.jit`` result or the
    ``make_epoch`` family's epoch wrapper). ``args``/``kwargs`` may be
    concrete arrays or ``ShapeDtypeStruct``s — only metadata is used.
    """
    digest = key.digest()
    with _lock:
        hit = _programs.get(digest)
    if hit is not None:
        _obs_inc("compile.cache_hits", step=key.step, tier="memory")
        return hit
    if store is None and use_default_store:
        store = default_store()
    if store is not None:
        loaded = store.load(key)
        if loaded is not None:
            program = CompiledProgram(key, loaded, "disk")
            _obs_inc("compile.cache_hits", step=key.step, tier="disk")
            with _lock:
                _programs[digest] = program
            return program
    _obs_inc("compile.cache_misses", step=key.step)
    from metrics_tpu.obs.recompile import suppress_note_trace

    lower = getattr(target, "lower", None)
    if lower is None:
        raise TypeError(
            f"compile_program target for {key.step!r} has no .lower — pass a"
            " jax.jit result or a make_epoch/make_stream_step/"
            "make_collection_epoch epoch (jit_epoch=True)"
        )
    aval_args, aval_kwargs = abstractify(args, kwargs)
    with suppress_note_trace():
        compiled = lower(*aval_args, **aval_kwargs).compile()
    if store is not None:
        store.save(key, compiled)
    program = CompiledProgram(key, compiled, "compiled")
    with _lock:
        _programs[digest] = program
    return program


class ExecutionEngine:
    """Protocol-ish base: an engine resolves (target, key, call signature)
    to the callable the hot path executes. Subclasses override
    :meth:`prepare`; ``name`` selects them by string."""

    name = "abstract"

    def prepare(self, target: Any, key: ProgramKey, *args: Any, **kwargs: Any) -> Callable:
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"


class EagerEngine(ExecutionEngine):
    """No compilation: execute the target's eager/Python form. ``target``
    here is the UN-jitted body (callers pass the right form — e.g.
    ``make_epoch(..., engine="eager")`` keeps the epoch un-jitted)."""

    name = "eager"

    def prepare(self, target: Any, key: ProgramKey, *args: Any, **kwargs: Any) -> Callable:
        return getattr(target, "__eager__", target)


class JitEngine(ExecutionEngine):
    """Status quo: the jitted target itself (in-process jit cache, first
    call per signature compiles)."""

    name = "jit"

    def prepare(self, target: Any, key: ProgramKey, *args: Any, **kwargs: Any) -> Callable:
        return target


class AotEngine(ExecutionEngine):
    """Ahead-of-time with a persistent executable store.

    Args:
        store: the :class:`ProgramStore` to load/save serialized
            executables through. ``None`` uses the module default
            (:func:`configure`); if that is also unset the engine still
            AOT-compiles (memory tier only) — correct, just not
            persistent.
    """

    name = "aot"

    def __init__(self, store: Optional[ProgramStore] = None) -> None:
        self.store = store

    def prepare(self, target: Any, key: ProgramKey, *args: Any, **kwargs: Any) -> Callable:
        return compile_program(target, key, *args, store=self.store, **kwargs)

    def __repr__(self) -> str:
        return f"AotEngine(store={self.store!r})"


_ENGINES: Dict[str, Callable[[], ExecutionEngine]] = {
    "eager": EagerEngine,
    "jit": JitEngine,
    "aot": AotEngine,
}


def get_engine(spec: Any) -> Optional[ExecutionEngine]:
    """Resolve an engine spec: None -> None (caller keeps its default
    path), an :class:`ExecutionEngine` -> itself, ``"eager"``/``"jit"``/
    ``"aot"`` -> a fresh instance (``"aot"`` with the default store)."""
    if spec is None or isinstance(spec, ExecutionEngine):
        return spec
    try:
        factory = _ENGINES[str(spec)]
    except KeyError:
        raise ValueError(
            f"unknown execution engine {spec!r}; expected one of"
            f" {sorted(_ENGINES)} or an ExecutionEngine instance"
        ) from None
    return factory()


def environment_manifest() -> Dict[str, Any]:
    """The live compile environment as a warmup-manifest header — what
    restore paths validate before trusting recorded program keys."""
    import jax

    from metrics_tpu.engine.keys import topology_fingerprint

    return {
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "topology": topology_fingerprint(),
    }
