"""Persistent serialized-executable store: compile once, revive warm.

One :class:`ProgramStore` is a directory of XLA executables serialized by
``jax.experimental.serialize_executable`` and keyed by
:class:`~metrics_tpu.engine.keys.ProgramKey` digests. Loading an entry
deserializes the compiled artifact directly into the runtime — **zero
tracing, zero lowering, zero backend compiles** (the compile-listener
assertion in ``tests/integrations/aot_smoke.py`` pins exactly that) —
which is what turns a revived or freshly autoscaled serving node's
minutes-of-degraded-freshness cold start into a sub-millisecond load.

Trust and validity:

* Entries are **pickle-based** (that is what jax's serializer emits).
  A store directory is therefore as trusted as a checkpoint directory —
  point it only at paths this deployment writes. It is NOT a transport
  format; the wire layer never carries executables.
* Every entry has a JSON **sidecar** recording the environment it was
  compiled under (jax version, backend, topology). A load validates the
  sidecar against the live process and the requested key; any mismatch —
  or any deserialization failure — is a loud one-shot-warned MISS, never
  a crash and never a silently wrong executable (a spoofed/stale entry
  falls back to a fresh compile).
* Writes are atomic: payload first, sidecar last via ``os.replace`` — a
  kill mid-write leaves an entry without a sidecar, which loads ignore
  and the next :meth:`ProgramStore.save` overwrites.
"""
import json
import os
import pickle
import time
import uuid
import warnings
from typing import Any, Dict, Optional, Tuple

from metrics_tpu.engine.keys import ProgramKey, environment_mismatches
from metrics_tpu.obs.registry import inc as _obs_inc

__all__ = ["ProgramStore"]

_PAYLOAD_SUFFIX = ".prog"
_SIDECAR_SUFFIX = ".json"


class ProgramStore:
    """Directory-backed cache of serialized compiled programs.

    Args:
        directory: root for ``<digest>.prog`` / ``<digest>.json`` entry
            pairs (created lazily on first save).

    Thread-safety: saves are atomic renames and loads read published pairs
    only, so concurrent readers/writers see complete entries or nothing.
    """

    def __init__(self, directory: "os.PathLike | str") -> None:
        self.directory = os.fspath(os.path.abspath(directory))
        self._warned_invalid = False

    def __repr__(self) -> str:
        return f"ProgramStore({self.directory!r})"

    def _paths(self, digest: str) -> Tuple[str, str]:
        base = os.path.join(self.directory, digest)
        return base + _PAYLOAD_SUFFIX, base + _SIDECAR_SUFFIX

    def entries(self) -> Dict[str, Dict[str, Any]]:
        """``{digest: sidecar}`` of every complete (sidecar-bearing) entry."""
        out: Dict[str, Dict[str, Any]] = {}
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return out
        for name in names:
            if not name.endswith(_SIDECAR_SUFFIX):
                continue
            digest = name[: -len(_SIDECAR_SUFFIX)]
            payload, sidecar = self._paths(digest)
            if not os.path.isfile(payload):
                continue
            try:
                with open(sidecar) as f:
                    out[digest] = json.load(f)
            except (OSError, ValueError):
                continue
        return out

    # ------------------------------------------------------------------

    def save(self, key: ProgramKey, compiled: Any) -> str:
        """Serialize ``compiled`` under ``key``; returns the payload path.
        Failures (an unserializable backend executable) warn once and
        return "" — the in-memory program still serves this process."""
        from jax.experimental import serialize_executable as _se

        digest = key.digest()
        payload_path, sidecar_path = self._paths(digest)
        os.makedirs(self.directory, exist_ok=True)
        try:
            blob, in_tree, out_tree = _se.serialize(compiled)
            payload = pickle.dumps(
                {"blob": blob, "in_tree": in_tree, "out_tree": out_tree},
                protocol=pickle.HIGHEST_PROTOCOL,
            )
        except Exception as err:  # noqa: BLE001 — backend-specific serializers
            self._warn_invalid(f"could not serialize program {key.step!r}: {err}")
            _obs_inc("compile.store_errors", step=key.step, kind="serialize")
            return ""
        sidecar = dict(key.to_manifest())
        sidecar["created_unix"] = time.time()
        sidecar["nbytes"] = len(payload)
        # per-writer unique staging names: a shared store directory means
        # two cold-starting processes can save the same digest
        # concurrently, and a FIXED tmp name would interleave their writes
        # into a corrupt published payload
        suffix = f".tmp.{os.getpid()}.{uuid.uuid4().hex[:8]}"
        tmp = payload_path + suffix
        tmp_side = sidecar_path + suffix
        try:
            with open(tmp, "wb") as f:
                f.write(payload)
            os.replace(tmp, payload_path)
            with open(tmp_side, "w") as f:
                json.dump(sidecar, f, indent=2, sort_keys=True)
            os.replace(tmp_side, sidecar_path)
        except OSError as err:
            for leftover in (tmp, tmp_side):
                try:
                    os.unlink(leftover)
                except OSError:
                    pass
            self._warn_invalid(f"could not persist program {key.step!r}: {err}")
            _obs_inc("compile.store_errors", step=key.step, kind="write")
            return ""
        return payload_path

    def load(self, key: ProgramKey) -> Optional[Any]:
        """The deserialized executable for ``key``, or None (miss).

        A hit is only served when the sidecar's recorded jax version /
        backend / topology match BOTH the requested key and the live
        process — a stale or spoofed entry (e.g. a manifest carried over
        from another jax release) is refused with a one-shot warning and
        the caller compiles fresh.
        """
        from jax.experimental import serialize_executable as _se

        digest = key.digest()
        payload_path, sidecar_path = self._paths(digest)
        if not (os.path.isfile(payload_path) and os.path.isfile(sidecar_path)):
            return None
        try:
            with open(sidecar_path) as f:
                sidecar = json.load(f)
        except (OSError, ValueError) as err:
            self._warn_invalid(f"unreadable sidecar for {key.step!r} ({err}); recompiling")
            _obs_inc("compile.store_errors", step=key.step, kind="sidecar")
            return None
        mismatches = environment_mismatches(sidecar)
        # a sidecar MISSING an environment field is as untrusted as a
        # mismatching one (environment_mismatches skips absent fields)
        missing = [
            f for f in ("jax_version", "backend", "topology") if sidecar.get(f) is None
        ]
        for field in missing:
            mismatches[field] = (None, "<required>")
        if mismatches:
            field, (recorded, now) = sorted(mismatches.items())[0]
            self._warn_invalid(
                f"stored program {key.step!r} was compiled under {field}="
                f"{recorded!r} but this process runs {now!r}; refusing the"
                " cached executable and compiling fresh"
            )
            for field in mismatches:
                _obs_inc("compile.store_invalid", step=key.step, field=field)
            return None
        try:
            with open(payload_path, "rb") as f:
                entry = pickle.loads(f.read())
            return _se.deserialize_and_load(entry["blob"], entry["in_tree"], entry["out_tree"])
        except Exception as err:  # noqa: BLE001 — a corrupt entry must be a miss
            self._warn_invalid(
                f"could not deserialize stored program {key.step!r} ({err}); recompiling"
            )
            _obs_inc("compile.store_errors", step=key.step, kind="deserialize")
            return None

    def _warn_invalid(self, message: str) -> None:
        if self._warned_invalid:
            return
        self._warned_invalid = True
        warnings.warn(
            f"ProgramStore({self.directory}): {message}. Further store"
            " faults are counted under compile.store_invalid /"
            " compile.store_errors without warning again.",
            RuntimeWarning,
            stacklevel=3,
        )
