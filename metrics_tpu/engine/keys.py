"""Program cache keys: WHAT makes two compiled metric programs the same.

A serialized executable is only reusable when everything that shaped it is
identical — the traced computation (the metric/tenant schema), the input
shapes and dtypes, the static configuration baked into the trace, and the
environment that compiled it (backend, device topology, jax version: XLA
serialization is not portable across any of them). :class:`ProgramKey`
captures exactly that tuple and nothing else; its :meth:`~ProgramKey.digest`
names the cache entry.

The **schema fingerprint is the data half of the key**: two tenants whose
sketches differ only in bin count have different
:func:`~metrics_tpu.serve.wire.schema_fingerprint` values, therefore
different keys — a collision there would fold one tenant's payloads with
the other's executable, which is why the fingerprint (not the tenant id,
which is operator-chosen and reusable) keys the program
(``tests/engine/test_engine.py`` pins the discipline).
"""
import hashlib
import json
from dataclasses import asdict, dataclass
from typing import Any, Dict, Tuple

__all__ = [
    "ProgramKey",
    "abstractify",
    "environment_mismatches",
    "input_signature",
    "topology_fingerprint",
]


def environment_mismatches(recorded: Dict[str, Any]) -> Dict[str, Tuple[Any, Any]]:
    """``{field: (recorded, live)}`` for every compile-environment field
    (jax version / backend / topology) in ``recorded`` that differs from
    the live process — the ONE comparison every validation site shares
    (store loads, warmup manifests, :meth:`ProgramKey.environment_mismatches`).
    Fields absent from ``recorded`` are not mismatches."""
    import jax

    live = {
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "topology": topology_fingerprint(),
    }
    return {
        field: (recorded.get(field), now)
        for field, now in live.items()
        if recorded.get(field) is not None and recorded.get(field) != now
    }


_topology_cache: "str | None" = None


def topology_fingerprint() -> str:
    """The live process's compile environment: platform, device kind,
    device count, process count — everything a serialized executable is
    pinned to besides the jax version. Computed once per process (key
    construction sits near dispatch paths); configure the backend/mesh
    BEFORE the first engine use, like every other jax platform setting."""
    global _topology_cache
    if _topology_cache is None:
        import jax

        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", str(dev))
        _topology_cache = (
            f"{dev.platform}:{kind}:d{jax.device_count()}:p{jax.process_count()}"
        )
    return _topology_cache


def _leaf_sig(leaf: Any) -> Any:
    if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
        return [str(leaf.dtype), list(leaf.shape)]
    return ["py", repr(leaf)]


def input_signature(args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> Tuple[Any, ...]:
    """Canonical (shape, dtype) signature of a call: every array-like leaf
    of the flattened ``(args, kwargs)`` in tree order, non-arrays by repr.
    JSON-serializable (the key digest and the warmup manifest both carry
    it)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, dict(kwargs)))
    return (str(treedef), tuple(json.dumps(_leaf_sig(leaf)) for leaf in leaves))


def abstractify(args: Tuple[Any, ...], kwargs: Dict[str, Any]):
    """Replace every array-like leaf with a ``ShapeDtypeStruct`` — the
    zero-materialization call signature AOT lowering runs on (donated or
    device-resident buffers are never touched, only their metadata)."""
    import jax

    def _abs(leaf: Any) -> Any:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype)
        return leaf

    return jax.tree_util.tree_map(_abs, (tuple(args), dict(kwargs)))


@dataclass(frozen=True)
class ProgramKey:
    """Identity of one compiled metric program.

    Args:
        step: the program's step label (``"Accuracy.epoch"``,
            ``"serve.fold_stacked"`` ...) — the ``step=`` label on the
            cache-hit/miss counters and the manifest's human handle.
        fingerprint: the data-schema half — a
            :func:`~metrics_tpu.serve.wire.schema_fingerprint` (tenant or
            metric template). Two programs over different schemas must
            never share an executable even if their traced shapes collide.
        input_sig: canonical input signature (:func:`input_signature`).
        static_sig: static configuration baked into the trace (e.g. the
            fold's reduction tuple) as a stable string.
        backend: jax platform the program was (or will be) compiled for.
        jax_version: serialized executables are not portable across jax
            releases; the version rides the key so an upgraded process
            computes different digests and recompiles instead of loading
            a stale artifact.
        topology: :func:`topology_fingerprint` of the compiling process.
    """

    step: str
    fingerprint: str
    input_sig: Tuple[Any, ...]
    static_sig: str = ""
    backend: str = ""
    jax_version: str = ""
    topology: str = ""

    @classmethod
    def build(
        cls,
        step: str,
        fingerprint: str,
        args: Tuple[Any, ...] = (),
        kwargs: Dict[str, Any] = None,
        static_sig: str = "",
    ) -> "ProgramKey":
        """Key for calling a program with ``(args, kwargs)`` in the LIVE
        process (backend/jax version/topology filled in from it)."""
        import jax

        return cls(
            step=str(step),
            fingerprint=str(fingerprint),
            input_sig=input_signature(tuple(args), dict(kwargs or {})),
            static_sig=str(static_sig),
            backend=jax.default_backend(),
            jax_version=jax.__version__,
            topology=topology_fingerprint(),
        )

    def digest(self) -> str:
        blob = json.dumps(asdict(self), sort_keys=True, default=str).encode()
        return hashlib.sha256(blob).hexdigest()[:32]

    def to_manifest(self) -> Dict[str, Any]:
        """JSON-ready form for a warmup manifest entry."""
        entry = asdict(self)
        entry["input_sig"] = [self.input_sig[0], list(self.input_sig[1])]
        entry["digest"] = self.digest()
        return entry

    @classmethod
    def from_manifest(cls, entry: Dict[str, Any]) -> "ProgramKey":
        return cls(
            step=entry["step"],
            fingerprint=entry["fingerprint"],
            input_sig=(entry["input_sig"][0], tuple(entry["input_sig"][1])),
            static_sig=entry.get("static_sig", ""),
            backend=entry.get("backend", ""),
            jax_version=entry.get("jax_version", ""),
            topology=entry.get("topology", ""),
        )

    def environment_mismatches(self) -> Dict[str, Tuple[str, str]]:
        """``{field: (recorded, live)}`` for every environment field that
        differs from the live process — the loud-warn-then-recompile
        validation restore paths run (never a crash, never a silently
        wrong executable)."""
        return environment_mismatches(
            {
                "jax_version": self.jax_version or None,
                "backend": self.backend or None,
                "topology": self.topology or None,
            }
        )

    def rekeyed_to_live(self) -> "ProgramKey":
        """The same program identity with the environment fields replaced
        by the live process's — what a mismatched manifest entry warms
        instead (fresh compile under the correct key)."""
        import jax

        return ProgramKey(
            step=self.step,
            fingerprint=self.fingerprint,
            input_sig=self.input_sig,
            static_sig=self.static_sig,
            backend=jax.default_backend(),
            jax_version=jax.__version__,
            topology=topology_fingerprint(),
        )
