"""MetricCollection — dict-of-metrics with shared update and compute groups.

Behavioral equivalent of the reference's ``torchmetrics/collections.py:28``
(``MetricCollection``): a keyed collection of metrics updated with a single
``update``/``forward`` call, with **compute groups** — metrics whose states
are identical (e.g. Precision/Recall/F1 over one shared tp/fp/tn/fn pipeline)
are deduplicated so only one group member runs ``update``; its state is
broadcast to the others at ``compute`` (reference ``collections.py:138-224``,
documented 2-3x cost saving at ``docs/source/pages/overview.rst:306-310``).

TPU note: dedup matters *more* here than in the reference — every avoided
``update`` is an avoided XLA dispatch, and identical state pytrees share the
same HBM buffers when copied by reference.
"""
from copy import deepcopy
from typing import Any, Dict, List, Optional, Sequence, Union

import jax

from metrics_tpu.metric import Metric
from metrics_tpu.obs.tracing import trace_span as _obs_span
from metrics_tpu.streaming.sketches import Sketch
from metrics_tpu.utilities.buffers import CapacityBuffer
from metrics_tpu.utilities.data import _flatten_dict, allclose, coerce_foreign_tensors, foreign_coercion_scope

Array = jax.Array


def _rebuild_collection(cls: type, data: Dict[str, "Metric"], attrs: Dict[str, Any]) -> "MetricCollection":
    obj = cls.__new__(cls)
    dict.update(obj, data)
    obj.__dict__.update(attrs)
    return obj


class MetricCollection(dict):
    """A dict-like collection of metrics with a single update entry point.

    Args:
        metrics: a ``Metric``, a sequence of metrics, or a ``dict`` mapping
            names to metrics.
        additional_metrics: further metrics when ``metrics`` is positional.
        prefix: string prepended to every returned metric name.
        postfix: string appended to every returned metric name.
        compute_groups: when True (default), detect metrics with identical
            states after the first update and only update one per group.

    Example::

        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy, MetricCollection, Precision, Recall
        >>> target = jnp.asarray([0, 2, 0, 2, 0, 1, 0, 2])
        >>> preds = jnp.asarray([2, 1, 2, 0, 1, 2, 2, 2])
        >>> metrics = MetricCollection([
        ...     Accuracy(),
        ...     Precision(num_classes=3, average='macro'),
        ...     Recall(num_classes=3, average='macro'),
        ... ])
        >>> sorted(metrics(preds, target))
        ['Accuracy', 'Precision', 'Recall']
    """

    _modules: Dict[str, Metric]

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
        compute_groups: Union[bool, List[List[str]]] = True,
    ) -> None:
        super().__init__()
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self._enable_compute_groups = compute_groups
        self._groups_checked: bool = False
        self._state_is_copy: bool = False
        self._groups: Dict[int, List[str]] = {}

        self.add_metrics(metrics, *additional_metrics)

    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    # ------------------------------------------------------------------
    # Construction
    # ------------------------------------------------------------------

    def add_metrics(
        self, metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]], *additional_metrics: Metric
    ) -> None:
        """Add metrics to the collection (reference ``collections.py:253``)."""
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence):
            metrics = list(metrics)  # keep the caller's sequence untouched
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                raise ValueError(f"You have passed extra arguments {remain} which are not `Metric` instances.")
        elif additional_metrics:
            raise ValueError(
                f"You have passed extra arguments {additional_metrics} which are not compatible"
                f" with the first passed dictionary {metrics}."
            )

        if isinstance(metrics, dict):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Value {metric} belonging to key {name} is not an instance of"
                        " `metrics_tpu.Metric` or `metrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    self[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self[f"{name}_{k}"] = v
        elif isinstance(metrics, Sequence):
            for metric in metrics:
                if not isinstance(metric, (Metric, MetricCollection)):
                    raise ValueError(
                        f"Input {metric} to `MetricCollection` is not a instance of"
                        " `metrics_tpu.Metric` or `metrics_tpu.MetricCollection`"
                    )
                if isinstance(metric, Metric):
                    name = metric.__class__.__name__
                    if name in self:
                        raise ValueError(f"Encountered two metrics both named {name}")
                    self[name] = metric
                else:
                    for k, v in metric.items(keep_base=False):
                        self[k] = v
        else:
            raise ValueError("Unknown input to MetricCollection.")

        self._groups_checked = False
        if self._enable_compute_groups:
            self._init_compute_groups()
        else:
            self._groups = {}

    def _init_compute_groups(self) -> None:
        """Initialize every metric as its own group; user-specified groups are
        validated (reference ``collections.py:131-157``)."""
        if isinstance(self._enable_compute_groups, list):
            self._groups = {i: list(group) for i, group in enumerate(self._enable_compute_groups)}
            covered = set()
            for group in self._groups.values():
                for name in group:
                    if name not in self:
                        raise ValueError(
                            f"Input {name} in `compute_groups` argument does not match a metric in the collection."
                        )
                    covered.add(name)
            # metrics absent from the user's groups still need updating:
            # give each its own singleton group
            for name in self.keys(keep_base=True):
                if name not in covered:
                    self._groups[len(self._groups)] = [name]
            self._groups_checked = True
        else:
            self._groups = {i: [name] for i, name in enumerate(self.keys(keep_base=True))}

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Per-metric forward; batch values under collection keys."""
        from metrics_tpu.utilities.checks import shared_input_format_scope

        # convert torch inputs ONCE for the whole collection — every member
        # metric would otherwise pay the host transfer independently
        args = coerce_foreign_tensors(args)
        kwargs = coerce_foreign_tensors(kwargs)
        with _obs_span("MetricCollection.forward", category="forward"):
            with foreign_coercion_scope(args, kwargs):  # member forwards must not re-walk these
                if self._state_is_copy:
                    # the last compute aliased group state by reference;
                    # members with in-place states (buffers, cat lists) must
                    # not update through the alias
                    self._compute_groups_create_state_ref(copy=True)
                    self._state_is_copy = False
                with shared_input_format_scope():  # format/check pass once per parameterization
                    res = {
                        k: m(*args, **m._filter_kwargs(**kwargs))
                        for k, m in self.items(keep_base=True, copy_state=False)
                    }
                # forward is an update entry point too: detect compute groups
                # after the first real batch, same as update()
                self._maybe_merge_compute_groups()
        res = _flatten_dict(res)
        return {self._set_name(k): v for k, v in res.items()}

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def update(self, *args: Any, **kwargs: Any) -> None:
        """Update each underlying metric once per compute group."""
        args = coerce_foreign_tensors(args)
        kwargs = coerce_foreign_tensors(kwargs)
        with _obs_span("MetricCollection.update", category="update"):
            with foreign_coercion_scope(args, kwargs):  # member updates must not re-walk these
                self._update_members(*args, **kwargs)

    def _update_members(self, *args: Any, **kwargs: Any) -> None:
        from metrics_tpu.utilities.checks import shared_input_format_scope

        if self._groups_checked:
            with shared_input_format_scope():  # format/check pass once per parameterization
                for group in self._groups.values():
                    m0 = self[group[0]]
                    m0.update(*args, **m0._filter_kwargs(**kwargs))
            if self._state_is_copy:
                # previous compute copied states by reference; members must
                # not be updated while aliasing the representative
                self._compute_groups_create_state_ref(copy=True)
                self._state_is_copy = False
        else:
            with shared_input_format_scope():
                for m in self.values(copy_state=False):
                    m.update(*args, **m._filter_kwargs(**kwargs))
            self._maybe_merge_compute_groups()

    def _maybe_merge_compute_groups(self) -> None:
        """Run the O(n^2) pairwise group detection ONCE, after the first
        REAL batch, and cache the verdict.

        Two guards around :meth:`_merge_compute_groups`: the verdict is
        cached in ``_groups_checked`` so no later update (from any entry
        point — ``update`` or ``forward``) re-runs the pairwise comparison;
        and detection waits for a batch that actually moved some state off
        its default — on an all-default collection (an empty first batch, a
        zero-preserving update) every same-structure member compares equal
        and would falsely merge into one group, silently dropping updates
        of the non-representatives forever after.
        """
        if self._groups_checked or not self._enable_compute_groups:
            return
        if all(self._states_at_defaults(m) for m in self.values(copy_state=False)):
            return  # no real batch yet: all-default states would falsely merge
        self._merge_compute_groups()
        self._groups_checked = True

    @staticmethod
    def _states_at_defaults(metric: Metric) -> bool:
        """Whether every state still equals its reset default (cheap O(state)
        scan, not the pairwise comparison)."""
        for name, default in metric._defaults.items():
            value = getattr(metric, name)
            if isinstance(value, (list, CapacityBuffer)):
                if len(value):
                    return False
            elif isinstance(value, Sketch):
                leaves_v = jax.tree_util.tree_leaves(value)
                leaves_d = jax.tree_util.tree_leaves(default)
                if not all(allclose(a, b) for a, b in zip(leaves_v, leaves_d)):
                    return False
            elif not allclose(value, jax.numpy.asarray(default)):
                return False
        return True

    def _merge_compute_groups(self) -> None:
        """Iteratively merge groups whose representatives share equal states
        (reference ``collections.py:159-193``)."""
        num_groups = len(self._groups)
        while True:
            for cg_idx1, cg_members1 in deepcopy(self._groups).items():
                for cg_idx2, cg_members2 in deepcopy(self._groups).items():
                    if cg_idx1 == cg_idx2:
                        continue
                    metric1 = self[cg_members1[0]]
                    metric2 = self[cg_members2[0]]
                    if self._equal_metric_states(metric1, metric2):
                        self._groups[cg_idx1].extend(self._groups.pop(cg_idx2))
                        break
                else:
                    continue
                break
            if len(self._groups) == num_groups:
                break
            num_groups = len(self._groups)
        self._groups = {i: group for i, group in enumerate(self._groups.values())}

    @staticmethod
    def _equal_metric_states(metric1: Metric, metric2: Metric) -> bool:
        """Shape + allclose state equality (reference ``collections.py:194-213``)."""
        if not metric1._defaults or not metric2._defaults:
            return False
        if metric1._defaults.keys() != metric2._defaults.keys():
            return False
        for key in metric1._defaults:
            state1 = getattr(metric1, key)
            state2 = getattr(metric2, key)
            if type(state1) != type(state2):  # noqa: E721
                return False
            if isinstance(state1, list):
                if len(state1) != len(state2):
                    return False
                if not all(allclose(s1, s2) for s1, s2 in zip(state1, state2)):
                    return False
            elif isinstance(state1, CapacityBuffer):
                if len(state1) != len(state2):
                    return False
                if len(state1) and not allclose(state1.materialize(), state2.materialize()):
                    return False
            elif isinstance(state1, Sketch):
                if state1.config() != state2.config():
                    return False
                leaves1 = jax.tree_util.tree_leaves(state1)
                leaves2 = jax.tree_util.tree_leaves(state2)
                if not all(allclose(s1, s2) for s1, s2 in zip(leaves1, leaves2)):
                    return False
            elif not allclose(state1, state2):
                return False
        return True

    def _compute_groups_create_state_ref(self, copy: bool = False) -> None:
        """Alias (or deep-copy) representative state onto group members
        (reference ``collections.py:217-224``)."""
        for group in self._groups.values():
            m0 = self[group[0]]
            for name in group[1:]:
                mi = self[name]
                for state in m0._defaults:
                    value = getattr(m0, state)
                    setattr(mi, state, deepcopy(value) if copy else value)
                mi._update_count = m0._update_count

    def compute(self) -> Dict[str, Any]:
        """Compute every metric; group members read the representative state."""
        with _obs_span("MetricCollection.compute", category="compute"):
            if self._groups_checked:
                self._compute_groups_create_state_ref()
                self._state_is_copy = True
            res = {k: m.compute() for k, m in self.items(keep_base=True, copy_state=False)}
        res = _flatten_dict(res)
        return {self._set_name(k): v for k, v in res.items()}

    def reset(self) -> None:
        for m in self.values(copy_state=False):
            m.reset()
        if self._enable_compute_groups and self._groups_checked:
            # states equal again only at defaults; keep discovered groups
            self._compute_groups_create_state_ref(copy=True)
        self._state_is_copy = False

    def _resync_compute_groups_after_restore(self) -> None:
        """Re-establish group bookkeeping after members were restored
        individually (checkpoint load).

        A restored member holds real state, never a reference to its group
        representative, so ``_state_is_copy`` must drop. And when the
        restored states contradict the discovered grouping (the checkpoint
        came from a differently-grouped or groups-off collection), keeping
        the groups would have the next ``update`` touch only the
        representative and the next ``compute`` alias its state over the
        differing restored member state — silently discarding it. Groups
        are then re-derived from scratch on the next update.
        """
        self._state_is_copy = False
        if not self._groups_checked:
            return
        consistent = all(
            self._equal_metric_states(self[group[0]], self[name])
            for group in self._groups.values()
            for name in group[1:]
        )
        if consistent:
            return
        if isinstance(self._enable_compute_groups, list):
            from metrics_tpu.utilities.prints import rank_zero_warn

            rank_zero_warn(
                "Restored member states contradict the user-specified `compute_groups`;"
                " dissolving the groups so the restored state survives. Check that the"
                " checkpoint was saved from an identically-grouped collection.",
                UserWarning,
            )
        self._groups = {i: [name] for i, name in enumerate(self.keys(keep_base=True))}
        self._groups_checked = False

    def save(self, path: Any) -> None:
        """Atomically persist every member's state to ``path`` (orbax tree
        keyed by metric name; see ``utilities/checkpoint.save_state``). For
        rotation, manifests and async saves use
        :class:`metrics_tpu.ft.CheckpointManager`."""
        from metrics_tpu.utilities.checkpoint import save_state

        save_state(path, self)

    def restore(self, path: Any) -> "MetricCollection":
        """Restore member states saved by :meth:`save`; returns ``self``.
        Compute-group bookkeeping is re-synced so a post-restore ``update``
        cannot clobber restored non-representative state."""
        from metrics_tpu.utilities.checkpoint import restore_state

        restore_state(path, self)
        return self

    # ------------------------------------------------------------------
    # dict protocol with prefix/postfix
    # ------------------------------------------------------------------

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        return name if self.postfix is None else name + self.postfix

    def keys(self, keep_base: bool = False):  # type: ignore[override]
        if keep_base:
            return super().keys()
        return [self._set_name(k) for k in super().keys()]

    def items(self, keep_base: bool = False, copy_state: bool = True):  # type: ignore[override]
        """Return (name, metric) pairs; ``copy_state`` materializes group
        state refs first so every member is safe to read."""
        if copy_state and self._state_is_copy:
            self._compute_groups_create_state_ref(copy=True)
            self._state_is_copy = False
        if keep_base:
            return super().items()
        return [(self._set_name(k), v) for k, v in super().items()]

    def values(self, copy_state: bool = True):  # type: ignore[override]
        if copy_state and self._state_is_copy:
            self._compute_groups_create_state_ref(copy=True)
            self._state_is_copy = False
        return super().values()

    def __getitem__(self, key: str) -> Metric:
        return dict.__getitem__(self, key)

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        """Deep-copy, optionally re-keying with new prefix/postfix."""
        mc = deepcopy(self)
        if prefix is not None:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix is not None:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def persistent(self, mode: bool = True) -> None:
        for m in self.values(copy_state=False):
            m.persistent(mode)

    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k, m in self.items(keep_base=True):
            out.update(m.state_dict(prefix=f"{k}."))
        return out

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        for k, m in self.items(keep_base=True, copy_state=False):
            m.load_state_dict(state_dict, prefix=f"{k}.")

    @property
    def compute_groups(self) -> Dict[int, List[str]]:
        """The discovered compute groups."""
        return self._groups

    def __reduce__(self):
        # dict's default __reduce_ex__ rebuilds the mapping from
        # ``iter(self.items())`` — our override returns prefixed names, which
        # would mangle keys on deepcopy/pickle. Rebuild from raw dict items.
        return (_rebuild_collection, (self.__class__, dict(dict.items(self)), self.__dict__.copy()))

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "("
        for k, v in self.items(keep_base=True, copy_state=False):
            repr_str += f"\n  {k}: {v!r}"
        if self.prefix:
            repr_str += f"\n  prefix={self.prefix}"
        if self.postfix:
            repr_str += f"\n  postfix={self.postfix}"
        return repr_str + "\n)"
