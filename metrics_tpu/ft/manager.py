"""Rotating, atomic, optionally-async checkpoint manager for metric state.

:func:`~metrics_tpu.utilities.checkpoint.save_state` persists ONE pytree to
ONE path; a preemptible-pod eval sweep needs the operational layer above
it, which is this class:

* **atomic publication** — every checkpoint is staged and renamed into
  place (:func:`~metrics_tpu.utilities.checkpoint.atomic_dir_swap`); a
  SIGKILL at any instant leaves either the previous complete checkpoint or
  the new complete one, never a torn directory. Leftover ``.tmp.*``
  staging dirs from a hard kill are swept on the next save.
* **rotating retention** — ``keep_last=N`` bounds disk: after each
  successful save the oldest checkpoints beyond N are deleted.
* **monotonic discovery** — checkpoints are named ``ckpt-<seq>`` by a
  monotonically increasing sequence number and :meth:`latest` orders by
  that number, never by file timestamps, so clock skew between hosts (or
  an injected :func:`metrics_tpu.ft.faults.clock_skew`) cannot resurrect
  an old checkpoint as "latest".
* **async background save** — ``async_save=True`` snapshots the state
  pytree on the calling thread (a device-side buffer copy: immutability
  alone is not enough, since the caller's next jitted step may DONATE the
  aliased buffers) and persists it from a single background worker; the
  training loop stalls for the snapshot, not the serialization. Saves
  serialize (a new save waits for the previous), and a background failure
  surfaces on the next :meth:`save`/:meth:`wait`.
* **bundled manifest** — ``manifest.json`` beside the state records the
  ``(epoch, step)`` watermark of the bundled
  :class:`~metrics_tpu.ft.journal.BatchJournal`, the jax/process topology
  it was saved under, an :func:`metrics_tpu.obs.snapshot` (counters only,
  when the layer is armed) and an optional
  :class:`~metrics_tpu.integrations.MetricLogger` history — everything a
  resumed process needs to continue exactly-once.

Multi-host note: like the reference's DDP recipe, save globally-reduced
state from process 0 inside ``sync_context()``, or give every process its
own ``directory`` and save local state everywhere; the manifest records
``process_index``/``process_count`` so a mismatched restore is detectable.
"""
import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from metrics_tpu.ft import faults as _faults
from metrics_tpu.ft.journal import BatchJournal
from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.obs.registry import set_gauge as _obs_gauge
from metrics_tpu.utilities.checkpoint import (
    atomic_dir_swap,
    load_metric_state_tree,
    metric_state_to_tree,
)

__all__ = [
    "CheckpointManager",
    "MANIFEST_NAME",
    "STATE_DIR",
    "validate_manifest_environment",
]

MANIFEST_NAME = "manifest.json"
STATE_DIR = "state"
_CKPT_RE = re.compile(r"^ckpt-(\d{8})$")
_MANIFEST_SCHEMA = 1

# one-shot guard for the restore-time environment validation warning
_warned_env_mismatch = False


def validate_manifest_environment(manifest: Dict[str, Any], context: str = "restore") -> Dict[str, Any]:
    """Compare a checkpoint manifest's recorded jax version / backend /
    process topology against the live process.

    Returns ``{field: {"recorded": ..., "live": ...}}`` for every
    mismatching field (empty = clean). A mismatch is a LOUD one-shot
    ``rank_zero_warn`` plus ``ft.manifest_env_mismatches{field=}`` counters
    — never an exception: states restore fine across jax versions (orbax
    arrays are portable), but anything derived from the compile
    environment (cached executables, AOT warmup manifests, topology-
    dependent shards) must be rebuilt fresh, and the operator must see
    why their revival ran cold."""
    import jax

    live: Dict[str, Any] = {
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "process_count": jax.process_count(),
        "device_count": jax.device_count(),
    }
    mismatches: Dict[str, Any] = {}
    for field, now in live.items():
        recorded = manifest.get(field)
        if recorded is not None and recorded != now:
            mismatches[field] = {"recorded": recorded, "live": now}
    if mismatches:
        if _obs_enabled():
            for field in mismatches:
                _obs_inc("ft.manifest_env_mismatches", field=field)
        global _warned_env_mismatch
        if not _warned_env_mismatch:
            _warned_env_mismatch = True
            from metrics_tpu.utilities.prints import rank_zero_warn

            detail = "; ".join(
                f"{field}: checkpoint={m['recorded']!r} live={m['live']!r}"
                for field, m in sorted(mismatches.items())
            )
            rank_zero_warn(
                f"Checkpoint {context}: the manifest was recorded under a different"
                f" environment ({detail}). States restore fine, but cached"
                " executables / AOT warmup manifests from that environment are"
                " invalid here and will be recompiled fresh (cold first fold)."
                " Further mismatches are counted under ft.manifest_env_mismatches"
                " without warning again.",
                RuntimeWarning,
            )
    return mismatches


class CheckpointManager:
    """Atomic rotating checkpoints with resume discovery.

    Args:
        directory: root for ``ckpt-<seq>`` checkpoint dirs (created lazily).
        keep_last: retention bound; older checkpoints are deleted after
            each successful save. ``None`` keeps everything.
        async_save: persist off-thread; :meth:`save` returns after the
            main-thread state snapshot.
        save_timeout_s: watchdog budget for one async persist. Without it
            a hung persist thread (wedged filesystem, dead NFS mount) is
            INVISIBLE — the loop keeps training, no checkpoint ever lands,
            and the next preemption loses everything since the last good
            one. With it, a persist that overruns is warned about once,
            counted under ``ft.save_timeouts``, and abandoned by the next
            :meth:`save`/:meth:`wait` (which surface an
            :class:`~metrics_tpu.ft.retry.AttemptTimeout` instead of
            joining a hung thread forever). The writer thread itself
            cannot be cancelled — it is left as a daemon, exactly like a
            timed-out retry attempt. Sync saves are not watched (the hang
            is visible: the caller is inside it).

    Example::

        manager = CheckpointManager(ckpt_dir, keep_last=3)
        journal = BatchJournal()
        manifest = manager.restore(metric, journal=journal)  # None on fresh start
        start = journal.resume_from
        for epoch in range(start.epoch, num_epochs):
            for step, batch in enumerate(batches):
                if not journal.should_fold(epoch, step):
                    continue
                metric.update(*batch)
                journal.record(epoch, step)
                if step % save_every == 0:
                    manager.save(metric, journal=journal, epoch=epoch, step=step)
    """

    def __init__(
        self,
        directory: "os.PathLike | str",
        keep_last: Optional[int] = 3,
        async_save: bool = False,
        save_timeout_s: Optional[float] = None,
    ) -> None:
        if keep_last is not None and keep_last < 1:
            raise ValueError(f"keep_last must be >= 1 (or None to keep all), got {keep_last}")
        if save_timeout_s is not None and save_timeout_s <= 0:
            raise ValueError(f"save_timeout_s must be positive (or None), got {save_timeout_s}")
        self.directory = os.fspath(os.path.abspath(directory))
        self.keep_last = keep_last
        self.async_save = bool(async_save)
        self.save_timeout_s = save_timeout_s
        self._worker: Optional[threading.Thread] = None
        self._worker_error: Optional[BaseException] = None
        self._lock = threading.Lock()
        # per-save watchdog record: {"done": Event, "noted": bool, "final": path}
        self._pending: Optional[Dict[str, Any]] = None
        self._warned_timeout = False
        # floor for the next sequence number: an abandoned hung save is
        # unpublished (invisible to checkpoints()) but its writer may still
        # land ckpt-<seq> later — the next save must never reuse that seq
        self._next_seq = 0

    # ------------------------------------------------------------------
    # Discovery
    # ------------------------------------------------------------------

    def checkpoints(self) -> List[Tuple[int, str]]:
        """All complete checkpoints as ``(seq, path)``, oldest first.

        Ordering is by the monotonic sequence number in the directory name —
        never mtime or manifest timestamps, which clock skew can corrupt.
        Staging leftovers (``.tmp.*``) and dirs without a manifest are
        invisible (the manifest is written inside the stage BEFORE the
        atomic rename, so a published dir always has one).
        """
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        found = []
        for name in names:
            m = _CKPT_RE.match(name)
            if not m:
                continue
            path = os.path.join(self.directory, name)
            if os.path.isfile(os.path.join(path, MANIFEST_NAME)):
                found.append((int(m.group(1)), path))
        found.sort()
        return found

    def latest(self) -> Optional[str]:
        """Path of the newest complete checkpoint, or None."""
        all_ckpts = self.checkpoints()
        return all_ckpts[-1][1] if all_ckpts else None

    def read_manifest(self, path: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """The manifest of ``path`` (default: latest), or None when absent."""
        path = path if path is not None else self.latest()
        if path is None:
            return None
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            return json.load(f)

    # ------------------------------------------------------------------
    # Save
    # ------------------------------------------------------------------

    def save(
        self,
        metric: Any,
        journal: Optional[BatchJournal] = None,
        logger: Optional[Any] = None,
        epoch: Optional[int] = None,
        step: Optional[int] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Persist ``metric`` (+ journal watermark, logger history) atomically.

        Returns the path the checkpoint is published at (for async saves:
        will be published at — call :meth:`wait` to block on completion).
        The state snapshot happens on the calling thread either way, so the
        saved state is exactly the state at the call, even if updates
        continue while an async save serializes.
        """
        self._drain(reraise=True)
        existing = self.checkpoints()
        with self._lock:
            seq = max(existing[-1][0] + 1 if existing else 0, self._next_seq)
            self._next_seq = seq + 1
        final = os.path.join(self.directory, f"ckpt-{seq:08d}")
        tree = metric_state_to_tree(metric)
        manifest = self._build_manifest(seq, journal, logger, epoch, step, extra)
        if self.async_save:
            # defensively copy the device leaves: jnp arrays are immutable
            # but NOT donation-proof — if the caller's next jitted step
            # donates the buffers this tree aliases (make_epoch jits with
            # the carry donated), the background write would read deleted
            # arrays and the checkpoint would silently not exist
            import jax

            tree = jax.tree_util.tree_map(
                lambda x: jax.numpy.array(x) if isinstance(x, jax.Array) else x, tree
            )
            pending = {
                "done": threading.Event(),
                "noted": False,
                "abandoned": False,
                "final": final,
                "timer": None,
            }
            if self.save_timeout_s is not None:
                # the watchdog fires even if nobody ever calls wait(): a hung
                # persist must be loud on its own, not only when joined. The
                # worker cancels it on completion, else every fast save would
                # leave an idle timer thread alive for the whole budget.
                timer = threading.Timer(self.save_timeout_s, self._note_save_timeout, args=(pending,))
                timer.daemon = True
                pending["timer"] = timer
            with self._lock:
                self._pending = pending
                self._worker = threading.Thread(
                    target=self._persist_guarded,
                    args=(tree, manifest, final, pending),
                    name=f"ft-ckpt-save-{seq}",
                    daemon=True,
                )
                self._worker.start()
            if pending["timer"] is not None:
                pending["timer"].start()
        else:
            self._persist(tree, manifest, final)
        return final

    def wait(self) -> None:
        """Block until a pending async save completes; re-raise its error.
        With ``save_timeout_s`` set, a persist still running past the
        budget is abandoned (daemon thread) and surfaces as
        :class:`~metrics_tpu.ft.retry.AttemptTimeout`."""
        self._drain(reraise=True)

    def _drain(self, reraise: bool) -> None:
        with self._lock:
            worker = self._worker
            pending = self._pending
        if worker is not None:
            worker.join(self.save_timeout_s)
            if worker.is_alive():
                # hung past the watchdog budget: abandon the daemon thread
                # (it cannot be cancelled) and record the hang as THIS
                # save's failure so the caller sees it like any other
                # background save error
                if pending is not None:
                    self._note_save_timeout(pending)
                with self._lock:
                    if pending is not None:
                        # the hung writer keeps running (daemon, uncancellable);
                        # once abandoned it must not touch shared state — a late
                        # failure would otherwise be misattributed to the NEXT
                        # save via _worker_error
                        pending["abandoned"] = True
                    if self._worker is worker:
                        self._worker = None
                        self._pending = None
                    if self._worker_error is None:
                        from metrics_tpu.ft.retry import AttemptTimeout

                        self._worker_error = AttemptTimeout(
                            f"async checkpoint save to {pending['final'] if pending else self.directory}"
                            f" exceeded save_timeout_s={self.save_timeout_s}; the writer thread was"
                            " abandoned and the checkpoint must be assumed missing"
                        )
            else:
                with self._lock:
                    self._worker = None
                    self._pending = None
        if reraise and self._worker_error is not None:
            error, self._worker_error = self._worker_error, None
            raise error

    def _note_save_timeout(self, pending: Dict[str, Any]) -> None:
        """One ``ft.save_timeouts`` bump + one-shot warn per hung save."""
        with self._lock:
            if pending["done"].is_set() or pending["noted"]:
                return
            pending["noted"] = True
            first = not self._warned_timeout
            self._warned_timeout = True
        if _obs_enabled():
            _obs_inc("ft.save_timeouts")
        if first:
            from metrics_tpu.utilities.prints import rank_zero_warn

            rank_zero_warn(
                f"Async checkpoint save to {pending['final']} has run past"
                f" save_timeout_s={self.save_timeout_s}s and may be hung (wedged"
                " filesystem?). The writer thread cannot be cancelled; the next"
                " save()/wait() will stop waiting on it after the same budget and"
                " raise AttemptTimeout. Further hung saves are counted under"
                " ft.save_timeouts without warning again.",
                RuntimeWarning,
            )

    def _persist_guarded(
        self, tree: Any, manifest: Dict[str, Any], final: str, pending: Dict[str, Any]
    ) -> None:
        try:
            self._persist(tree, manifest, final)
        except BaseException as err:  # noqa: BLE001 — surfaced on next save()/wait()
            with self._lock:
                if not pending["abandoned"]:
                    self._worker_error = err
        finally:
            pending["done"].set()
            if pending["timer"] is not None:
                pending["timer"].cancel()

    def _persist(self, tree: Any, manifest: Dict[str, Any], final: str) -> None:
        import orbax.checkpoint as ocp

        t0 = time.perf_counter()
        with atomic_dir_swap(final) as stage:
            with ocp.PyTreeCheckpointer() as ckptr:
                ckptr.save(os.path.join(stage, STATE_DIR), tree)
            # manifest last: its presence inside a published dir certifies a
            # complete state payload
            with open(os.path.join(stage, MANIFEST_NAME), "w") as f:
                json.dump(manifest, f, indent=2, sort_keys=True)
        self._rotate()
        self._sweep_stale_stages()
        if _obs_enabled():
            _obs_inc("ft.checkpoint_saves", mode="async" if self.async_save else "sync")
            _obs_gauge("ft.checkpoint_save_ms", (time.perf_counter() - t0) * 1000.0)

    def _build_manifest(
        self,
        seq: int,
        journal: Optional[BatchJournal],
        logger: Optional[Any],
        epoch: Optional[int],
        step: Optional[int],
        extra: Optional[Dict[str, Any]],
    ) -> Dict[str, Any]:
        import jax

        from metrics_tpu import obs

        dev = jax.devices()[0]
        manifest: Dict[str, Any] = {
            "schema": _MANIFEST_SCHEMA,
            "seq": seq,
            "epoch": epoch,
            "step": step,
            # faults.now() so clock-skew tests can plant lying timestamps;
            # discovery never reads this field (seq order only)
            "recorded_unix": _faults.now(),
            "jax_version": jax.__version__,
            "backend": jax.default_backend(),
            "device_kind": getattr(dev, "device_kind", str(dev)),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
        }
        if journal is not None:
            manifest["journal"] = journal.state_dict()
        if logger is not None:
            manifest["logger"] = logger.state_dict()
        if obs.enabled():
            snap = obs.snapshot(spans=False)
            manifest["obs"] = {"counters": snap["counters"], "gauges": snap["gauges"]}
        if extra:
            manifest["extra"] = extra
        return manifest

    def _rotate(self) -> None:
        if self.keep_last is None:
            return
        stale = self.checkpoints()[: -self.keep_last]
        for _, path in stale:
            # delete the manifest first so a kill mid-delete leaves an
            # incomplete dir that discovery already ignores
            try:
                os.unlink(os.path.join(path, MANIFEST_NAME))
            except FileNotFoundError:
                pass
            shutil.rmtree(path, ignore_errors=True)
        if stale and _obs_enabled():
            _obs_inc("ft.checkpoints_rotated", float(len(stale)))

    def _sweep_stale_stages(self) -> None:
        """Remove kill leftovers: ``.tmp.*`` staging dirs (their
        atomic_dir_swap never reached its cleanup) and manifest-less
        ``ckpt-*`` husks older than the newest complete checkpoint (a kill
        between rotation's manifest unlink and its rmtree leaves the state
        payload behind, invisible to discovery but real on disk)."""
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return
        complete = self.checkpoints()
        newest_seq = complete[-1][0] if complete else -1
        for name in names:
            if name.startswith(".tmp."):
                shutil.rmtree(os.path.join(self.directory, name), ignore_errors=True)
                continue
            m = _CKPT_RE.match(name)
            if m and int(m.group(1)) < newest_seq:
                path = os.path.join(self.directory, name)
                if not os.path.isfile(os.path.join(path, MANIFEST_NAME)):
                    shutil.rmtree(path, ignore_errors=True)

    # ------------------------------------------------------------------
    # Restore
    # ------------------------------------------------------------------

    def restore(
        self,
        metric: Any,
        path: Optional[str] = None,
        journal: Optional[BatchJournal] = None,
        logger: Optional[Any] = None,
    ) -> Optional[Dict[str, Any]]:
        """Restore ``metric`` (and journal/logger) from ``path`` or latest.

        Returns the checkpoint's manifest, or None when no checkpoint
        exists (fresh start: metric, journal and logger are untouched).
        """
        import orbax.checkpoint as ocp

        self._drain(reraise=True)
        path = path if path is not None else self.latest()
        if path is None:
            return None
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            manifest = json.load(f)
        # loud one-shot validation of the recorded jax version / topology
        # against the live process — a mismatch restores states but warns
        # that compile-environment-derived artifacts must be rebuilt
        validate_manifest_environment(manifest, context=f"restore from {path}")
        with ocp.PyTreeCheckpointer() as ckptr:
            tree = ckptr.restore(os.path.join(os.fspath(os.path.abspath(path)), STATE_DIR))
        load_metric_state_tree(metric, tree)
        if journal is not None:
            if manifest.get("journal") is not None:
                journal.load_state_dict(manifest["journal"])
            else:
                from metrics_tpu.utilities.prints import rank_zero_warn

                rank_zero_warn(
                    f"Checkpoint {path} carries no journal (it was saved without"
                    " journal=), but restore() was asked to populate one: the"
                    " resume cursor stays at (0, 0) and a loop gating on"
                    " should_fold will RE-FOLD every batch onto the restored"
                    " state — the double-count this subsystem exists to prevent."
                    " Save with journal= to make resume exactly-once.",
                    UserWarning,
                )
        if logger is not None and manifest.get("logger") is not None:
            logger.load_state_dict(manifest["logger"])
        if _obs_enabled():
            _obs_inc("ft.checkpoint_restores")
        return manifest
