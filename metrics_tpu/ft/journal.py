"""Exactly-once batch accounting for preemption-safe metric streams.

A metric checkpoint alone is not resume-safe: the training/eval loop that
feeds it must know *which batches the saved state already contains*, or a
restart re-folds the tail of the last epoch (double-count) or skips it
(drop). :class:`BatchJournal` is that missing piece — a monotonic
``(epoch, step)`` watermark advanced as batches are folded and persisted
inside every :class:`~metrics_tpu.ft.manager.CheckpointManager` manifest.

On restore, :attr:`BatchJournal.resume_from` hands the loop a
:class:`ResumeCursor` naming the first not-yet-folded batch:

* eager loops ask :meth:`BatchJournal.should_fold` per batch;
* :func:`~metrics_tpu.steps.make_epoch` consumers pass the cursor straight
  to the epoch entry point (``epoch(state, *batches, resume_from=cursor,
  epoch_index=e)``) and the already-folded leading batches of the resumed
  epoch are sliced off host-side before launch;
* :func:`trim_epoch_batches` is the same slicing as a standalone helper
  for hand-rolled pipelines.

Because ``Metric._update_count`` rides the checkpoint tree and the skipped
batches are never re-applied, the restored count stays exactly the
uninterrupted run's count — the invariant the kill-and-resume tests pin
bitwise (``tests/ft/test_kill_resume.py``).

Step indices are per-epoch (batch index within the epoch), epochs are
absolute; both are plain Python ints so the journal never touches the
device.
"""
from typing import Any, Dict, NamedTuple, Optional, Tuple

__all__ = ["BatchJournal", "ResumeCursor", "trim_epoch_batches"]


class ResumeCursor(NamedTuple):
    """First batch NOT yet folded into the checkpointed state."""

    epoch: int
    step: int


class BatchJournal:
    """Monotonic ``(epoch, step)`` watermark of folded batches.

    ``record(epoch, step)`` marks batch ``step`` of ``epoch`` as folded into
    metric state; out-of-order records raise (a regressing watermark means
    the caller's accounting is broken, and persisting it would corrupt every
    later resume). ``epoch_end(epoch, num_steps)`` is a convenience for
    whole-epoch folds (:func:`~metrics_tpu.steps.make_epoch`).

    Example::

        journal = BatchJournal()
        for epoch in range(E):
            for step, batch in enumerate(batches):
                if not journal.should_fold(epoch, step):
                    continue          # already in the restored state
                metric.update(*batch)
                journal.record(epoch, step)
            manager.save(metric, journal=journal, epoch=epoch)
    """

    def __init__(self) -> None:
        self._watermark: Optional[Tuple[int, int]] = None
        self._folded: int = 0

    # ------------------------------------------------------------------
    # Recording
    # ------------------------------------------------------------------

    def record(self, epoch: int, step: int) -> None:
        """Mark batch ``step`` of ``epoch`` as folded (monotonic)."""
        mark = (int(epoch), int(step))
        if mark[0] < 0 or mark[1] < 0:
            raise ValueError(f"epoch/step must be non-negative, got {mark}")
        if self._watermark is not None and mark <= self._watermark:
            raise ValueError(
                f"non-monotonic journal record {mark}: watermark is already {self._watermark}."
                " Each (epoch, step) may be folded exactly once."
            )
        self._watermark = mark
        self._folded += 1

    def epoch_end(self, epoch: int, num_steps: int) -> None:
        """Record a whole epoch of ``num_steps`` batches folded at once
        (counting any prefix of the epoch already on the watermark).

        Idempotent for epochs the watermark already covers: a resumed loop
        replays ``for e in range(num_epochs)`` from zero, the fused epoch
        entry no-ops on fully-folded epochs, and this must match — an
        already-recorded ``epoch_end`` is a no-op, never an error (unlike
        :meth:`record`, whose per-batch callers gate on
        :meth:`should_fold` instead).
        """
        if num_steps <= 0:
            return
        mark = (int(epoch), int(num_steps) - 1)
        if self._watermark is not None and mark <= self._watermark:
            return  # epoch already folded (resume replay)
        already = 0
        if self._watermark is not None and self._watermark[0] == int(epoch):
            already = self._watermark[1] + 1
        self._watermark = mark
        self._folded += int(num_steps) - already

    # ------------------------------------------------------------------
    # Resume
    # ------------------------------------------------------------------

    @property
    def watermark(self) -> Optional[Tuple[int, int]]:
        """Last folded ``(epoch, step)``, or None before any fold."""
        return self._watermark

    @property
    def folded(self) -> int:
        """Total batches folded — mirrors the metric's honest update count."""
        return self._folded

    @property
    def resume_from(self) -> ResumeCursor:
        """Cursor of the first batch a resumed loop must fold.

        The step index is within the watermark epoch; a loop whose epochs
        are shorter than ``watermark.step + 1`` simply finds
        :meth:`should_fold` False for the whole epoch and moves on.
        """
        if self._watermark is None:
            return ResumeCursor(0, 0)
        return ResumeCursor(self._watermark[0], self._watermark[1] + 1)

    def should_fold(self, epoch: int, step: int) -> bool:
        """False when batch ``(epoch, step)`` is already in the restored
        state — the exactly-once predicate for eager loops."""
        if self._watermark is None:
            return True
        return (int(epoch), int(step)) > self._watermark

    # ------------------------------------------------------------------
    # Persistence (rides the CheckpointManager manifest as plain JSON)
    # ------------------------------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        return {
            "watermark": None if self._watermark is None else list(self._watermark),
            "folded": self._folded,
        }

    def load_state_dict(self, state: Dict[str, Any]) -> "BatchJournal":
        mark = state.get("watermark")
        self._watermark = None if mark is None else (int(mark[0]), int(mark[1]))
        self._folded = int(state.get("folded", 0))
        return self

    def __repr__(self) -> str:
        return f"BatchJournal(watermark={self._watermark}, folded={self._folded})"


def trim_epoch_batches(cursor: Any, epoch_index: int, leaves: list) -> Tuple[list, int, bool]:
    """Slice already-folded leading batches off an epoch's stacked inputs.

    Args:
        cursor: a :class:`ResumeCursor` (or ``(epoch, step)`` tuple) from a
            restored journal, or a :class:`BatchJournal` itself.
        epoch_index: which epoch these batches belong to.
        leaves: the epoch's input leaves; array leaves carry the
            ``(num_batches, ...)`` epoch axis (non-arrays pass through).

    Returns:
        ``(trimmed_leaves, n_skipped, fully_folded)`` — ``fully_folded``
        True means every batch of this epoch is already in the restored
        state and the caller should skip the launch entirely.
    """
    if isinstance(cursor, BatchJournal):
        cursor = cursor.resume_from
    epoch0, step0 = int(cursor[0]), int(cursor[1])
    epoch_index = int(epoch_index)
    if epoch_index < epoch0:
        return leaves, _leading_axis(leaves), True
    if epoch_index > epoch0 or step0 == 0:
        return leaves, 0, False
    n_batches = _leading_axis(leaves)
    if step0 >= n_batches:
        return leaves, n_batches, True
    trimmed = [a[step0:] if _has_epoch_axis(a) else a for a in leaves]
    return trimmed, step0, False


def _has_epoch_axis(a: Any) -> bool:
    return getattr(a, "ndim", 0) >= 1


def _leading_axis(leaves: list) -> int:
    return next((a.shape[0] for a in leaves if _has_epoch_axis(a)), 0)
