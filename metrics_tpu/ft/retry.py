"""Retry / timeout / backoff policy for the eager DCN collectives.

The in-jit SPMD collectives (``lax.psum`` et al.) live inside XLA and fail
as a program; the *eager* cross-process path
(:func:`~metrics_tpu.utilities.distributed.gather_all_tensors` over
``multihost_utils.process_allgather``) is a host-side RPC against every
other process — on a preemptible pod it sees flaky hosts, restarting
workers and transient transport errors. The reference has no failure
handling there at all (``torchmetrics/utilities/distributed.py:102``: one
``all_gather``, hang or raise); this module gives the port a policy:

* **retry with exponential backoff** — transient failures are retried up
  to :attr:`RetryPolicy.max_retries` times, sleeping
  ``backoff_s * backoff_factor**attempt`` (capped at ``max_backoff_s``)
  between attempts; every retry bumps the ``ft.retries{op=...}`` counter.
* **decorrelated jitter** — with :attr:`RetryPolicy.jitter` set to
  ``"decorrelated"``, each sleep is drawn uniformly from
  ``[backoff_s, 3 * previous_sleep]`` (capped at ``max_backoff_s``): a
  thousand clients that lost the same aggregator at the same instant
  spread their retries across the window instead of thundering back in
  lockstep at ``backoff_s, 2*backoff_s, ...``. The randomness is
  **seeded, never wall-clock**: :attr:`RetryPolicy.jitter_seed` plus the
  op label (plus each caller's distinct identity folded into the seed)
  fully determines the schedule — :func:`backoff_schedule` exposes it, so
  tests pin exact sleep sequences and two processes with different seeds
  provably decorrelate.
* **timeout** — with :attr:`RetryPolicy.timeout_s` set, each attempt runs
  in a watchdog thread and a hang counts as a failure. The hung attempt's
  thread cannot be cancelled (the collective owns it); it is abandoned as
  a daemon — acceptable for a process that is about to degrade or die,
  which is exactly when timeouts fire. A timed-out attempt is NOT retried
  by default (:attr:`RetryPolicy.retry_on_timeout`): the abandoned call
  may still be inside the collective, and issuing a second concurrent one
  from the same process could pair with peers' collectives out of order —
  a timeout goes straight to the degraded fallback (or raises).
* **degraded fallback** — when retries are exhausted and the policy allows
  it, the caller's fallback produces a *per-host partial result* (for a
  gather: just the local shard) instead of hanging the fleet; a one-shot
  ``rank_zero_warn`` per op names the degradation and the
  ``ft.degraded_syncs{op=...}`` counter records every occurrence, so a
  degraded eval is loud in both logs and the obs snapshot.

Fault injection: each attempt first consults
:func:`metrics_tpu.ft.faults.maybe_fail` under the op label, so tests arm
transient failures without touching the network stack.
"""
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, replace
from typing import Any, Callable, Dict, Iterator, Optional, Set

from metrics_tpu.ft import faults as _faults
from metrics_tpu.obs.registry import enabled as _obs_enabled
from metrics_tpu.obs.registry import inc as _obs_inc
from metrics_tpu.utilities.prints import rank_zero_warn

__all__ = [
    "AttemptTimeout",
    "DegradedSyncError",
    "RetryPolicy",
    "active_scope_degraded",
    "backoff_schedule",
    "call_with_retries",
    "collective_fence_armed",
    "configure_retries",
    "degraded_sync_scope",
    "get_retry_policy",
    "reset_collective_fence",
    "reset_degraded_warnings",
]


class DegradedSyncError(RuntimeError):
    """Retries exhausted and the policy forbids the degraded fallback."""


class AttemptTimeout(TimeoutError):
    """An attempt exceeded ``RetryPolicy.timeout_s`` (watchdog-raised; the
    abandoned attempt may still be running inside the collective)."""


@dataclass(frozen=True)
class RetryPolicy:
    """Failure-handling policy for one eager collective call.

    Args:
        max_retries: attempts AFTER the first (0 = fail fast).
        backoff_s: sleep before the first retry.
        backoff_factor: multiplier per further retry.
        max_backoff_s: backoff ceiling.
        timeout_s: per-attempt wall-clock budget (None = no watchdog — a
            hard-hung collective is then NOT detected; set this on
            preemptible fleets).
        deadline_s: TOTAL wall-clock budget for the whole retry cycle
            (None = unbounded). The attempt cap bounds *how many* retries
            run, but a full backoff schedule can still stack far past the
            caller's own timeout — a cross-region replication tick with a
            2 s cadence must not sleep 30 s into the next three ticks. The
            deadline truncates the backoff schedule so cumulative sleep
            never exceeds it (:func:`backoff_schedule` reflects the
            truncation deterministically — pinnable in tests), and
            ``call_with_retries`` additionally stops retrying the moment
            the measured elapsed time (attempts included) reaches the
            budget. Exhausting the deadline behaves exactly like
            exhausting ``max_retries``: degraded fallback or raise.
        degraded_fallback: on exhaustion, return the caller's per-host
            partial result instead of raising.
        retry_on_timeout: retry after a timed-out attempt. Default False:
            the abandoned attempt may still sit inside the collective, and
            a second concurrent call from this process could mis-pair with
            peers' collectives — a timeout exhausts immediately. Enable
            only for ops that are safe to run concurrently with their own
            ghost (idempotent RPCs, not collectives).
        non_retryable: exception types re-raised immediately — no retry,
            no degradation. Defaults to the deterministic programming-error
            family (a TypeError from a bad state leaf will fail every
            retry identically, and degrading it would silently turn a bug
            into fleet-wide local-only metric values forever). Transport /
            runtime failures stay retryable.
        jitter: ``"none"`` (pure exponential — attempt N sleeps
            ``backoff_s * backoff_factor**N``) or ``"decorrelated"`` —
            each sleep drawn uniformly from ``[backoff_s, 3 * previous]``,
            capped at ``max_backoff_s``. Use decorrelated whenever MANY
            callers share one failure (1k serve clients retrying a downed
            aggregator): synchronized exponential backoff re-arrives in
            waves exactly ``backoff_factor`` apart, which is the
            thundering herd with extra steps.
        jitter_seed: base seed for the jitter stream. The effective
            per-call stream is ``sha256(jitter_seed, op)`` — deterministic
            and pinnable in tests (no wall-clock randomness), while
            distinct seeds (e.g. a hash of the client id) give distinct,
            decorrelated schedules. ``None`` draws a fresh OS-entropy seed
            per call: maximal spread, not reproducible.
    """

    max_retries: int = 3
    backoff_s: float = 0.25
    backoff_factor: float = 2.0
    max_backoff_s: float = 30.0
    timeout_s: Optional[float] = None
    deadline_s: Optional[float] = None
    degraded_fallback: bool = True
    retry_on_timeout: bool = False
    non_retryable: tuple = (TypeError, ValueError, AssertionError, NotImplementedError)
    jitter: str = "none"
    jitter_seed: Optional[int] = None

    def __post_init__(self) -> None:
        # a negative count would run ZERO attempts and "degrade" without
        # ever issuing the collective
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries}")
        if self.timeout_s is not None and self.timeout_s <= 0:
            raise ValueError(f"timeout_s must be positive (or None), got {self.timeout_s}")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(f"deadline_s must be positive (or None), got {self.deadline_s}")
        if self.jitter not in ("none", "decorrelated"):
            raise ValueError(
                f"jitter must be 'none' or 'decorrelated', got {self.jitter!r}"
            )


_policy = RetryPolicy()
_policy_lock = threading.Lock()
_warned_ops: Set[str] = set()
# once ANY attempt in this process has failed or timed out, a ghost /
# mis-paired collective becomes possible — consumers (the gather's
# self-echo fence) stay on the unfenced fast path until then
_observed_failures = False
_scope_tls = threading.local()


def configure_retries(**kwargs: Any) -> RetryPolicy:
    """Update fields of the process-wide default policy; returns the
    PREVIOUS policy (pass its fields back to restore)."""
    global _policy
    with _policy_lock:
        previous = _policy
        _policy = replace(_policy, **kwargs)
    return previous


def get_retry_policy() -> RetryPolicy:
    """The current process-wide default policy."""
    return _policy


def reset_degraded_warnings() -> None:
    """Re-arm the one-shot per-op degraded-mode warning (test hook, and for
    long-lived processes that want the warning once per incident window)."""
    with _policy_lock:
        _warned_ops.clear()


def collective_fence_armed() -> bool:
    """True once any retry attempt in this process failed or timed out.

    Before that, no abandoned/ghost collective can exist in this process,
    so consistency fences (the gather's self-echo check) can skip their
    per-call cost; afterwards they stay armed for the process lifetime
    (a ghost can linger arbitrarily long inside a hung collective)."""
    return _observed_failures


def reset_collective_fence() -> None:
    """Disarm the failure-observed flag (test hook only: in production a
    ghost collective can outlive any incident window)."""
    global _observed_failures
    _observed_failures = False


@contextmanager
def degraded_sync_scope():
    """Observe whether any ``call_with_retries`` on this thread degraded
    while the scope was open.

    Yields a dict whose ``"degraded"`` flag flips True the moment a call
    inside the scope takes its fallback — the hook
    :meth:`Metric._sync_dist` uses to make degradation atomic across a
    multi-state sync (one state gathered globally + another degraded
    locally would be a hybrid worse than either)."""
    stack = getattr(_scope_tls, "stack", None)
    if stack is None:
        stack = _scope_tls.stack = []
    box = {"degraded": False}
    stack.append(box)
    try:
        yield box
    finally:
        stack.pop()


def active_scope_degraded() -> bool:
    """True when an enclosing :func:`degraded_sync_scope` on this thread has
    already degraded. Later collectives in the same scope consult this to
    short-circuit straight to their per-host partial: their results will be
    discarded by the atomic fallback anyway, so paying the full
    retry/backoff cycle per remaining state (and bumping
    ``ft.degraded_syncs`` once per state) would only stall the sync and
    inflate the counter."""
    return any(box["degraded"] for box in getattr(_scope_tls, "stack", []) or [])


def backoff_schedule(policy: RetryPolicy, op: str = "") -> Iterator[float]:
    """The policy's deterministic sleep schedule, one delay per retry.

    For ``jitter="none"`` this is the plain capped exponential. For
    ``jitter="decorrelated"`` it is the seeded decorrelated-jitter chain:
    ``d_0 ~ U[backoff_s, 3*backoff_s]``, ``d_n ~ U[backoff_s, 3*d_{n-1}]``,
    every draw capped at ``max_backoff_s``. The stream is a pure function
    of ``(jitter_seed, op)`` — the property the thundering-herd tests pin
    (same seed → same schedule; different seeds → decorrelated ones). With
    ``jitter_seed=None`` the stream seeds from OS entropy per call.

    With :attr:`RetryPolicy.deadline_s` set, the schedule is additionally
    truncated so the CUMULATIVE sleep never exceeds the deadline: the
    first delay that would overrun yields only the remaining budget, and
    the schedule then STOPS (``StopIteration``) — deterministic, so the
    exact truncated production sleeps are pinnable too. (Attempt run time
    also spends the budget; ``call_with_retries`` enforces that half
    against the wall clock.)

    ``call_with_retries`` consumes exactly this generator, so a pinned
    schedule in a test is the schedule production sleeps.
    """
    budget = policy.deadline_s

    def _spend(delay: float) -> Iterator[float]:
        # truncate against the remaining deadline budget; a zero-budget
        # yield would be a pointless no-sleep retry, so the schedule ends
        nonlocal budget
        if budget is not None:
            if budget <= 0.0:
                return
            delay = min(delay, budget)
            budget -= delay
        yield delay

    if policy.jitter == "none":
        delay = policy.backoff_s
        while True:
            yield from _spend(min(delay, policy.max_backoff_s))
            if budget is not None and budget <= 0.0:
                return
            delay *= policy.backoff_factor
    import hashlib
    import random

    if policy.jitter_seed is None:
        rng = random.Random()  # OS entropy: spread, not reproducible
    else:
        digest = hashlib.sha256(f"{policy.jitter_seed}:{op}".encode()).digest()
        rng = random.Random(int.from_bytes(digest[:8], "little"))
    prev = policy.backoff_s
    while True:
        prev = min(rng.uniform(policy.backoff_s, 3.0 * prev), policy.max_backoff_s)
        yield from _spend(prev)
        if budget is not None and budget <= 0.0:
            return


def _attempt(fn: Callable[[], Any], timeout_s: Optional[float], op: str) -> Any:
    _faults.maybe_fail(op)
    if timeout_s is None:
        return fn()
    box: Dict[str, Any] = {}
    done = threading.Event()

    def runner() -> None:
        try:
            box["value"] = fn()
        except BaseException as err:  # noqa: BLE001 — relayed to the caller below
            box["error"] = err
        finally:
            done.set()

    # daemon: a hung collective keeps its thread; the watchdog abandons it
    thread = threading.Thread(target=runner, daemon=True, name=f"ft-retry-{op}")
    thread.start()
    if not done.wait(timeout_s):
        raise AttemptTimeout(f"{op} exceeded timeout_s={timeout_s}")
    if "error" in box:
        raise box["error"]
    return box["value"]


def call_with_retries(
    fn: Callable[[], Any],
    *,
    op: str,
    policy: Optional[RetryPolicy] = None,
    fallback: Optional[Callable[[BaseException], Any]] = None,
) -> Any:
    """Run ``fn`` under the retry/timeout/degraded policy.

    Args:
        fn: zero-arg callable performing the collective.
        op: label for counters, warnings and fault injection
            (e.g. ``"gather_all_tensors"``).
        policy: override the process-wide default for this call.
        fallback: ``(last_error) -> degraded result`` — the per-host
            partial answer used when retries are exhausted and
            ``degraded_fallback`` is set. Without one, exhaustion raises
            :class:`DegradedSyncError` regardless of the policy.

    Returns:
        ``fn()``'s result, or the fallback's degraded result.
    """
    p = policy if policy is not None else _policy
    delays = backoff_schedule(p, op)
    start = time.monotonic()
    last_error: Optional[BaseException] = None
    attempts = 0
    for attempt in range(p.max_retries + 1):
        try:
            attempts += 1
            return _attempt(fn, p.timeout_s, op)
        except Exception as err:  # noqa: BLE001 — policy decides what survives
            if isinstance(err, p.non_retryable):
                raise  # deterministic bug: every retry would fail identically
            last_error = err
            global _observed_failures
            _observed_failures = True  # ghost collectives now possible; arm fences
            if isinstance(err, AttemptTimeout) and not p.retry_on_timeout:
                break  # the ghost attempt may still be in flight; don't race it
            if attempt < p.max_retries:
                # the deadline covers attempts AND sleeps: the schedule
                # already bounds cumulative sleep, but slow failing
                # attempts spend the budget too — measure the wall clock
                # and stop the cycle the moment it is gone (exhaustion,
                # same as running out of attempts)
                remaining = (
                    None if p.deadline_s is None else p.deadline_s - (time.monotonic() - start)
                )
                if remaining is not None and remaining <= 0.0:
                    break
                try:
                    delay = next(delays)
                except StopIteration:
                    break  # schedule's sleep budget exhausted
                if remaining is not None:
                    delay = min(delay, remaining)
                if _obs_enabled():
                    _obs_inc("ft.retries", op=op)
                time.sleep(delay)
    assert last_error is not None
    # report the attempts that actually ran — a no-retry timeout breaks out
    # after ONE, and claiming max_retries+1 would mislead incident triage
    if p.degraded_fallback and fallback is not None:
        if _obs_enabled():
            _obs_inc("ft.degraded_syncs", op=op)
        with _policy_lock:
            first = op not in _warned_ops
            _warned_ops.add(op)
        if first:
            rank_zero_warn(
                f"{op} failed after {attempts} attempt(s) ({last_error!r});"
                " degrading to per-host partial results for this and further"
                " occurrences. Metric values on this host now reflect ONLY its"
                " local shard until the collective recovers."
                " (ft.degraded_syncs counts every degraded sync.)",
                RuntimeWarning,
            )
        for box in getattr(_scope_tls, "stack", []) or []:
            box["degraded"] = True
        return fallback(last_error)
    reason = (
        "the policy forbids degraded mode (degraded_fallback=False)"
        if not p.degraded_fallback
        else "the call site provided no fallback"
    )
    raise DegradedSyncError(f"{op} failed after {attempts} attempt(s) and {reason}") from last_error
