"""``metrics_tpu.ft`` — fault tolerance for preemptible, flaky fleets.

Production-scale eval on preemptible TPU pods fails in three ways the core
library must survive (the ROADMAP north star): the process is **killed**
mid-sweep (preemption), a peer host is **flaky** during an eager DCN sync,
and a checkpoint write is **torn** by the kill. Four components, one per
failure mode plus the tooling to prove them:

1. :class:`~metrics_tpu.ft.manager.CheckpointManager` — atomic
   (stage + rename) rotating checkpoints with async background saves,
   monotonic latest-checkpoint discovery and a bundled manifest
   (watermark, topology, obs snapshot, logger history).
2. :class:`~metrics_tpu.ft.journal.BatchJournal` — exactly-once batch
   accounting: a monotonic ``(epoch, step)`` watermark saved with every
   checkpoint; on restore the :class:`~metrics_tpu.ft.journal.ResumeCursor`
   tells the loop (or ``make_epoch``'s ``resume_from=``) which batches are
   already folded, so a preempted run resumes with bitwise-identical
   ``compute()`` — no drops, no double counts.
3. :mod:`~metrics_tpu.ft.retry` — retry/timeout/backoff around the eager
   DCN collectives with a degraded local-only fallback: exhausted retries
   return per-host partial results, warn once, and bump
   ``ft.retries``/``ft.degraded_syncs`` in the obs registry instead of
   hanging the fleet.
4. :mod:`~metrics_tpu.ft.faults` — the fault-injection harness (transient
   gather failures, crash-mid-save, clock-skewed manifests) the
   kill-and-resume and degraded-sync tests are built on.

Convenience surface: ``Metric.save(path)`` / ``Metric.restore(path)`` and
the :class:`~metrics_tpu.collections.MetricCollection` equivalents wrap
the atomic single-checkpoint path; reach for the manager when you need
rotation, manifests or async saves. See ``docs/fault_tolerance.md``.

Always-on monitors are first-class here too: streaming sketch states and
window-ring bookkeeping (:mod:`metrics_tpu.streaming`) ride the same
manifest round-trip, and gating folds on the journal watermark makes a
preempted monitoring loop's resume reproduce ``compute()`` bitwise
(``tests/streaming/test_windows.py``).
"""
from metrics_tpu.ft import faults  # noqa: F401  (import order: retry consumes it)
from metrics_tpu.ft.journal import BatchJournal, ResumeCursor, trim_epoch_batches
from metrics_tpu.ft.retry import (
    AttemptTimeout,
    DegradedSyncError,
    RetryPolicy,
    backoff_schedule,
    call_with_retries,
    configure_retries,
    get_retry_policy,
    reset_degraded_warnings,
)
from metrics_tpu.ft.manager import CheckpointManager, validate_manifest_environment

__all__ = [
    "AttemptTimeout",
    "BatchJournal",
    "CheckpointManager",
    "DegradedSyncError",
    "ResumeCursor",
    "RetryPolicy",
    "backoff_schedule",
    "call_with_retries",
    "configure_retries",
    "faults",
    "get_retry_policy",
    "reset_degraded_warnings",
    "trim_epoch_batches",
    "validate_manifest_environment",
]
