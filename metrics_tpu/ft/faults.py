"""Fault-injection harness for the fault-tolerance subsystem.

Correctness tooling, not production machinery: the kill-and-resume and
degraded-sync guarantees in :mod:`metrics_tpu.ft` are only guarantees if a
test can *make* the failure happen on demand. This module arms named
injection points that the production seams consult:

* ``"checkpoint.pre_rename"`` — inside
  :func:`metrics_tpu.utilities.checkpoint.atomic_dir_swap`, after the
  staged checkpoint is fully written but BEFORE the atomic rename — the
  crash-mid-save window. Injecting here must never corrupt the previous
  "latest" checkpoint.
* ``"gather_all_tensors"`` / any retry ``op`` label — inside
  :func:`metrics_tpu.ft.retry.call_with_retries`, before each attempt —
  transient DCN collective failures.
* clock skew — :func:`clock_skew` shifts the wall clock the
  :class:`~metrics_tpu.ft.manager.CheckpointManager` stamps into manifests,
  so ordering-by-timestamp bugs (NTP drift across hosts) become testable;
  discovery must order by monotonic sequence number instead.

Production cost when nothing is armed: :func:`maybe_fail` is a single
dict read per seam hit (the module rides the normal ``metrics_tpu.ft``
import; seams in ``utilities/`` import it deferred only to avoid the
module-level cycle with ``ft.manager``).
"""
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional, Type

__all__ = [
    "FaultInjected",
    "SimulatedPreemption",
    "armed",
    "clock_skew",
    "crash_mid_save",
    "inject",
    "maybe_fail",
    "now",
    "transient_gather_failures",
]

_lock = threading.Lock()
_armed: Dict[str, Dict[str, Any]] = {}
_clock_skew_s: float = 0.0


class FaultInjected(RuntimeError):
    """Raised at an armed injection point (a simulated transient failure)."""


class SimulatedPreemption(FaultInjected):
    """A simulated preemption/crash (e.g. SIGKILL mid-save) for in-process
    tests; the CI smoke test delivers a real SIGKILL from a subprocess."""


def maybe_fail(point: str) -> None:
    """Raise at ``point`` if a fault is armed there; no-op otherwise.

    Called by the production seams (checkpoint rename, retry attempts).
    ``after`` skips the first N hits; ``count`` bounds how many raise.
    """
    spec = _armed.get(point)
    if spec is None:
        return
    with _lock:
        spec = _armed.get(point)
        if spec is None:
            return
        if spec["after"] > 0:
            spec["after"] -= 1
            return
        if spec["count"] <= 0:
            return
        spec["count"] -= 1
        spec["raised"] += 1
        exc = spec["exc"]
    raise exc(f"injected fault at {point!r}")


@contextmanager
def inject(
    point: str,
    *,
    count: int = 1,
    after: int = 0,
    exc: Type[BaseException] = FaultInjected,
) -> Iterator[Dict[str, Any]]:
    """Arm injection point ``point`` for the duration of the ``with`` block.

    The first ``after`` hits pass through, then the next ``count`` hits
    raise ``exc``. Yields the live spec dict — ``spec["raised"]`` counts
    how many faults actually fired (assert it in tests so a fault that was
    never reached cannot silently pass).
    """
    spec = {"count": int(count), "after": int(after), "exc": exc, "raised": 0}
    with _lock:
        if point in _armed:
            raise RuntimeError(f"injection point {point!r} is already armed")
        _armed[point] = spec
    try:
        yield spec
    finally:
        with _lock:
            _armed.pop(point, None)


@contextmanager
def transient_gather_failures(
    count: int = 1, *, after: int = 0, exc: Type[BaseException] = FaultInjected
) -> Iterator[Dict[str, Any]]:
    """Fail the next ``count`` eager DCN gather attempts (retry op
    ``"gather_all_tensors"``) — the transient-collective scenario the
    :mod:`metrics_tpu.ft.retry` policy exists for."""
    with inject("gather_all_tensors", count=count, after=after, exc=exc) as spec:
        yield spec


@contextmanager
def crash_mid_save(count: int = 1, *, after: int = 0) -> Iterator[Dict[str, Any]]:
    """Simulate a crash after the checkpoint payload is staged but before
    the atomic rename publishes it — the previous checkpoint must survive
    intact and discovery must not see a half-written one."""
    with inject("checkpoint.pre_rename", count=count, after=after, exc=SimulatedPreemption) as spec:
        yield spec


def now() -> float:
    """``time.time()`` plus any armed clock skew — the manifest timestamp
    source for :class:`~metrics_tpu.ft.manager.CheckpointManager`."""
    return time.time() + _clock_skew_s


@contextmanager
def clock_skew(offset_s: float) -> Iterator[None]:
    """Shift the manifest wall clock by ``offset_s`` seconds (positive =
    future). Checkpoints saved under skew get lying timestamps; ordering
    must come from the monotonic sequence number, never from the clock."""
    global _clock_skew_s
    previous = _clock_skew_s
    _clock_skew_s = float(offset_s)
    try:
        yield
    finally:
        _clock_skew_s = previous


def armed(point: Optional[str] = None) -> bool:
    """True when ``point`` (or, with None, anything) is armed."""
    if point is None:
        return bool(_armed)
    return point in _armed
