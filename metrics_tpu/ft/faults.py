"""Fault-injection harness for the fault-tolerance subsystem.

Correctness tooling, not production machinery: the kill-and-resume and
degraded-sync guarantees in :mod:`metrics_tpu.ft` are only guarantees if a
test can *make* the failure happen on demand. This module arms named
injection points that the production seams consult:

* ``"checkpoint.pre_rename"`` — inside
  :func:`metrics_tpu.utilities.checkpoint.atomic_dir_swap`, after the
  staged checkpoint is fully written but BEFORE the atomic rename — the
  crash-mid-save window. Injecting here must never corrupt the previous
  "latest" checkpoint.
* ``"gather_all_tensors"`` / any retry ``op`` label — inside
  :func:`metrics_tpu.ft.retry.call_with_retries`, before each attempt —
  transient DCN collective failures.
* clock skew — :func:`clock_skew` shifts the wall clock the
  :class:`~metrics_tpu.ft.manager.CheckpointManager` stamps into manifests,
  so ordering-by-timestamp bugs (NTP drift across hosts) become testable;
  discovery must order by monotonic sequence number instead.

**Serve-level chaos** (the :mod:`metrics_tpu.serve` self-healing harness):
:class:`WireChaos` is a *seeded* per-payload fault schedule for the
serving tier's delivery path — drop / duplicate / reorder / corrupt /
delay decisions drawn from one ``random.Random(seed)`` so an entire chaos
run is reproducible bit for bit and the harness can compute the exact
oracle set of accepted snapshots; :func:`corrupt_payload` flips a body
byte so the wire format's per-leaf crc32 must refuse it;
:func:`partition` severs tree nodes' uplinks for the duration of a
``with`` block (the subtree heals by cumulative re-ship on exit); and
:func:`kill_node` hard-kills a tree node the way a SIGKILL would (state
gone, no cleanup) for a :class:`~metrics_tpu.serve.resilience.Supervisor`
to detect and rebuild. Every injected event is counted under
``chaos.injected{kind=}`` when the obs layer is armed, so a chaos run's
fault budget is auditable from the same snapshot as its effects.

Production cost when nothing is armed: :func:`maybe_fail` is a single
dict read per seam hit (the module rides the normal ``metrics_tpu.ft``
import; seams in ``utilities/`` import it deferred only to avoid the
module-level cycle with ``ft.manager``).
"""
import random
import struct
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple, Type

__all__ = [
    "FaultInjected",
    "SimulatedPreemption",
    "WireChaos",
    "armed",
    "clock_skew",
    "corrupt_payload",
    "crash_mid_save",
    "drain_node",
    "inject",
    "join_node",
    "kill_node",
    "kill_region",
    "maybe_fail",
    "now",
    "partition",
    "promote_region",
    "region_partition",
    "split_node",
    "transient_gather_failures",
]

_lock = threading.Lock()
_armed: Dict[str, Dict[str, Any]] = {}
_clock_skew_s: float = 0.0


class FaultInjected(RuntimeError):
    """Raised at an armed injection point (a simulated transient failure)."""


class SimulatedPreemption(FaultInjected):
    """A simulated preemption/crash (e.g. SIGKILL mid-save) for in-process
    tests; the CI smoke test delivers a real SIGKILL from a subprocess."""


def maybe_fail(point: str) -> None:
    """Raise at ``point`` if a fault is armed there; no-op otherwise.

    Called by the production seams (checkpoint rename, retry attempts).
    ``after`` skips the first N hits; ``count`` bounds how many raise.
    """
    spec = _armed.get(point)
    if spec is None:
        return
    with _lock:
        spec = _armed.get(point)
        if spec is None:
            return
        if spec["after"] > 0:
            spec["after"] -= 1
            return
        if spec["count"] <= 0:
            return
        spec["count"] -= 1
        spec["raised"] += 1
        exc = spec["exc"]
    raise exc(f"injected fault at {point!r}")


@contextmanager
def inject(
    point: str,
    *,
    count: int = 1,
    after: int = 0,
    exc: Type[BaseException] = FaultInjected,
) -> Iterator[Dict[str, Any]]:
    """Arm injection point ``point`` for the duration of the ``with`` block.

    The first ``after`` hits pass through, then the next ``count`` hits
    raise ``exc``. Yields the live spec dict — ``spec["raised"]`` counts
    how many faults actually fired (assert it in tests so a fault that was
    never reached cannot silently pass).
    """
    spec = {"count": int(count), "after": int(after), "exc": exc, "raised": 0}
    with _lock:
        if point in _armed:
            raise RuntimeError(f"injection point {point!r} is already armed")
        _armed[point] = spec
    try:
        yield spec
    finally:
        with _lock:
            _armed.pop(point, None)


@contextmanager
def transient_gather_failures(
    count: int = 1, *, after: int = 0, exc: Type[BaseException] = FaultInjected
) -> Iterator[Dict[str, Any]]:
    """Fail the next ``count`` eager DCN gather attempts (retry op
    ``"gather_all_tensors"``) — the transient-collective scenario the
    :mod:`metrics_tpu.ft.retry` policy exists for."""
    with inject("gather_all_tensors", count=count, after=after, exc=exc) as spec:
        yield spec


@contextmanager
def crash_mid_save(count: int = 1, *, after: int = 0) -> Iterator[Dict[str, Any]]:
    """Simulate a crash after the checkpoint payload is staged but before
    the atomic rename publishes it — the previous checkpoint must survive
    intact and discovery must not see a half-written one."""
    with inject("checkpoint.pre_rename", count=count, after=after, exc=SimulatedPreemption) as spec:
        yield spec


def now() -> float:
    """``time.time()`` plus any armed clock skew — the manifest timestamp
    source for :class:`~metrics_tpu.ft.manager.CheckpointManager`."""
    return time.time() + _clock_skew_s


@contextmanager
def clock_skew(offset_s: float) -> Iterator[None]:
    """Shift the manifest wall clock by ``offset_s`` seconds (positive =
    future). Checkpoints saved under skew get lying timestamps; ordering
    must come from the monotonic sequence number, never from the clock."""
    global _clock_skew_s
    previous = _clock_skew_s
    _clock_skew_s = float(offset_s)
    try:
        yield
    finally:
        _clock_skew_s = previous


def armed(point: Optional[str] = None) -> bool:
    """True when ``point`` (or, with None, anything) is armed."""
    if point is None:
        return bool(_armed)
    return point in _armed


# ----------------------------------------------------------------------
# Serve-level chaos: seeded wire-delivery faults, partitions, node kills
# ----------------------------------------------------------------------

# the serve wire preamble (metrics_tpu/serve/wire.py _PREAMBLE): magic,
# major, minor, header length — duplicated here rather than imported so the
# ft layer never pulls the serve package in at import time
_WIRE_PREAMBLE = struct.Struct("<4sHHI")


def _chaos_inc(kind: str) -> None:
    from metrics_tpu.obs.registry import enabled as _obs_enabled
    from metrics_tpu.obs.registry import inc as _obs_inc

    if _obs_enabled():
        _obs_inc("chaos.injected", kind=kind)


def corrupt_payload(data: bytes, rng: random.Random) -> bytes:
    """Flip one random byte of a wire payload's LEAF BODY.

    The returned bytes frame and parse — the corruption is in a leaf's
    extent, so ``decode_state`` must refuse it via the per-leaf crc32,
    naming the leaf (the integrity contract the minor-1 wire bump added).
    Payloads too short to carry a body get a header byte flipped instead
    (refused as malformed JSON / bad framing — still refused, just
    unattributable)."""
    if not data:
        return data
    body_start = _WIRE_PREAMBLE.size
    if len(data) >= _WIRE_PREAMBLE.size:
        body_start += _WIRE_PREAMBLE.unpack_from(data)[3]
    at = rng.randrange(min(body_start, len(data) - 1), len(data))
    flipped = bytearray(data)
    flipped[at] ^= rng.randrange(1, 256)
    return bytes(flipped)


class WireChaos:
    """Seeded per-payload fault schedule for serve-tier delivery.

    One ``random.Random(seed)`` drives every decision, so a chaos run is
    reproducible and the harness can derive the exact **oracle**: a
    payload whose fate is ``drop`` or ``corrupt`` is never accepted
    (corruption is refused by the wire crc32); every other fate delivers
    the original bytes at least once eventually, and under the
    aggregator's keep-latest dedup contributes iff it carries the
    client's highest delivered watermark.

    The harness drives it payload by payload::

        chaos = WireChaos(seed=7, p_drop=0.03, p_corrupt=0.02, ...)
        for blob in round_payloads:
            fate, now_blobs = chaos.plan(blob)
            deliver(now_blobs)                  # [] for drop/reorder/delay
        deliver(chaos.end_round())              # reorders (shuffled) + held delays
        ...
        deliver(chaos.flush())                  # stream end: everything still held

    ``reorder`` re-delivers within the same round in shuffled order;
    ``delay`` holds the payload until the NEXT round boundary. For
    cumulative keep-latest snapshots both reduce to out-of-order delivery
    — exactly the hostility the watermark dedup must absorb. ``counts``
    tallies every fate; each non-``deliver`` fate also bumps the
    ``chaos.injected{kind=}`` obs counter when the layer is armed.
    """

    FATES = ("drop", "duplicate", "reorder", "corrupt", "delay")

    def __init__(
        self,
        seed: int,
        *,
        p_drop: float = 0.02,
        p_duplicate: float = 0.03,
        p_reorder: float = 0.05,
        p_corrupt: float = 0.02,
        p_delay: float = 0.03,
    ) -> None:
        probs = dict(
            drop=p_drop, duplicate=p_duplicate, reorder=p_reorder, corrupt=p_corrupt, delay=p_delay
        )
        for kind, p in probs.items():
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"p_{kind} must be in [0, 1], got {p}")
        if sum(probs.values()) > 1.0:
            raise ValueError(f"fault probabilities sum to {sum(probs.values())} > 1")
        self._rng = random.Random(seed)
        self._probs = probs
        self.counts: Dict[str, int] = {kind: 0 for kind in self.FATES}
        self.counts["deliver"] = 0
        self._reordered: List[bytes] = []
        self._delayed: List[bytes] = []

    def plan(self, payload: bytes) -> Tuple[str, List[bytes]]:
        """Draw this payload's fate; returns ``(fate, deliver_now)``."""
        draw = self._rng.random()
        fate = "deliver"
        upto = 0.0
        for kind in self.FATES:
            upto += self._probs[kind]
            if draw < upto:
                fate = kind
                break
        self.counts[fate] += 1
        if fate != "deliver":
            _chaos_inc(fate)
        if fate == "drop":
            return fate, []
        if fate == "duplicate":
            return fate, [payload, payload]
        if fate == "corrupt":
            return fate, [corrupt_payload(payload, self._rng)]
        if fate == "reorder":
            self._reordered.append(payload)
            return fate, []
        if fate == "delay":
            self._delayed.append(payload)
            return fate, []
        return fate, [payload]

    def end_round(self) -> List[bytes]:
        """Payloads due at this round boundary: the round's reordered
        payloads (shuffled) plus anything delayed from earlier rounds."""
        due, self._reordered = self._reordered, []
        self._rng.shuffle(due)
        delayed, self._delayed = self._delayed, []
        return delayed + due

    def flush(self) -> List[bytes]:
        """Everything still held (stream end — nothing may be lost that
        chaos did not explicitly drop, or the oracle would be wrong)."""
        return self.end_round()

    def shuffle(self, items: Sequence[Any]) -> List[Any]:
        """Seeded shuffle from the same stream (harness-side ordering)."""
        out = list(items)
        self._rng.shuffle(out)
        return out

    def choice(self, items: Sequence[Any]) -> Any:
        """Seeded pick from the same stream (e.g. WHICH node to kill)."""
        return items[self._rng.randrange(len(items))]


@contextmanager
def partition(*nodes: Any) -> Iterator[None]:
    """Sever the uplink of serve tree nodes for the ``with`` block.

    Every :meth:`~metrics_tpu.serve.tree.AggregatorNode.forward` ship from
    a partitioned node is silently dropped (counted under
    ``chaos.injected{kind=partition}``) — the network-partition half of
    the self-healing contract. On exit the original transport is restored
    (the heal); the next forward ships the node's **cumulative** snapshot,
    so the parent's view converges with nothing replayed. The parent-side
    symptom during the partition is a growing child ship age — the
    ``stale_child`` condition :class:`~metrics_tpu.serve.resilience.Supervisor`
    flags."""

    def _drop(_payload: bytes) -> None:
        _chaos_inc("partition")

    saved = [(node, node._send) for node in nodes]
    for node in nodes:
        node._send = _drop
    try:
        yield
    finally:
        for node, send in saved:
            node._send = send


def kill_node(node: Any) -> None:
    """Hard-kill a serve tree node (``AggregatorNode.hard_kill``): its
    in-memory state vanishes with no cleanup, the in-process analogue of
    SIGKILL (the real-signal arm lives in the preemption/serve smokes).
    Counted under ``chaos.injected{kind=kill}``; detection and rebuild are
    the :class:`~metrics_tpu.serve.resilience.Supervisor`'s job."""
    _chaos_inc("kill")
    node.hard_kill()


# -- topology-churn injectors (the elastic_smoke harness's levers) ---------
#
# Thin seams over the real membership operations of
# :class:`~metrics_tpu.serve.elastic.ElasticFleet` — the chaos harness does
# not get a private rebalance implementation, it drives the production one
# (exactly one correctness mechanism), and every churn event it injects is
# auditable from the same ``chaos.injected{kind=}`` family as the wire
# faults, alongside the production ``serve.rebalances{kind=}`` counters.


def join_node(fleet: Any, name: Optional[str] = None, parent: Any = None) -> Any:
    """Inject a live node JOIN mid-run (``chaos.injected{kind=join}``):
    the full admission protocol — build, warm, readiness probe, ring
    admission, client re-homing — runs under whatever wire faults are
    armed. Returns the admitted node."""
    _chaos_inc("join")
    return fleet.join_node(name, parent)


def drain_node(fleet: Any, node: Any, **kwargs: Any) -> Any:
    """Inject a live node DRAIN mid-run (``chaos.injected{kind=drain}``):
    ring exit, queue folded to empty, final cumulative ship, client
    handoff, subtree re-parenting, tombstoned retirement — nothing the
    node accepted may be lost, which the smoke's bitwise oracle checks.
    Returns the drain summary."""
    _chaos_inc("drain")
    return fleet.drain_node(node, **kwargs)


def split_node(fleet: Any, node: Any, name: Optional[str] = None) -> Any:
    """Inject a live SPLIT of an overloaded node mid-run
    (``chaos.injected{kind=split}``): a sibling joins under the same
    parent and the ring hands it its share of keys. Returns the new
    node."""
    _chaos_inc("split")
    return fleet.split_node(node, name)


# -- multi-region injectors (the region_smoke harness's levers) ------------
#
# Same philosophy as the churn injectors: thin seams over the PRODUCTION
# mechanisms of :class:`~metrics_tpu.serve.region.RegionalMesh` — the
# chaos harness drives the real replication links and the real promotion
# protocol, and every injected event is auditable under the same
# ``chaos.injected{kind=}`` family as the wire faults.


@contextmanager
def region_partition(mesh: Any, *names: str) -> Iterator[None]:
    """Sever the DCN between the named region(s) and the rest of the mesh
    for the ``with`` block — both directions, like a real partition.

    Every cross-partition replication ship is silently dropped (counted
    under ``chaos.injected{kind=region_partition}``); links WITHIN each
    side stay up, so a two-sided partition is the named set vs everyone
    else. During the partition each side keeps answering ``/query`` with
    local-complete / global-stale values (the degraded-read contract);
    the sender-side symptom is ``serve.replication_errors`` — none here,
    the drop looks like success to the link, matching a black-holing
    network — and the receiver-side symptom is a growing
    ``serve.peer_staleness_ms{peer=}``, the ``peer_stale`` /
    ``partition_detected`` conditions'
    :class:`~metrics_tpu.obs.health.HealthMonitor` signal. On exit the
    original links are restored and the next cumulative cross-ship repairs
    every global view **bitwise** — no anti-entropy pass exists to need.
    """
    isolated = {str(n) for n in names}

    def _drop(_payload: bytes) -> None:
        _chaos_inc("region_partition")

    with mesh._lock:
        saved = {
            key: link
            for key, link in mesh._links.items()
            if (key[0] in isolated) != (key[1] in isolated)
        }
        for key in saved:
            mesh._links[key] = _drop
    try:
        yield
    finally:
        with mesh._lock:
            for key, link in saved.items():
                # restore only links the block did not rewire underneath us
                # (a concurrent promote rebuilds its region's links)
                if mesh._links.get(key) is _drop:
                    mesh._links[key] = link


def kill_region(mesh: Any, name: str) -> Any:
    """Hard-kill a region's root (``Region.hard_kill``): its in-memory
    regional state vanishes with no cleanup and every region surface
    raises until :func:`promote_region` installs a warm standby. Counted
    under ``chaos.injected{kind=region_kill}``. Returns the (now dead)
    region — the harness's would-be zombie."""
    _chaos_inc("region_kill")
    region = mesh.region(name)
    region.hard_kill()
    return region


def promote_region(mesh: Any, name: str) -> Any:
    """Inject a generation-fenced failover (``chaos.injected{kind=promote}``):
    the full production promotion — warm standby, checkpoint restore,
    successor generation minted and fenced at every reachable peer — runs
    under whatever wire faults are armed. Returns the promoted region."""
    _chaos_inc("promote")
    return mesh.promote(name)
