# Developer entry points (the reference drives test/docs the same way,
# /root/reference/Makefile).

.PHONY: test docs doctest api clean-docs parity-weights

test:
	python -m pytest tests/ -q

# executable docstring examples (CI runs this as its own job)
doctest:
	JAX_PLATFORMS=cpu python -m pytest --doctest-modules metrics_tpu -q

# regenerate the per-symbol API pages that the sphinx site includes
api:
	JAX_PLATFORMS=cpu python docs/generate_api.py

# build the documentation site (pip install -e ".[docs]" first)
docs:
	sphinx-build -W --keep-going -b html docs docs/_build/html
	@echo "site at docs/_build/html/index.html"

clean-docs:
	rm -rf docs/_build

# published-value parity battery; needs converted checkpoints discoverable
# (convert --install or $METRICS_TPU_WEIGHTS_DIR) — see docs/parity.md
parity-weights:
	python -m pytest tests/image/test_pretrained_parity.py tests/audio/test_pesq.py -v -rs
