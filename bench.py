"""Benchmarks for every BASELINE.md north-star config.

Prints ONE JSON line per config — ``{"metric", "value", "unit",
"vs_baseline"}`` — with the headline (Accuracy update+compute at 1M-sample
accumulation) printed LAST.

Ours = the shipped jitted kernels on the default JAX device (TPU when
available); each workload repeats K times inside one jit and subtracts the
measured null-dispatch RTT (tunneled TPUs add ~65 ms per dispatch; see
``benchmarks/_timing.py``). Baseline = the reference's eager data path
(TorchMetrics 0.9 patterns) re-timed in torch/scipy on this host's CPU —
the reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is the
measured speedup over that equivalent. Configs:

- Accuracy, 10 classes, 1M samples (reference ``_stat_scores_update`` path)
- exact AUROC, 1M samples (reference sort+cumsum ``_binary_clf_curve``)
- binned TP/FP/FN counts, 1M samples x 100 thresholds (reference per-
  threshold loop, ``binned_precision_recall.py:117-132``)
- RetrievalMAP / RetrievalNDCG, 10k queries x 100 docs (reference per-query
  dict grouping + per-group kernel, ``utilities/data.py:196-220``)
- FID compute, 10k x 2048-d features (reference torch cov + scipy sqrtm,
  ``image/fid.py:60-124``)
- COCO mAP, 2k images (reference-style per-(image,class,threshold) Python
  loop — the tests' independent plain-loop oracle implements exactly that
  protocol).
"""
import json
import time

N_SAMPLES = 1_000_000
N_BATCHES = 16
N_CLASSES = 10
BATCH = N_SAMPLES // N_BATCHES
K_REPEATS = 200  # ~20 ms device time per trial: swamps tunnel jitter


# ---------------------------------------------------------------------------
# ours (jax / shipped kernels)
# ---------------------------------------------------------------------------


def bench_accuracy_tpu() -> float:
    import jax
    import jax.numpy as jnp

    from metrics_tpu.functional.classification.stat_scores import _stat_scores_update

    def epoch(preds, target):
        # The shipped kernel: input gate + stat scores, one fused scan.
        def body(state, batch):
            p, t = batch
            btp, bfp, btn, bfn = _stat_scores_update(
                p, t, reduce="micro", threshold=0.5, validate_args=False
            )
            tp, fp, tn, fn = state
            return (tp + btp, fp + bfp, tn + btn, fn + bfn), None

        z = jnp.zeros((), dtype=jnp.int32)
        (tp, fp, tn, fn), _ = jax.lax.scan(body, (z, z, z, z), (preds, target))
        return tp / jnp.maximum(tp + fn, 1)

    @jax.jit
    def run(preds, target):
        def body(i, acc):
            # scale inputs per repeat so the loop body stays loop-variant
            # (argmax is scale-invariant, so the metric value is unchanged)
            scale = (1.0 + 0.001 * i.astype(jnp.float32)).astype(jnp.bfloat16)
            return acc + epoch(preds * scale, target)

        return jax.lax.fori_loop(0, K_REPEATS, body, jnp.zeros(()))

    key = jax.random.PRNGKey(0)
    preds = jax.random.normal(key, (N_BATCHES, BATCH, N_CLASSES), dtype=jnp.bfloat16)
    target = jax.random.randint(jax.random.PRNGKey(1), (N_BATCHES, BATCH), 0, N_CLASSES)
    preds.block_until_ready()

    from benchmarks._timing import measure_ms

    return measure_ms(lambda: run(preds, target), K_REPEATS)


# ---------------------------------------------------------------------------
# torch-eager reference baselines (the reference's own data paths, CPU)
# ---------------------------------------------------------------------------


def _min_ms(run, n_trials=3) -> float:
    run()
    times = []
    for _ in range(n_trials):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    return min(times) * 1000.0


def base_accuracy() -> float:
    import torch

    torch.manual_seed(0)
    preds = torch.randn(N_BATCHES, BATCH, N_CLASSES)
    target = torch.randint(0, N_CLASSES, (N_BATCHES, BATCH))

    def run():
        tp = fp = tn = fn = torch.zeros((), dtype=torch.long)
        for i in range(N_BATCHES):
            onehot_p = torch.nn.functional.one_hot(preds[i].argmax(-1), N_CLASSES)
            onehot_t = torch.nn.functional.one_hot(target[i], N_CLASSES)
            true_pred = onehot_t == onehot_p
            pos_pred = onehot_p == 1
            tp = tp + (true_pred & pos_pred).sum()
            fp = fp + (~true_pred & pos_pred).sum()
            tn = tn + (true_pred & ~pos_pred).sum()
            fn = fn + (~true_pred & ~pos_pred).sum()
        return tp.float() / torch.clamp(tp + fn, min=1)

    return _min_ms(run)


def base_auroc() -> float:
    # reference functional/classification/roc.py -> _binary_clf_curve:
    # descending sort, cumsum of tps/fps, trapezoidal AUC
    import torch

    torch.manual_seed(0)
    preds = torch.rand(N_SAMPLES)
    target = (torch.rand(N_SAMPLES) > 0.5).long()

    def run():
        desc = torch.argsort(preds, descending=True)
        p, t = preds[desc], target[desc]
        distinct = torch.nonzero(p[1:] - p[:-1]).squeeze(-1)
        thresh_idx = torch.cat([distinct, torch.tensor([t.numel() - 1])])
        tps = torch.cumsum(t, 0)[thresh_idx].float()
        fps = (1 + thresh_idx - tps).float()
        tpr = tps / tps[-1]
        fpr = fps / fps[-1]
        return torch.trapz(tpr, fpr)

    return _min_ms(run)


def base_binned() -> float:
    # reference binned_precision_recall.py:117-132: per-threshold loop of
    # compare + masked sums
    import torch

    torch.manual_seed(0)
    preds = torch.rand(N_SAMPLES)
    target = (torch.rand(N_SAMPLES) > 0.5).long()
    thresholds = torch.linspace(0, 1, 100)

    def run():
        tps = torch.empty(100)
        fps = torch.empty(100)
        fns = torch.empty(100)
        for i in range(100):
            pred_pos = preds >= thresholds[i]
            tps[i] = (pred_pos & (target == 1)).sum()
            fps[i] = (pred_pos & (target == 0)).sum()
            fns[i] = (~pred_pos & (target == 1)).sum()
        return tps.sum()

    return _min_ms(run, n_trials=2)


def base_retrieval(kind: str) -> float:
    # reference retrieval/base.py:114-143: Python dict grouping, then a
    # per-group sort-based kernel
    import torch

    torch.manual_seed(0)
    n_queries, docs = 10_000, 100
    preds = torch.rand(n_queries * docs)
    target = (torch.rand(n_queries * docs) > 0.9).long()
    indexes = torch.arange(n_queries).repeat_interleave(docs)

    def group_indexes():
        groups = {}
        for i, idx in enumerate(indexes.tolist()):
            groups.setdefault(idx, []).append(i)
        return [torch.tensor(g) for g in groups.values()]

    def ap(p, t):
        order = torch.argsort(p, descending=True)
        rel = t[order]
        if rel.sum() == 0:
            return torch.tensor(0.0)
        pos = torch.arange(1, rel.numel() + 1, dtype=torch.float32)
        prec = torch.cumsum(rel, 0).float() / pos
        return (prec * rel).sum() / rel.sum()

    def ndcg(p, t):
        order = torch.argsort(p, descending=True)
        rel = t[order].float()
        disc = 1.0 / torch.log2(torch.arange(2, rel.numel() + 2, dtype=torch.float32))
        dcg = (rel * disc).sum()
        ideal = (torch.sort(rel, descending=True).values * disc).sum()
        return dcg / ideal if float(ideal) > 0 else torch.tensor(0.0)

    kernel = ap if kind == "map" else ndcg

    def run():
        vals = [kernel(preds[g], target[g]) for g in group_indexes()]
        return torch.stack(vals).mean()

    return _min_ms(run, n_trials=2)


def base_fid() -> float:
    # reference image/fid.py:60-124: torch cov matmuls + scipy sqrtm on CPU
    import numpy as np
    import scipy.linalg
    import torch

    torch.manual_seed(0)
    n, d = 10_000, 2048
    fr = torch.randn(n, d) * 0.5
    ff = torch.randn(n, d) * 0.55 + 0.05

    def run():
        mu1, mu2 = fr.mean(0), ff.mean(0)
        c1 = (fr - mu1).T.mm(fr - mu1) / (n - 1)
        c2 = (ff - mu2).T.mm(ff - mu2) / (n - 1)
        res = scipy.linalg.sqrtm(c1.mm(c2).numpy().astype("float64"))
        covmean = res[0] if isinstance(res, tuple) else res
        diff = mu1 - mu2
        return float(diff.dot(diff) + torch.trace(c1) + torch.trace(c2)) - 2 * float(np.trace(covmean.real))

    return _min_ms(run, n_trials=1)


def base_map(n_images: int) -> float:
    # reference detection/mean_ap.py: per-(image, class) Python evaluation
    # with per-threshold greedy matching loops (the tests' independent
    # oracle implements exactly this protocol)
    from benchmarks.bench_detection import make_inputs
    from tests.detection.test_map import _oracle_map

    preds, targets = make_inputs(n_images)
    t0 = time.perf_counter()
    _oracle_map(preds, targets)
    return (time.perf_counter() - t0) * 1000.0


def main() -> None:
    rows = []

    from benchmarks import bench_curves, bench_detection, bench_image, bench_retrieval

    curves = bench_curves.measure()
    rows.append(("auroc_exact_1M_compute", curves["auroc_exact_1M_compute"], base_auroc()))
    rows.append(("binned_counts_1M_T100_update", curves["binned_counts_1M_T100_update"], base_binned()))

    retr = bench_retrieval.measure()
    rows.append(("retrieval_map_1M_docs_compute", retr["retrieval_map_1M_docs_compute"], base_retrieval("map")))
    rows.append(
        ("retrieval_ndcg_1M_docs_compute", retr["retrieval_ndcg_1M_docs_compute"], base_retrieval("ndcg"))
    )

    fid = bench_image.measure()
    rows.append(("fid_10k_2048d_compute", fid["fid_10k_2048d_compute"], base_fid()))

    rows.append(("detection_map_2k_images_compute", bench_detection.measure(n_trials=2), base_map(2_000)))

    # headline LAST (the driver's tail-line parse keeps its round-1 meaning)
    rows.append(("accuracy_1M_update_compute_wallclock", bench_accuracy_tpu(), base_accuracy()))

    for name, ours_ms, base_ms in rows:
        print(
            json.dumps(
                {
                    "metric": name,
                    "value": round(ours_ms, 3),
                    "unit": "ms",
                    "vs_baseline": round(base_ms / ours_ms, 3),
                }
            )
        )


if __name__ == "__main__":
    main()
