"""Benchmark: Accuracy update+compute wall-clock at 1M-sample accumulation.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config: multiclass accuracy, 10 classes, 1M samples in 16 batches (the
BASELINE.md headline config). Ours = the fused jitted (state, batch) ->
(state', value) StatScores kernel on the default JAX device (TPU when
available). Baseline = the reference's eager-op pattern (torchmetrics
0.9 ``_stat_scores_update`` data path: argmax/eq/masked sums per batch)
in torch on CPU — the reference publishes no numbers (BASELINE.md), so
vs_baseline is measured speedup over that torch-eager equivalent on this
host. value = our wall-clock in ms.
"""
import json
import time

N_SAMPLES = 1_000_000
N_BATCHES = 16
N_CLASSES = 10
BATCH = N_SAMPLES // N_BATCHES


def bench_tpu() -> float:
    import jax
    import jax.numpy as jnp

    from metrics_tpu.functional.classification.stat_scores import _stat_scores_update

    @jax.jit
    def step(tp, fp, tn, fn, preds, target):
        # The shipped kernel: input gate + stat scores, jitted end-to-end.
        btp, bfp, btn, bfn = _stat_scores_update(
            preds, target, reduce="micro", threshold=0.5, validate_args=False
        )
        return tp + btp, fp + bfp, tn + btn, fn + bfn

    @jax.jit
    def compute(tp, fp, tn, fn):
        return tp / jnp.maximum(tp + fn, 1)

    key = jax.random.PRNGKey(0)
    preds = jax.random.normal(key, (N_BATCHES, BATCH, N_CLASSES), dtype=jnp.bfloat16)
    target = jax.random.randint(jax.random.PRNGKey(1), (N_BATCHES, BATCH), 0, N_CLASSES)
    preds.block_until_ready()

    def run():
        z = jnp.zeros((), dtype=jnp.int32)
        tp, fp, tn, fn = z, z, z, z
        for i in range(N_BATCHES):
            tp, fp, tn, fn = step(tp, fp, tn, fn, preds[i], target[i])
        return compute(tp, fp, tn, fn).block_until_ready()

    run()  # warmup + compile
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    return min(times) * 1000.0  # ms


def bench_torch_eager() -> float:
    import torch

    torch.manual_seed(0)
    preds = torch.randn(N_BATCHES, BATCH, N_CLASSES)
    target = torch.randint(0, N_CLASSES, (N_BATCHES, BATCH))

    def run():
        tp = fp = tn = fn = torch.zeros((), dtype=torch.long)
        for i in range(N_BATCHES):
            onehot_p = torch.nn.functional.one_hot(preds[i].argmax(-1), N_CLASSES)
            onehot_t = torch.nn.functional.one_hot(target[i], N_CLASSES)
            true_pred = onehot_t == onehot_p
            pos_pred = onehot_p == 1
            tp = tp + (true_pred & pos_pred).sum()
            fp = fp + (~true_pred & pos_pred).sum()
            tn = tn + (true_pred & ~pos_pred).sum()
            fn = fn + (~true_pred & ~pos_pred).sum()
        return tp.float() / torch.clamp(tp + fn, min=1)

    run()
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    return min(times) * 1000.0


def main() -> None:
    ours_ms = bench_tpu()
    base_ms = bench_torch_eager()
    print(
        json.dumps(
            {
                "metric": "accuracy_1M_update_compute_wallclock",
                "value": round(ours_ms, 3),
                "unit": "ms",
                "vs_baseline": round(base_ms / ours_ms, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
