"""Benchmark: Accuracy update+compute wall-clock at 1M-sample accumulation.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config: multiclass accuracy, 10 classes, 1M samples in 16 batches (the
BASELINE.md headline config). Ours = the fused jitted (state, batch) ->
(state', value) StatScores kernel on the default JAX device (TPU when
available); the batch loop is a lax.scan inside one jit so the measurement
is device throughput, and the full 1M-sample epoch is repeated K times
inside the jit to amortize host<->device dispatch latency (a tunneled TPU
adds ~65 ms RTT per dispatch, which would otherwise dominate). Baseline =
the reference's eager-op pattern (torchmetrics 0.9 ``_stat_scores_update``
data path: argmax/eq/masked sums per batch) in torch on CPU — the reference
publishes no numbers (BASELINE.md), so vs_baseline is measured speedup over
that torch-eager equivalent on this host. value = our per-epoch wall-clock
in ms.
"""
import json
import time

N_SAMPLES = 1_000_000
N_BATCHES = 16
N_CLASSES = 10
BATCH = N_SAMPLES // N_BATCHES
K_REPEATS = 200  # ~20 ms device time per trial (K x ~0.1 ms/epoch): swamps tunnel jitter


def bench_tpu() -> float:
    import jax
    import jax.numpy as jnp

    from metrics_tpu.functional.classification.stat_scores import _stat_scores_update

    def epoch(preds, target):
        # The shipped kernel: input gate + stat scores, one fused scan.
        def body(state, batch):
            p, t = batch
            btp, bfp, btn, bfn = _stat_scores_update(
                p, t, reduce="micro", threshold=0.5, validate_args=False
            )
            tp, fp, tn, fn = state
            return (tp + btp, fp + bfp, tn + btn, fn + bfn), None

        z = jnp.zeros((), dtype=jnp.int32)
        (tp, fp, tn, fn), _ = jax.lax.scan(body, (z, z, z, z), (preds, target))
        return tp / jnp.maximum(tp + fn, 1)

    @jax.jit
    def run(preds, target):
        def body(i, acc):
            # scale inputs per repeat so the loop body stays loop-variant
            # (argmax is scale-invariant, so the metric value is unchanged)
            scale = (1.0 + 0.001 * i.astype(jnp.float32)).astype(jnp.bfloat16)
            return acc + epoch(preds * scale, target)

        return jax.lax.fori_loop(0, K_REPEATS, body, jnp.zeros(()))

    key = jax.random.PRNGKey(0)
    preds = jax.random.normal(key, (N_BATCHES, BATCH, N_CLASSES), dtype=jnp.bfloat16)
    target = jax.random.randint(jax.random.PRNGKey(1), (N_BATCHES, BATCH), 0, N_CLASSES)
    preds.block_until_ready()

    # shared harness: min over 12 trials, null-dispatch RTT subtracted —
    # the same jitter defense every benchmarks/bench_*.py uses
    from benchmarks._timing import measure_ms

    return measure_ms(lambda: run(preds, target), K_REPEATS)  # ms per 1M-sample epoch


def bench_torch_eager() -> float:
    import torch

    torch.manual_seed(0)
    preds = torch.randn(N_BATCHES, BATCH, N_CLASSES)
    target = torch.randint(0, N_CLASSES, (N_BATCHES, BATCH))

    def run():
        tp = fp = tn = fn = torch.zeros((), dtype=torch.long)
        for i in range(N_BATCHES):
            onehot_p = torch.nn.functional.one_hot(preds[i].argmax(-1), N_CLASSES)
            onehot_t = torch.nn.functional.one_hot(target[i], N_CLASSES)
            true_pred = onehot_t == onehot_p
            pos_pred = onehot_p == 1
            tp = tp + (true_pred & pos_pred).sum()
            fp = fp + (~true_pred & pos_pred).sum()
            tn = tn + (true_pred & ~pos_pred).sum()
            fn = fn + (~true_pred & ~pos_pred).sum()
        return tp.float() / torch.clamp(tp + fn, min=1)

    run()
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    return min(times) * 1000.0


def main() -> None:
    ours_ms = bench_tpu()
    base_ms = bench_torch_eager()
    print(
        json.dumps(
            {
                "metric": "accuracy_1M_update_compute_wallclock",
                "value": round(ours_ms, 3),
                "unit": "ms",
                "vs_baseline": round(base_ms / ours_ms, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
