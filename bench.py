"""Benchmarks for every BASELINE.md north-star config.

Prints ONE JSON line per config — ``{"metric", "value", "unit",
"vs_baseline"}`` — with the headline (Accuracy update+compute at 1M-sample
accumulation) printed LAST.

Ours = the shipped jitted kernels on the default JAX device (TPU when
available); each workload runs K and 2K times inside one jit and the
per-repeat time is the difference — cancelling the tunnel dispatch RTT,
which swings between ~20 us and ~90 ms (see ``benchmarks/_timing.py``). Baseline = the reference's eager data path
(TorchMetrics 0.9 patterns) re-timed in torch/scipy on this host's CPU —
the reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is the
measured speedup over that equivalent. Configs:

- Accuracy, 10 classes, 1M samples (reference ``_stat_scores_update`` path)
- exact AUROC, 1M samples (reference sort+cumsum ``_binary_clf_curve``)
- binned TP/FP/FN counts, 1M samples x 100 thresholds (reference per-
  threshold loop, ``binned_precision_recall.py:117-132``)
- RetrievalMAP / RetrievalNDCG, 10k queries x 100 docs (reference per-query
  dict grouping + per-group kernel, ``utilities/data.py:196-220``)
- FID compute, 10k x 2048-d features (reference torch cov + scipy sqrtm,
  ``image/fid.py:60-124``)
- COCO mAP, 2k images (reference-style per-(image,class,threshold) Python
  loop — the tests' independent plain-loop oracle implements exactly that
  protocol)
- MetricCollection compute-group stat-scores update, binary + multiclass 1M
  (the shared P/R/F1 accumulation; reference one-hot eager path)
- LPIPS AlexNet forward, 32 image pairs at 64x64 (reference: the lpips
  package's eager tower + heads)
- BERTScore greedy cosine matching, 256 x 128 tokens x 256-d (reference
  ``functional/text/bert.py:327-360`` eager bmm/max path)
- corpus WER, 10k sentence pairs (reference per-pair pure-python DP loop,
  ``functional/text/wer.py:23-48``)
- batched SSIM, 64 x 3 x 256x256 gaussian 11x11 window (reference eager
  depthwise-conv path, ``functional/image/ssim.py``)
- MetricCollection compute-groups on-vs-off A/B on the same P/R/F1
  collection (the reference's documented 2-3x claim)
- 1M-sample CapacityBuffer mesh sync on 8 virtual devices (A/B vs the
  replicated psum-of-scatter gather).
"""
import json
import time

N_SAMPLES = 1_000_000
N_BATCHES = 16
N_CLASSES = 10
BATCH = N_SAMPLES // N_BATCHES
K_REPEATS = 200  # ~20 ms device time per trial: swamps tunnel jitter


# ---------------------------------------------------------------------------
# ours (jax / shipped kernels)
# ---------------------------------------------------------------------------


def bench_accuracy_tpu() -> float:
    import jax
    import jax.numpy as jnp

    from metrics_tpu.functional.classification.stat_scores import _stat_scores_update

    def epoch(preds, target):
        # The shipped fused-epoch formulation (make_epoch's merge-fold flat
        # path): ONE update over the flattened (B*batch, C) epoch instead of
        # a sequential 16-step scan chain — valid for sum-merged states by
        # the same invariant the DDP gather-reduce sync relies on. The
        # argmax-compare itself runs through ops/argmax_compare's streaming
        # pallas tile on TPU (classes lane-resident, no relayout).
        p = preds.reshape(-1, N_CLASSES)
        t = target.reshape(-1)
        tp, fp, tn, fn = _stat_scores_update(
            p, t, reduce="micro", threshold=0.5, validate_args=False
        )
        return tp / jnp.maximum(tp + fn, 1)

    def make_run(k):
        @jax.jit
        def run(preds, target):
            def body(i, acc):
                # scale inputs per repeat so the loop body stays loop-variant
                # (argmax is scale-invariant, so the metric value is unchanged)
                scale = (1.0 + 0.001 * i.astype(jnp.float32)).astype(jnp.bfloat16)
                return acc + epoch(preds * scale, target)

            return jax.lax.fori_loop(0, k, body, jnp.zeros(()))
        return run

    key = jax.random.PRNGKey(0)
    preds = jax.random.normal(key, (N_BATCHES, BATCH, N_CLASSES), dtype=jnp.bfloat16)
    target = jax.random.randint(jax.random.PRNGKey(1), (N_BATCHES, BATCH), 0, N_CLASSES)
    preds.block_until_ready()

    from benchmarks._timing import measure_ms_scaled

    return measure_ms_scaled(
        lambda k: (lambda run=make_run(k): run(preds, target)), K_REPEATS
    )


# ---------------------------------------------------------------------------
# torch-eager reference baselines (the reference's own data paths, CPU)
# ---------------------------------------------------------------------------


def _min_ms(run, n_trials=3) -> float:
    run()
    times = []
    for _ in range(n_trials):
        t0 = time.perf_counter()
        run()
        times.append(time.perf_counter() - t0)
    return min(times) * 1000.0


def base_accuracy() -> float:
    import torch

    torch.manual_seed(0)
    preds = torch.randn(N_BATCHES, BATCH, N_CLASSES)
    target = torch.randint(0, N_CLASSES, (N_BATCHES, BATCH))

    def run():
        tp = fp = tn = fn = torch.zeros((), dtype=torch.long)
        for i in range(N_BATCHES):
            onehot_p = torch.nn.functional.one_hot(preds[i].argmax(-1), N_CLASSES)
            onehot_t = torch.nn.functional.one_hot(target[i], N_CLASSES)
            true_pred = onehot_t == onehot_p
            pos_pred = onehot_p == 1
            tp = tp + (true_pred & pos_pred).sum()
            fp = fp + (~true_pred & pos_pred).sum()
            tn = tn + (true_pred & ~pos_pred).sum()
            fn = fn + (~true_pred & ~pos_pred).sum()
        return tp.float() / torch.clamp(tp + fn, min=1)

    return _min_ms(run)


def base_auroc() -> float:
    # reference functional/classification/roc.py -> _binary_clf_curve:
    # descending sort, cumsum of tps/fps, trapezoidal AUC
    import torch

    torch.manual_seed(0)
    preds = torch.rand(N_SAMPLES)
    target = (torch.rand(N_SAMPLES) > 0.5).long()

    def run():
        desc = torch.argsort(preds, descending=True)
        p, t = preds[desc], target[desc]
        distinct = torch.nonzero(p[1:] - p[:-1]).squeeze(-1)
        thresh_idx = torch.cat([distinct, torch.tensor([t.numel() - 1])])
        tps = torch.cumsum(t, 0)[thresh_idx].float()
        fps = (1 + thresh_idx - tps).float()
        tpr = tps / tps[-1]
        fpr = fps / fps[-1]
        return torch.trapz(tpr, fpr)

    return _min_ms(run)


def base_binned() -> float:
    # reference binned_precision_recall.py:117-132: per-threshold loop of
    # compare + masked sums
    import torch

    torch.manual_seed(0)
    preds = torch.rand(N_SAMPLES)
    target = (torch.rand(N_SAMPLES) > 0.5).long()
    thresholds = torch.linspace(0, 1, 100)

    def run():
        tps = torch.empty(100)
        fps = torch.empty(100)
        fns = torch.empty(100)
        for i in range(100):
            pred_pos = preds >= thresholds[i]
            tps[i] = (pred_pos & (target == 1)).sum()
            fps[i] = (pred_pos & (target == 0)).sum()
            fns[i] = (~pred_pos & (target == 1)).sum()
        return tps.sum()

    return _min_ms(run, n_trials=2)


def base_retrieval(kind: str) -> float:
    # reference retrieval/base.py:114-143: Python dict grouping, then a
    # per-group sort-based kernel
    import torch

    torch.manual_seed(0)
    n_queries, docs = 10_000, 100
    preds = torch.rand(n_queries * docs)
    target = (torch.rand(n_queries * docs) > 0.9).long()
    indexes = torch.arange(n_queries).repeat_interleave(docs)

    def group_indexes():
        groups = {}
        for i, idx in enumerate(indexes.tolist()):
            groups.setdefault(idx, []).append(i)
        return [torch.tensor(g) for g in groups.values()]

    def ap(p, t):
        order = torch.argsort(p, descending=True)
        rel = t[order]
        if rel.sum() == 0:
            return torch.tensor(0.0)
        pos = torch.arange(1, rel.numel() + 1, dtype=torch.float32)
        prec = torch.cumsum(rel, 0).float() / pos
        return (prec * rel).sum() / rel.sum()

    def ap_k10(p, t, k=10):
        npos = int(t.sum())
        if npos == 0:
            return torch.tensor(0.0)
        order = torch.argsort(p, descending=True)
        rel = t[order][:k]
        pos = torch.arange(1, rel.numel() + 1, dtype=torch.float32)
        prec = torch.cumsum(rel, 0).float() / pos
        return (prec * rel).sum() / min(npos, k)

    def ndcg(p, t):
        order = torch.argsort(p, descending=True)
        rel = t[order].float()
        disc = 1.0 / torch.log2(torch.arange(2, rel.numel() + 2, dtype=torch.float32))
        dcg = (rel * disc).sum()
        ideal = (torch.sort(rel, descending=True).values * disc).sum()
        return dcg / ideal if float(ideal) > 0 else torch.tensor(0.0)

    kernel = {"map": ap, "map_k10": ap_k10, "ndcg": ndcg}[kind]

    def run():
        vals = [kernel(preds[g], target[g]) for g in group_indexes()]
        return torch.stack(vals).mean()

    return _min_ms(run, n_trials=2)


def base_fid() -> float:
    # reference image/fid.py:60-124: torch cov matmuls + scipy sqrtm on CPU
    import numpy as np
    import scipy.linalg
    import torch

    torch.manual_seed(0)
    n, d = 10_000, 2048
    fr = torch.randn(n, d) * 0.5
    ff = torch.randn(n, d) * 0.55 + 0.05

    def run():
        mu1, mu2 = fr.mean(0), ff.mean(0)
        c1 = (fr - mu1).T.mm(fr - mu1) / (n - 1)
        c2 = (ff - mu2).T.mm(ff - mu2) / (n - 1)
        res = scipy.linalg.sqrtm(c1.mm(c2).numpy().astype("float64"))
        covmean = res[0] if isinstance(res, tuple) else res
        diff = mu1 - mu2
        return float(diff.dot(diff) + torch.trace(c1) + torch.trace(c2)) - 2 * float(np.trace(covmean.real))

    return _min_ms(run, n_trials=1)


def base_wer() -> float:
    # the reference's WER data path: a per-pair pure-python list-of-lists
    # DP loop (reference functional/text/wer.py:23-48, helper._edit_distance)
    from benchmarks.bench_text_image import wer_corpus

    preds, targets = wer_corpus()

    def edit(a, b):
        dp = [[0] * (len(b) + 1) for _ in range(len(a) + 1)]
        for i in range(len(a) + 1):
            dp[i][0] = i
        for j in range(len(b) + 1):
            dp[0][j] = j
        for i in range(1, len(a) + 1):
            for j in range(1, len(b) + 1):
                cost = 0 if a[i - 1] == b[j - 1] else 1
                dp[i][j] = min(dp[i - 1][j] + 1, dp[i][j - 1] + 1, dp[i - 1][j - 1] + cost)
        return dp[-1][-1]

    def run():
        errors = total = 0
        for p, t in zip(preds, targets):
            pt, tt = p.split(), t.split()
            errors += edit(pt, tt)
            total += len(tt)
        return errors / total

    return _min_ms(run, n_trials=1)


def base_ssim() -> float:
    # eager torch replica of the reference's SSIM data path
    # (functional/image/ssim.py): gaussian 11x11 window via depthwise
    # F.conv2d over the 5 SSIM maps, k1/k2 stabilized
    import torch
    import torch.nn.functional as F

    torch.manual_seed(0)
    batch, c, side = 64, 3, 256
    preds = torch.rand(batch, c, side, side)
    target = (preds + 0.05 * torch.randn_like(preds)).clamp(0, 1)
    coords = torch.arange(11, dtype=torch.float32) - 5
    g = torch.exp(-(coords**2) / (2 * 1.5**2))
    g = (g / g.sum()).outer(g / g.sum())
    kernel = g.expand(c, 1, 11, 11).contiguous()
    c1, c2 = (0.01 * 1.0) ** 2, (0.03 * 1.0) ** 2

    def run():
        # reflection-pad, valid conv, crop the pad border — the same
        # region accounting as the shipped kernel (functional/image/ssim.py)
        pp = F.pad(preds, (5, 5, 5, 5), mode="reflect")
        tt = F.pad(target, (5, 5, 5, 5), mode="reflect")

        def blur(x):
            return F.conv2d(x, kernel, groups=c)

        mu_x, mu_y = blur(pp), blur(tt)
        sx = blur(pp * pp) - mu_x * mu_x
        sy = blur(tt * tt) - mu_y * mu_y
        sxy = blur(pp * tt) - mu_x * mu_y
        num = (2 * mu_x * mu_y + c1) * (2 * sxy + c2)
        den = (mu_x * mu_x + mu_y * mu_y + c1) * (sx + sy + c2)
        return float((num / den)[..., 5:-5, 5:-5].mean())

    return _min_ms(run, n_trials=2)


def base_map(n_images: int) -> float:
    # reference detection/mean_ap.py: per-(image, class) Python evaluation
    # with per-threshold greedy matching loops (the tests' independent
    # oracle implements exactly this protocol)
    from benchmarks.bench_detection import make_inputs
    from benchmarks.map_oracle import _oracle_map

    preds, targets = make_inputs(n_images)
    t0 = time.perf_counter()
    _oracle_map(preds, targets)
    return (time.perf_counter() - t0) * 1000.0


def base_collection(mode: str) -> float:
    # the reference's collection compute-group shares one stat-scores
    # update between P/R/F1; this is that eager data path per batch
    import torch

    torch.manual_seed(0)
    if mode == "binary":
        preds = torch.rand(N_SAMPLES)
        target = torch.randint(0, 2, (N_SAMPLES,))

        def run():
            pred_pos = preds >= 0.5
            pos = target == 1
            tp = (pred_pos & pos).sum()
            fp = (pred_pos & ~pos).sum()
            fn = (~pred_pos & pos).sum()
            return tp, fp, fn

    else:
        preds = torch.rand(N_SAMPLES, N_CLASSES)
        target = torch.randint(0, N_CLASSES, (N_SAMPLES,))

        def run():
            onehot_p = torch.nn.functional.one_hot(preds.argmax(-1), N_CLASSES)
            onehot_t = torch.nn.functional.one_hot(target, N_CLASSES)
            tp = (onehot_p & onehot_t).sum(0)
            fp = (onehot_p & ~onehot_t.bool()).sum(0)
            fn = (~onehot_p.bool() & onehot_t.bool()).sum(0)
            return tp, fp, fn

    return _min_ms(run)


def base_lpips() -> float:
    # eager torch replica of the LPIPS-alex forward (the lpips package's
    # data path: tower, unit-normalize, diff^2, 1x1 heads, spatial mean)
    import torch

    torch.manual_seed(0)
    from benchmarks.bench_text_image import LPIPS_SHAPE

    a = torch.rand(*LPIPS_SHAPE) * 2 - 1
    b = torch.rand(*LPIPS_SHAPE) * 2 - 1
    shapes = [(64, 3, 11, 11), (192, 64, 5, 5), (384, 192, 3, 3), (256, 384, 3, 3), (256, 256, 3, 3)]
    convs = [
        (torch.randn(s) * 0.05, torch.randn(s[0]) * 0.05, (4, 2) if i == 0 else (1, s[2] // 2))
        for i, s in enumerate(shapes)
    ]
    heads = [torch.rand(1, s[0], 1, 1) for s in shapes]

    def taps(x):
        feats = []
        for i, (w, bia, (stride, pad)) in enumerate(convs):
            if i in (1, 2):
                x = torch.nn.functional.max_pool2d(x, 3, 2)
            x = torch.relu(torch.nn.functional.conv2d(x, w, bia, stride=stride, padding=pad))
            feats.append(x)
        return feats

    def run():
        f0, f1 = taps(a), taps(b)
        total = torch.zeros(a.shape[0])
        for head, (x, y) in zip(heads, zip(f0, f1)):
            x = x / (x.norm(dim=1, keepdim=True) + 1e-10)
            y = y / (y.norm(dim=1, keepdim=True) + 1e-10)
            total = total + torch.nn.functional.conv2d((x - y) ** 2, head).mean(dim=(2, 3)).squeeze(1)
        return total

    with torch.no_grad():
        return _min_ms(run, n_trials=2)


def base_bertscore() -> float:
    # reference greedy cosine matching (functional/text/bert.py:327-360):
    # bmm similarity matrix, row/col max, idf-weighted sums — eager torch
    import torch

    torch.manual_seed(0)
    from benchmarks.bench_text_image import BS_B, BS_D, BS_S

    emb_p = torch.randn(BS_B, BS_S, BS_D)
    emb_t = torch.randn(BS_B, BS_S, BS_D)
    w = torch.ones(BS_B, BS_S) / BS_S

    def run():
        p = emb_p / emb_p.norm(dim=-1, keepdim=True)
        t = emb_t / emb_t.norm(dim=-1, keepdim=True)
        sim = torch.bmm(p, t.transpose(1, 2))
        precision = (sim.max(dim=2).values * w).sum(-1)
        recall = (sim.max(dim=1).values * w).sum(-1)
        return 2 * precision * recall / (precision + recall)

    with torch.no_grad():
        return _min_ms(run, n_trials=2)


def bench_checkpoint() -> dict:
    """Checkpoint save/restore latency over a realistic eval-sweep state.

    A 1M-sample f32 ``CapacityBuffer``-backed AUROC (the heaviest ordinary
    checkpoint payload: ~8 MB of cat-state plus scalars). Three numbers:

    - ``checkpoint_save_1M_sync`` — full blocking ``CheckpointManager.save``
      (stage + orbax write + manifest + atomic rename + rotation).
    - ``checkpoint_save_1M_async_stall`` — the time ``save()`` holds the
      training loop in async mode: the main-thread state snapshot only,
      persistence rides the background worker (drained before each timing
      so successive saves never queue).
    - ``checkpoint_restore_1M`` — latest-checkpoint discovery + orbax read
      + state load, the resume-path cost after a preemption.
    """
    import os
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from metrics_tpu import AUROC
    from metrics_tpu.ft import CheckpointManager

    n = N_SAMPLES
    metric = AUROC(sample_capacity=n)
    key = jax.random.PRNGKey(3)
    preds = jax.random.uniform(key, (n,), dtype=jnp.float32)
    target = jax.random.bernoulli(jax.random.PRNGKey(4), 0.5, (n,)).astype(jnp.int32)
    metric.update(preds, target)

    root = tempfile.mkdtemp(prefix="bench_ckpt.")
    out: dict = {}
    try:
        sync_mgr = CheckpointManager(os.path.join(root, "sync"), keep_last=2)
        out["checkpoint_save_1M_sync"] = _min_ms(lambda: sync_mgr.save(metric), n_trials=3)

        async_mgr = CheckpointManager(os.path.join(root, "async"), keep_last=2, async_save=True)
        # warm + measure only the save() call (the stall), not the drain;
        # each wait() between timings keeps the NEXT timed save from queuing
        async_mgr.save(metric)
        async_mgr.wait()
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            async_mgr.save(metric)
            times.append((time.perf_counter() - t0) * 1000.0)
            async_mgr.wait()
        out["checkpoint_save_1M_async_stall"] = min(times)

        restored = AUROC(sample_capacity=n)
        out["checkpoint_restore_1M"] = _min_ms(lambda: sync_mgr.restore(restored), n_trials=3)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def bench_streaming(n: int = N_SAMPLES) -> dict:
    """Streaming-subsystem hot paths over the standard 1M-sample stream.

    - ``streaming_auroc_1M_update`` — fold 1M (score, label) pairs into the
      default 2048-bin ``ScoreLabelSketch`` (one jitted scatter-add /
      binned-counts launch): the per-epoch cost that replaces the exact
      path's O(N) HBM accumulation.
    - ``streaming_auroc_1M_merge`` — one sketch merge (the mesh/window/
      resume combine op), fori-loop amortized so dispatch doesn't swamp a
      16 KB elementwise add.
    - ``streaming_auroc_1M_compute`` — AUROC envelope midpoint from the
      sketch, amortized likewise; compare ``auroc_exact_1M_compute`` to see
      what the documented error bound buys.
    - ``windowed_fold_k16`` — one ``make_stream_step`` launch on a 16-shard
      ring (fold + rotate + expire + window compute in one program).
    """
    import jax
    import jax.numpy as jnp

    from metrics_tpu.steps import make_stream_step
    from metrics_tpu.streaming import ScoreLabelSketch, StreamingAUROC, WindowedMetric

    preds = jax.random.uniform(jax.random.PRNGKey(7), (n,), dtype=jnp.float32)
    target = jax.random.bernoulli(jax.random.PRNGKey(8), 0.5, (n,)).astype(jnp.int32)
    out: dict = {}

    template = ScoreLabelSketch(2048)
    fold = jax.jit(lambda p, t: template.fold(p, t))
    out["streaming_auroc_1M_update"] = _min_ms(lambda: jax.block_until_ready(fold(preds, target)))

    sketch_a = fold(preds, target)
    sketch_b = fold(jnp.flip(preds), jnp.flip(1 - target))

    @jax.jit
    def merge_k(a, b):
        return jax.lax.fori_loop(0, K_REPEATS, lambda _, acc: acc.merge(b), a)

    out["streaming_auroc_1M_merge"] = (
        _min_ms(lambda: jax.block_until_ready(merge_k(sketch_a, sketch_b))) / K_REPEATS
    )

    @jax.jit
    def compute_k(s):
        return jax.lax.fori_loop(0, K_REPEATS, lambda _, acc: acc + s.auroc(), jnp.float32(0))

    out["streaming_auroc_1M_compute"] = (
        _min_ms(lambda: jax.block_until_ready(compute_k(sketch_a))) / K_REPEATS
    )

    init, step, _ = make_stream_step(
        WindowedMetric(StreamingAUROC(num_bins=2048), window=16, updates_per_slot=1)
    )
    pb, tb = preds[: max(1, n // N_BATCHES)], target[: max(1, n // N_BATCHES)]
    state = init()
    state, value = step(state, pb, tb)  # warm: one trace+compile
    jax.block_until_ready(value)
    times = []
    for _ in range(8):  # the donated carry re-threads, so time calls singly
        t0 = time.perf_counter()
        state, value = step(state, pb, tb)
        jax.block_until_ready(value)
        times.append(time.perf_counter() - t0)
    out["windowed_fold_k16"] = min(times) * 1000.0
    return out


def bench_sketch_families(n: int = N_SAMPLES) -> dict:
    """The new sketch trio's hot paths over the standard 1M-sample stream.

    - ``streaming_topk_1M_update`` — fold 1M zipf-distributed ids into a
      256-bucket x 4-row :class:`~metrics_tpu.streaming.HeavyHitterSketch`
      (one jitted scatter-add launch over counts + per-bit mass planes).
    - ``streaming_topk_1M_merge`` — one heavy-hitter merge (the mesh /
      window / resume combine op; pure elementwise adds), fori-loop
      amortized like the AUROC merge row.
    - ``distinct_count_1M_update`` — fold 1M ids into a precision-12
      :class:`~metrics_tpu.streaming.DistinctCountSketch` (hash + rho +
      scatter-max over 4096 registers).
    - ``cooccur_fold_1M`` — fold 1M (row, col) label pairs into a
      5000x5000-space :class:`~metrics_tpu.streaming.CoOccurrenceSketch`
      (pair packing + binned scatter-adds + exact marginals).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from metrics_tpu.streaming import (
        CoOccurrenceSketch,
        DistinctCountSketch,
        HeavyHitterSketch,
    )

    rng = np.random.default_rng(17)
    ids = jnp.asarray((rng.zipf(1.3, n) % 100_000).astype(np.int32))
    rows_lbl = jnp.asarray((rng.integers(0, 5000, n)).astype(np.int32))
    cols_lbl = jnp.asarray((rng.integers(0, 5000, n)).astype(np.int32))
    out: dict = {}

    hh = HeavyHitterSketch(capacity=256, depth=4, id_bits=24)
    hh_fold = jax.jit(lambda x: hh.fold(x))
    out["streaming_topk_1M_update"] = _min_ms(lambda: jax.block_until_ready(hh_fold(ids)))

    sketch_a = hh_fold(ids)
    sketch_b = hh_fold(jnp.flip(ids))

    @jax.jit
    def merge_k(a, b):
        return jax.lax.fori_loop(0, K_REPEATS, lambda _, acc: acc.merge(b), a)

    out["streaming_topk_1M_merge"] = (
        _min_ms(lambda: jax.block_until_ready(merge_k(sketch_a, sketch_b))) / K_REPEATS
    )

    dc = DistinctCountSketch(precision=12)
    dc_fold = jax.jit(lambda x: dc.fold(x))
    out["distinct_count_1M_update"] = _min_ms(lambda: jax.block_until_ready(dc_fold(ids)))

    co = CoOccurrenceSketch(num_rows=5000, num_cols=5000, capacity=256, depth=4)
    co_fold = jax.jit(lambda r, c: co.fold(r, c))
    out["cooccur_fold_1M"] = _min_ms(
        lambda: jax.block_until_ready(co_fold(rows_lbl, cols_lbl))
    )
    return out


def bench_serve(n_clients: int = 1000) -> dict:
    """Serving-tier sustained aggregation: 1k clients, 3-level tree.

    - ``serve_ingest_merges_per_s`` — client-snapshot merges folded per
      second across every node of a root + 4 intermediate + 16 leaf
      :class:`~metrics_tpu.serve.AggregationTree` while 1000 simulated
      clients ship two cumulative sketch snapshots each (RATE row,
      ``unit="/s"``: higher is better, the gate inverts).
    - ``serve_ingest_p99_ms`` — p99 of the per-payload ingest latency
      (decode + validate + queue wait + dedup + snapshot store) from the
      ``serve.ingest_ms`` obs histogram.
    - ``serve_e2e_freshness_ms`` — p99 end-to-end freshness: client encode
      wall time -> state queryable at the ROOT after 3 hops, from the
      per-hop trace context every armed payload carries
      (``serve.e2e_freshness_ms{node=root}``).
    - ``serve_hop_fold_p99_ms`` — p99 of the root's per-flush fold latency
      (``serve.hop_fold_ms{node=root}``) — where a fleet-wide freshness
      regression is usually hiding.

    Payload encoding happens outside the timed window (client-side cost);
    the rows measure the aggregation tier. The run folds the same
    ``run_loadgen`` harness the serve smoke pins bitwise (``verify=True``
    there; skipped here — verification is correctness, not speed).
    """
    from metrics_tpu.serve.loadgen import run_loadgen

    out = run_loadgen(
        n_clients=n_clients,
        fan_out=(4, 16),
        payloads_per_client=2,
        samples_per_payload=256,
        num_bins=256,
        verify=False,
    )
    return {
        "serve_ingest_merges_per_s": out["serve_ingest_merges_per_s"],
        "serve_ingest_p99_ms": out["serve_ingest_p99_ms"],
        "serve_e2e_freshness_ms": out["serve_e2e_freshness_ms"],
        "serve_hop_fold_p99_ms": out["serve_hop_fold_p99_ms"],
        "serve_cold_first_fold_ms": out["serve_cold_first_fold_ms"],
    }


def bench_serve_degraded(n_clients: int = 1000) -> dict:
    """Serving-tier throughput UNDER FAULTS: the self-healing overhead row.

    ``serve_ingest_degraded_merges_per_s`` — the same 1k-client / 3-level
    run as :func:`bench_serve` but with a 10% seeded fault schedule
    (:class:`~metrics_tpu.ft.faults.WireChaos`: drops, duplicates,
    reordering, crc-refused corruption) against resilience-armed nodes
    (per-client circuit breakers, poison firewall, shed watermark). A RATE
    row (``unit="/s"``, gate inverted): a regression here means the
    firewall/chaos path got more expensive relative to the clean row —
    exactly the hot-path tax the opt-in design promises to bound.
    """
    from metrics_tpu.serve.loadgen import run_loadgen

    out = run_loadgen(
        n_clients=n_clients,
        fan_out=(4, 16),
        payloads_per_client=2,
        samples_per_payload=256,
        num_bins=256,
        verify=False,
        fault_rate=0.10,
        seed=7,
    )
    return {"serve_ingest_degraded_merges_per_s": out["serve_ingest_merges_per_s"]}


def bench_serve_churn(n_clients: int = 1000) -> dict:
    """Serving-tier throughput UNDER TOPOLOGY CHURN: the elasticity row.

    ``serve_churn_merges_per_s`` — the 1k-client run routed through the
    consistent-hash :class:`~metrics_tpu.serve.elastic.Router` (clients
    consult it per ship) with three snapshot rounds, while **one node
    JOINS** (full admission protocol: build, warm, readiness probe, ring
    re-homing) after round one and **one intermediate is HARD-KILLED and
    supervisor-healed** after round two — both inside the timed window. A
    RATE row (``unit="/s"``, gate inverted): a regression means a
    rebalance or heal got more expensive relative to steady-state — the
    membership-change tax ``docs/serving.md`` §7 promises to bound. The
    ``elastic_smoke`` CI step pins the same run's root bitwise-equal to
    the flat oracle; this row only times it.
    """
    from metrics_tpu.serve.loadgen import run_loadgen

    out = run_loadgen(
        n_clients=n_clients,
        fan_out=(4, 16),
        payloads_per_client=3,
        samples_per_payload=256,
        num_bins=256,
        verify=False,
        churn=True,
        seed=11,
    )
    return {"serve_churn_merges_per_s": out["serve_churn_merges_per_s"]}


def bench_region(n_regions: int = 3, n_clients: int = 300) -> dict:
    """Multi-region serving: cross-root replication throughput + the
    freshness cost of global reads.

    - ``serve_cross_region_merges_per_s`` — accepted ``region:<name>``
      replica merges per second across every region's global view while
      clients keep ingesting regionally (a RATE row, ``unit="/s"``, gate
      inverted): a regression means the cross-region replication path —
      encode + retry-policied ship + watermark-dedup'd accept + fold —
      got more expensive.
    - ``serve_global_query_staleness_ms`` — p99 of the worst-peer replica
      age observed by :meth:`Region.query_global` (each round queries
      every region): how stale the global answer runs at this replication
      cadence. Lower is better, gated like any latency row. The
      ``region_smoke`` CI step pins the same mesh's partition-heal and
      kill+promote arms bitwise; these rows only time it.
    """
    from metrics_tpu.serve.loadgen import run_region_loadgen

    out = run_region_loadgen(
        n_regions=n_regions,
        n_clients=n_clients,
        fan_out=(2,),
        payloads_per_client=2,
        samples_per_payload=256,
        num_bins=256,
        verify=False,
        seed=13,
    )
    return {
        "serve_cross_region_merges_per_s": out["serve_cross_region_merges_per_s"],
        "serve_global_query_staleness_ms": out["serve_global_query_staleness_ms"],
    }


def bench_history(n_clients: int = 64, n_intervals: int = 48) -> dict:
    """Time-travel tier: interval ring-cut cost and range-query latency
    at full ring length.

    - ``history_ring_cut_ms`` — mean wall time of one
      :meth:`~metrics_tpu.serve.MetricHistory.cut` (copy the folded
      leaves, append through the compaction ladder, evaluate alert
      rules) while ``n_intervals`` cumulative rounds stream through a
      history-armed root: the per-interval tax of retaining history, the
      cost a cadence-armed flush pays on the ingest path.
    - ``history_range_query_p99_ms`` — p99 of full-horizon stepped delta
      range queries (`/query?start=&end=&step=`) once every ring is at
      capacity: resolve + exact monoid delta + load-and-compute per
      interval, the read-side cost at MAX retained ring length. The
      ``history_smoke`` CI step pins the same tier's accepted-snapshot
      oracle bitwise; these rows only time it.
    """
    import time as _time

    import numpy as np

    import jax.numpy as jnp

    from metrics_tpu import SumMetric
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.serve import Aggregator, HistoryConfig
    from metrics_tpu.serve.wire import encode_state
    from metrics_tpu.streaming import StreamingAUROC

    def factory():
        return MetricCollection({"auroc": StreamingAUROC(num_bins=256), "seen": SumMetric()})

    tenant = "bench"
    rng = np.random.default_rng(17)
    blobs = []  # [interval][client] cumulative snapshots, encoded untimed
    colls = [factory() for _ in range(n_clients)]
    for interval in range(n_intervals):
        round_blobs = []
        for c, coll in enumerate(colls):
            preds = jnp.asarray(rng.uniform(0, 1, 256).astype(np.float32))
            target = jnp.asarray(
                (rng.uniform(0, 1, 256) < 0.3 + 0.4 * np.asarray(preds)).astype(np.int32)
            )
            coll["auroc"].update(preds, target)
            coll["seen"].update(jnp.asarray(256.0))
            round_blobs.append(
                encode_state(coll, tenant=tenant, client_id=f"c{c:03d}", watermark=(0, interval))
            )
        blobs.append(round_blobs)

    # a ladder deep enough that steady-state cuts keep rolling up
    agg = Aggregator(
        "bench-history",
        history=HistoryConfig(cut_every_s=float("inf"), levels=((1.0, 16), (4.0, 8), (16.0, 4))),
    )
    agg.register_tenant(tenant, factory)
    cut_ms = []
    for interval in range(n_intervals):
        for blob in blobs[interval]:
            agg.ingest(blob)
        agg.flush()
        t0 = _time.perf_counter()
        agg.history.cut(agg, now=float(interval))
        cut_ms.append((_time.perf_counter() - t0) * 1000.0)

    th = agg.history._tenants[tenant]
    ts = [snap.t for _, snap in th.retained()]
    query_ms = []
    for _ in range(20):
        t0 = _time.perf_counter()
        agg.history_query(tenant, ts[0], ts[-1], step=1.0, mode="delta")
        query_ms.append((_time.perf_counter() - t0) * 1000.0)
    return {
        "history_ring_cut_ms": float(np.mean(cut_ms)),
        "history_range_query_p99_ms": float(np.percentile(query_ms, 99)),
    }


def bench_llm_experiment(n_queries: int = 10_000, docs: int = 100) -> dict:
    """LLM-eval + experimentation tier: the three hot paths the new
    tenants add.

    - ``llm_perplexity_1M_update`` — fold 1M masked per-token log-probs
      into :class:`~metrics_tpu.llm.StreamingPerplexity`'s sum states
      (two masked reductions; the whole-eval-stream ingest cost).
    - ``rag_ndcg_k10_1M_docs_compute`` — score 10k queries x 100 docs at
      k=10 through :class:`~metrics_tpu.llm.StreamingRAGQuality`'s dense
      segment-local ``lax.top_k`` path (hit-rate + MRR + NDCG in one
      launch over the 1M-document batch).
    - ``experiment_decision_p99_ms`` — p99 wall time of one
      :meth:`~metrics_tpu.experiment.DecisionEngine.evaluate` against
      retained history snapshots (arm fold + stats extraction + mSPRT
      step): the per-cut tax every armed experiment adds to the root's
      cut path. The ``experiment_smoke`` CI step pins the same tier's
      decisions bitwise; these rows only time it.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from benchmarks._timing import measure_ms_scaled
    from metrics_tpu.llm import StreamingPerplexity, StreamingRAGQuality

    out: dict = {}
    n = n_queries * docs

    lp = jax.random.uniform(jax.random.PRNGKey(0), (n,), minval=-6.0, maxval=0.0)
    mask = (jax.random.uniform(jax.random.PRNGKey(1), (n,)) > 0.1).astype(jnp.float32)
    ppl = StreamingPerplexity()

    def make_ppl(k, lp=lp, mask=mask):
        @jax.jit
        def run(lp=lp, mask=mask):
            def body(i, carry):
                s, c = carry
                lpi = lp + 0.0001 * i
                return (s + (lpi * mask).sum(), c + mask.sum())

            s, c = jax.lax.fori_loop(0, k, body, (jnp.zeros(()), jnp.zeros(())))
            return s + c

        return run

    out["llm_perplexity_1M_update"] = measure_ms_scaled(make_ppl, K_REPEATS)

    preds = jax.random.uniform(jax.random.PRNGKey(2), (n,))
    target = (jax.random.uniform(jax.random.PRNGKey(3), (n,)) > 0.9).astype(jnp.int32)
    rag = StreamingRAGQuality(k=10)

    def make_rag(k, preds=preds, target=target):
        @jax.jit
        def run(preds=preds, target=target):
            def body(i, acc):
                hit, rr, ndcg = rag._dense_scores(
                    preds * (1.0 + 0.0001 * i), target, (n_queries, docs)
                )
                return acc + hit.sum() + rr.sum() + ndcg.sum()

            return jax.lax.fori_loop(0, k, body, jnp.zeros(()))

        return run

    out["rag_ndcg_k10_1M_docs_compute"] = measure_ms_scaled(make_rag, 40)

    # the decision row is host-side: a real history-armed root with one
    # retained cut per arm, timed through the engine's evaluate() path
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.experiment import ArmSpec, DecisionEngine, Experiment, SequentialTest
    from metrics_tpu.serve import Aggregator, HistoryConfig
    from metrics_tpu.serve.wire import encode_state
    from metrics_tpu.streaming import StreamingQuantile

    def factory():
        return MetricCollection({"lat": StreamingQuantile(num_bins=128, lo=0.0, hi=1.0)})

    agg = Aggregator("bench-exp", history=HistoryConfig(cut_every_s=float("inf")))
    exp = Experiment(
        "bench",
        arms=[ArmSpec("control", factory), ArmSpec("treatment", factory)],
        metric="lat",
        # a null feed + huge min_samples keeps the verdict "continue", so
        # every timed evaluate() runs the FULL stats + mSPRT path (sticky
        # decided experiments short-circuit and would time a dict copy)
        test=SequentialTest(alpha=0.05, tau=0.1, min_samples=1 << 40, family="mean"),
    )
    exp.register(agg)
    engine = DecisionEngine(agg, [exp])
    rng = np.random.default_rng(17)
    for tid in exp.tenant_ids():
        for c in range(64):
            coll = factory()
            coll["lat"].update(jnp.asarray(rng.uniform(0, 1, 256).astype(np.float32)))
            agg.ingest(encode_state(coll, tenant=tid, client_id=f"c{c:03d}", watermark=(0, 0)))
    agg.flush()
    agg.history.cut(agg, now=0.0)
    engine.evaluate("bench")  # warm the fold caches untimed
    eval_ms = []
    for _ in range(200):
        t0 = _time.perf_counter()
        engine.evaluate("bench")
        eval_ms.append((_time.perf_counter() - t0) * 1000.0)
    out["experiment_decision_p99_ms"] = float(np.percentile(eval_ms, 99))
    return out


def bench_slo(n_tenants: int = 8, n_clients: int = 32, n_rounds: int = 24) -> dict:
    """Tenant-facing SLO plane: the two costs the tier adds to a root.

    - ``slo_eval_p99_ms`` — p99 wall time of one
      :meth:`~metrics_tpu.obs.slo.SLOEngine.evaluate_all` across
      ``n_tenants`` tenants with live ingest/freshness/canary budgets
      (registry reads + window differencing + burn-rate rules): the
      per-cut tax every armed SLO adds to the root's cut path.
    - ``meter_overhead_pct`` — percent of UNARMED ingest throughput
      retained with obs (metering + SLO counters) armed on the ingest
      hot path, i.e. ``100 * unarmed_wall / armed_wall``: 100 means zero
      overhead, lower means the armed tax grew — the ``%`` convention
      gates it inverted (higher is better) like the prefetch-overlap
      row. The ``slo_smoke`` CI step pins the tier's alert/canary
      semantics bitwise; these rows only time it.
    """
    import time as _time

    import numpy as np

    import jax.numpy as jnp

    from metrics_tpu import obs
    from metrics_tpu.aggregation import SumMetric
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.obs.prober import CanaryProber
    from metrics_tpu.obs.slo import SLOEngine
    from metrics_tpu.serve import Aggregator, HistoryConfig
    from metrics_tpu.serve.wire import encode_state
    from metrics_tpu.streaming import StreamingQuantile

    def factory():
        return MetricCollection(
            {"seen": SumMetric(), "lat": StreamingQuantile(num_bins=64, lo=0.0, hi=1.0)}
        )

    out: dict = {}
    was_enabled = obs.enabled()
    rng = np.random.default_rng(23)
    try:
        obs.enable()
        agg = Aggregator("bench-slo", history=HistoryConfig(cut_every_s=float("inf")))
        tenants = [f"t{i:02d}" for i in range(n_tenants)]
        for tid in tenants:
            agg.register_tenant(tid, factory)
        prober = CanaryProber(agg)
        for tid in tenants:
            for c in range(n_clients):
                coll = factory()
                coll["seen"].update(jnp.asarray(1.0))
                coll["lat"].update(jnp.asarray(rng.uniform(0, 1, 64).astype(np.float32)))
                agg.ingest(
                    encode_state(coll, tenant=tid, client_id=f"{tid}:c{c:03d}", watermark=(0, 0))
                )
        prober.probe()
        agg.flush()
        engine = SLOEngine(agg)  # default ingest/freshness/query/canary slos
        agg.history.cut(agg, now=0.0)  # warms the budget table untimed
        eval_ms = []
        for i in range(200):
            t0 = _time.perf_counter()
            engine.evaluate_all(now=float(i + 1))
            eval_ms.append((_time.perf_counter() - t0) * 1000.0)
        out["slo_eval_p99_ms"] = float(np.percentile(eval_ms, 99))

        # metering tax: identical pre-encoded cumulative streams through
        # two fresh roots, obs armed vs disarmed; one round warms each
        # (compile + dedup-journal setup) before the timed remainder
        streams = []
        for c in range(n_clients):
            coll = factory()
            blobs = []
            for r in range(n_rounds):
                coll["seen"].update(jnp.asarray(1.0))
                coll["lat"].update(jnp.asarray(rng.uniform(0, 1, 64).astype(np.float32)))
                blobs.append(
                    encode_state(coll, tenant="t00", client_id=f"m:c{c:03d}", watermark=(0, r))
                )
            streams.append(blobs)

        def run_mode(armed: bool) -> float:
            obs.enable(armed)
            root = Aggregator(f"bench-meter-{'armed' if armed else 'unarmed'}")
            root.register_tenant("t00", factory)
            for blobs in streams:  # warm round, untimed
                root.ingest(blobs[0])
            root.flush()
            t0 = _time.perf_counter()
            for r in range(1, n_rounds):
                for blobs in streams:
                    root.ingest(blobs[r])
                root.flush()
            return _time.perf_counter() - t0

        unarmed_s = run_mode(False)
        armed_s = run_mode(True)
        out["meter_overhead_pct"] = 100.0 * unarmed_s / armed_s
    finally:
        obs.enable(was_enabled)
    return out


def bench_aot() -> dict:
    """Cold-vs-warm first fold: the execution-engine acceptance rows.

    - ``first_fold_cold_ms`` — an AOT-armed
      :class:`~metrics_tpu.serve.Aggregator`'s first tenant fold against an
      EMPTY :class:`~metrics_tpu.engine.ProgramStore`: trace + lower +
      backend compile + execute (what a freshly autoscaled node pays
      without warm start; ``jax.clear_caches()`` before each cold trial so
      jax's in-process trace cache cannot fake a warm start).
    - ``first_fold_warm_ms`` — the revival path on the SAME store: a fresh
      aggregator (process restart simulated by clearing the engine's
      in-memory program registry), ``warmup()`` replaying the checkpoint's
      warmup manifest (deserialize, prime — untimed, it happens before
      traffic), ``restore()``, then the timed first fold: execute only,
      ZERO backend compiles. Its ``vs_baseline`` against the cold row is
      the warm-start win; acceptance requires >= 10x
      (``tests/integrations/aot_smoke.py`` asserts it with a real process
      boundary).

    Both rows time :meth:`_Tenant.fold` itself (payload accept runs
    untimed first): the row is first-FOLD latency, not ingest accounting.
    """
    import os
    import queue as _queue
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from metrics_tpu import engine as eng
    from metrics_tpu.collections import MetricCollection
    from metrics_tpu.serve.aggregator import Aggregator
    from metrics_tpu.serve.wire import encode_state
    from metrics_tpu.streaming import StreamingAUROC, StreamingAveragePrecision, StreamingQuantile

    def factory():
        return MetricCollection(
            {
                "auroc": StreamingAUROC(num_bins=256),
                "ap": StreamingAveragePrecision(num_bins=256),
                "q50": StreamingQuantile(q=0.5, num_bins=256),
            }
        )

    rng = np.random.default_rng(11)
    cold_payloads, warm_payloads = [], []
    for i in range(3):
        client = factory()
        p = rng.uniform(0, 1, 1024).astype(np.float32)
        t = (rng.uniform(0, 1, 1024) < p).astype(np.int32)
        client.update(jnp.asarray(p), jnp.asarray(t))
        # same cumulative snapshot at two watermarks: the warm aggregator
        # restores the cold one's watermarks, so its payloads must advance
        cold_payloads.append(encode_state(client, tenant="bench", client_id=f"c{i}", watermark=(0, 0)))
        warm_payloads.append(encode_state(client, tenant="bench", client_id=f"c{i}", watermark=(0, 1)))

    def drain_accept(agg: Aggregator) -> None:
        # accept runs untimed so the rows time the FOLD, not payload
        # decode/validate (flush() would fold inline with the drain)
        while True:
            try:
                payload, t0 = agg._queue.get_nowait()
            except _queue.Empty:
                return
            agg._accept(payload, t0)

    root = tempfile.mkdtemp(prefix="bench_aot.")
    cold_times, warm_times = [], []
    try:
        for trial in range(3):
            store = eng.ProgramStore(os.path.join(root, f"store{trial}"))
            ckpt = os.path.join(root, f"ckpt{trial}")
            eng.reset_memory_cache()
            jax.clear_caches()  # a REAL cold start: no in-process trace reuse
            cold = Aggregator(
                "cold", engine=eng.AotEngine(store), prewarm_buckets=(), checkpoint_dir=ckpt
            )
            cold.register_tenant("bench", factory)
            for blob in cold_payloads:
                cold.ingest(blob)
            drain_accept(cold)
            t0 = time.perf_counter()
            cold._tenants["bench"].fold()
            cold_times.append((time.perf_counter() - t0) * 1000.0)
            cold.save()

            eng.reset_memory_cache()  # simulated process restart
            jax.clear_caches()
            warm = Aggregator(
                "warm", engine=eng.AotEngine(store), prewarm_buckets=(), checkpoint_dir=ckpt
            )
            warm.register_tenant("bench", factory)
            warm.warmup()  # untimed: replay manifest, deserialize, prime
            warm.restore()
            for blob in warm_payloads:
                warm.ingest(blob)
            drain_accept(warm)
            t0 = time.perf_counter()
            warm._tenants["bench"].fold()
            warm_times.append((time.perf_counter() - t0) * 1000.0)
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {
        "first_fold_cold_ms": min(cold_times),
        "first_fold_warm_ms": min(warm_times),
    }


def bench_mesh_rows() -> dict:
    """Sharded-state + topology-aware-sync rows (round 15; see
    ``benchmarks/bench_mesh.py`` for the row semantics).

    The two mesh rows need >= 2 devices: on a multi-device host (the TPU
    sweep — acceptance values come from there) they run in-process; a
    single-device CPU host spawns the module as a subprocess that
    self-provisions an 8-device virtual mesh BEFORE backend init (the
    parent's backend is already up, so the device count cannot change
    in-process). The prefetch-overlap row is single-device and always
    runs in-process.
    """
    import os
    import subprocess
    import sys

    import jax

    from benchmarks import bench_mesh

    out = dict(bench_mesh.measure_prefetch())
    if jax.device_count() >= 2:
        out.update(bench_mesh.measure())
        return out
    proc = subprocess.run(
        [sys.executable, "-m", "benchmarks.bench_mesh"],
        capture_output=True,
        text=True,
        timeout=900,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    if proc.returncode != 0:
        raise RuntimeError(f"bench_mesh subprocess failed: {proc.stderr[-500:]}")
    out.update(json.loads(proc.stdout.strip().splitlines()[-1]))
    return out


def bench_probes() -> dict:
    """Chip-state calibration probes, one per op class.

    The tunneled chip's performance state flips BETWEEN processes as well as
    within a session, and round-4/5 data shows it is per-op-class: one
    session ran sorts ~1.9x slow while matmuls sat at historical bests.
    These three fixed microkernels — a 1M-element sort, a bf16 matmul
    chain, a 1M x 10 elementwise reduce — are emitted as ordinary rows, so
    every BENCH_r*.json records the session's state per class and the
    regression gate can compare row regressions against the probe's own
    slowdown instead of blaming the code.
    """
    import jax
    import jax.numpy as jnp

    from benchmarks._timing import measure_ms_scaled

    x = jax.random.uniform(jax.random.PRNGKey(7), (N_SAMPLES,), dtype=jnp.float32)
    a = jax.random.normal(jax.random.PRNGKey(8), (1024, 1024), dtype=jnp.bfloat16) * 0.03
    e = jax.random.uniform(jax.random.PRNGKey(9), (N_SAMPLES, 10), dtype=jnp.bfloat16)

    def make_sort(k):
        @jax.jit
        def run():
            def body(i, acc):
                return acc + jnp.sort(x * (1.0 + 1e-6 * i))[0]

            return jax.lax.fori_loop(0, k, body, jnp.zeros(()))
        return run

    def make_matmul(k):
        @jax.jit
        def run():
            def body(i, y):
                y = jnp.matmul(y, a)  # bf16 MXU chain
                return y / (jnp.abs(y).max() + 1e-6)

            return jnp.sum(jax.lax.fori_loop(0, k, body, a).astype(jnp.float32))
        return run

    def make_elementwise(k):
        @jax.jit
        def run():
            def body(i, acc):
                return acc + jnp.sum((e * (1.0 + 1e-3 * i.astype(jnp.bfloat16)))).astype(jnp.float32)

            return jax.lax.fori_loop(0, k, body, jnp.zeros(()))
        return run

    # tunnel RTT: one device round trip (put + tiny add + sync), DIRECT
    # samples — the RTT phase swings 20us-90ms and dominates any row that
    # pays one synchronous round trip per call (e.g. the host-side WER row)
    import numpy as np

    from benchmarks._timing import cluster_direct_samples

    z = jnp.zeros(())
    float(z + 1.0)  # warm
    rtt = []
    for i in range(10):
        t0 = time.perf_counter()
        float(jax.device_put(np.float32(i)) + z)
        rtt.append((time.perf_counter() - t0) * 1000)

    w = jax.random.normal(jax.random.PRNGKey(10), (64, 64, 3, 3), dtype=jnp.bfloat16) * 0.05
    c_in = jax.random.normal(jax.random.PRNGKey(11), (16, 64, 32, 32), dtype=jnp.bfloat16)

    def make_conv(k):
        @jax.jit
        def run():
            def body(i, y):
                y = jax.lax.conv_general_dilated(
                    y, w, (1, 1), "SAME", dimension_numbers=("NCHW", "OIHW", "NCHW")
                )
                return y / (jnp.abs(y).max() + 1e-6)

            return jnp.sum(jax.lax.fori_loop(0, k, body, c_in).astype(jnp.float32))
        return run

    return {
        "probe_tunnel_rtt": cluster_direct_samples(rtt),
        "probe_sort_1M": measure_ms_scaled(make_sort, 8),
        "probe_matmul_1024_bf16": measure_ms_scaled(make_matmul, 1024),
        "probe_conv_64ch_3x3": measure_ms_scaled(make_conv, 256),
        "probe_elementwise_1Mx10": measure_ms_scaled(make_elementwise, 512),
    }


# which probe calibrates which row, matched by the row's actual dominant op
# class: big dense matmuls -> matmul probe; dense conv towers -> conv probe;
# separable-depthwise SSIM is bandwidth/VPU-bound -> elementwise probe;
# host-side rows have no probe (raw comparison with the confound note).
# Shared with the --compare gate so the two can never disagree about a
# row's calibration class.
from benchmarks.compare import PROBE_CLASS as _PROBE_CLASS  # noqa: E402
from benchmarks.compare import is_rate_metric as _is_rate  # noqa: E402


def _prior_rounds() -> tuple:
    """(per-file {metric: value} dicts in order, names seen with unit "/s").

    Rate-ness must ride along: the per-round dicts drop the row's ``unit``
    field, and ``is_rate_metric(name)`` alone only knows the ``*_per_s``
    naming convention — a rate row identified solely by its unit would
    otherwise get min() (worst prior) in the best-prior scans below,
    silently disarming the throughput gate.
    """
    import glob
    import os

    rounds = []
    rate_names: set = set()
    here = os.path.dirname(os.path.abspath(__file__))
    for path in sorted(glob.glob(os.path.join(here, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                tail = json.load(f).get("tail", "")
        except (OSError, ValueError):
            continue
        rows: dict = {}
        for line in tail.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            name, value = row.get("metric"), row.get("value")
            if isinstance(value, (int, float)) and value > 0:
                if _is_rate(name, row):  # throughput: best = highest
                    rate_names.add(name)
                    rows[name] = max(rows.get(name, 0.0), float(value))
                else:
                    rows[name] = min(rows.get(name, float("inf")), float(value))
        if rows:
            rounds.append(rows)
    return rounds, rate_names


def _best_prior_values() -> dict:
    """Best prior-round value per metric (lowest; highest for rate rows)."""
    best: dict = {}
    rounds, rate_names = _prior_rounds()
    for rows in rounds:
        for name, value in rows.items():
            if name in rate_names or _is_rate(name):
                best[name] = max(best.get(name, 0.0), value)
            else:
                best[name] = min(best.get(name, float("inf")), value)
    return best


def _best_prior_normalized() -> dict:
    """Best prior row-to-class-probe RATIO per metric.

    The chip's per-op-class performance state flips between sessions, so
    raw round-over-round value comparison confounds code changes with chip
    state. The row/probe ratio is state-invariant (row and probe scale
    together by construction), so the gate prefers it whenever a prior
    round recorded the probes (r5+); earlier rounds fall back to raw
    comparison with the confound note.
    """
    best: dict = {}
    rounds, rate_names = _prior_rounds()
    for rows in rounds:
        for name, probe in _PROBE_CLASS.items():
            if name in rows and rows.get(probe, 0) > 0:
                if name in rate_names or _is_rate(name):
                    # throughput x probe latency is the chip-invariant
                    # quantity for a rate row; best = highest
                    best[name] = max(best.get(name, 0.0), rows[name] * rows[probe])
                else:
                    ratio = rows[name] / rows[probe]
                    best[name] = min(best.get(name, float("inf")), ratio)
    return best


def main(
    json_path: "str | None" = None,
    compare_path: "str | None" = None,
    compare_threshold: float = 1.5,
) -> None:
    from benchmarks import (
        bench_collection,
        bench_curves,
        bench_detection,
        bench_image,
        bench_retrieval,
        bench_text_image,
    )

    import math
    import sys

    # compile split WITHOUT arming the full obs layer: the jax.monitoring
    # listener (recording once installed, independent of obs.enable)
    # accumulates backend compile seconds per section. The full layer stays
    # OFF — its eager-path spans/counters would sit inside the timed
    # regions of eager rows (e.g. the compute-group A/B) and confound the
    # comparison against prior rounds measured without it.
    from metrics_tpu import obs

    compile_listener_ok = obs.install_compile_listener()
    if not compile_listener_ok:
        print(
            "WARNING: jax.monitoring listener unavailable — section_compile_s"
            " will read 0.0 and does NOT mean fully-cached runs.",
            file=sys.stderr,
        )

    def _compile_seconds() -> float:
        return obs.get_counter("jax.compile_seconds")

    print(
        "NOTE: vs_baseline is the speedup over the REFERENCE'S EAGER DATA PATH RE-TIMED IN"
        " TORCH ON THIS HOST'S CPU (the reference publishes no numbers). BASELINE.md's"
        " '>=5x CUDA compute() throughput' north star is NOT measurable in this"
        " environment (no CUDA device); do not read the ratio as that target.",
        file=sys.stderr,
    )
    prior = _best_prior_values()
    prior_norm = _best_prior_normalized()
    emitted_rows: list = []
    emitted_dicts: list = []
    session_probe_values: dict = {}
    _section_compile_s: list = [0.0]  # compile seconds attributed to the current section

    def section(measure_fn, *args, **kwargs):
        """Run one measurement section, attributing its backend compile
        seconds (from the obs jax.monitoring listener) to the rows it
        emits — the compile-vs-run split the JSON record publishes."""
        c0 = _compile_seconds()
        out = measure_fn(*args, **kwargs)
        _section_compile_s[0] = _compile_seconds() - c0
        return out

    def emit(name: str, ours_ms: float, base_ms: float, baseline: str = "torch_cpu_eager", unit: str = "ms") -> None:
        # print each row as soon as it exists: a timeout mid-run must not
        # lose the rows already measured. A NaN measurement (dispatch-phase
        # noise swamped the workload) is reported to stderr and the row is
        # omitted — never published as a fabricated number.
        if not math.isfinite(ours_ms) or ours_ms <= 0:
            print(f"SKIPPED {name}: measurement invalid (dispatch noise > workload)", file=sys.stderr)
            return
        # higher-is-better rows: throughput ("/s") and percentage-recovered
        # ("%", e.g. prefetch overlap) — vs_baseline and the gates invert
        higher_better = unit in ("/s", "%")
        row = {
            "metric": name,
            "value": round(ours_ms, 3),
            "unit": unit,
            # >1 always means "better than baseline": time ratio for
            # latency rows, value ratio for rate/percent rows
            "vs_baseline": round(ours_ms / base_ms if higher_better else base_ms / ours_ms, 3),
            "baseline": baseline,
        }
        # bimodal-chip protocol (benchmarks/_timing.py): the value IS the
        # fast-mode median; both mode medians + sample counts ride along so
        # rounds stay comparable regardless of which state the sweep hit
        if hasattr(ours_ms, "n_fast"):
            row["fast_mode_median"] = round(ours_ms.fast_mode_median, 3)
            row["slow_mode_median"] = (
                None if ours_ms.slow_mode_median is None else round(ours_ms.slow_mode_median, 3)
            )
            row["n_fast"] = ours_ms.n_fast
            row["n_slow"] = ours_ms.n_slow
        # split-reported host rows (WER): the tunnel round trip the end-to-end
        # call would add, published separately from the kernel time
        if hasattr(ours_ms, "tunnel_rtt_ms"):
            row["tunnel_rtt_ms"] = round(ours_ms.tunnel_rtt_ms, 3)
        # compile-vs-run split: the row's `value` is steady-state run time;
        # `section_compile_s` is the backend compile time the row's section
        # paid once (shared across rows measured in the same section)
        row["section_compile_s"] = round(_section_compile_s[0], 3)
        line = json.dumps(row)
        print(line, flush=True)
        emitted_rows.append(line)
        emitted_dicts.append(row)
        if name.startswith("probe_"):
            return  # probes RECORD session state; gating them is meaningless
        best = prior.get(name)
        if best is None:
            return
        # state-invariant gate: compare the row/class-probe RATIO against
        # the best prior ratio whenever a probe-bearing round exists — the
        # chip's per-op-class state cancels out of the ratio. Rounds
        # predating the probes can only be compared raw (confounded).
        # Rate rows (unit="/s": higher is better) gate INVERTED, on the
        # throughput x probe-latency product (the chip-invariant quantity).
        probe = _PROBE_CLASS.get(name)
        probe_now = session_probe_values.get(probe)
        norm_best = prior_norm.get(name)
        if probe_now and norm_best is not None:
            if higher_better:
                product = float(ours_ms) * probe_now
                if product < norm_best / 1.5:
                    print(
                        f"REGRESSION {name}: throughput x probe {product:.1f} vs best prior"
                        f" {norm_best:.1f} ({norm_best / product:.2f}x lower) — state-invariant"
                        " comparison, this is NOT chip-mode noise.",
                        file=sys.stderr,
                    )
                return
            ratio = float(ours_ms) / probe_now
            if ratio > 1.5 * norm_best:
                print(
                    f"REGRESSION {name}: row/probe ratio {ratio:.1f} vs best prior"
                    f" {norm_best:.1f} ({ratio / norm_best:.2f}x) — state-invariant"
                    " comparison, this is NOT chip-mode noise.",
                    file=sys.stderr,
                )
            return
        if higher_better:
            if ours_ms < best / 1.5:
                print(
                    f"REGRESSION {name}: {float(ours_ms):.1f}{unit} vs best prior round"
                    f" {best:.1f}{unit} ({best / float(ours_ms):.2f}x lower). No probe-bearing"
                    " prior round exists for a state-invariant comparison.",
                    file=sys.stderr,
                )
            return
        if ours_ms > 1.5 * best:
            print(
                f"REGRESSION {name}: fast-mode {float(ours_ms):.3f} ms vs best prior round"
                f" {best:.3f} ms ({float(ours_ms) / best:.2f}x). No probe-bearing prior"
                " round exists for a state-invariant comparison; the per-class probe rows"
                " in THIS sweep record the session state (benchmarks/_timing.py).",
                file=sys.stderr,
            )

    # chip-state probes first: they calibrate the gate for every later row
    probes = section(bench_probes)
    for pname, pval in probes.items():
        if math.isfinite(pval) and pval > 0:
            session_probe_values[pname] = float(pval)
            pbest = prior.get(pname)
            emit(pname, pval, pbest if pbest is not None else float(pval), baseline="best_prior_probe")

    curves = section(bench_curves.measure)
    emit("auroc_exact_1M_compute", curves["auroc_exact_1M_compute"], base_auroc())
    emit("binned_counts_1M_T100_update", curves["binned_counts_1M_T100_update"], base_binned())

    coll = section(bench_collection.measure)
    emit("collection_statscores_binary_1M_update", coll["collection_statscores_binary_1M_update"], base_collection("binary"))
    emit(
        "collection_statscores_multiclass_1M_update",
        coll["collection_statscores_multiclass_1M_update"],
        base_collection("multiclass"),
    )
    # the reference's ONE quantitative perf claim: compute groups give
    # "2x-3x lower computational cost" (docs overview; SURVEY.md §6). A/B
    # on the same collection, so the baseline is our own groups-off path.
    savings = section(bench_collection.measure_compute_group_savings)
    emit(
        "collection_prf1_200k_update_groups_on",
        savings["collection_prf1_200k_update_groups_on"],
        savings["collection_prf1_200k_update_groups_off"],
        baseline="same_collection_compute_groups_off",
    )

    # whole-collection fusion (round 7): the 12-metric acceptance config in
    # ONE launch per epoch fold vs the (group-deduped) eager batch loop on
    # the same device, plus the launch-count pin — a fusion break to
    # per-member launches reads 12x and fails the --compare gate.
    try:
        fusion = section(bench_collection.measure_collection_fusion)
        eager_epoch_ms = section(bench_collection.measure_collection_eager_epoch)
        emit(
            "collection12_1M_epoch_wallclock",
            fusion["collection12_1M_epoch_wallclock"],
            eager_epoch_ms,
            baseline="eager_collection_same_device",
        )
        emit(
            "collection12_launch_count",
            fusion["collection12_launch_count"],
            prior.get("collection12_launch_count", fusion["collection12_launch_count"]),
            baseline="best_prior_self",
            unit="launches",
        )
    except Exception as err:  # noqa: BLE001 — fusion rows must not kill the sweep
        print(f"SKIPPED collection fusion rows: {err}", file=sys.stderr)

    retr = section(bench_retrieval.measure)
    emit("retrieval_map_1M_docs_compute", retr["retrieval_map_1M_docs_compute"], base_retrieval("map"))
    emit("retrieval_ndcg_1M_docs_compute", retr["retrieval_ndcg_1M_docs_compute"], base_retrieval("ndcg"))
    # MAP@k=10, same 1M docs: the segment-local top-k path (per-query
    # lax.top_k on the dense view; no full multi-operand sort)
    emit(
        "retrieval_map_k10_1M_docs_compute",
        retr["retrieval_map_k10_1M_docs_compute"],
        base_retrieval("map_k10"),
    )

    fid = section(bench_image.measure)
    emit("fid_10k_2048d_compute", fid["fid_10k_2048d_compute"], base_fid())
    ssim = section(bench_image.measure_ssim)
    emit("ssim_64x3x256x256_compute", ssim["ssim_64x3x256x256_compute"], base_ssim())

    ti = section(bench_text_image.measure)
    emit("lpips_alex_32x64x64_forward", ti["lpips_alex_32x64x64_forward"], base_lpips())
    emit("bertscore_match_256x128x256", ti["bertscore_match_256x128x256"], base_bertscore())
    emit("wer_10k_pairs_compute", ti["wer_10k_pairs_compute"], base_wer())

    emit("detection_map_2k_images_compute", section(bench_detection.measure, n_trials=2), base_map(2_000))

    # large-state mesh sync (8 virtual CPU devices; own process because the
    # backend here is already initialized on the TPU). The ratio is the old
    # replicated psum-of-scatter gather over the shipped 1x-payload
    # all_gather path — a same-mesh A/B, not a torch baseline.
    import subprocess

    try:
        import os

        sync_out = subprocess.run(
            [sys.executable, "-m", "benchmarks.bench_sync"],
            capture_output=True, text=True, timeout=600, check=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout
        rows = {}
        for line in sync_out.splitlines():
            if line.startswith("{"):
                row = json.loads(line)
                rows[row["metric"]] = row["value"]
        # this row compiles in the SUBPROCESS, invisible to the in-process
        # compile listener — 0.0 is the honest attribution (never the
        # previous section's leftovers)
        _section_compile_s[0] = 0.0
        emit(
            "buffer_sync_1M_8dev_compute",
            rows["buffer_sync_1M_8dev_static_varying"],
            rows["buffer_sync_1M_8dev_static_invariant"],
            baseline="psum_of_scatter_gather_same_mesh",
        )
    except (subprocess.SubprocessError, OSError, KeyError, ValueError) as err:
        print(f"SKIPPED buffer_sync_1M_8dev_compute: {err}", file=sys.stderr)

    # fault-tolerance: checkpoint save/restore latency, sync vs async (the
    # async row's ratio is the training-loop stall saved by the background
    # writer — an A/B on the same manager/state, not a torch baseline)
    try:
        ckpt = section(bench_checkpoint)
        sync_ms = ckpt["checkpoint_save_1M_sync"]
        emit(
            "checkpoint_save_1M_sync",
            sync_ms,
            prior.get("checkpoint_save_1M_sync", sync_ms),
            baseline="best_prior_self",
        )
        emit(
            "checkpoint_save_1M_async_stall",
            ckpt["checkpoint_save_1M_async_stall"],
            sync_ms,
            baseline="sync_save_same_state",
        )
        emit(
            "checkpoint_restore_1M",
            ckpt["checkpoint_restore_1M"],
            prior.get("checkpoint_restore_1M", ckpt["checkpoint_restore_1M"]),
            baseline="best_prior_self",
        )
    except Exception as err:  # noqa: BLE001 — a missing orbax must not kill the sweep
        print(f"SKIPPED checkpoint rows: {err}", file=sys.stderr)

    # streaming subsystem: bounded-memory sketch fold/merge/compute + the
    # one-launch windowed monitor step. The compute row's A/B is the exact
    # 1M AUROC compute from the curves section — what the documented error
    # bound buys; the others gate against their own best prior round.
    try:
        stream_rows = section(bench_streaming)
        for row_name in ("streaming_auroc_1M_update", "streaming_auroc_1M_merge", "windowed_fold_k16"):
            emit(
                row_name,
                stream_rows[row_name],
                prior.get(row_name, stream_rows[row_name]),
                baseline="best_prior_self",
            )
        emit(
            "streaming_auroc_1M_compute",
            stream_rows["streaming_auroc_1M_compute"],
            curves["auroc_exact_1M_compute"],
            baseline="exact_auroc_same_stream",
        )
    except Exception as err:  # noqa: BLE001 — streaming rows must not kill the sweep
        print(f"SKIPPED streaming rows: {err}", file=sys.stderr)

    # sketch families: heavy-hitter / distinct-count / co-occurrence fold
    # and merge hot paths over the same 1M-sample stream; each row gates
    # against its own best prior round
    try:
        sketch_rows = section(bench_sketch_families)
        for row_name in (
            "streaming_topk_1M_update",
            "streaming_topk_1M_merge",
            "distinct_count_1M_update",
            "cooccur_fold_1M",
        ):
            emit(
                row_name,
                sketch_rows[row_name],
                prior.get(row_name, sketch_rows[row_name]),
                baseline="best_prior_self",
            )
    except Exception as err:  # noqa: BLE001 — sketch rows must not kill the sweep
        print(f"SKIPPED sketch family rows: {err}", file=sys.stderr)

    # serving tier: 1000 simulated clients shipping sketch snapshots
    # through a 3-level aggregation tree — sustained merge throughput
    # (rate row, gate inverted) and per-payload ingest p99
    try:
        serve_rows = section(bench_serve)
        emit(
            "serve_ingest_merges_per_s",
            serve_rows["serve_ingest_merges_per_s"],
            prior.get("serve_ingest_merges_per_s", serve_rows["serve_ingest_merges_per_s"]),
            baseline="best_prior_self",
            unit="/s",
        )
        emit(
            "serve_ingest_p99_ms",
            serve_rows["serve_ingest_p99_ms"],
            prior.get("serve_ingest_p99_ms", serve_rows["serve_ingest_p99_ms"]),
            baseline="best_prior_self",
        )
        # fleet-observability rows (PR 10): end-to-end freshness at the
        # root and the root's fold latency, both off the per-hop trace
        # context — ms rows, lower is better, gated like any latency row
        for row_name in ("serve_e2e_freshness_ms", "serve_hop_fold_p99_ms"):
            emit(
                row_name,
                serve_rows[row_name],
                prior.get(row_name, serve_rows[row_name]),
                baseline="best_prior_self",
            )
        # cold-start rows (round 11): the untimed warmup flush's measured
        # cost — the first-fold compile chain the timed window no longer
        # smears into steady-state tail latency
        emit(
            "serve_cold_first_fold_ms",
            serve_rows["serve_cold_first_fold_ms"],
            prior.get("serve_cold_first_fold_ms", serve_rows["serve_cold_first_fold_ms"]),
            baseline="best_prior_self",
        )
        degraded_rows = section(bench_serve_degraded)
        emit(
            "serve_ingest_degraded_merges_per_s",
            degraded_rows["serve_ingest_degraded_merges_per_s"],
            prior.get(
                "serve_ingest_degraded_merges_per_s",
                degraded_rows["serve_ingest_degraded_merges_per_s"],
            ),
            baseline="best_prior_self",
            unit="/s",
        )
        # elasticity row (round 13): merges/s sustained while one node
        # joins and one intermediate dies mid-window — rate row, inverted
        # gate, like the other /s rows (TPU sweep supplies acceptance)
        churn_rows = section(bench_serve_churn)
        emit(
            "serve_churn_merges_per_s",
            churn_rows["serve_churn_merges_per_s"],
            prior.get(
                "serve_churn_merges_per_s",
                churn_rows["serve_churn_merges_per_s"],
            ),
            baseline="best_prior_self",
            unit="/s",
        )
        # multi-region rows (round 14): cross-root replication throughput
        # (rate row, inverted gate) and the global-read freshness cost —
        # the region_smoke CI step pins the same mesh bitwise
        region_rows = section(bench_region)
        emit(
            "serve_cross_region_merges_per_s",
            region_rows["serve_cross_region_merges_per_s"],
            prior.get(
                "serve_cross_region_merges_per_s",
                region_rows["serve_cross_region_merges_per_s"],
            ),
            baseline="best_prior_self",
            unit="/s",
        )
        emit(
            "serve_global_query_staleness_ms",
            region_rows["serve_global_query_staleness_ms"],
            prior.get(
                "serve_global_query_staleness_ms",
                region_rows["serve_global_query_staleness_ms"],
            ),
            baseline="best_prior_self",
        )
        # time-travel tier rows (round 17): the per-interval ring-cut tax
        # and the read-side range-query latency at full ring length — the
        # history_smoke CI step pins the same tier's oracle bitwise
        history_rows = section(bench_history)
        emit(
            "history_ring_cut_ms",
            history_rows["history_ring_cut_ms"],
            prior.get("history_ring_cut_ms", history_rows["history_ring_cut_ms"]),
            baseline="best_prior_self",
        )
        emit(
            "history_range_query_p99_ms",
            history_rows["history_range_query_p99_ms"],
            prior.get(
                "history_range_query_p99_ms",
                history_rows["history_range_query_p99_ms"],
            ),
            baseline="best_prior_self",
        )
    except Exception as err:  # noqa: BLE001 — serve rows must not kill the sweep
        print(f"SKIPPED serve rows: {err}", file=sys.stderr)

    # execution engine (round 11): cold vs warm first fold through the
    # persistent program store — the warm row's vs_baseline IS the
    # warm-start win (acceptance: >= 10x; aot_smoke asserts it with a
    # real process boundary, the gate keeps both rows from regressing)
    try:
        aot_rows = section(bench_aot)
        cold_ms = aot_rows["first_fold_cold_ms"]
        emit(
            "first_fold_cold_ms",
            cold_ms,
            prior.get("first_fold_cold_ms", cold_ms),
            baseline="best_prior_self",
        )
        emit(
            "first_fold_warm_ms",
            aot_rows["first_fold_warm_ms"],
            cold_ms,
            baseline="cold_first_fold_same_store",
        )
    except Exception as err:  # noqa: BLE001 — engine rows must not kill the sweep
        print(f"SKIPPED aot engine rows: {err}", file=sys.stderr)

    # sharded-state + topology-aware sync (round 15): the sharded 1M
    # buffer-AUROC sync gates against its replicated A/B (the win IS the
    # vs_baseline), the hierarchical/flat ratio and the prefetch-overlap
    # percentage gate against their own best prior (overlap is a
    # higher-is-better "%" row — inverted gate, the "/s" convention)
    try:
        mesh_rows = section(bench_mesh_rows)
        emit(
            "sharded_auroc_1M_sync_ms",
            mesh_rows["sharded_auroc_1M_sync_ms"],
            mesh_rows["replicated_auroc_1M_sync_ms"],
            baseline="replicated_gather_same_state",
        )
        emit(
            "hier_reduce_vs_flat_ratio",
            mesh_rows["hier_reduce_vs_flat_ratio"],
            prior.get("hier_reduce_vs_flat_ratio", mesh_rows["hier_reduce_vs_flat_ratio"]),
            baseline="best_prior_self",
            unit="x",
        )
        emit(
            "epoch_prefetch_overlap_pct",
            mesh_rows["epoch_prefetch_overlap_pct"],
            prior.get("epoch_prefetch_overlap_pct", mesh_rows["epoch_prefetch_overlap_pct"]),
            baseline="best_prior_self",
            unit="%",
        )
    except Exception as err:  # noqa: BLE001 — mesh rows must not kill the sweep
        print(f"SKIPPED mesh rows: {err}", file=sys.stderr)

    # LLM-eval + experimentation tier (round 19): the eval-stream ingest
    # and RAG scoring kernels plus the host-side per-cut decision tax —
    # the experiment_smoke CI step pins the tier's decisions bitwise,
    # these rows only time it (TPU sweep supplies acceptance values)
    try:
        llm_rows = section(bench_llm_experiment)
        for row_name in (
            "llm_perplexity_1M_update",
            "rag_ndcg_k10_1M_docs_compute",
            "experiment_decision_p99_ms",
        ):
            emit(
                row_name,
                llm_rows[row_name],
                prior.get(row_name, llm_rows[row_name]),
                baseline="best_prior_self",
            )
    except Exception as err:  # noqa: BLE001 — llm rows must not kill the sweep
        print(f"SKIPPED llm/experiment rows: {err}", file=sys.stderr)

    # tenant-facing SLO plane (round 20): the per-cut budget-evaluation
    # tax and the metering tax on the ingest hot path — the slo_smoke CI
    # step pins the tier's alert/canary/bitwise semantics, these rows
    # only time it (TPU sweep supplies acceptance values). The overhead
    # row is retained-throughput percent: 100 = zero armed overhead,
    # and the "%" unit gates it inverted (lower = regression)
    try:
        slo_rows = section(bench_slo)
        emit(
            "slo_eval_p99_ms",
            slo_rows["slo_eval_p99_ms"],
            prior.get("slo_eval_p99_ms", slo_rows["slo_eval_p99_ms"]),
            baseline="best_prior_self",
        )
        emit(
            "meter_overhead_pct",
            slo_rows["meter_overhead_pct"],
            prior.get("meter_overhead_pct", slo_rows["meter_overhead_pct"]),
            baseline="best_prior_self",
            unit="%",
        )
    except Exception as err:  # noqa: BLE001 — slo rows must not kill the sweep
        print(f"SKIPPED slo rows: {err}", file=sys.stderr)

    # headline LAST (the driver's tail-line parse keeps its round-1 meaning)
    emit("accuracy_1M_update_compute_wallclock", section(bench_accuracy_tpu), base_accuracy())

    # repeat the full compact table as the FINAL stdout block, headline row
    # still last: the driver's BENCH_r*.json tail capture truncates early
    # output, so this guarantees every row survives into the record
    # (VERDICT r4 weak #6). Rows are identical JSON to the incremental
    # prints; duplicate lines are harmless to the prior-round min scan.
    print("=== full row table (headline last) ===")
    for line in emitted_rows:
        print(line, flush=True)

    record = build_record(emitted_dicts) if (json_path or compare_path) else None
    if json_path:
        _dump_record(json_path, record)

    if compare_path:
        # regression gate against a prior record: exits nonzero on a gated
        # regression (EXIT_REGRESSED) or a cross-device refusal
        # (EXIT_REFUSED) so CI fails loudly instead of archiving a slower
        # round as if nothing happened. The SAME record object --json just
        # wrote is compared (rows normalized by the same rows_by_metric as
        # load_record), so in-memory and reloaded gating can never differ.
        from benchmarks.compare import (
            BenchRecord,
            CompareRefused,
            EXIT_REFUSED,
            compare_records,
            load_record,
            render_report,
            rows_by_metric,
        )

        new_rec = BenchRecord(
            rows_by_metric(record["rows"]),
            path="<this sweep>",
            device_kind=record["device_kind"],
            platform=record["platform"],
            jax_version=record["jax_version"],
            device_count=record["device_count"],
            process_count=record["process_count"],
        )
        try:
            result = compare_records(load_record(compare_path), new_rec, threshold=compare_threshold)
        except CompareRefused as err:
            print(f"REFUSED: {err}", file=sys.stderr)
            sys.exit(EXIT_REFUSED)
        print(render_report(result), end="")
        if result["exit_code"]:
            sys.exit(result["exit_code"])


def build_record(rows: list) -> dict:
    """The machine-readable sweep record as a dict (see ``--json``): device
    kind + jax version + host count header (so a TPU sweep, a CPU fallback
    and a multi-host run can never be confused), every row with its
    compile-vs-run split, and the obs compile totals."""
    import platform
    import time as _time

    import jax

    from metrics_tpu import obs

    dev = jax.devices()[0]
    return {
        "schema": 1,
        "recorded_unix": int(_time.time()),
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "platform": dev.platform,
        "device_count": jax.device_count(),
        "process_count": jax.process_count(),
        "jax_version": jax.__version__,
        "python_version": platform.python_version(),
        "rows": rows,
        "obs": {
            # False means the monitoring API was unavailable: every
            # section_compile_s is then 0.0 by construction, NOT a sign of
            # fully-cached runs — trajectory tooling must check this flag.
            # Read-only probe: writing a record must not install anything.
            "compile_listener_installed": obs.compile_listener_installed(),
            "jax_compile_seconds": obs.get_counter("jax.compile_seconds"),
            "jax_compiles": obs.get_counter("jax.compiles"),
        },
    }


def _dump_record(path: str, record: dict) -> None:
    import sys

    with open(path, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {path} ({len(record['rows'])} rows)", file=sys.stderr)


def write_json_record(path: str, rows: list) -> None:
    """Write the machine-readable sweep record (``--json BENCH_rNN.json``);
    see :func:`build_record` for the shape."""
    _dump_record(path, build_record(rows))


if __name__ == "__main__":
    import argparse

    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "--json",
        metavar="PATH",
        default=None,
        help="also write the full sweep as one machine-readable JSON record"
        " (device kind, jax version, per-row compile-vs-run split, obs totals)",
    )
    parser.add_argument(
        "--compare",
        metavar="OLD.json",
        default=None,
        help="gate this sweep against a prior bench record (benchmarks/compare.py):"
        " prints the delta report and exits nonzero on a regression past"
        " --compare-threshold; refuses cross-device comparisons (exit 2)",
    )
    parser.add_argument(
        "--compare-threshold",
        type=float,
        default=1.5,
        metavar="X",
        help="regression gate ratio for --compare (default 1.5)",
    )
    parser.add_argument(
        "--trend",
        nargs="*",
        metavar="RECORD",
        default=None,
        help="render the metric x round trend table over the given bench"
        " records (default: BENCH_r*.json beside this script) instead of"
        " running the sweep; rounds missing a row render as gaps (—), so"
        " rows added in later rounds never break the table",
    )
    _args = parser.parse_args()
    if _args.trend is not None:
        # delegate to the compare CLI's trend mode (benchmarks/compare.py):
        # no sweep runs, and absent rows are rendered as gaps per round
        from benchmarks.compare import main as _compare_main

        raise SystemExit(_compare_main(["--trend", *_args.trend]))
    main(
        json_path=_args.json,
        compare_path=_args.compare,
        compare_threshold=_args.compare_threshold,
    )
