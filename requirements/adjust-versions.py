"""Align jax-ecosystem pins for a target jax release.

TPU analogue of the reference's ``requirements/adjust-versions.py`` (which
aligns torch/torchvision/torchtext triplets): given a jax version, rewrite
the requirements files so jaxlib/flax/optax/orbax pins match the validated
row. Usage::

    python requirements/adjust-versions.py requirements/base.txt [jax_version]

With no explicit version, the latest validated row applies.
"""
import re
import sys
from pathlib import Path

# validated (jax, jaxlib, flax, optax, orbax-checkpoint) rows, newest first
VERSIONS = [
    dict(jax="0.8.0", jaxlib="0.8.0", flax="0.12.0", optax="0.2.6", orbax="0.11.0"),
    dict(jax="0.7.0", jaxlib="0.7.0", flax="0.11.0", optax="0.2.5", orbax="0.11.0"),
    dict(jax="0.6.0", jaxlib="0.6.0", flax="0.10.6", optax="0.2.4", orbax="0.11.0"),
]
PACKAGE_KEY = {"jax": "jax", "jaxlib": "jaxlib", "flax": "flax", "optax": "optax", "orbax-checkpoint": "orbax"}


def find_row(jax_version: str | None) -> dict:
    if jax_version is None:
        return VERSIONS[0]
    for row in VERSIONS:
        if jax_version.startswith(row["jax"].rsplit(".", 1)[0]):
            return row
    return VERSIONS[0]


def adjust(text: str, row: dict) -> str:
    out = []
    for line in text.splitlines():
        m = re.match(r"^([A-Za-z0-9_.-]+)\s*([<>=!~].*)?$", line.split("#")[0].strip())
        name = m.group(1).lower() if m and m.group(1) else None
        if name in PACKAGE_KEY:
            pin = row[PACKAGE_KEY[name]]
            comment = "" if "#" not in line else "  #" + line.split("#", 1)[1]
            out.append(f"{name}>={pin}{comment}")
        else:
            out.append(line)
    return "\n".join(out) + "\n"


def main() -> None:
    if len(sys.argv) < 2:
        raise SystemExit(__doc__)
    path = Path(sys.argv[1])
    row = find_row(sys.argv[2] if len(sys.argv) > 2 else None)
    path.write_text(adjust(path.read_text(), row))
    print(f"{path}: aligned to jax {row['jax']} row")


if __name__ == "__main__":
    main()
