"""DecisionEngine: cut-hook evaluation, fencing, durability, endpoint."""
import json
import urllib.error
import urllib.request

import numpy as np
import jax.numpy as jnp
import pytest

import metrics_tpu.obs as obs
from metrics_tpu.collections import MetricCollection
from metrics_tpu.experiment import ArmSpec, DecisionEngine, Experiment, SequentialTest
from metrics_tpu.serve import Aggregator, MetricsServer, ServeError
from metrics_tpu.serve.history import HistoryConfig
from metrics_tpu.serve.wire import encode_state
from metrics_tpu.streaming import StreamingQuantile

EXP = "latency-cut"
N_CLIENTS = 2
SAMPLES = 64


@pytest.fixture(autouse=True)
def _obs_reset():
    was = obs.enabled()
    obs.enable(False)
    obs.reset()
    yield
    obs.reset()
    obs.enable(was)


def _factory():
    return MetricCollection({"lat": StreamingQuantile(num_bins=128, lo=0.0, hi=1.0)})


def _build(checkpoint_dir=None, alpha=0.05):
    agg = Aggregator(
        "root",
        history=HistoryConfig(cut_every_s=float("inf")),
        checkpoint_dir=checkpoint_dir,
    )
    exp = Experiment(
        EXP,
        arms=[ArmSpec("control", _factory), ArmSpec("treatment", _factory)],
        metric="lat",
        test=SequentialTest(alpha=alpha, tau=0.1, min_samples=100, family="mean"),
        higher_is_better=False,  # latency: lower is better
    )
    exp.register(agg)
    engine = DecisionEngine(agg, [exp])
    return agg, exp, engine


def _feed(agg, exp, interval, effect):
    """Cumulative clients re-ship interval [0, interval] per arm."""
    for arm, shift in ((exp.control, 0.0), (exp.treatment, -effect)):
        tid = exp.tenant_id(arm)
        for c in range(N_CLIENTS):
            coll = _factory()
            rng = np.random.default_rng(1000 * c + (7 if shift == 0.0 else 13))
            for _ in range(interval + 1):
                vals = np.clip(rng.normal(0.5 + shift, 0.05, SAMPLES), 0.0, 1.0)
                coll["lat"].update(jnp.asarray(vals.astype(np.float32)))
            agg.ingest(
                encode_state(coll, tenant=tid, client_id=f"c{c}", watermark=(0, interval))
            )
    agg.flush()


class TestDecisions:
    def test_true_effect_ships_once_and_sticks(self):
        obs.enable()
        obs.reset()
        agg, exp, engine = _build()
        decided_at = None
        with pytest.warns(UserWarning, match="DECIDED: SHIP"):
            for interval in range(6):
                _feed(agg, exp, interval, effect=0.2)
                agg.history.cut(agg, now=float(interval))  # hook evaluates
                rec = engine.report(EXP)
                if rec["verdict"] != "continue" and decided_at is None:
                    decided_at = (interval, rec["evaluations"])
        assert decided_at is not None
        final = engine.evaluate(EXP)
        assert final["verdict"] == "ship"
        # sticky: later cuts never re-litigate or re-count the decision
        assert final["evaluations"] == decided_at[1]
        dec = [
            v
            for k, v in obs.snapshot()["counters"].items()
            if k.startswith("experiment.decisions")
        ]
        assert sum(dec) == 1
        assert final["decision"]["verdict"] == "ship"
        assert final["decision"]["p_value"] <= 0.05

    def test_null_effect_never_fires(self):
        obs.enable()
        obs.reset()
        agg, exp, engine = _build()
        for interval in range(6):
            _feed(agg, exp, interval, effect=0.0)
            agg.history.cut(agg, now=float(interval))
        rec = engine.report(EXP)
        assert rec["verdict"] == "continue"
        assert rec["decision"] is None
        assert rec["evaluations"] == 6

    def test_generation_fence_skips_cross_failover_comparison(self):
        obs.enable()
        obs.reset()
        agg, exp, engine = _build()
        _feed(agg, exp, 0, effect=0.0)
        agg.history.cut(agg, now=0.0)
        before = engine.report(EXP)["fenced"]
        # a failover bumps the history generation: retained snapshots now
        # belong to the old history and must not be compared
        agg.history.generation += 1
        rec = engine.evaluate(EXP)
        assert rec["fenced"] == before + 1
        assert rec["verdict"] == "continue"
        fenced = [
            v
            for k, v in obs.snapshot()["counters"].items()
            if k.startswith("experiment.fenced_evaluations")
        ]
        assert sum(fenced) >= 1


class TestDurability:
    def test_checkpoint_roundtrip_is_bitwise(self, tmp_path):
        obs.enable()
        obs.reset()
        agg, exp, engine = _build(checkpoint_dir=str(tmp_path))
        with pytest.warns(UserWarning, match="DECIDED"):
            for interval in range(4):
                _feed(agg, exp, interval, effect=0.2)
                agg.history.cut(agg, now=float(interval))
        assert engine.report(EXP)["verdict"] == "ship"
        path = agg.save()
        agg2, exp2, engine2 = _build(checkpoint_dir=str(tmp_path))
        agg2.restore(path)
        assert json.dumps(engine.state_for_checkpoint(), sort_keys=True) == json.dumps(
            engine2.state_for_checkpoint(), sort_keys=True
        )
        # a restored root must not re-announce (or re-count) the decision
        assert ("decision", EXP) in engine2._warned

    def test_unknown_saved_experiments_are_ignored(self):
        agg, exp, engine = _build()
        engine.load_checkpoint_state({"never-attached": {"verdict": "ship"}})
        with pytest.raises(KeyError):
            engine.report("never-attached")


class TestReporting:
    def test_report_shape_and_unknown_id(self):
        agg, exp, engine = _build()
        rep = engine.report(EXP)
        assert rep["arms"] == {
            "control": f"{EXP}/control",
            "treatment": f"{EXP}/treatment",
        }
        assert rep["test"]["alpha"] == 0.05
        assert rep["verdict"] == "continue"
        with pytest.raises(KeyError):
            engine.report("nope")

    def test_http_endpoint(self):
        agg, exp, engine = _build()
        server = MetricsServer(agg, port=0).start()
        try:
            body = json.loads(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/experiment/{EXP}"
                ).read()
            )
            assert body["experiment"] == EXP and body["verdict"] == "continue"
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/experiment/nope"
                )
            assert exc.value.code == 404
        finally:
            server.stop()

    def test_endpoint_without_engine_is_400(self):
        agg = Aggregator("plain", history=HistoryConfig(cut_every_s=float("inf")))
        server = MetricsServer(agg, port=0)
        with pytest.raises(ServeError, match="no decision engine"):
            server.render_experiment("anything")


class TestContracts:
    def test_engine_requires_history(self):
        agg = Aggregator("nohist")
        with pytest.raises(ServeError, match="no history armed"):
            DecisionEngine(agg)

    def test_duplicate_experiment_rejected(self):
        agg, exp, engine = _build()
        with pytest.raises(ServeError, match="already attached"):
            engine.add(exp)

    def test_experiment_validation(self):
        arms = [ArmSpec("a", _factory), ArmSpec("b", _factory)]
        with pytest.raises(ValueError, match="exactly 2 arms"):
            Experiment("x", arms[:1], metric="lat")
        with pytest.raises(ValueError, match="must differ"):
            Experiment("x", [arms[0], ArmSpec("a", _factory)], metric="lat")
        with pytest.raises(ValueError, match="non-empty"):
            Experiment("", arms, metric="lat")
        with pytest.raises(ValueError, match="factory"):
            ArmSpec("a", factory=None)

    def test_missing_metric_member_warns_not_raises(self):
        """A decision bug must not kill the cut path: the hook swallows
        the error with a one-shot warning."""
        agg = Aggregator("root", history=HistoryConfig(cut_every_s=float("inf")))
        exp = Experiment(
            EXP,
            arms=[ArmSpec("control", _factory), ArmSpec("treatment", _factory)],
            metric="not-a-member",
            test=SequentialTest(min_samples=1),
        )
        exp.register(agg)
        engine = DecisionEngine(agg, [exp])
        _feed(agg, exp, 0, effect=0.0)
        with pytest.warns(UserWarning, match="evaluation failed"):
            agg.history.cut(agg, now=0.0)
        with pytest.raises(ServeError, match="not a.*member"):
            engine.evaluate(EXP)
