"""SequentialTest: always-valid calibration, coverage, envelope folding.

The ISSUE-pinned correctness properties live here:

* seeded null simulation — the always-valid p-value crosses ``alpha``
  in at most an ``alpha`` fraction of 1k monitored runs;
* the confidence sequence covers the true effect uniformly over cuts;
* sketch-derived decisions imply the exact-sample decision at the same
  cut (the envelope can delay significance, never fabricate it).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.experiment import (
    ArmStats,
    SequentialTest,
    arm_stats_from_samples,
    arm_stats_from_sketch,
    mixture_lr,
)
from metrics_tpu.streaming import QuantileSketch, ScoreLabelSketch


def _cumulative_stats(x: np.ndarray):
    """Per-cut (n, mean, var) for runs x (cuts*batch) sample matrices."""
    runs, cuts, batch = x.shape
    flat = x.reshape(runs, cuts * batch)
    csum = np.cumsum(flat, axis=1)
    csq = np.cumsum(flat**2, axis=1)
    ends = np.arange(1, cuts + 1) * batch
    n = ends.astype(np.float64)
    s = csum[:, ends - 1]
    s2 = csq[:, ends - 1]
    mean = s / n
    var = np.maximum(s2 / n - mean**2, 0.0)
    return n, mean, var


class TestNullCalibration:
    def test_always_valid_under_null_1k_runs(self):
        """Monitoring at every cut, the p-value dips below alpha in at
        most an alpha fraction of null runs (Ville's inequality)."""
        rng = np.random.default_rng(2026)
        runs, cuts, batch = 1000, 10, 100
        control = rng.standard_normal((runs, cuts, batch))
        treatment = rng.standard_normal((runs, cuts, batch))
        test = SequentialTest(alpha=0.05, tau=0.2, min_samples=batch)
        n, mean_c, var_c = _cumulative_stats(control)
        _, mean_t, var_t = _cumulative_stats(treatment)
        crossed = np.zeros(runs, dtype=bool)
        for r in range(runs):
            p = 1.0
            for c in range(cuts):
                out = test.step(
                    ArmStats(n[c], mean_c[r, c], var_c[r, c], 0.0),
                    ArmStats(n[c], mean_t[r, c], var_t[r, c], 0.0),
                    prev_p=p,
                )
                p = out["p_value"]
            crossed[r] = p <= test.alpha
        assert crossed.mean() <= test.alpha

    def test_mixture_lr_is_martingale_shaped(self):
        # LR = 1 exactly at zero effect, grows with |diff|, vectorized
        assert float(mixture_lr(0.0, 1.0, 0.5)) < 1.0
        assert float(mixture_lr(0.0, 0.0, 0.5)) == 1.0
        lrs = mixture_lr(np.asarray([0.0, 0.5, 1.0]), 0.01, 0.5)
        assert lrs.shape == (3,) and np.all(np.diff(lrs) > 0)


class TestConfidenceSequence:
    def test_covers_true_effect_uniformly(self):
        rng = np.random.default_rng(7)
        runs, cuts, batch, effect = 400, 8, 100, 0.3
        control = rng.standard_normal((runs, cuts, batch))
        treatment = rng.standard_normal((runs, cuts, batch)) + effect
        test = SequentialTest(alpha=0.05, tau=0.2, min_samples=batch)
        n, mean_c, var_c = _cumulative_stats(control)
        _, mean_t, var_t = _cumulative_stats(treatment)
        covered = np.zeros(runs, dtype=bool)
        for r in range(runs):
            ok = True
            for c in range(cuts):
                out = test.step(
                    ArmStats(n[c], mean_c[r, c], var_c[r, c], 0.0),
                    ArmStats(n[c], mean_t[r, c], var_t[r, c], 0.0),
                )
                lo, hi = out["ci"]
                ok = ok and (lo <= effect <= hi)
            covered[r] = ok
        assert covered.mean() >= 1.0 - test.alpha

    def test_halfwidth_shrinks_with_evidence(self):
        test = SequentialTest(alpha=0.05, tau=0.2)
        assert test.confidence_halfwidth(0.0) == float("inf")
        assert test.confidence_halfwidth(0.001) < test.confidence_halfwidth(0.1)


class TestSketchNeverFabricates:
    def test_sketch_decision_implies_exact_decision(self):
        """Whenever the sketch-evidence chain fires, the exact-sample
        chain has already fired the same verdict — the envelope only
        delays, never fabricates."""
        rng = np.random.default_rng(42)
        cuts, batch, effect = 12, 200, 0.08
        test = SequentialTest(alpha=0.05, tau=0.1, min_samples=batch)
        sk_c = QuantileSketch(num_bins=64, lo=0.0, hi=1.0)
        sk_t = QuantileSketch(num_bins=64, lo=0.0, hi=1.0)
        all_c, all_t = [], []
        p_exact = p_sketch = 1.0
        exact_fired_at = sketch_fired_at = None
        for cut in range(cuts):
            c = np.clip(rng.normal(0.5, 0.1, batch), 0.0, 1.0)
            t = np.clip(rng.normal(0.5 + effect, 0.1, batch), 0.0, 1.0)
            all_c.append(c)
            all_t.append(t)
            sk_c = sk_c.fold(jnp.asarray(c))
            sk_t = sk_t.fold(jnp.asarray(t))
            exact = test.step(
                arm_stats_from_samples(np.concatenate(all_c)),
                arm_stats_from_samples(np.concatenate(all_t)),
                prev_p=p_exact,
            )
            sketch = test.step(
                arm_stats_from_sketch(sk_c, family="mean"),
                arm_stats_from_sketch(sk_t, family="mean"),
                prev_p=p_sketch,
            )
            p_exact, p_sketch = exact["p_value"], sketch["p_value"]
            if exact["verdict"] != "continue" and exact_fired_at is None:
                exact_fired_at = (cut, exact["verdict"])
            if sketch["verdict"] != "continue" and sketch_fired_at is None:
                sketch_fired_at = (cut, sketch["verdict"])
            if sketch["verdict"] != "continue":
                assert exact["verdict"] == sketch["verdict"]
        # non-vacuous: this seeded stream fires on both evidence paths
        assert exact_fired_at is not None and exact_fired_at[1] == "ship"
        assert sketch_fired_at is not None and sketch_fired_at[1] == "ship"
        assert exact_fired_at[0] <= sketch_fired_at[0]

    def test_envelope_swallows_small_effects(self):
        # combined halfwidth exceeds the observed diff: the effective
        # effect is zero, the LR stays at 1 and no verdict can fire
        test = SequentialTest(alpha=0.05, tau=0.1, min_samples=10)
        out = test.step(
            ArmStats(1000.0, 0.50, 0.01, 0.03),
            ArmStats(1000.0, 0.52, 0.01, 0.03),
        )
        assert out["effective_diff"] == 0.0
        assert out["verdict"] == "continue"
        assert out["p_value"] == 1.0
        assert out["envelope"] == pytest.approx(0.06)

    def test_rate_family_is_exact(self):
        sk = ScoreLabelSketch(num_bins=64)
        sk = sk.fold(
            jnp.asarray([0.1, 0.8, 0.4, 0.9, 0.7]), jnp.asarray([0, 1, 0, 1, 1])
        )
        stats = arm_stats_from_sketch(sk, family="rate")
        assert stats.n == 5.0
        assert stats.mean == pytest.approx(0.6)
        assert stats.var == pytest.approx(0.24)
        assert stats.halfwidth == 0.0

    def test_mean_family_halfwidth_bounds_mean_error(self):
        rng = np.random.default_rng(5)
        x = rng.uniform(0.0, 1.0, 2000)
        sk = QuantileSketch(num_bins=128, lo=0.0, hi=1.0).fold(jnp.asarray(x))
        stats = arm_stats_from_sketch(sk, family="mean")
        assert abs(stats.mean - x.mean()) <= stats.halfwidth + 1e-6
        assert stats.var >= x.var() - 1e-6  # conservative upper bound


class TestContracts:
    def test_constructor_validation(self):
        with pytest.raises(ValueError, match="alpha"):
            SequentialTest(alpha=1.5)
        with pytest.raises(ValueError, match="tau"):
            SequentialTest(tau=0.0)
        with pytest.raises(ValueError, match="family"):
            SequentialTest(family="median")

    def test_sketch_family_validation(self):
        with pytest.raises(ValueError, match="rate"):
            arm_stats_from_sketch(QuantileSketch(8, 0.0, 1.0), family="rate")
        with pytest.raises(ValueError, match="mean"):
            arm_stats_from_sketch(ScoreLabelSketch(8), family="mean")

    def test_step_is_pure(self):
        test = SequentialTest(alpha=0.05, tau=0.1, min_samples=10)
        c = ArmStats(500.0, 0.4, 0.02, 0.0)
        t = ArmStats(500.0, 0.55, 0.02, 0.0)
        assert test.step(c, t, 0.7) == test.step(c, t, 0.7)

    def test_empty_arm_stats(self):
        assert arm_stats_from_samples([]) == ArmStats(0.0, 0.0, 0.0, 0.0)
        empty = arm_stats_from_sketch(QuantileSketch(8, 0.0, 1.0), "mean")
        assert empty.n == 0.0
