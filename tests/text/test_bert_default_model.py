"""BERTScore through the REAL default-`transformers` code path.

The reference's core path runs a HF encoder inside the metric
(``/root/reference/torchmetrics/functional/text/bert.py:248-325``). No
pretrained checkpoint can be downloaded here, so a tiny random-init
``FlaxBertModel`` + WordPiece tokenizer are saved to a local directory and
loaded back via ``model_name_or_path`` — which exercises the genuine
``_load_tokenizer_and_model`` -> ``_tokenize`` -> ``_get_embeddings`` ->
matching pipeline, including ``num_layers`` / ``all_layers`` / ``idf`` /
batching and the baseline rescale.
"""
import numpy as np
import pytest

from tests.conftest import strict_dtype_promotion

if strict_dtype_promotion():
    pytest.skip("FlaxBert internals mix int/float dtypes (third-party)", allow_module_level=True)

transformers = pytest.importorskip("transformers")

from metrics_tpu.functional import bert_score  # noqa: E402
from metrics_tpu.text import BERTScore  # noqa: E402

_VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "hello", "world", "the", "cat", "sat", "on", "a", "mat", "dog", "ran", "fast", "master", "kenobi",
]
_N_LAYERS = 3

PREDS = ["hello world", "the cat sat on the mat", "master kenobi"]
TARGET = ["hello there world", "a cat sat on a mat", "hello master kenobi"]


@pytest.fixture(scope="module")
def tiny_model_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tiny_bert")
    vocab_file = d / "vocab.txt"
    vocab_file.write_text("\n".join(_VOCAB) + "\n")
    tokenizer = transformers.BertTokenizerFast(vocab_file=str(vocab_file))
    tokenizer.save_pretrained(str(d))
    config = transformers.BertConfig(
        vocab_size=len(_VOCAB) + 10,
        hidden_size=32,
        num_hidden_layers=_N_LAYERS,
        num_attention_heads=2,
        intermediate_size=64,
        max_position_embeddings=64,
    )
    model = transformers.FlaxBertModel(config, seed=0)
    model.save_pretrained(str(d))
    return str(d)


def test_default_model_basic(tiny_model_dir):
    out = bert_score(PREDS, TARGET, model_name_or_path=tiny_model_dir, max_length=16)
    for key in ("precision", "recall", "f1"):
        assert len(out[key]) == len(PREDS)
        assert np.isfinite(out[key]).all()
        assert (np.abs(np.asarray(out[key])) <= 1.0 + 1e-6).all()
    # identical corpora must be a perfect match through the real encoder
    same = bert_score(TARGET, TARGET, model_name_or_path=tiny_model_dir, max_length=16)
    np.testing.assert_allclose(same["f1"], 1.0, atol=1e-5)


@pytest.mark.parametrize("num_layers", [1, 2, _N_LAYERS])
def test_default_model_num_layers(tiny_model_dir, num_layers):
    out = bert_score(PREDS, TARGET, model_name_or_path=tiny_model_dir, num_layers=num_layers, max_length=16)
    assert np.isfinite(out["f1"]).all()


def test_default_model_layers_differ(tiny_model_dir):
    a = bert_score(PREDS, TARGET, model_name_or_path=tiny_model_dir, num_layers=1, max_length=16)
    b = bert_score(PREDS, TARGET, model_name_or_path=tiny_model_dir, num_layers=_N_LAYERS, max_length=16)
    assert not np.allclose(a["f1"], b["f1"])


def test_default_model_all_layers(tiny_model_dir):
    out = bert_score(PREDS, TARGET, model_name_or_path=tiny_model_dir, all_layers=True, max_length=16)
    scores = np.asarray(out["f1"])
    # hidden_states = embeddings + one per transformer layer
    assert scores.shape == (_N_LAYERS + 1, len(PREDS))
    assert np.isfinite(scores).all()


def test_default_model_idf(tiny_model_dir):
    plain = bert_score(PREDS, TARGET, model_name_or_path=tiny_model_dir, max_length=16)
    idf = bert_score(PREDS, TARGET, model_name_or_path=tiny_model_dir, idf=True, max_length=16)
    assert np.isfinite(idf["f1"]).all()
    assert not np.allclose(plain["f1"], idf["f1"])


def test_default_model_batching_invariant(tiny_model_dir):
    whole = bert_score(PREDS, TARGET, model_name_or_path=tiny_model_dir, batch_size=64, max_length=16)
    chunked = bert_score(PREDS, TARGET, model_name_or_path=tiny_model_dir, batch_size=1, max_length=16)
    np.testing.assert_allclose(whole["f1"], chunked["f1"], atol=1e-5)


def test_default_model_baseline_rescale(tiny_model_dir, tmp_path):
    base = tmp_path / "baseline.csv"
    base.write_text("LAYER,P,R,F\n" + "\n".join(f"{i},0.3,0.3,0.3" for i in range(_N_LAYERS + 1)) + "\n")
    plain = bert_score(PREDS, TARGET, model_name_or_path=tiny_model_dir, max_length=16)
    rescaled = bert_score(
        PREDS, TARGET, model_name_or_path=tiny_model_dir, max_length=16,
        rescale_with_baseline=True, baseline_path=str(base),
    )
    np.testing.assert_allclose(
        np.asarray(rescaled["f1"]), (np.asarray(plain["f1"]) - 0.3) / 0.7, atol=1e-5
    )


def test_default_model_all_layers_baseline_row_mismatch(tiny_model_dir, tmp_path):
    bad = tmp_path / "bad_baseline.csv"
    bad.write_text("LAYER,P,R,F\n" + "\n".join(f"{i},0.3,0.3,0.3" for i in range(_N_LAYERS + 7)) + "\n")
    with pytest.raises(ValueError, match="one row per layer"):
        bert_score(
            PREDS, TARGET, model_name_or_path=tiny_model_dir, max_length=16, all_layers=True,
            rescale_with_baseline=True, baseline_path=str(bad),
        )


def test_default_model_empty_corpus_all_layers(tiny_model_dir):
    out = bert_score([], [], model_name_or_path=tiny_model_dir, all_layers=True, max_length=16)
    assert out == {"precision": [], "recall": [], "f1": []}


def test_metric_class_default_model(tiny_model_dir):
    metric = BERTScore(model_name_or_path=tiny_model_dir, max_length=16)
    metric.update(PREDS[:2], TARGET[:2])
    metric.update(PREDS[2:], TARGET[2:])
    out = metric.compute()
    oracle = bert_score(PREDS, TARGET, model_name_or_path=tiny_model_dir, max_length=16)
    np.testing.assert_allclose(out["f1"], oracle["f1"], atol=1e-5)
