"""TER vs sacrebleu oracle, EED vs an independent cell-loop DP oracle,
SQuAD vs hand-computed values
(reference ``tests/text/test_{ter,eed,squad}.py``)."""
from math import inf

import numpy as np
import pytest
from sacrebleu.metrics import TER

from metrics_tpu.functional import extended_edit_distance, squad, translation_edit_rate
from metrics_tpu.functional.text.eed import _preprocess_en
from metrics_tpu.text import SQuAD, ExtendedEditDistance, TranslationEditRate
from tests.text.helpers import TextTester

_preds_b1 = ["the cat is on the mat", "There is a big tree near the house."]
_targets_b1 = [
    ["there is a cat on the mat", "a cat is on the mat"],
    ["A big tree is growing near the house.", "There is a tree close to the building."],
]
_preds_b2 = ["hello there general kenobi", "the fast brown fox jumped over the lazy dog"]
_targets_b2 = [
    ["hello there general kenobi", "hello there!"],
    ["the quick brown fox jumped over the lazy dog", "the fast brown fox leaps over a dog"],
]
BATCHES_PREDS = [_preds_b1, _preds_b2]
BATCHES_TARGET = [_targets_b1, _targets_b2]


def _to_sacre_refs(targets):
    n_refs = max(len(t) for t in targets)
    return [[t[i] if i < len(t) else t[-1] for t in targets] for i in range(n_refs)]


def _make_ter_oracle(normalized=False, no_punct=False, case_sensitive=False, asian_support=False):
    def oracle(preds, targets):
        ter = TER(
            normalized=normalized,
            no_punct=no_punct,
            case_sensitive=case_sensitive,
            asian_support=asian_support,
        )
        return ter.corpus_score(list(preds), _to_sacre_refs(targets)).score / 100

    return oracle


class TestTER(TextTester):
    @pytest.mark.parametrize(
        "normalize, no_punctuation, lowercase",
        [(False, False, True), (True, False, True), (False, True, True), (False, False, False)],
    )
    def test_functional_vs_sacrebleu(self, normalize, no_punctuation, lowercase):
        oracle = _make_ter_oracle(normalized=normalize, no_punct=no_punctuation, case_sensitive=not lowercase)
        for preds, targets in zip(BATCHES_PREDS, BATCHES_TARGET):
            got = float(
                translation_edit_rate(
                    preds, targets, normalize=normalize, no_punctuation=no_punctuation, lowercase=lowercase
                )
            )
            np.testing.assert_allclose(got, oracle(preds, targets), atol=1e-6)

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(ddp, BATCHES_PREDS, BATCHES_TARGET, TranslationEditRate, _make_ter_oracle())

    def test_shift_reduces_edits(self):
        """A pure phrase move costs 1 shift, not per-word edits."""
        # "d e a b c" -> shift "a b c" to front = 1 shift + 2 edits? vs plain lev 4
        got = float(translation_edit_rate(["d e a b c"], [["a b c d e"]]))
        ter = TER()
        want = ter.corpus_score(["d e a b c"], [["a b c d e"]]).score / 100
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_sentence_level(self):
        score, sentences = translation_edit_rate(_preds_b1, _targets_b1, return_sentence_level_score=True)
        assert sentences.shape == (2,)
        ter = TER()
        for i, (pred, refs) in enumerate(zip(_preds_b1, _targets_b1)):
            want = ter.sentence_score(pred, refs).score / 100
            np.testing.assert_allclose(float(sentences[i]), want, atol=1e-6)


def _ref_eed_function(hyp, ref, alpha=2.0, rho=0.3, deletion=0.2, insertion=1.0):
    """Independent plain-Python EED DP (published RWTH algorithm, cell loop)."""
    number_of_visits = [-1] * (len(hyp) + 1)
    row = [1.0] * (len(hyp) + 1)
    row[0] = 0.0
    next_row = [inf] * (len(hyp) + 1)
    for w in range(1, len(ref) + 1):
        for i in range(0, len(hyp) + 1):
            if i > 0:
                next_row[i] = min(
                    next_row[i - 1] + deletion,
                    row[i - 1] + (0 if hyp[i - 1] == ref[w - 1] else 1),
                    row[i] + insertion,
                )
            else:
                next_row[i] = row[i] + 1.0
        min_index = next_row.index(min(next_row))
        number_of_visits[min_index] += 1
        if ref[w - 1] == " ":
            jump = alpha + next_row[min_index]
            next_row = [min(x, jump) for x in next_row]
        row = next_row
        next_row = [inf] * (len(hyp) + 1)
    coverage = rho * sum(x if x >= 0 else 1 for x in number_of_visits)
    return min(1, (row[-1] + coverage) / (float(len(ref)) + coverage))


def _ref_eed(preds, targets):
    scores = []
    for pred, refs in zip(preds, targets):
        refs = [refs] if isinstance(refs, str) else refs
        scores.append(min(_ref_eed_function(_preprocess_en(pred), _preprocess_en(r)) for r in refs))
    return float(np.mean(scores))


class TestEED(TextTester):
    def test_functional_vs_cell_loop_oracle(self):
        for preds, targets in zip(BATCHES_PREDS, BATCHES_TARGET):
            got = float(extended_edit_distance(preds, targets))
            np.testing.assert_allclose(got, _ref_eed(preds, targets), atol=1e-6)

    def test_random_strings_vs_oracle(self):
        """Fuzz the vectorized DP against the cell loop.

        Costs are dyadic (0.25/1.0/2.0) so both arithmetics are exact: with
        the default 0.2 costs the reference's chained additions accumulate
        float noise that breaks coverage-argmin ties arbitrarily, which is
        tie-break noise, not an algorithmic difference.
        """
        rng = np.random.default_rng(7)
        letters = list("ab c")
        kw = dict(alpha=2.0, rho=0.25, deletion=0.25, insertion=1.0)
        for _ in range(50):
            hyp = "".join(rng.choice(letters, size=rng.integers(0, 15)))
            ref = "".join(rng.choice(letters, size=rng.integers(1, 15)))
            got = float(extended_edit_distance([hyp], [[ref]], **kw))
            want = np.mean([min(_ref_eed_function(_preprocess_en(hyp), _preprocess_en(ref), **kw), 1)])
            np.testing.assert_allclose(got, want, atol=1e-6)

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(ddp, BATCHES_PREDS, BATCHES_TARGET, ExtendedEditDistance, _ref_eed)

    def test_reference_doctest_value(self):
        preds = ["this is the prediction", "here is an other sample"]
        target = ["this is the reference", "here is another one"]
        np.testing.assert_allclose(float(extended_edit_distance(preds, target)), 0.3078, atol=1e-4)

    def test_invalid_args(self):
        with pytest.raises(ValueError):
            extended_edit_distance(["a"], [["b"]], alpha=-1.0)
        with pytest.raises(ValueError):
            ExtendedEditDistance(language="fr")


_squad_preds = [
    {"prediction_text": "1976", "id": "id1"},
    {"prediction_text": "Hello World", "id": "id2"},
    {"prediction_text": "totally wrong", "id": "id3"},
]
_squad_target = [
    {"answers": {"answer_start": [97], "text": ["1976"]}, "id": "id1"},
    {"answers": {"answer_start": [0], "text": ["hello world!", "Hi World"]}, "id": "id2"},
    {"answers": {"answer_start": [0], "text": ["right answer"]}, "id": "id3"},
]


class TestSQuAD:
    def test_functional_values(self):
        result = squad(_squad_preds, _squad_target)
        # EM: id1 exact, id2 exact after normalization (case/punct), id3 wrong
        np.testing.assert_allclose(float(result["exact_match"]), 100 * 2 / 3, atol=1e-4)
        # F1: id1=1, id2=1 (best gt), id3=0
        np.testing.assert_allclose(float(result["f1"]), 100 * 2 / 3, atol=1e-4)

    def test_partial_f1(self):
        preds = [{"prediction_text": "the quick brown fox", "id": "a"}]
        target = [{"answers": {"answer_start": [0], "text": ["quick brown dog"]}, "id": "a"}]
        result = squad(preds, target)
        assert float(result["exact_match"]) == 0.0
        # "the" is stripped as an article: p = r = 2/3 -> f1 = 2/3
        np.testing.assert_allclose(float(result["f1"]), 100 * 2 / 3, atol=1e-4)

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        from tests.helpers.testers import _wire_virtual_ddp

        world = 2 if ddp else 1
        metrics = [SQuAD() for _ in range(world)]
        if ddp:
            _wire_virtual_ddp(metrics)
        for i, (p, t) in enumerate(zip(_squad_preds, _squad_target)):
            metrics[i % world].update([p], [t])
        result = metrics[0].compute()
        np.testing.assert_allclose(float(result["exact_match"]), 100 * 2 / 3, atol=1e-4)
        np.testing.assert_allclose(float(result["f1"]), 100 * 2 / 3, atol=1e-4)

    def test_missing_keys_raise(self):
        with pytest.raises(KeyError):
            squad([{"id": "x"}], _squad_target[:1])
        with pytest.raises(KeyError):
            squad(_squad_preds[:1], [{"id": "x"}])
