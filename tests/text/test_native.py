"""Native Levenshtein kernel: parity with the numpy fallback and dispatch.

The C kernel (``metrics_tpu/native/levenshtein.c``) and the numpy row DP
(``functional/text/helper.py``) must agree exactly on random corpora, the
batch entry must equal per-pair calls, and the WER family must produce
identical values whichever backend is active.
"""
import numpy as np
import pytest

from metrics_tpu import native
from metrics_tpu.functional.text.helper import (
    _edit_distance,
    _edit_distance_corpus,
    _edit_distance_numpy,
)

_rng = np.random.default_rng(11)


def _rand_tokens(n, vocab=20):
    return [f"w{i}" for i in _rng.integers(0, vocab, n)]


@pytest.mark.skipif(not native.native_available(), reason="no C toolchain")
@pytest.mark.parametrize("trial", range(20))
def test_native_matches_numpy(trial):
    a = _rand_tokens(int(_rng.integers(0, 40)))
    b = _rand_tokens(int(_rng.integers(0, 40)))
    got = _edit_distance(a, b)  # dispatches native
    vocab = {}
    enc = lambda ts: np.asarray([vocab.setdefault(t, len(vocab)) for t in ts], dtype=np.int64)
    ea, eb = enc(a), enc(b)
    if len(a) and len(b):
        assert got == _edit_distance_numpy(ea, eb)
    else:
        assert got == max(len(a), len(b))


@pytest.mark.skipif(not native.native_available(), reason="no C toolchain")
def test_batch_equals_singles():
    pairs = [(_rand_tokens(int(_rng.integers(0, 30))), _rand_tokens(int(_rng.integers(0, 30)))) for _ in range(32)]
    batch = _edit_distance_corpus([p for p, _ in pairs], [r for _, r in pairs])
    singles = [_edit_distance(p, r) for p, r in pairs]
    assert batch == singles


def test_corpus_fallback_matches(monkeypatch):
    """With the native library forced off, the corpus path uses numpy and
    agrees with the per-pair computation."""
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_TRIED", True)
    pairs = [(_rand_tokens(10), _rand_tokens(12)), ([], _rand_tokens(3)), (_rand_tokens(4), [])]
    batch = _edit_distance_corpus([p for p, _ in pairs], [r for _, r in pairs])
    assert batch == [_edit_distance(p, r) for p, r in pairs]


def test_wer_same_value_both_backends(monkeypatch):
    from metrics_tpu.functional import word_error_rate

    preds = ["this is the prediction", "there is an other sample"]
    target = ["this is the reference", "there is another one"]
    with_native = float(word_error_rate(preds, target))
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_TRIED", True)
    without = float(word_error_rate(preds, target))
    assert with_native == without == 0.5
