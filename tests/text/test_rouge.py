"""ROUGE vs the rouge-score package oracle
(reference ``tests/text/test_rouge.py``)."""
import numpy as np
import pytest
from rouge_score.rouge_scorer import RougeScorer

from metrics_tpu.functional import rouge_score
from metrics_tpu.text import ROUGEScore
from tests.text.helpers import TextTester

ROUGE_KEYS = ("rouge1", "rouge2", "rougeL", "rougeLsum")

_preds_b1 = [
    "My name is John",
    "The quick brown fox jumps over the lazy dog .\nIt was a sunny day today .",
]
_targets_b1 = [
    ["Is your name John", "My name is indeed John"],
    ["A quick brown fox jumped over a lazy dog .\nToday was a sunny day .", "The dog was lazy ."],
]
_preds_b2 = [
    "the cat was found under the bed",
    "global warming affects the entire planet .\nWe must act now .",
]
_targets_b2 = [
    ["the cat was hiding under the bed", "the tiny cat hid under the bed"],
    ["climate change affects the whole planet .\nAction must happen now .", "the planet is warming ."],
]
BATCHES_PREDS = [_preds_b1, _preds_b2]
BATCHES_TARGET = [_targets_b1, _targets_b2]


def _oracle(preds, targets, use_stemmer=False, accumulate="best"):
    """Per-sample rouge-score results averaged with a plain mean.

    (The package's BootstrapAggregator ``mid`` is a stochastic bootstrap
    percentile, so the mean is taken directly instead.)
    """
    scorer = RougeScorer(ROUGE_KEYS, use_stemmer=use_stemmer)
    per_sample = {f"{k}_{s}": [] for k in ROUGE_KEYS for s in ("precision", "recall", "fmeasure")}
    for pred, refs in zip(preds, targets):
        refs = [refs] if isinstance(refs, str) else refs
        results = [scorer.score(ref, pred) for ref in refs]
        if accumulate == "best":
            key0 = ROUGE_KEYS[0]
            best = int(np.argmax([r[key0].fmeasure for r in results]))
            chosen = {
                f"{k}_{s}": getattr(results[best][k], s)
                for k in ROUGE_KEYS
                for s in ("precision", "recall", "fmeasure")
            }
        else:
            chosen = {
                f"{k}_{s}": float(np.mean([getattr(r[k], s) for r in results]))
                for k in ROUGE_KEYS
                for s in ("precision", "recall", "fmeasure")
            }
        for k, v in chosen.items():
            per_sample[k].append(v)
    return {k: float(np.mean(v)) for k, v in per_sample.items()}


class TestROUGE(TextTester):
    atol = 1e-5

    @pytest.mark.parametrize("use_stemmer", [False, True])
    @pytest.mark.parametrize("accumulate", ["best", "avg"])
    def test_functional_vs_rouge_score(self, use_stemmer, accumulate):
        for preds, targets in zip(BATCHES_PREDS, BATCHES_TARGET):
            got = rouge_score(preds, targets, use_stemmer=use_stemmer, accumulate=accumulate)
            want = _oracle(preds, targets, use_stemmer=use_stemmer, accumulate=accumulate)
            for key, value in want.items():
                np.testing.assert_allclose(float(got[key]), value, atol=1e-5, err_msg=key)

    @pytest.mark.parametrize("ddp", [False, True])
    def test_class(self, ddp):
        self.run_class_metric_test(ddp, BATCHES_PREDS, BATCHES_TARGET, ROUGEScore, _oracle)

    def test_single_string_inputs(self):
        got = rouge_score("My name is John", "Is your name John", rouge_keys="rouge1")
        np.testing.assert_allclose(float(got["rouge1_fmeasure"]), 0.75, atol=1e-6)

    def test_custom_normalizer_tokenizer(self):
        """tm_examples/rouge_score-own_normalizer_and_tokenizer.py pattern."""
        got = rouge_score(
            "ABC def",
            "abc DEF",
            rouge_keys="rouge1",
            normalizer=lambda s: s.upper(),
            tokenizer=lambda s: s.split(),
        )
        np.testing.assert_allclose(float(got["rouge1_fmeasure"]), 1.0, atol=1e-6)

    def test_invalid_args(self):
        with pytest.raises(ValueError):
            rouge_score(["a"], ["b"], rouge_keys="rouge42")
        with pytest.raises(ValueError):
            rouge_score(["a"], ["b"], accumulate="bestest")
        with pytest.raises(ValueError):
            ROUGEScore(rouge_keys="rouge42")
